"""Checkpoint integrity: per-array checksum manifests.

Orbax's commit is atomic (tmp dir + rename) and its OCDBT reads validate
compressed frames, so most torn writes surface as restore exceptions — but
"the restore raised" and "the restore returned the bytes we saved" are
different guarantees.  At pod scale the checkpoint path crosses enough
layers (host DMA, network filesystem, storage firmware) that silent
corruption is a when, not an if (MLPerf-pod postmortems treat checkpoint
integrity as a first-class goodput risk), and a training run resumed from
a silently-corrupt checkpoint wastes the whole remaining run.

The manifest is a sidecar JSON written at save time from the *in-memory*
state (so it never races the storage commit), one record per array leaf::

    {"version": 1, "step": 40, "t": 1690000000.0,
     "arrays": {"['params']['Dense_0']['kernel']": {
         "crc32": 123456, "shape": [784, 300], "dtype": "float32",
         "nbytes": 941", ...}, ...}}

``restore_latest`` recomputes the checksums over the restored tree and
compares; a mismatch (or a restore exception) marks the step corrupt and
falls back to the next-newest checkpoint that verifies.  Non-fully-
addressable arrays (multi-host shardings) are recorded as ``skipped`` and
exempt from verification — the chief can't see their bytes; the per-host
restore exception path still covers them.

All writes are atomic (temp file + ``os.replace``) and chief-only, so a
preemption mid-write can never leave a torn manifest next to a good
checkpoint.
"""

from __future__ import annotations

import json
import logging
import os
import time
import zlib
from typing import Any

import jax
import numpy as np

logger = logging.getLogger("distributedtensorflow_tpu")

__all__ = [
    "CheckpointCorruptError",
    "load_manifest",
    "manifest_path",
    "tree_checksums",
    "verify_tree",
    "write_manifest",
]

#: Manifest sidecar directory name under the checkpoint root (kept out of
#: the numbered step dirs — orbax owns those and renames them at commit).
MANIFEST_DIRNAME = "manifests"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed restore or checksum verification."""


def manifest_path(directory: str, step: int) -> str:
    return os.path.join(str(directory), MANIFEST_DIRNAME, f"{int(step)}.json")


def tree_checksums(tree: Any) -> dict[str, dict]:
    """Per-leaf checksum records, keyed by ``jax.tree_util.keystr`` path.

    CRC32 over the row-major host bytes of each leaf — cheap enough to run
    at every save (one pass over the state), strong enough to catch the
    torn-write/bit-flip class (this is an integrity check against storage
    faults, not an adversary).  Leaves this process cannot fully address
    (multi-host shardings) are recorded as ``skipped``.
    """
    out: dict[str, dict] = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if getattr(leaf, "is_fully_addressable", True) is False:
            out[key] = {"skipped": "not fully addressable"}
            continue
        arr = np.ascontiguousarray(np.asarray(leaf))
        out[key] = {
            "crc32": zlib.crc32(arr.tobytes()) & 0xFFFFFFFF,
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "nbytes": int(arr.nbytes),
        }
    return out


def write_manifest(directory: str, step: int,
                   checksums: dict[str, dict]) -> str | None:
    """Atomically write the manifest sidecar for ``step``; chief-only
    (every host computes the same checksums for replicated arrays; one
    writer avoids cross-host tmp-file races on shared storage).  Returns
    the path written, or None on non-chief hosts."""
    if jax.process_index() != 0:
        return None
    path = manifest_path(directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {
        "version": 1,
        "step": int(step),
        "t": time.time(),
        "arrays": checksums,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_manifest(directory: str, step: int) -> dict | None:
    """The parsed manifest for ``step``, or None when absent/unreadable
    (an unreadable manifest downgrades the step to unverified — the
    restore-exception path still guards it — rather than rejecting a
    possibly-fine checkpoint)."""
    path = manifest_path(directory, step)
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, ValueError):
        logger.warning("checkpoint manifest %s unreadable; treating step "
                       "as unverified", path)
        return None
    if not isinstance(doc, dict) or not isinstance(doc.get("arrays"), dict):
        logger.warning("checkpoint manifest %s malformed; treating step "
                       "as unverified", path)
        return None
    return doc


def verify_tree(tree: Any, manifest: dict) -> list[str]:
    """Mismatches between a restored tree and its save-time manifest.

    Empty list = verified.  Only leaves the manifest holds checksums for
    are compared (``skipped`` records and leaves unaddressable *here* are
    exempt); shape/dtype drift counts as a mismatch — a checkpoint that
    restores into different geometry did not round-trip.
    """
    got = tree_checksums(tree)
    problems: list[str] = []
    for key, rec in manifest.get("arrays", {}).items():
        if "crc32" not in rec:
            continue  # skipped at save time
        here = got.get(key)
        if here is None:
            problems.append(f"{key}: missing from restored state")
            continue
        if "crc32" not in here:
            continue  # not addressable on this host
        if list(rec.get("shape", [])) != here["shape"] or \
                str(rec.get("dtype", "")) != here["dtype"]:
            problems.append(
                f"{key}: geometry changed "
                f"({rec.get('shape')}/{rec.get('dtype')} -> "
                f"{here['shape']}/{here['dtype']})"
            )
        elif int(rec["crc32"]) != here["crc32"]:
            problems.append(
                f"{key}: checksum mismatch (saved {int(rec['crc32'])}, "
                f"restored {here['crc32']})"
            )
    return problems


def prune_manifests(directory: str, keep_steps: list[int]) -> None:
    """Drop manifest sidecars whose checkpoint was rotated away (orbax
    deletes the step dir; the sidecar would otherwise leak forever)."""
    mdir = os.path.join(str(directory), MANIFEST_DIRNAME)
    try:
        names = os.listdir(mdir)
    except OSError:
        return
    keep = {f"{int(s)}.json" for s in keep_steps}
    for name in names:
        if name.endswith(".json") and name not in keep:
            try:
                os.remove(os.path.join(mdir, name))
            except OSError:
                pass
