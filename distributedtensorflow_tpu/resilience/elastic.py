"""Elastic training: live replica resize without a cold restart.

The harness this repo grew from assumes a fixed cluster shape: losing or
gaining capacity means killing the process, re-forming the mesh at the
new size, and replaying the epoch — a cold restart that costs minutes of
goodput and (without exactly-once input accounting) silently re-trains or
skips batches.  The :class:`ElasticController` composes primitives the
repo already owns into *live* resize inside one process:

1. **Signal** — ``SIGUSR2`` (target device count read from
   ``<logdir>/resize_devices``; absent/invalid means "all visible
   devices") or ``POST /resizez?devices=N`` on the StatusServer, or a
   chaos-plan ``resize`` fault, or a direct :meth:`request_resize` call.
2. **Drain** — the controller is a Trainer :class:`~..train.trainer.
   Callback`: at the next dispatch boundary it opens the resize window
   (``resize_begin`` flight event, goodput window stamp) and sets
   ``trainer.stop_training``; the fit exits through its normal
   final-checkpoint path, so the drain save rides the existing
   integrity-manifest machinery — nothing resize-specific to corrupt.
3. **Re-form** — the entrypoint-supplied ``resize_fn(devices, state)``
   rebuilds the mesh at the new device count, re-chunks ZeRO optimizer
   state through :func:`~..parallel.zero.restore_latest_zero`'s
   cross-degree migration, and rebuilds the train step.  The function is
   TRANSACTIONAL: it commits (rebinds the live mesh/step/state) only at
   the very end, so a crash mid-resize leaves the old-size world intact
   and the supervisor's restart resumes from the drain checkpoint at the
   old size.
4. **Resume** — the outer loop (:class:`~.supervisor.Supervisor` or
   ``train.py --elastic``) rebuilds the input iterator against the SAME
   data-service epoch: the dispatcher journal's ``client_progress`` rows
   carry per-split *consumed* counts, so the new client resumes each
   split exactly after the last batch the trainer actually saw — no
   duplicate, no lost batch, even across several trainer hosts sharing
   one elastic epoch.

Bookkeeping per window: ``resize_begin``/``resize_end`` flight events
(device counts + outcome), the whole drain→rechunk→resume residual booked
into the goodput ``resize`` bucket (inner save/restore/compile spans keep
their own buckets — the sum stays exclusive), an
``elastic_resizes_total{outcome=}`` counter
(outcomes: ``completed`` / ``failed`` / ``rejected``), and live state on
``/resizez`` + ``/statusz``.

Failure contract: anything raised between drain and commit falls into the
supervisor's normal restart path; :meth:`abandon` closes the window as
``failed`` and DROPS the pending request, so the restart resumes from the
pre-resize checkpoint at the old device count instead of re-running the
resize.  A drain that wedges (``TimeoutError`` while :attr:`draining`) is
classified ``resize_drain`` — retryable, same fallback.
"""

from __future__ import annotations

import logging
import os
import signal as signal_mod
import threading
import time
from typing import Any, Callable

from .. import obs
from ..train.trainer import Callback

logger = logging.getLogger("distributedtensorflow_tpu")

__all__ = [
    "RESIZE_OUTCOMES",
    "ElasticController",
]

#: The ``elastic_resizes_total`` outcome label vocabulary (duplicated
#: stdlib-side in tools/check_metrics_schema.py — keep in sync).
RESIZE_OUTCOMES = ("completed", "failed", "rejected")

_M_RESIZES = obs.counter(
    "elastic_resizes_total", "elastic resize requests, by outcome"
)


class ElasticController(Callback):
    """Drives live replica resizes through the drain→re-form→resume
    sequence (module docstring).

    ``resize_fn(devices, state) -> state`` performs the actual re-form
    (train.py wires a transactional closure over its mesh/workload/step
    state).  ``current_devices_fn() -> int`` reports the live mesh's
    device count (validates requests, labels the flight events).
    Construction is cheap and jax-free; all device work happens inside
    ``resize_fn``.
    """

    def __init__(
        self,
        *,
        resize_fn: Callable[[int, Any], Any] | None = None,
        current_devices_fn: Callable[[], int] | None = None,
        logdir: str | None = None,
        devices_file: str | None = None,
    ):
        self.resize_fn = resize_fn
        self.current_devices_fn = current_devices_fn
        self._devices_file = devices_file or (
            os.path.join(logdir, "resize_devices") if logdir else None
        )
        self._lock = threading.Lock()
        #: Accepted-but-not-yet-performed request:
        #: {"devices", "source", "on_done", "t_req"}.
        self._pending: dict | None = None
        #: Open resize window (drain begun): {"t0", "from_devices",
        #: "to_devices", "source", "on_done", "drain_step",
        #: "anchor_step", "performed"}.
        self._window: dict | None = None
        self._draining = False
        #: Closed-window history (JSON-safe rows), newest last.
        self.history: list[dict] = []

    # -- request intake ------------------------------------------------------

    def request_resize(
        self, devices, *, source: str = "api",
        on_done: Callable[[str, dict], None] | None = None,
    ) -> tuple[bool, str]:
        """Ask for a resize to ``devices``; returns ``(accepted, message)``.

        Thread-safe and signal-safe (one lock, no I/O).  A request is
        rejected — counted under ``outcome="rejected"``, ``on_done`` NOT
        registered — when the count is invalid, equals the current size,
        or another resize is already in flight.  ``on_done(outcome,
        info)`` fires exactly once when an accepted request finishes
        (the chaos harness pairs its ``faults.jsonl`` rows through it).
        """
        try:
            n = int(devices)
        except (TypeError, ValueError):
            n = -1
        if n < 1:
            _M_RESIZES.inc(outcome="rejected")
            return False, f"bad device count {devices!r}"
        cur = self._current_devices()
        with self._lock:
            if self._pending is not None or self._window is not None:
                _M_RESIZES.inc(outcome="rejected")
                return False, "a resize is already in flight"
            if cur is not None and n == int(cur):
                _M_RESIZES.inc(outcome="rejected")
                return False, f"already at {n} device(s)"
            self._pending = {
                "devices": n, "source": str(source), "on_done": on_done,
                "t_req": time.time(),
            }
        logger.warning(
            "elastic: resize %s -> %d devices requested (source=%s)",
            cur if cur is not None else "?", n, source,
        )
        return True, f"resize to {n} device(s) pending"

    def install_signal_handler(self, signum: int = signal_mod.SIGUSR2) -> bool:
        """SIGUSR2 contract: the target device count is read from
        ``<logdir>/resize_devices`` at delivery time; a missing or invalid
        file means "grow back to all visible devices".  Returns False when
        not on the main thread (signal.signal would raise)."""

        def _handler(_sig, _frame):
            self.request_resize(self._devices_from_file(), source="signal")

        try:
            signal_mod.signal(signum, _handler)
        except ValueError:
            logger.error(
                "elastic: cannot install the resize signal handler off the "
                "main thread"
            )
            return False
        return True

    def _devices_from_file(self) -> int:
        if self._devices_file:
            try:
                with open(self._devices_file) as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                pass
        try:
            import jax  # noqa: PLC0415

            return len(jax.devices())
        except Exception:
            return self._current_devices() or 1

    def routes(self) -> dict:
        """StatusServer extra routes: ``GET /resizez`` (live state) and
        ``POST /resizez?devices=N`` (request; 400 bad count, 409 already
        in flight)."""

        def _get(_query):
            return 200, self.status()

        def _post(query, body: bytes):
            from urllib.parse import parse_qs  # noqa: PLC0415

            dev = (parse_qs(query).get("devices") or [None])[0]
            if dev is None and body:
                try:
                    import json  # noqa: PLC0415

                    dev = json.loads(body.decode("utf-8", "replace")) \
                        .get("devices")
                except (ValueError, AttributeError):
                    dev = None
            ok, msg = self.request_resize(dev, source="api")
            if ok:
                status = 200
            else:
                status = 400 if "bad device count" in msg else 409
            return status, {"ok": ok, "message": msg, **self.status()}

        return {("GET", "/resizez"): _get, ("POST", "/resizez"): _post}

    # -- Callback hooks (drain + window close) -------------------------------

    def on_fit_begin(self, trainer, state) -> None:
        trainer.elastic = self
        with self._lock:
            performed = bool(self._window and self._window.get("performed"))
        if performed:
            # The resized fit is running again: the window — drain, save,
            # mesh re-form, ZeRO rechunk, input rebuild — is over.
            self._close_window("completed", resumed_step=int(state.step))

    def on_step_end(self, trainer, step: int, state, metrics) -> None:
        with self._lock:
            if self._pending is None or self._window is not None:
                return
            p = self._pending
            self._window = {
                "t0": time.time(),
                "from_devices": self._current_devices() or 0,
                "to_devices": int(p["devices"]),
                "source": p["source"],
                "on_done": p.get("on_done"),
                "drain_step": int(step),
                "anchor_step": getattr(trainer, "_last_ckpt_step", None),
                "performed": False,
            }
            self._draining = True
            w = self._window
        obs.goodput.mark_resize_begin()
        obs.record_event(
            "resize_begin", step=int(step),
            from_devices=w["from_devices"], to_devices=w["to_devices"],
            source=w["source"],
        )
        logger.warning(
            "elastic: draining at step %d for resize %d -> %d (pre-resize "
            "checkpoint: step %s)", step, w["from_devices"],
            w["to_devices"], w["anchor_step"],
        )
        trainer.stop_training = True

    # -- the resize itself (called by the outer loop) ------------------------

    @property
    def draining(self) -> bool:
        """True between the drain request and the fit's exit — the
        supervisor classifies a TimeoutError in this window as
        ``resize_drain``, not ``data_stall``."""
        return self._draining

    @property
    def pending_target(self) -> int | None:
        with self._lock:
            return self._pending["devices"] if self._pending else None

    def should_perform(self, step: int, total_steps: int | None = None) -> bool:
        """After a clean fit exit: is there a drained resize to execute?
        A request that outlived the run (``step >= total_steps``) is
        rejected here so its bookkeeping still closes."""
        with self._lock:
            has_pending = self._pending is not None
        if not has_pending:
            return False
        if total_steps is not None and int(step) >= int(total_steps):
            self._reject_pending("run complete")
            return False
        return True

    def perform(self, state):
        """Execute the drained resize; returns the state restored at the
        new device count.  Raises whatever ``resize_fn`` raises — the
        caller routes the failure through the normal restart path and
        :meth:`abandon` closes the window as ``failed``."""
        with self._lock:
            p, self._pending = self._pending, None
            self._draining = False
            if p is not None and self._window is None:
                # The request landed after the last dispatch boundary (no
                # on_step_end fired): open the window here so the
                # begin/end pair still books.
                self._window = {
                    "t0": time.time(),
                    "from_devices": self._current_devices() or 0,
                    "to_devices": int(p["devices"]),
                    "source": p["source"],
                    "on_done": p.get("on_done"),
                    "drain_step": int(getattr(state, "step", 0)),
                    "anchor_step": None,
                    "performed": False,
                }
                late_open = self._window
            else:
                late_open = None
            w = self._window
        if p is None:
            return state
        if late_open is not None:
            obs.goodput.mark_resize_begin()
            obs.record_event(
                "resize_begin", step=late_open["drain_step"],
                from_devices=late_open["from_devices"],
                to_devices=late_open["to_devices"],
                source=late_open["source"],
            )
        if self.resize_fn is None:
            raise RuntimeError("elastic: no resize_fn wired")
        target = int(p["devices"])
        logger.warning(
            "elastic: re-forming mesh %d -> %d devices (drained at step %d)",
            w["from_devices"], target, w["drain_step"],
        )
        new_state = self.resize_fn(target, state)
        with self._lock:
            if self._window is not None:
                self._window["performed"] = True
        return new_state

    def abandon(self, reason: str = "restart") -> None:
        """Supervisor restart path: close an in-flight window as
        ``failed`` and DROP any pending request — the restart resumes
        from the pre-resize checkpoint at the old device count, and the
        resize is not re-run."""
        with self._lock:
            p, self._pending = self._pending, None
            self._draining = False
            has_window = self._window is not None
        if has_window:
            self._close_window("failed", error=str(reason))
        elif p is not None:
            _M_RESIZES.inc(outcome="rejected")
            self._finish(p.get("on_done"), "rejected",
                         {"reason": str(reason)})

    # -- window close --------------------------------------------------------

    def _reject_pending(self, reason: str) -> None:
        with self._lock:
            p, self._pending = self._pending, None
            self._draining = False
            has_window = self._window is not None
        if has_window:
            self._close_window("rejected", error=reason)
        elif p is not None:
            _M_RESIZES.inc(outcome="rejected")
            self._finish(p.get("on_done"), "rejected", {"reason": reason})

    def _close_window(self, outcome: str, *, resumed_step: int | None = None,
                      error: str | None = None) -> None:
        with self._lock:
            w, self._window = self._window, None
            self._draining = False
        if w is None:
            return
        dur = obs.goodput.mark_resize_end()
        if not dur:
            dur = max(time.time() - float(w["t0"]), 0.0)
        row = {
            "outcome": outcome,
            "from_devices": w["from_devices"],
            "to_devices": w["to_devices"],
            "source": w["source"],
            "drain_step": w["drain_step"],
            "anchor_step": w["anchor_step"],
            "resumed_step": resumed_step,
            "duration_s": round(dur, 3),
            "t": time.time(),
        }
        if error:
            row["error"] = error[:300]
        fields = {k: v for k, v in row.items() if k != "t" and v is not None}
        obs.record_event(
            "resize_end",
            step=int(resumed_step if resumed_step is not None
                     else w["drain_step"]),
            **fields,
        )
        _M_RESIZES.inc(outcome=outcome)
        self.history.append(row)
        logger.warning(
            "elastic: resize %d -> %d %s in %.2fs",
            w["from_devices"], w["to_devices"], outcome, dur,
        )
        info = {
            "resumed_step": (resumed_step if resumed_step is not None
                             else w["drain_step"]),
            "duration_s": row["duration_s"],
        }
        self._finish(w.get("on_done"), outcome, info)

    def _finish(self, on_done, outcome: str, info: dict) -> None:
        if on_done is None:
            return
        try:
            on_done(outcome, info)
        except Exception:
            logger.exception("elastic: resize on_done callback failed")

    # -- state ---------------------------------------------------------------

    def _current_devices(self) -> int | None:
        if self.current_devices_fn is None:
            return None
        try:
            return int(self.current_devices_fn())
        except Exception:
            return None

    def status(self) -> dict:
        """The ``/resizez`` (and ``/statusz`` ``elastic``) payload."""
        with self._lock:
            pending = (
                {k: v for k, v in self._pending.items() if k != "on_done"}
                if self._pending else None
            )
            window = (
                {k: v for k, v in self._window.items() if k != "on_done"}
                if self._window else None
            )
            recent = [dict(r) for r in self.history[-5:]]
            draining = self._draining
        counts: dict[str, int] = {}
        for r in self.history:
            counts[r["outcome"]] = counts.get(r["outcome"], 0) + 1
        return {
            "devices": self._current_devices(),
            "pending": pending,
            "in_flight": window,
            "draining": draining,
            "resizes": dict(counts),
            "recent": recent,
        }
