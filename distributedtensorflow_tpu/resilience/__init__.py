"""Self-healing training (resilience tentpole, PR 5).

Three cooperating layers turn the observability stack (PRs 1–4) into a
closed recovery loop:

- **checkpoint integrity** (``checkpoint/integrity.py`` +
  ``CheckpointManager``): per-array checksum manifests at save;
  ``restore_latest`` verifies and transparently falls back past
  truncated/corrupt checkpoints;
- **supervision** (:mod:`.supervisor`): bounded-retry/exponential-backoff
  restarts around ``Trainer.fit`` — classify, restore from the last
  verified checkpoint, re-enter, escalate to a clean non-zero exit when
  the budget runs out;
- **fault injection** (:mod:`.chaos`): deterministic fault plans
  (``train.py --fault-plan``) that exercise the whole stack on CPU in CI,
  logging every injection/recovery pair to ``<logdir>/faults.jsonl``;
- **elasticity** (:mod:`.elastic`): live replica resize without a cold
  restart (``train.py --elastic``) — drain to a checkpoint boundary,
  re-form the mesh, rechunk ZeRO state, resume the SAME data-service
  epoch exactly-once.
"""

from .chaos import (  # noqa: F401
    FAULT_KINDS,
    NET_FAULT_KINDS,
    ChaosInjector,
    DataStallFault,
    FaultPlan,
    InjectedFault,
    WorkerKilledFault,
)
from .elastic import (  # noqa: F401
    RESIZE_OUTCOMES,
    ElasticController,
)
from .supervisor import (  # noqa: F401
    RestartBudgetExhausted,
    Supervisor,
    SupervisorConfig,
    classify_failure,
)
