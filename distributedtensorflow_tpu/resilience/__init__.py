"""Self-healing training (resilience tentpole, PR 5).

Three cooperating layers turn the observability stack (PRs 1–4) into a
closed recovery loop:

- **checkpoint integrity** (``checkpoint/integrity.py`` +
  ``CheckpointManager``): per-array checksum manifests at save;
  ``restore_latest`` verifies and transparently falls back past
  truncated/corrupt checkpoints;
- **supervision** (:mod:`.supervisor`): bounded-retry/exponential-backoff
  restarts around ``Trainer.fit`` — classify, restore from the last
  verified checkpoint, re-enter, escalate to a clean non-zero exit when
  the budget runs out;
- **fault injection** (:mod:`.chaos`): deterministic fault plans
  (``train.py --fault-plan``) that exercise the whole stack on CPU in CI,
  logging every injection/recovery pair to ``<logdir>/faults.jsonl``.
"""

from .chaos import (  # noqa: F401
    FAULT_KINDS,
    NET_FAULT_KINDS,
    ChaosInjector,
    DataStallFault,
    FaultPlan,
    InjectedFault,
    WorkerKilledFault,
)
from .supervisor import (  # noqa: F401
    RestartBudgetExhausted,
    Supervisor,
    SupervisorConfig,
    classify_failure,
)
