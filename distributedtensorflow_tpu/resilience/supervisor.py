"""Supervised training: bounded-retry restarts around ``Trainer.fit``.

Four PRs of observability can *see* every failure — NaN spikes,
stragglers, preemptions, wedges — but the trainer still dies on the first
one and stays dead.  At pod scale, recoverability is the limiting factor
on goodput (MLPerf TPU-v3 pods, arxiv 1909.09756; pjit-on-TPUv4 runs,
arxiv 2204.06514): a run must survive worker loss, corrupt checkpoints,
and data stalls without a human in the loop.  The Supervisor is that
loop-closer:

1. **classify** the failure — chaos-injected faults carry their kind;
   a coordinator worker death is ``worker_crash``; a fired hang watchdog
   (or a :class:`~.chaos.DataStallFault`) is ``data_stall``; a NaN-loss
   anomaly (observed via a Callback that stops the fit) is ``nan_loss``;
   a consumed preemption notice is ``preemption``;
2. **restore** from the newest *verified* checkpoint
   (:func:`~..parallel.zero.restore_latest_zero` — corrupt steps are
   rejected and fallen back past, saved ZeRO layouts that differ from the
   restart's are rechunked rather than mistaken for corruption; NaN
   failures restore from strictly *before* the poisoned step);
3. **re-enter** ``fit`` after an exponential backoff (base × 2^attempt,
   clamped), rebuilding the input iterator at the resumed step;
4. **escalate** once the retry budget is exhausted: a
   :class:`RestartBudgetExhausted` carrying the failure history, which
   ``train.py`` converts to a clean non-zero exit for the job scheduler.

Every restart emits a ``restart`` flight event, a
``supervisor_restarts_total{kind=}`` counter, books its
classification+backoff window into the goodput ``badput_restart`` bucket
(the restore itself books under ``checkpoint_restore`` as usual — no
double counting), and updates ``trainer.supervisor_status`` so
``/statusz`` shows the retry budget live.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Iterable

from .. import obs
from ..parallel.coordinator import ClosureAborted, WorkerUnavailableError
from . import chaos as chaos_lib

logger = logging.getLogger("distributedtensorflow_tpu")

__all__ = [
    "RestartBudgetExhausted",
    "Supervisor",
    "SupervisorConfig",
    "classify_failure",
]

_M_RESTARTS = obs.counter(
    "supervisor_restarts_total",
    "supervised in-process restarts, by failure kind",
)

#: Failure kinds that must NOT be retried: restarting cannot help.
NONRETRYABLE_KINDS = frozenset({"data_exhausted"})


def classify_failure(
    exc: BaseException | None = None,
    *,
    preempted: bool = False,
    nan_anomaly: bool = False,
    watchdog_fired: bool = False,
    resize_draining: bool = False,
) -> str:
    """The failure-classification table (module docstring, rule order):
    chaos faults carry their kind; known exception types map to kinds; a
    fired watchdog turns an otherwise-unknown failure into ``data_stall``;
    everything else is ``unknown`` (still retried — an unknown crash is
    exactly what a restart policy is for).

    ``resize_draining``: a timeout while the ElasticController is draining
    the fit is the DRAIN wedging, not a dead input pipeline — classifying
    it ``data_stall`` restarted from the wrong state (and re-ran the
    resize that just wedged).  ``resize_drain`` is retryable and the
    restart path abandons the resize, falling back to the pre-resize
    checkpoint."""
    if preempted:
        return "preemption"
    if exc is None:
        return "nan_loss" if nan_anomaly else "unknown"
    if isinstance(exc, chaos_lib.InjectedFault):
        return exc.kind
    if isinstance(exc, (WorkerUnavailableError, ClosureAborted)):
        return "worker_crash"
    if isinstance(exc, StopIteration):
        return "data_exhausted"
    if isinstance(exc, TimeoutError):
        return "resize_drain" if resize_draining else "data_stall"
    if isinstance(exc, FloatingPointError):
        return "nan_loss"
    if watchdog_fired:
        return "resize_drain" if resize_draining else "data_stall"
    return "unknown"


class RestartBudgetExhausted(RuntimeError):
    """The retry budget ran out; ``failures`` is the per-attempt history
    (kind, step, error) and ``last_exception`` the final straw (when the
    final failure was exception-shaped)."""

    def __init__(self, message: str, *, failures: list[dict],
                 last_exception: BaseException | None = None):
        super().__init__(message)
        self.failures = failures
        self.last_exception = last_exception


@dataclasses.dataclass
class SupervisorConfig:
    #: Total in-process restarts allowed before escalating.
    max_restarts: int = 3
    #: Backoff before restart N (1-based) is ``base * factor**(N-1)``,
    #: clamped to ``backoff_max_s``.
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    #: Resume (rather than exit) after a preemption-shaped stop — the
    #: in-process analogue of the launcher restarting the job.  Real
    #: cluster preemptions kill the process anyway; this path serves
    #: synthetic/chaos preemptions and schedulers that rescind notices.
    resume_on_preemption: bool = True

    def backoff_s(self, attempt: int) -> float:
        """Clamped exponential backoff before restart ``attempt``
        (1-based)."""
        return min(
            self.backoff_base_s * (self.backoff_factor ** max(attempt - 1, 0)),
            self.backoff_max_s,
        )


class _NanWatch:
    """Trainer callback: a non-finite-loss anomaly ends the fit (the
    anomaly hook itself must never raise — the Watchdog convention — so it
    stops the loop via ``stop_training`` and the Supervisor reads the flag
    after ``fit`` returns)."""

    def __init__(self):
        self.anomaly = None

    def reset(self) -> None:
        self.anomaly = None

    def tripped(self) -> bool:
        return self.anomaly is not None

    # Callback surface (duck-typed; only on_anomaly matters here).
    def on_fit_begin(self, trainer, state) -> None: ...
    def on_step_end(self, trainer, step, state, metrics) -> None: ...
    def on_eval_end(self, trainer, step, state, eval_metrics) -> None: ...
    def on_checkpoint(self, trainer, step, state) -> None: ...
    def on_fit_end(self, trainer, state) -> None: ...

    def on_anomaly(self, trainer, anomaly) -> None:
        if anomaly.kind == "non_finite_loss" and self.anomaly is None:
            self.anomaly = anomaly
            logger.error(
                "supervisor: NaN loss at step %d — stopping the fit for a "
                "restore-and-restart", anomaly.step,
            )
            trainer.stop_training = True


class Supervisor:
    """Wraps a Trainer's ``fit`` in the restart policy.

    ``make_train_iter(start_step)`` must return a fresh train iterator
    positioned after ``start_step`` consumed batches (train.py's
    ``skip_batches`` fast-forward); it is called once per (re)start.
    ``state_template_fn`` rebuilds a pristine sharded state: the state fed
    to a failed fit was *donated* to the device, so restores need a fresh
    template (and a cold restart — no usable checkpoint — starts from it).
    ``chaos`` (a :class:`~.chaos.ChaosInjector`) gets its injected faults
    paired with ``recovered`` rows after each successful restart.
    """

    def __init__(
        self,
        trainer,
        *,
        make_train_iter: Callable[[int], Iterable],
        state_template_fn: Callable[[], Any] | None = None,
        eval_iter_fn: Callable[[], Iterable] | None = None,
        config: SupervisorConfig | None = None,
        chaos: chaos_lib.ChaosInjector | None = None,
        elastic=None,
    ):
        self.trainer = trainer
        self.config = config or SupervisorConfig()
        self._make_train_iter = make_train_iter
        self._state_template_fn = state_template_fn
        self._eval_iter_fn = eval_iter_fn
        self._chaos = chaos
        #: resilience.ElasticController (or None): drained resizes are
        #: performed inside the supervised loop, so a mid-resize crash
        #: falls into the same classify→restore→re-enter path.
        self._elastic = elastic
        self._nan_watch = _NanWatch()
        trainer.callbacks.append(self._nan_watch)
        #: Per-restart history: {"kind", "step", "attempt", "resumed_step",
        #: "backoff_s", "error"}.
        self.restarts: list[dict] = []

    # -- status --------------------------------------------------------------

    def status(self) -> dict:
        return {
            "restarts": len(self.restarts),
            "max_restarts": self.config.max_restarts,
            "last_failure": (
                self.restarts[-1]["kind"] if self.restarts else None
            ),
            "last_resumed_step": (
                self.restarts[-1]["resumed_step"] if self.restarts else None
            ),
        }

    def _publish_status(self) -> None:
        self.trainer.supervisor_status = self.status()

    # -- the restart loop ----------------------------------------------------

    def run(self, state, rng) -> Any:
        """Drive ``fit`` to completion under the restart policy; returns
        the final state, or raises :class:`RestartBudgetExhausted` /
        a non-retryable failure."""
        trainer = self.trainer
        cfg = self.config
        failures: list[dict] = []
        self._publish_status()
        while True:
            self._nan_watch.reset()
            exc: BaseException | None = None
            try:
                it = self._make_train_iter(int(state.step))
                state = trainer.fit(
                    state, it, rng, eval_iter_fn=self._eval_iter_fn
                )
            except (KeyboardInterrupt, SystemExit):
                raise  # operator intent / clean exits pass through
            except BaseException as e:  # noqa: BLE001 — classified below
                exc = e
            t_fail = time.time()
            step_now = int(getattr(state, "step", 0)) if exc is None else None
            total = trainer.config.total_steps
            if exc is None:
                preempted = bool(getattr(trainer, "preempted", False))
                if preempted and cfg.resume_on_preemption \
                        and int(state.step) < total:
                    kind = "preemption"
                elif self._nan_watch.tripped() and int(state.step) < total:
                    kind = "nan_loss"
                elif self._elastic is not None and self._elastic \
                        .should_perform(int(state.step), total):
                    # The fit drained for a resize: re-form the mesh and
                    # rechunk INSIDE the supervised loop, so a mid-resize
                    # crash is classified/restored like any other failure
                    # (abandon() in _restart falls back to the pre-resize
                    # checkpoint at the old size).
                    try:
                        state = self._elastic.perform(state)
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except BaseException as e:  # noqa: BLE001
                        exc = e
                        t_fail = time.time()
                        kind = classify_failure(e)
                        step_now = None
                    else:
                        continue
                else:
                    # Done: target reached, total_steps hit, or a
                    # user-requested stop — none of which is a failure.
                    self._publish_status()
                    return state
            else:
                kind = classify_failure(
                    exc,
                    watchdog_fired=bool(
                        getattr(trainer, "watchdog_fired", False)
                    ),
                    resize_draining=bool(
                        self._elastic is not None and self._elastic.draining
                    ),
                )
            failures.append({
                "kind": kind,
                "step": step_now,
                "error": (repr(exc)[:300] if exc is not None else None),
            })
            logger.error(
                "supervisor: fit failed (%s)%s — %d/%d restarts used",
                kind, f": {exc!r}" if exc else "", len(self.restarts),
                cfg.max_restarts,
            )
            if kind in NONRETRYABLE_KINDS:
                logger.error("supervisor: %s is not retryable; escalating",
                             kind)
                if exc is not None:
                    raise exc
                raise RestartBudgetExhausted(
                    f"non-retryable failure: {kind}", failures=failures,
                )
            if len(self.restarts) >= cfg.max_restarts:
                obs.record_event(
                    "supervisor_giving_up", restarts=len(self.restarts),
                    failure=kind,
                )
                raise RestartBudgetExhausted(
                    f"retry budget exhausted after {len(self.restarts)} "
                    f"restart(s); final failure: {kind}",
                    failures=failures, last_exception=exc,
                )
            state = self._restart(state, kind, exc, t_fail)

    def _restart(self, state, kind: str, exc: BaseException | None,
                 t_fail: float):
        """One restart: backoff, restore from the newest verified
        checkpoint, book the badput, pair chaos recoveries; returns the
        state to resume from."""
        trainer = self.trainer
        cfg = self.config
        attempt = len(self.restarts) + 1
        # A resize in flight does NOT survive a restart: close its window
        # as failed and drop the pending request BEFORE booking the
        # restart badput (the resize window's residual stops here), so
        # the restore below lands on the pre-resize checkpoint and the
        # resize is not re-run.
        if self._elastic is not None:
            self._elastic.abandon(reason=kind)
        backoff = cfg.backoff_s(attempt)
        logger.warning(
            "supervisor: restart %d/%d after %s — backing off %.2fs",
            attempt, cfg.max_restarts, kind, backoff,
        )
        if backoff > 0:
            time.sleep(backoff)
        # Book classification + backoff as badput_restart BEFORE the
        # restore starts: the restore's own span already books under
        # checkpoint_restore, and the goodput buckets must stay exclusive.
        obs.goodput.note_restart(time.time() - t_fail)
        before_step = None
        if kind == "nan_loss":
            # Resume from BEFORE the poisoned step — the stop-save the
            # trainer force-wrote on the way out is downstream of the NaN.
            if self._nan_watch.anomaly is not None:
                before_step = self._nan_watch.anomaly.step
            else:
                # Exception-shaped NaN (e.g. FloatingPointError under
                # jax_debug_nans): the NaN surfaced during the step AFTER
                # the last completed one, so a checkpoint at _last_step
                # itself still predates it.
                last = getattr(trainer, "_last_step", None)
                if last is not None:
                    before_step = int(last) + 1
        rejected_steps: list[int] = []
        resumed = None
        if trainer.checkpointer is not None:
            template = (
                self._state_template_fn() if self._state_template_fn
                else state
            )
            if getattr(template, "tx", None) is not None:
                # Layout-aware: a mixed-layout history (a replicated run
                # restarted --zero, or vice versa) must rechunk the saved
                # optimizer state, not reject every differently-chunked
                # step as corrupt and cold-start.  Needs the template's
                # ``tx`` for the layout probe; templates without one
                # (host-only tests) take the plain path.
                from ..parallel.zero import restore_latest_zero  # noqa: PLC0415

                resumed = restore_latest_zero(
                    trainer.checkpointer, template, before_step=before_step
                )
            else:
                resumed = trainer.checkpointer.restore_latest(
                    template, before_step=before_step
                )
            report = getattr(trainer.checkpointer, "last_restore_report",
                             None) or {}
            rejected_steps = [
                r.get("step") for r in report.get("rejected", ())
            ]
            if resumed is None:
                logger.warning(
                    "supervisor: no usable checkpoint%s; cold restart from "
                    "step %d", f" below step {before_step}" if before_step
                    else "", int(template.step),
                )
                resumed = template
        elif self._state_template_fn is not None:
            resumed = self._state_template_fn()
        else:
            resumed = state  # last resort: caller manages state lifetime
        resumed_step = int(getattr(resumed, "step", 0))
        # Re-arm consumed one-shot machinery before the next fit.
        clear = getattr(trainer, "clear_preempted", None)
        if clear is not None:
            clear()
        _M_RESTARTS.inc(kind=kind)
        # NaN-provenance hint (obs/dynamics.py): a nan_loss restart that
        # knows WHICH module went bad says so — "restored from step K"
        # becomes "module h3 produced the first non-finite at step K".
        prov_fields = {}
        if kind == "nan_loss":
            try:
                from ..obs import dynamics as dynlib  # noqa: PLC0415

                prov = dynlib.last_provenance()
            except Exception:  # pragma: no cover — hint only, never fatal
                prov = None
            if prov and prov.get("module"):
                prov_fields = {
                    "nan_module": prov["module"],
                    "provenance_step": prov.get("step"),
                }
                logger.warning(
                    "supervisor: nan provenance — module %r produced the "
                    "first non-finite value at step %s (via %s)",
                    prov["module"], prov.get("step"), prov.get("method"),
                )
        obs.record_event(
            "restart", step=resumed_step, failure=kind, attempt=attempt,
            backoff_s=round(backoff, 3),
            rejected_checkpoints=len(rejected_steps),
            **prov_fields,
        )
        self.restarts.append({
            "kind": kind, "attempt": attempt, "resumed_step": resumed_step,
            "backoff_s": backoff,
            "error": repr(exc)[:300] if exc is not None else None,
        })
        if self._chaos is not None:
            self._chaos.mark_recovered(
                resumed_step=resumed_step, attempt=attempt,
                rejected_steps=rejected_steps,
            )
        self._publish_status()
        logger.warning(
            "supervisor: resuming from step %d (restart %d/%d)",
            resumed_step, attempt, cfg.max_restarts,
        )
        return resumed
