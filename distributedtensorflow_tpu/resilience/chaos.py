"""Deterministic fault injection: make the recovery stack testable on CPU.

None of the recovery paths built since PR 1 — restore fallback, preemption
save, worker respawn, supervised restart — mean anything until they have
been *exercised under real faults*, and waiting for production to supply
the faults means debugging them at 3am on a pod.  This module injects them
on demand, deterministically, from a JSON *fault plan*
(``train.py --fault-plan``)::

    {"faults": [
      {"step": 35,  "kind": "worker_kill"},
      {"step": 45,  "kind": "checkpoint_truncate"},
      {"step": 70,  "kind": "nan_loss"},
      {"step": 100, "kind": "data_stall", "stall_s": 0.1},
      {"step": 110, "kind": "preemption"}
    ]}

(a bare JSON list of fault objects is accepted too).  Fault kinds:

``nan_loss``
    The wrapped train step reports a NaN loss at the trigger step; the
    streaming AnomalyDetector flags it at the next log boundary and the
    Supervisor's watch callback turns it into a restart from a checkpoint
    *before* the poisoned step.
``checkpoint_truncate``
    The first checkpoint save at/after the trigger step is truncated on
    disk post-commit (the torn-write storage fault), so the next
    ``restore_latest`` must reject it and fall back to an older verified
    step.
``worker_kill``
    SIGKILLs a process-backed coordinator worker when one is attached
    (:meth:`ChaosInjector.attach_coordinator` — exercising the bounded
    respawn path), then raises :class:`WorkerKilledFault` out of the fit:
    sync SPMD training treats worker loss as fatal, and recovery is the
    supervisor's restore-and-restart.
``data_stall``
    Blocks the fit loop for ``stall_s`` seconds at the trigger step (long
    enough and the hang watchdog fires mid-stall), then raises
    :class:`DataStallFault` — the dead-input-pipeline failure.
``preemption``
    Calls ``PreemptionHandler.trigger()`` (attach via
    :meth:`attach_preemption`): the trainer's own consistent-save path
    runs and the fit exits preempted; the supervisor resumes it.

Every injection and recovery is appended to ``<logdir>/faults.jsonl``
(one JSON object per line, ``t`` non-decreasing)::

    {"t": ..., "id": 0, "step": 35, "kind": "worker_kill",
     "phase": "injected"}
    {"t": ..., "id": 0, "step": 35, "kind": "worker_kill",
     "phase": "recovered", "resumed_step": 20, "attempt": 1}

``id`` is the injection index (strictly increasing across injected rows;
injected steps non-decreasing), and a healthy run pairs every injected
``id`` with a recovered row — ``tools/check_metrics_schema.py`` enforces
exactly that.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any

from .. import obs
from ..train.trainer import Callback

logger = logging.getLogger("distributedtensorflow_tpu")

__all__ = [
    "FAULT_KINDS",
    "ChaosInjector",
    "DataStallFault",
    "FaultPlan",
    "InjectedFault",
    "WorkerKilledFault",
]

#: The known fault kinds (duplicated stdlib-side in
#: tools/check_metrics_schema.py FAULT_KINDS — keep in sync).
FAULT_KINDS = (
    "nan_loss",
    "checkpoint_truncate",
    "worker_kill",
    "data_stall",
    "preemption",
)

_M_INJECTED = obs.counter(
    "faults_injected_total", "chaos faults injected, by kind"
)
_M_RECOVERED = obs.counter(
    "faults_recovered_total", "chaos faults recovered from, by kind"
)


class InjectedFault(RuntimeError):
    """Base of chaos-raised failures; ``kind`` drives the supervisor's
    classification and ``fault_id`` pairs the recovery row."""

    kind = "injected"

    def __init__(self, message: str, *, fault_id: int, step: int):
        super().__init__(message)
        self.fault_id = fault_id
        self.step = step


class WorkerKilledFault(InjectedFault):
    kind = "worker_kill"


class DataStallFault(InjectedFault):
    kind = "data_stall"


@dataclasses.dataclass
class _Fault:
    id: int
    step: int
    kind: str
    params: dict
    injected: bool = False
    recovered: bool = False
    #: The step the injection actually fired at (>= the plan's trigger
    #: step); recovery rows echo it so a pair shares one step.
    injected_step: int | None = None
    #: checkpoint_truncate: the step of the save actually truncated.
    detail_step: int | None = None


class FaultPlan:
    """A validated, step-sorted list of fault triggers."""

    def __init__(self, faults: list[dict]):
        parsed: list[_Fault] = []
        for i, f in enumerate(faults):
            if not isinstance(f, dict):
                raise ValueError(f"fault[{i}]: not an object: {f!r}")
            kind = f.get("kind")
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"fault[{i}]: unknown kind {kind!r} "
                    f"(known: {', '.join(FAULT_KINDS)})"
                )
            step = f.get("step")
            if not isinstance(step, int) or isinstance(step, bool) \
                    or step < 0:
                raise ValueError(
                    f"fault[{i}]: step {step!r} is not a non-negative int"
                )
            params = {k: v for k, v in f.items() if k not in ("kind", "step")}
            parsed.append(_Fault(id=i, step=int(step), kind=kind,
                                 params=params))
        parsed.sort(key=lambda f: (f.step, f.id))
        # Re-id in trigger order so injected ids are strictly increasing.
        for i, f in enumerate(parsed):
            f.id = i
        self.faults = parsed

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict):
            doc = doc.get("faults")
        if not isinstance(doc, list):
            raise ValueError(
                f"{path}: expected a JSON list of faults or an object "
                "with a 'faults' list"
            )
        return cls(doc)

    def __len__(self) -> int:
        return len(self.faults)


class ChaosInjector(Callback):
    """Executes a :class:`FaultPlan` against a run and logs
    ``faults.jsonl``.

    Wiring (train.py does all of this under ``--fault-plan``):

    - append the injector itself to the Trainer's callbacks (it is a
      :class:`~..train.trainer.Callback`; ``on_step_end`` fires the
      worker-kill / data-stall / preemption triggers);
    - ``train_step = injector.wrap_train_step(train_step)`` for NaN
      injection (adds one host sync of ``state.step`` per dispatch —
      chaos mode is a test harness, not a production path);
    - ``checkpointer = injector.wrap_checkpointer(checkpointer)`` for
      post-commit truncation;
    - :meth:`attach_preemption` / :meth:`attach_coordinator` for the
      signal-shaped faults.

    The Supervisor closes the loop: :meth:`mark_recovered` after each
    successful restart writes the paired ``recovered`` rows.
    """

    def __init__(self, plan: FaultPlan, logdir: str | None = None):
        self.plan = plan
        self._lock = threading.Lock()
        self._path = (
            os.path.join(logdir, "faults.jsonl") if logdir else None
        )
        self._preemption = None
        self._coordinator = None
        if self._path:
            os.makedirs(logdir, exist_ok=True)
            # Truncate a prior run's log: the plan restarts from scratch.
            open(self._path, "w").close()

    # -- wiring --------------------------------------------------------------

    def attach_preemption(self, handler) -> None:
        """The PreemptionHandler ``preemption`` faults trigger()."""
        self._preemption = handler

    def attach_coordinator(self, coord) -> None:
        """A process-backed Coordinator whose worker 0 ``worker_kill``
        faults SIGKILL (optional — without one the fault only raises)."""
        self._coordinator = coord

    def wrap_train_step(self, train_step):
        """NaN-loss injection: at the trigger step the returned metrics
        report a NaN loss (the state itself is untouched — the detection
        and recovery machinery downstream is what is under test)."""
        import jax.numpy as jnp  # noqa: PLC0415

        def chaotic_step(state, batch, rng):
            step_before = int(state.step)
            new_state, metrics = train_step(state, batch, rng)
            fault = self._pending("nan_loss", step_before + 1)
            if fault is not None and "loss" in metrics:
                self._inject(fault, at_step=step_before + 1)
                metrics = dict(
                    metrics,
                    loss=jnp.full_like(
                        jnp.asarray(metrics["loss"]), jnp.nan
                    ),
                )
            return new_state, metrics

        return chaotic_step

    def wrap_checkpointer(self, manager):
        """Proxy whose ``save`` truncates the on-disk checkpoint when a
        ``checkpoint_truncate`` fault has come due."""
        return _ChaosCheckpointer(manager, self)

    # -- Callback hooks (worker_kill / data_stall / preemption) --------------

    def on_step_end(self, trainer, step: int, state, metrics) -> None:
        fault = self._pending("preemption", step)
        if fault is not None:
            self._inject(fault, at_step=step)
            if self._preemption is not None:
                self._preemption.trigger()
            else:
                logger.error(
                    "chaos: preemption fault at step %d but no handler "
                    "attached; fault is a no-op", step,
                )
        fault = self._pending("data_stall", step)
        if fault is not None:
            stall_s = float(fault.params.get("stall_s", 0.0))
            self._inject(fault, at_step=step, stall_s=stall_s)
            if stall_s > 0:
                # The fit loop stops making progress right here — a long
                # enough stall fires the hang watchdog mid-sleep.
                time.sleep(stall_s)
            raise DataStallFault(
                f"chaos: input pipeline stalled at step {step}",
                fault_id=fault.id, step=step,
            )
        fault = self._pending("worker_kill", step)
        if fault is not None:
            self._inject(fault, at_step=step)
            if self._coordinator is not None:
                try:
                    self._coordinator.kill_worker_process(
                        int(fault.params.get("worker", 0))
                    )
                except Exception:
                    logger.exception("chaos: coordinator worker kill failed")
            raise WorkerKilledFault(
                f"chaos: worker killed at step {step}",
                fault_id=fault.id, step=step,
            )

    # -- recovery bookkeeping (called by the Supervisor) ---------------------

    def mark_recovered(self, *, resumed_step: int, attempt: int,
                       rejected_steps: list[int] | None = None) -> int:
        """Write ``recovered`` rows for every injected-but-unrecovered
        fault this restart resolves: the restart-shaped kinds always; a
        ``checkpoint_truncate`` only once a fallback restore actually
        rejected its truncated step (``rejected_steps``).  Returns the
        number of rows written."""
        rejected = set(rejected_steps or ())
        n = 0
        with self._lock:
            for f in self.plan.faults:
                if not f.injected or f.recovered:
                    continue
                if f.kind == "checkpoint_truncate":
                    if f.detail_step not in rejected:
                        continue
                f.recovered = True
                n += 1
                _M_RECOVERED.inc(kind=f.kind)
                self._write({
                    "t": time.time(), "id": f.id,
                    "step": (f.injected_step if f.injected_step is not None
                             else f.step),
                    "kind": f.kind, "phase": "recovered",
                    "resumed_step": int(resumed_step),
                    "attempt": int(attempt),
                })
        return n

    def unrecovered(self) -> list[dict]:
        """Injected faults still awaiting a recovery row (a non-empty
        answer at run end = the run did not actually self-heal)."""
        with self._lock:
            return [
                {"id": f.id, "step": f.step, "kind": f.kind}
                for f in self.plan.faults
                if f.injected and not f.recovered
            ]

    # -- internals -----------------------------------------------------------

    def _pending(self, kind: str, step: int) -> _Fault | None:
        """The first uninjected fault of ``kind`` whose trigger step has
        come (<= step), or None."""
        with self._lock:
            for f in self.plan.faults:
                if f.kind == kind and not f.injected and f.step <= step:
                    return f
        return None

    def _inject(self, fault: _Fault, *, at_step: int, **fields) -> None:
        with self._lock:
            if fault.injected:
                return
            fault.injected = True
            fault.injected_step = int(at_step)
            _M_INJECTED.inc(kind=fault.kind)
            row = {
                "t": time.time(), "id": fault.id, "step": int(at_step),
                "kind": fault.kind, "phase": "injected",
            }
            row.update(fields)
            self._write(row)
        logger.warning(
            "chaos: injected %s (fault #%d) at step %d",
            fault.kind, fault.id, at_step,
        )
        obs.record_event(
            "fault", step=int(at_step), fault=fault.kind, phase="injected",
            id=fault.id,
        )

    def _note_truncated(self, fault: _Fault, save_step: int) -> None:
        with self._lock:
            fault.detail_step = int(save_step)

    def _write(self, row: dict[str, Any]) -> None:
        """Append one faults.jsonl line (caller holds the lock); a write
        failure must never escalate an injected fault into a crash."""
        if self._path is None:
            return
        try:
            with open(self._path, "a") as f:
                f.write(json.dumps(row) + "\n")
        except OSError:
            logger.exception("chaos: faults.jsonl append failed")


class _ChaosCheckpointer:
    """CheckpointManager proxy that truncates the bytes of a just-saved
    step when a ``checkpoint_truncate`` fault is due — the torn-write
    storage fault, injected at the exact layer it happens in production."""

    def __init__(self, manager, injector: ChaosInjector):
        self._manager = manager
        self._injector = injector

    def save(self, step: int, state, **kwargs) -> bool:
        saved = self._manager.save(step, state, **kwargs)
        if saved:
            fault = self._injector._pending("checkpoint_truncate", step)
            if fault is not None:
                self._manager.wait()  # the bytes must be on disk to tear
                self._injector._inject(fault, at_step=step,
                                       truncated_step=step)
                self._injector._note_truncated(fault, step)
                self._truncate(step)
        return saved

    def _truncate(self, step: int) -> None:
        directory = getattr(self._manager, "_directory", None)
        if directory is None:
            return
        step_dir = os.path.join(directory, str(int(step)))
        biggest, size = None, -1
        for root, _dirs, files in os.walk(step_dir):
            for name in files:
                p = os.path.join(root, name)
                s = os.path.getsize(p)
                if s > size:
                    biggest, size = p, s
        if biggest is None:
            logger.error("chaos: no files to truncate under %s", step_dir)
            return
        with open(biggest, "r+b") as f:
            f.truncate(max(size // 2, 1))
        logger.warning(
            "chaos: truncated %s (%d -> %d bytes)", biggest, size,
            max(size // 2, 1),
        )

    def __getattr__(self, name):
        return getattr(self._manager, name)
