"""Deterministic fault injection: make the recovery stack testable on CPU.

None of the recovery paths built since PR 1 — restore fallback, preemption
save, worker respawn, supervised restart — mean anything until they have
been *exercised under real faults*, and waiting for production to supply
the faults means debugging them at 3am on a pod.  This module injects them
on demand, deterministically, from a JSON *fault plan*
(``train.py --fault-plan``)::

    {"faults": [
      {"step": 35,  "kind": "worker_kill"},
      {"step": 45,  "kind": "checkpoint_truncate"},
      {"step": 70,  "kind": "nan_loss"},
      {"step": 100, "kind": "data_stall", "stall_s": 0.1},
      {"step": 110, "kind": "preemption"}
    ]}

(a bare JSON list of fault objects is accepted too).  Fault kinds:

``nan_loss``
    The wrapped train step reports a NaN loss at the trigger step; the
    streaming AnomalyDetector flags it at the next log boundary and the
    Supervisor's watch callback turns it into a restart from a checkpoint
    *before* the poisoned step.  An optional ``"module": "h1"`` param
    ALSO poisons that top-level module's parameters to NaN — the
    end-to-end NaN-provenance drill (obs/dynamics.py must name exactly
    that module).  For a sharp verdict keep the trigger step a multiple
    of ``log_every``: detection then runs while the poison is still
    localized to the one module.
``checkpoint_truncate``
    The first checkpoint save at/after the trigger step is truncated on
    disk post-commit (the torn-write storage fault), so the next
    ``restore_latest`` must reject it and fall back to an older verified
    step.
``worker_kill``
    SIGKILLs a process-backed coordinator worker when one is attached
    (:meth:`ChaosInjector.attach_coordinator` — exercising the bounded
    respawn path), then raises :class:`WorkerKilledFault` out of the fit:
    sync SPMD training treats worker loss as fatal, and recovery is the
    supervisor's restore-and-restart.
``data_stall``
    Blocks the fit loop for ``stall_s`` seconds at the trigger step (long
    enough and the hang watchdog fires mid-stall), then raises
    :class:`DataStallFault` — the dead-input-pipeline failure.
``preemption``
    Calls ``PreemptionHandler.trigger()`` (attach via
    :meth:`attach_preemption`): the trainer's own consistent-save path
    runs and the fit exits preempted; the supervisor resumes it.
``resize``
    Requests an elastic resize to ``devices`` at the trigger step via the
    attached :class:`~.elastic.ElasticController`
    (:meth:`attach_elastic`) — the drain → mesh re-form → ZeRO rechunk →
    same-epoch resume path, shrink and grow alike.  The ``recovered`` row
    is written when the controller reports the window's outcome.  An
    optional ``"compose": "worker_kill"`` arms a crash MID-resize (raised
    from the entrypoint's resize_fn between the drain save and the mesh
    commit via :meth:`mid_resize_fault`): the supervisor must classify it,
    fall back to the pre-resize checkpoint, and resume at the old size —
    resize-interrupted-by-crash, end to end.

Network fault kinds (ISSUE 13 — injected at the :mod:`..net` layer, and
recovered by the TRANSPORT, not by a supervised restart; their
``recovered`` row is written when the first successful matching call
proves the fault was absorbed):

``net_delay``
    Arms a delay of ``delay_s`` (default 0.05) against the next
    ``calls`` (default 4) RPC attempts whose endpoint contains
    ``endpoint`` (default: every endpoint).
``net_drop``
    Drops (fails with ``ConnectionError`` before any byte is sent) the
    next ``calls`` (default 2) matching RPC attempts; retries absorb
    them.
``net_sever``
    Forcibly severs every live registered persistent stream matching
    ``endpoint`` (data-service fetch streams); the streaming client
    reconnects to the same worker and resumes exactly-once.
``dispatcher_kill``
    Kills the attached data-service dispatcher mid-epoch (simulated
    crash: no clean shutdown), drives its circuit breaker through a full
    open cycle with failing probes, restarts it from the durable journal
    (:meth:`attach_data_service` supplies the restart hook), and probes
    until the transport recovers — the breaker's open → half_open →
    closed transitions land in ``breaker_transitions_total``.

Every injection and recovery is appended to ``<logdir>/faults.jsonl``
(one JSON object per line, ``t`` non-decreasing)::

    {"t": ..., "id": 0, "step": 35, "kind": "worker_kill",
     "phase": "injected"}
    {"t": ..., "id": 0, "step": 35, "kind": "worker_kill",
     "phase": "recovered", "resumed_step": 20, "attempt": 1}

``id`` is the injection index (strictly increasing across injected rows;
injected steps non-decreasing), and a healthy run pairs every injected
``id`` with a recovered row — ``tools/check_metrics_schema.py`` enforces
exactly that.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any

from .. import obs
from ..train.trainer import Callback

logger = logging.getLogger("distributedtensorflow_tpu")

__all__ = [
    "FAULT_KINDS",
    "NET_FAULT_KINDS",
    "ChaosInjector",
    "DataStallFault",
    "FaultPlan",
    "InjectedFault",
    "WorkerKilledFault",
]

#: Fault kinds recovered by the resilient transport itself (no restart):
#: the supervisor's mark_recovered must NOT claim these — their recovery
#: row is written when the net layer observes a post-fault success.
NET_FAULT_KINDS = (
    "net_delay",
    "net_drop",
    "net_sever",
    "dispatcher_kill",
)

#: The known fault kinds (duplicated stdlib-side in
#: tools/check_metrics_schema.py FAULT_KINDS — keep in sync).
FAULT_KINDS = (
    "nan_loss",
    "checkpoint_truncate",
    "worker_kill",
    "data_stall",
    "preemption",
    "resize",
) + NET_FAULT_KINDS

_M_INJECTED = obs.counter(
    "faults_injected_total", "chaos faults injected, by kind"
)
_M_RECOVERED = obs.counter(
    "faults_recovered_total", "chaos faults recovered from, by kind"
)


class InjectedFault(RuntimeError):
    """Base of chaos-raised failures; ``kind`` drives the supervisor's
    classification and ``fault_id`` pairs the recovery row."""

    kind = "injected"

    def __init__(self, message: str, *, fault_id: int, step: int):
        super().__init__(message)
        self.fault_id = fault_id
        self.step = step


class WorkerKilledFault(InjectedFault):
    kind = "worker_kill"


class DataStallFault(InjectedFault):
    kind = "data_stall"


@dataclasses.dataclass
class _Fault:
    id: int
    step: int
    kind: str
    params: dict
    injected: bool = False
    recovered: bool = False
    #: The step the injection actually fired at (>= the plan's trigger
    #: step); recovery rows echo it so a pair shares one step.
    injected_step: int | None = None
    #: checkpoint_truncate: the step of the save actually truncated.
    detail_step: int | None = None


class FaultPlan:
    """A validated, step-sorted list of fault triggers."""

    def __init__(self, faults: list[dict]):
        parsed: list[_Fault] = []
        for i, f in enumerate(faults):
            if not isinstance(f, dict):
                raise ValueError(f"fault[{i}]: not an object: {f!r}")
            kind = f.get("kind")
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"fault[{i}]: unknown kind {kind!r} "
                    f"(known: {', '.join(FAULT_KINDS)})"
                )
            step = f.get("step")
            if not isinstance(step, int) or isinstance(step, bool) \
                    or step < 0:
                raise ValueError(
                    f"fault[{i}]: step {step!r} is not a non-negative int"
                )
            params = {k: v for k, v in f.items() if k not in ("kind", "step")}
            parsed.append(_Fault(id=i, step=int(step), kind=kind,
                                 params=params))
        parsed.sort(key=lambda f: (f.step, f.id))
        # Re-id in trigger order so injected ids are strictly increasing.
        for i, f in enumerate(parsed):
            f.id = i
        self.faults = parsed

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict):
            doc = doc.get("faults")
        if not isinstance(doc, list):
            raise ValueError(
                f"{path}: expected a JSON list of faults or an object "
                "with a 'faults' list"
            )
        return cls(doc)

    def __len__(self) -> int:
        return len(self.faults)


class ChaosInjector(Callback):
    """Executes a :class:`FaultPlan` against a run and logs
    ``faults.jsonl``.

    Wiring (train.py does all of this under ``--fault-plan``):

    - append the injector itself to the Trainer's callbacks (it is a
      :class:`~..train.trainer.Callback`; ``on_step_end`` fires the
      worker-kill / data-stall / preemption triggers);
    - ``train_step = injector.wrap_train_step(train_step)`` for NaN
      injection (adds one host sync of ``state.step`` per dispatch —
      chaos mode is a test harness, not a production path);
    - ``checkpointer = injector.wrap_checkpointer(checkpointer)`` for
      post-commit truncation;
    - :meth:`attach_preemption` / :meth:`attach_coordinator` for the
      signal-shaped faults.

    The Supervisor closes the loop: :meth:`mark_recovered` after each
    successful restart writes the paired ``recovered`` rows.
    """

    def __init__(self, plan: FaultPlan, logdir: str | None = None):
        self.plan = plan
        self._lock = threading.Lock()
        self._path = (
            os.path.join(logdir, "faults.jsonl") if logdir else None
        )
        self._preemption = None
        self._coordinator = None
        self._dispatcher = None
        self._dispatcher_restart = None
        self._elastic = None
        self._mid_resize_kill: _Fault | None = None
        if self._path:
            os.makedirs(logdir, exist_ok=True)
            # Truncate a prior run's log: the plan restarts from scratch.
            open(self._path, "w").close()

    # -- wiring --------------------------------------------------------------

    def attach_preemption(self, handler) -> None:
        """The PreemptionHandler ``preemption`` faults trigger()."""
        self._preemption = handler

    def attach_coordinator(self, coord) -> None:
        """A process-backed Coordinator whose worker 0 ``worker_kill``
        faults SIGKILL (optional — without one the fault only raises)."""
        self._coordinator = coord

    def attach_elastic(self, controller) -> None:
        """The :class:`~.elastic.ElasticController` that ``resize``
        faults drive; its completion callback writes the paired
        ``recovered`` row whatever the window's outcome."""
        self._elastic = controller

    def attach_data_service(self, dispatcher, restart_fn) -> None:
        """The data-service control plane ``dispatcher_kill`` faults
        target: ``dispatcher`` is the live ``DispatchServer``,
        ``restart_fn()`` builds its replacement on the SAME port from
        the durable journal.  Also gives :meth:`on_fit_end` a live
        endpoint to probe when pairing net-fault recovery rows."""
        self._dispatcher = dispatcher
        self._dispatcher_restart = restart_fn

    def wrap_train_step(self, train_step):
        """NaN-loss injection: at the trigger step the returned metrics
        report a NaN loss.  Without a ``module`` param the state itself
        is untouched (the detection and recovery machinery downstream is
        what is under test); with ``{"module": "h1"}`` the named
        top-level module's parameter subtree is ALSO poisoned to NaN —
        the provenance-accuracy drill: exactly one module is bad at the
        detection boundary, and obs.dynamics must name it."""
        import jax  # noqa: PLC0415
        import jax.numpy as jnp  # noqa: PLC0415

        def _poison_module(state, module: str):
            params = state.params
            if not hasattr(params, "get") or params.get(module) is None:
                logger.error(
                    "chaos: nan_loss module %r not a top-level param "
                    "module (have: %s) — loss-only injection",
                    module, sorted(params) if hasattr(params, "keys")
                    else type(params).__name__)
                return state, False
            poisoned = jax.tree.map(
                lambda x: jnp.full_like(x, jnp.nan), params[module]
            )
            if isinstance(params, dict):
                new_params = {**params, module: poisoned}
            else:  # flax FrozenDict
                new_params = params.copy({module: poisoned})
            return state.replace(params=new_params), True

        def chaotic_step(state, batch, rng):
            step_before = int(state.step)
            new_state, metrics = train_step(state, batch, rng)
            fault = self._pending("nan_loss", step_before + 1)
            if fault is not None and "loss" in metrics:
                module = fault.params.get("module")
                extra = {}
                if module:
                    new_state, ok = _poison_module(new_state, str(module))
                    if ok:
                        extra["module"] = str(module)
                self._inject(fault, at_step=step_before + 1, **extra)
                metrics = dict(
                    metrics,
                    loss=jnp.full_like(
                        jnp.asarray(metrics["loss"]), jnp.nan
                    ),
                )
            return new_state, metrics

        return chaotic_step

    def wrap_checkpointer(self, manager):
        """Proxy whose ``save`` truncates the on-disk checkpoint when a
        ``checkpoint_truncate`` fault has come due."""
        return _ChaosCheckpointer(manager, self)

    # -- Callback hooks (worker_kill / data_stall / preemption) --------------

    #: Kinds fired from on_step_end (nan_loss fires inside the wrapped
    #: train step, checkpoint_truncate inside the wrapped save).
    _STEP_KINDS = ("preemption", "data_stall", "worker_kill", "resize") \
        + NET_FAULT_KINDS

    def on_step_end(self, trainer, step: int, state, metrics) -> None:
        # Due faults fire in id (= plan trigger) order, so injected rows
        # keep their strictly-increasing-id invariant even when a
        # transport fault and a process fault share a trigger step; a
        # raising kind naturally ends the batch (the rest re-trigger
        # after the supervised restart re-reaches this step).
        while True:
            with self._lock:
                due = [
                    f for f in self.plan.faults
                    if not f.injected and f.step <= step
                    and f.kind in self._STEP_KINDS
                ]
            if not due:
                return
            self._fire_one(min(due, key=lambda f: f.id), step)

    def _fire_one(self, fault: _Fault, step: int) -> None:
        kind = fault.kind
        if kind in NET_FAULT_KINDS:
            self._fire_net_fault(fault, step)
            return
        if kind == "preemption":
            self._inject(fault, at_step=step)
            if self._preemption is not None:
                self._preemption.trigger()
            else:
                logger.error(
                    "chaos: preemption fault at step %d but no handler "
                    "attached; fault is a no-op", step,
                )
            return
        if kind == "data_stall":
            stall_s = float(fault.params.get("stall_s", 0.0))
            self._inject(fault, at_step=step, stall_s=stall_s)
            if stall_s > 0:
                # The fit loop stops making progress right here — a long
                # enough stall fires the hang watchdog mid-sleep.
                time.sleep(stall_s)
            raise DataStallFault(
                f"chaos: input pipeline stalled at step {step}",
                fault_id=fault.id, step=step,
            )
        if kind == "resize":
            devices = int(fault.params.get("devices", 0))
            compose = fault.params.get("compose")
            extra = {"devices": devices}
            if compose:
                extra["compose"] = str(compose)
            self._inject(fault, at_step=step, **extra)
            if self._elastic is None:
                logger.error(
                    "chaos: resize fault at step %d but no elastic "
                    "controller attached; fault cannot recover", step,
                )
                return
            if compose == "worker_kill":
                with self._lock:
                    self._mid_resize_kill = fault
            ok, msg = self._elastic.request_resize(
                devices, source="chaos",
                on_done=lambda outcome, info, f=fault:
                    self._resize_done(f, outcome, info),
            )
            if not ok:
                logger.error("chaos: resize fault #%d rejected: %s",
                             fault.id, msg)
                with self._lock:
                    if self._mid_resize_kill is fault:
                        self._mid_resize_kill = None
                self._resize_done(fault, "rejected", {})
            return
        if kind == "worker_kill":
            self._inject(fault, at_step=step)
            if self._coordinator is not None:
                try:
                    self._coordinator.kill_worker_process(
                        int(fault.params.get("worker", 0))
                    )
                except Exception:
                    logger.exception("chaos: coordinator worker kill failed")
            raise WorkerKilledFault(
                f"chaos: worker killed at step {step}",
                fault_id=fault.id, step=step,
            )

    # -- elastic resize faults (controller-recovered) ------------------------

    def mid_resize_fault(self) -> None:
        """Hook for the entrypoint's resize_fn, called between the drain
        save and the mesh commit: raises the armed composed
        ``worker_kill`` (a ``resize`` fault with ``"compose":
        "worker_kill"``), simulating a crash landing mid-resize.  A no-op
        when nothing is armed."""
        with self._lock:
            fault, self._mid_resize_kill = self._mid_resize_kill, None
        if fault is None:
            return
        step = (fault.injected_step if fault.injected_step is not None
                else fault.step)
        raise WorkerKilledFault(
            f"chaos: worker killed mid-resize (fault #{fault.id})",
            fault_id=fault.id, step=step,
        )

    def _resize_done(self, fault: _Fault, outcome: str, info: dict) -> None:
        """Completion callback from the ElasticController: write the
        paired ``recovered`` row (idempotent).  Every outcome pairs the
        row — a ``failed`` resize recovered by falling back to the
        pre-resize checkpoint, a ``rejected`` one by never starting."""
        with self._lock:
            if not fault.injected or fault.recovered:
                return
            fault.recovered = True
            _M_RECOVERED.inc(kind=fault.kind)
            step = (fault.injected_step if fault.injected_step is not None
                    else fault.step)
            resumed = info.get("resumed_step")
            self._write({
                "t": time.time(), "id": fault.id, "step": step,
                "kind": fault.kind, "phase": "recovered",
                "resumed_step": int(resumed if resumed is not None
                                    else step),
                "attempt": int(info.get("attempt", 0)),
                "outcome": str(outcome),
            })
        logger.warning("chaos: resize fault #%d finished (%s)",
                       fault.id, outcome)

    # -- network faults (transport-recovered; ISSUE 13) ----------------------

    def _fire_net_fault(self, fault: _Fault, step: int) -> None:
        """Arm/execute one due ``net_*`` / ``dispatcher_kill`` fault.
        None of these raise: the resilient transport is what is under
        test, and the run must proceed THROUGH the fault."""
        from ..net import rpc as netrpc  # noqa: PLC0415 (jax-free)

        if fault.kind == "net_delay":
            self._inject(fault, at_step=step)
            netrpc.arm_fault(
                "net_delay",
                calls=int(fault.params.get("calls", 4)),
                delay_s=float(fault.params.get("delay_s", 0.05)),
                match=str(fault.params.get("endpoint", "")),
                on_recovered=lambda f=fault: self._recover_net(f),
            )
        elif fault.kind == "net_drop":
            self._inject(fault, at_step=step)
            netrpc.arm_fault(
                "net_drop",
                calls=int(fault.params.get("calls", 2)),
                match=str(fault.params.get("endpoint", "")),
                on_recovered=lambda f=fault: self._recover_net(f),
            )
        elif fault.kind == "net_sever":
            n = netrpc.sever_streams(str(fault.params.get("endpoint", "")))
            self._inject(fault, at_step=step, severed=n)
            # Recovery = the next successful matching attempt (the
            # severed streams' reconnect, or — when nothing was live to
            # sever — any healthy call proving the plane still works).
            netrpc.watch_recovery(
                str(fault.params.get("endpoint", "")),
                on_recovered=lambda f=fault: self._recover_net(f),
            )
        elif fault.kind == "dispatcher_kill":
            self._inject(fault, at_step=step)
            self._dispatcher_kill(fault, step)

    def _recover_net(self, fault: _Fault, *, resumed_step: int | None = None,
                     attempt: int = 0) -> None:
        """Write the paired ``recovered`` row for a transport-absorbed
        fault (idempotent; callable from any thread — the net layer fires
        it from whichever thread observed the post-fault success)."""
        with self._lock:
            if not fault.injected or fault.recovered:
                return
            fault.recovered = True
            _M_RECOVERED.inc(kind=fault.kind)
            step = (fault.injected_step if fault.injected_step is not None
                    else fault.step)
            self._write({
                "t": time.time(), "id": fault.id, "step": step,
                "kind": fault.kind, "phase": "recovered",
                "resumed_step": int(resumed_step if resumed_step is not None
                                    else step),
                "attempt": int(attempt),
            })
        logger.warning("chaos: transport recovered from %s (fault #%d)",
                       fault.kind, fault.id)

    def _dispatcher_kill(self, fault: _Fault, step: int) -> None:
        """Kill → breaker-open → journal-replay restart → probe-closed.

        Runs synchronously on the trainer thread (chaos is a test
        harness): the data streams to the WORKERS keep flowing the whole
        time — only the control plane dies — and the dispatcher endpoint
        breaker is driven through a full open → half_open → closed cycle
        so the recovery is visible in ``breaker_transitions_total``."""
        from ..net import breaker as netbreaker  # noqa: PLC0415
        from ..net import rpc as netrpc  # noqa: PLC0415

        if self._dispatcher is None or self._dispatcher_restart is None:
            logger.error(
                "chaos: dispatcher_kill at step %d but no data service "
                "attached; fault cannot recover", step,
            )
            return
        target = self._dispatcher.target()
        ep = f"dispatcher:{target}"
        probe = netrpc.RetryPolicy(deadline_s=0.5, max_attempts=1,
                                   connect_timeout_s=0.3)
        self._dispatcher.kill()
        logger.warning("chaos: dispatcher %s killed at step %d", target,
                       step)
        # Fail fast probes until the endpoint breaker trips open.
        br = netbreaker.breaker_for(ep)
        deadline = time.monotonic() + 15.0
        while br.state != "open" and time.monotonic() < deadline:
            try:
                netrpc.call(target, {"kind": "get_workers"}, endpoint=ep,
                            policy=probe)
            except OSError:
                pass
        # Restart on the same port from the journal (the port may sit in
        # TIME_WAIT for a beat — retry the bind briefly).
        restarted = None
        deadline = time.monotonic() + 15.0
        while restarted is None and time.monotonic() < deadline:
            try:
                restarted = self._dispatcher_restart()
            except OSError:
                time.sleep(0.2)
        if restarted is None:
            logger.error("chaos: dispatcher restart failed; fault #%d "
                         "stays unrecovered", fault.id)
            return
        self._dispatcher = restarted
        # Probe until the breaker's half-open probe closes it again.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                resp, _ = netrpc.call(target, {"kind": "get_workers"},
                                      endpoint=ep, policy=probe)
            except OSError:
                time.sleep(0.2)
                continue
            if resp.get("ok"):
                self._recover_net(fault, resumed_step=step)
                logger.warning(
                    "chaos: dispatcher %s restarted from journal "
                    "(breaker %s)", target, br.state,
                )
                return
        logger.error("chaos: restarted dispatcher %s never answered; "
                     "fault #%d stays unrecovered", target, fault.id)

    def on_fit_end(self, trainer, state) -> None:
        """Pair any armed-but-unproven net faults before the run ends: a
        successful probe against the attached dispatcher counts as the
        post-fault success for every matching fault still watching."""
        from ..net import rpc as netrpc  # noqa: PLC0415

        pending = [
            f for f in self.plan.faults
            if f.kind in NET_FAULT_KINDS and f.injected and not f.recovered
        ]
        if not pending or self._dispatcher is None:
            return
        target = self._dispatcher.target()
        probe = netrpc.RetryPolicy(deadline_s=1.0, max_attempts=1)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                netrpc.call(target, {"kind": "get_workers"},
                            endpoint=f"dispatcher:{target}", policy=probe)
            except OSError:
                time.sleep(0.2)
                continue
            if all(f.recovered for f in pending):
                return
            time.sleep(0.1)

    # -- recovery bookkeeping (called by the Supervisor) ---------------------

    def mark_recovered(self, *, resumed_step: int, attempt: int,
                       rejected_steps: list[int] | None = None) -> int:
        """Write ``recovered`` rows for every injected-but-unrecovered
        fault this restart resolves: the restart-shaped kinds always; a
        ``checkpoint_truncate`` only once a fallback restore actually
        rejected its truncated step (``rejected_steps``).  Returns the
        number of rows written."""
        rejected = set(rejected_steps or ())
        n = 0
        with self._lock:
            for f in self.plan.faults:
                if not f.injected or f.recovered:
                    continue
                if f.kind in NET_FAULT_KINDS:
                    # Transport-recovered, not restart-recovered: their
                    # row is written when the net layer proves a
                    # post-fault success (_recover_net).
                    continue
                if f.kind == "resize":
                    # Controller-recovered: the ElasticController's
                    # completion callback writes the row (_resize_done)
                    # whatever the window's outcome.
                    continue
                if f.kind == "checkpoint_truncate":
                    if f.detail_step not in rejected:
                        continue
                f.recovered = True
                n += 1
                _M_RECOVERED.inc(kind=f.kind)
                self._write({
                    "t": time.time(), "id": f.id,
                    "step": (f.injected_step if f.injected_step is not None
                             else f.step),
                    "kind": f.kind, "phase": "recovered",
                    "resumed_step": int(resumed_step),
                    "attempt": int(attempt),
                })
        return n

    def unrecovered(self) -> list[dict]:
        """Injected faults still awaiting a recovery row (a non-empty
        answer at run end = the run did not actually self-heal)."""
        with self._lock:
            return [
                {"id": f.id, "step": f.step, "kind": f.kind}
                for f in self.plan.faults
                if f.injected and not f.recovered
            ]

    # -- internals -----------------------------------------------------------

    def _pending(self, kind: str, step: int) -> _Fault | None:
        """The first uninjected fault of ``kind`` whose trigger step has
        come (<= step), or None."""
        with self._lock:
            for f in self.plan.faults:
                if f.kind == kind and not f.injected and f.step <= step:
                    return f
        return None

    def _inject(self, fault: _Fault, *, at_step: int, **fields) -> None:
        with self._lock:
            if fault.injected:
                return
            fault.injected = True
            fault.injected_step = int(at_step)
            _M_INJECTED.inc(kind=fault.kind)
            row = {
                "t": time.time(), "id": fault.id, "step": int(at_step),
                "kind": fault.kind, "phase": "injected",
            }
            row.update(fields)
            self._write(row)
        logger.warning(
            "chaos: injected %s (fault #%d) at step %d",
            fault.kind, fault.id, at_step,
        )
        obs.record_event(
            "fault", step=int(at_step), fault=fault.kind, phase="injected",
            id=fault.id,
        )

    def _note_truncated(self, fault: _Fault, save_step: int) -> None:
        with self._lock:
            fault.detail_step = int(save_step)

    def _write(self, row: dict[str, Any]) -> None:
        """Append one faults.jsonl line (caller holds the lock); a write
        failure must never escalate an injected fault into a crash."""
        if self._path is None:
            return
        try:
            with open(self._path, "a") as f:
                f.write(json.dumps(row) + "\n")
        except OSError:
            logger.exception("chaos: faults.jsonl append failed")


class _ChaosCheckpointer:
    """CheckpointManager proxy that truncates the bytes of a just-saved
    step when a ``checkpoint_truncate`` fault is due — the torn-write
    storage fault, injected at the exact layer it happens in production."""

    def __init__(self, manager, injector: ChaosInjector):
        self._manager = manager
        self._injector = injector

    def save(self, step: int, state, **kwargs) -> bool:
        saved = self._manager.save(step, state, **kwargs)
        if saved:
            fault = self._injector._pending("checkpoint_truncate", step)
            if fault is not None:
                self._manager.wait()  # the bytes must be on disk to tear
                self._injector._inject(fault, at_step=step,
                                       truncated_step=step)
                self._injector._note_truncated(fault, step)
                self._truncate(step)
        return saved

    def _truncate(self, step: int) -> None:
        directory = getattr(self._manager, "_directory", None)
        if directory is None:
            return
        step_dir = os.path.join(directory, str(int(step)))
        biggest, size = None, -1
        for root, _dirs, files in os.walk(step_dir):
            for name in files:
                p = os.path.join(root, name)
                s = os.path.getsize(p)
                if s > size:
                    biggest, size = p, s
        if biggest is None:
            logger.error("chaos: no files to truncate under %s", step_dir)
            return
        with open(biggest, "r+b") as f:
            f.truncate(max(size // 2, 1))
        logger.warning(
            "chaos: truncated %s (%d -> %d bytes)", biggest, size,
            max(size // 2, 1),
        )

    def __getattr__(self, name):
        return getattr(self._manager, name)
