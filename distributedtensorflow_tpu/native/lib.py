"""Locate, build (if needed), and load ``libdtf_native.so``.

Build-on-demand keeps the no-network constraint honest: the .so is compiled
from the in-repo C++ sources with the system g++, never downloaded.  The
build is cheap (<5s) and happens at most once per checkout; concurrent
builders (e.g. pytest-xdist, multi-process tests) are serialized with an
exclusive lock file.
"""

from __future__ import annotations

import ctypes
import fcntl
import logging
import os
import subprocess
from pathlib import Path

logger = logging.getLogger("distributedtensorflow_tpu")

_PACKAGE_DIR = Path(__file__).resolve().parent
_NATIVE_DIR = _PACKAGE_DIR.parent.parent / "native"
_SOURCES = ("src/crc32c.cc", "src/recordio.cc", "src/ringcomm.cc")

_lib: ctypes.CDLL | None = None


def _lib_path() -> Path:
    override = os.environ.get("DTF_NATIVE_LIB")
    if override:
        return Path(override)
    return _NATIVE_DIR / "libdtf_native.so"


def _needs_build(so: Path) -> bool:
    if not so.exists():
        return True
    so_mtime = so.stat().st_mtime
    for rel in _SOURCES + ("src/crc32c.h",):
        src = _NATIVE_DIR / rel
        if src.exists() and src.stat().st_mtime > so_mtime:
            return True
    return False


def build_native_library(force: bool = False) -> Path:
    """Compile the shared library from ``native/src`` if missing or stale."""
    so = _lib_path()
    if not force and not _needs_build(so):
        return so
    if not (_NATIVE_DIR / "src").is_dir():
        raise FileNotFoundError(
            f"native sources not found under {_NATIVE_DIR}; set DTF_NATIVE_LIB "
            "to a prebuilt libdtf_native.so"
        )
    so.parent.mkdir(parents=True, exist_ok=True)
    lock_path = so.with_suffix(".lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if not force and not _needs_build(so):
                return so  # another process built it while we waited
            # Link to a temp path and atomically rename: a concurrent
            # process's lock-free _needs_build() fast path must never see
            # (and dlopen) a half-written .so.
            tmp = so.with_suffix(f".tmp.{os.getpid()}.so")
            cmd = [
                os.environ.get("CXX", "g++"),
                "-O3", "-std=c++17", "-fPIC", "-Wall", "-Wextra", "-pthread",
                *[str(_NATIVE_DIR / s) for s in _SOURCES],
                "-shared", "-pthread", "-o", str(tmp),
            ]
            logger.info("building native library: %s", " ".join(cmd))
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, so)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed:\n{e.stderr}"
            ) from e
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return so


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    u8p = c.POINTER(c.c_uint8)
    # record IO
    lib.dtf_writer_open.restype = c.c_void_p
    lib.dtf_writer_open.argtypes = [c.c_char_p]
    lib.dtf_writer_write.restype = c.c_int
    lib.dtf_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.dtf_writer_flush.restype = c.c_int
    lib.dtf_writer_flush.argtypes = [c.c_void_p]
    lib.dtf_writer_close.restype = None
    lib.dtf_writer_close.argtypes = [c.c_void_p]
    lib.dtf_reader_open.restype = c.c_void_p
    lib.dtf_reader_open.argtypes = [
        c.POINTER(c.c_char_p), c.c_int, c.c_int, c.c_int, c.c_uint64, c.c_int,
    ]
    lib.dtf_reader_next.restype = c.c_int64
    lib.dtf_reader_next.argtypes = [c.c_void_p, c.POINTER(u8p)]
    lib.dtf_reader_next_packed.restype = c.c_int64
    lib.dtf_reader_next_packed.argtypes = [
        c.c_void_p, c.POINTER(u8p), c.POINTER(c.POINTER(c.c_uint64)),
        c.c_int64, c.c_int64,
    ]
    lib.dtf_reader_batch_records.restype = c.c_int64
    lib.dtf_reader_batch_records.argtypes = []
    lib.dtf_reader_batch_bytes.restype = c.c_int64
    lib.dtf_reader_batch_bytes.argtypes = []
    lib.dtf_reader_close.restype = None
    lib.dtf_reader_close.argtypes = [c.c_void_p]
    lib.dtf_free.restype = None
    lib.dtf_free.argtypes = [c.c_void_p]
    lib.dtf_crc32c.restype = c.c_uint32
    lib.dtf_crc32c.argtypes = [c.c_char_p, c.c_uint64]
    lib.dtf_crc32c_masked.restype = c.c_uint32
    lib.dtf_crc32c_masked.argtypes = [c.c_char_p, c.c_uint64]
    # ring collectives
    lib.dtf_comm_create.restype = c.c_void_p
    lib.dtf_comm_create.argtypes = [
        c.c_int, c.c_int, c.POINTER(c.c_char_p), c.c_int,
    ]
    lib.dtf_comm_rank.restype = c.c_int
    lib.dtf_comm_rank.argtypes = [c.c_void_p]
    lib.dtf_comm_size.restype = c.c_int
    lib.dtf_comm_size.argtypes = [c.c_void_p]
    lib.dtf_comm_destroy.restype = None
    lib.dtf_comm_destroy.argtypes = [c.c_void_p]
    lib.dtf_comm_allreduce.restype = c.c_int
    lib.dtf_comm_allreduce.argtypes = [
        c.c_void_p, c.c_void_p, c.c_uint64, c.c_int, c.c_int,
    ]
    lib.dtf_comm_allgather.restype = c.c_int
    lib.dtf_comm_allgather.argtypes = [
        c.c_void_p, c.c_void_p, c.c_uint64, c.c_void_p,
    ]
    lib.dtf_comm_broadcast.restype = c.c_int
    lib.dtf_comm_broadcast.argtypes = [
        c.c_void_p, c.c_void_p, c.c_uint64, c.c_int,
    ]
    lib.dtf_comm_barrier.restype = c.c_int
    lib.dtf_comm_barrier.argtypes = [c.c_void_p]
    return lib


def load_native_library() -> ctypes.CDLL:
    """Load (building first if necessary) the native library, once."""
    global _lib
    if _lib is None:
        _lib = _declare(ctypes.CDLL(str(build_native_library())))
    return _lib


def native_available() -> bool:
    """True when the native library can be loaded on this machine."""
    try:
        load_native_library()
        return True
    except Exception as e:  # no g++, unwritable checkout, ...
        logger.warning("native library unavailable: %s", e)
        return False
