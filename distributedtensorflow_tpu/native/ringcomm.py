"""Host collectives: numpy-facing surface over the C++ TCP ring.

Covers the host-side role of the reference's compiled collectives
(SURVEY.md §2.2 RingReducer/RingGatherer + gRPC rendezvous): CPU-resident
tensors moving between processes — metric aggregation, input-pipeline
coordination, CPU fallback in the multi-process test harness.  Device-side
(TPU) collectives never come here; they are XLA-compiled onto ICI via
``parallel.collectives``.
"""

from __future__ import annotations

import ctypes
from collections.abc import Sequence

import numpy as np

from .lib import load_native_library

_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
}
_OPS = {"sum": 0, "max": 1, "min": 2, "prod": 3}


class HostCollectives:
    """A ring communicator over TCP among ``world`` host processes.

    Every process passes the same ``peers`` list ("host:port" per rank);
    rank ``r`` listens on ``peers[r]`` and connects to ``peers[(r+1)%world]``.
    Construction is a rendezvous: it returns once both neighbor links are up.
    """

    def __init__(
        self,
        rank: int,
        peers: Sequence[str],
        *,
        timeout_ms: int = 300_000,
    ):
        self._lib = load_native_library()
        world = len(peers)
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} out of range for {world} peers")
        arr = (ctypes.c_char_p * world)(*[p.encode() for p in peers])
        self._h = self._lib.dtf_comm_create(rank, world, arr, timeout_ms)
        if not self._h:
            raise ConnectionError(
                f"ring setup failed (rank {rank}, peers {list(peers)})"
            )
        self.rank = rank
        self.world = world

    def _check(self, status: int, what: str) -> None:
        if status != 0:
            raise ConnectionError(f"{what} failed (rank {self.rank})")

    def all_reduce(self, x: np.ndarray, op: str = "sum") -> np.ndarray:
        """Ring all-reduce; returns a new array with the reduced values."""
        dt = _DTYPES.get(np.dtype(x.dtype))
        if dt is None:
            raise TypeError(f"unsupported dtype {x.dtype}")
        out = np.ascontiguousarray(x).copy()
        self._check(
            self._lib.dtf_comm_allreduce(
                self._h,
                out.ctypes.data_as(ctypes.c_void_p),
                out.size,
                dt,
                _OPS[op],
            ),
            "all_reduce",
        )
        return out

    def all_gather(self, x: np.ndarray) -> np.ndarray:
        """Gather equal-shaped arrays from all ranks; output has a leading
        ``world`` axis ordered by rank."""
        x = np.ascontiguousarray(x)
        out = np.empty((self.world,) + x.shape, dtype=x.dtype)
        self._check(
            self._lib.dtf_comm_allgather(
                self._h,
                x.ctypes.data_as(ctypes.c_void_p),
                x.nbytes,
                out.ctypes.data_as(ctypes.c_void_p),
            ),
            "all_gather",
        )
        return out

    def all_gather_bytes(self, blob: bytes, max_len: int = 1 << 20) -> list[bytes]:
        """Gather variable-length byte strings (padded under the hood)."""
        if len(blob) > max_len:
            raise ValueError(f"blob of {len(blob)} bytes exceeds max_len={max_len}")
        buf = np.zeros(max_len + 8, dtype=np.uint8)
        buf[:8] = np.frombuffer(
            len(blob).to_bytes(8, "little"), dtype=np.uint8
        )
        buf[8 : 8 + len(blob)] = np.frombuffer(blob, dtype=np.uint8)
        gathered = self.all_gather(buf)
        out = []
        for r in range(self.world):
            n = int.from_bytes(gathered[r, :8].tobytes(), "little")
            out.append(gathered[r, 8 : 8 + n].tobytes())
        return out

    def broadcast(self, x: np.ndarray, root: int = 0) -> np.ndarray:
        """Broadcast ``x`` from ``root``; non-root input values are ignored
        (shape/dtype must match)."""
        out = np.ascontiguousarray(x).copy()
        self._check(
            self._lib.dtf_comm_broadcast(
                self._h, out.ctypes.data_as(ctypes.c_void_p), out.nbytes, root
            ),
            "broadcast",
        )
        return out

    def barrier(self) -> None:
        self._check(self._lib.dtf_comm_barrier(self._h), "barrier")

    def close(self) -> None:
        if self._h is not None:
            self._lib.dtf_comm_destroy(self._h)
            self._h = None

    def __enter__(self) -> "HostCollectives":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
