"""ctypes bindings for the native runtime library (C++).

The compiled layer of the framework, mirroring the reference stack's native
components (SURVEY.md §2.2/§2.3): record IO (the tf.data C++ record-reader
role, ``hdr/data/``) and host-side ring collectives (the RingReducer /
rendezvous-transport role, ``hdr/common_runtime/ring_reducer.h:32``,
``hdr/distributed_runtime/rpc/rpc_rendezvous_mgr.h:45``).  Device-side
collectives are XLA-compiled onto ICI and never touch this module; this is
the *host* path — data loading, CPU tensors, cross-process control.

pybind11 is not available in this image, so the library exposes a flat C ABI
consumed here with ctypes.  The shared object is built on demand from
``native/src`` with g++ (no network, no pip).
"""

from .lib import build_native_library, load_native_library, native_available
from .recordio import RecordReader, RecordWriter, crc32c, masked_crc32c
from .ringcomm import HostCollectives

__all__ = [
    "HostCollectives",
    "RecordReader",
    "RecordWriter",
    "build_native_library",
    "crc32c",
    "load_native_library",
    "masked_crc32c",
    "native_available",
]
