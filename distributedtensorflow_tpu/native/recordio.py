"""Python surface over the native record IO (C++ threaded reader/writer).

The record format is the classic length+CRC32C framing, so files written
here are interchangeable with TFRecord files (the reference's on-disk input
format — SURVEY.md §2.3 tf.data).  The reader's multi-file threading and
shuffle buffer run entirely in C++; Python only sees finished ``bytes``.
"""

from __future__ import annotations

import ctypes
import weakref
from collections.abc import Iterator, Sequence

from .lib import load_native_library


def crc32c(data: bytes) -> int:
    """Raw CRC32-C of ``data`` (native: SSE4.2 crc32 instruction when the
    CPU has it, slice-by-8 table fallback)."""
    return load_native_library().dtf_crc32c(data, len(data))


def masked_crc32c(data: bytes) -> int:
    """Masked CRC32-C as stored in the record framing."""
    return load_native_library().dtf_crc32c_masked(data, len(data))


class RecordWriter:
    """Writes length+CRC framed records to one file."""

    def __init__(self, path: str):
        self._lib = load_native_library()
        self._h = self._lib.dtf_writer_open(str(path).encode())
        if not self._h:
            raise OSError(f"cannot open {path!r} for writing")
        # GC safety net: a dropped writer still flushes and closes its FILE*.
        self._finalizer = weakref.finalize(
            self, self._lib.dtf_writer_close, self._h
        )

    def write(self, record: bytes) -> None:
        if self._h is None:
            raise ValueError("writer is closed")
        if self._lib.dtf_writer_write(self._h, record, len(record)) != 0:
            raise OSError("record write failed")

    def flush(self) -> None:
        if self._h is not None:
            self._lib.dtf_writer_flush(self._h)

    def close(self) -> None:
        if self._h is not None:
            self._finalizer.detach()
            self._lib.dtf_writer_close(self._h)
            self._h = None

    def __enter__(self) -> "RecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RecordCorruptionError(IOError):
    """A record failed CRC verification or had broken framing."""


def available_cpus() -> int:
    """CPUs THIS PROCESS may use — affinity/cgroup-aware where the OS
    exposes it (``sched_getaffinity``), else ``cpu_count``.  The single
    definition behind reader-thread defaults and the bench's
    ``hw_concurrency`` field, so the two cannot disagree."""
    import os

    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


class RecordReader:
    """Iterates records from many files with C++ reader threads.

    Args:
      paths: record files; assigned round-robin to reader threads, so with
        ``num_threads > 1`` records from different files interleave (the
        tf.data ``interleave`` behavior).
      num_threads: C++ reader threads (clamped to ``len(paths)``).
      shuffle_buffer: >1 enables streaming shuffle over a buffer of this many
        records (the ``shuffle(buffer_size)`` contract).
      seed: shuffle RNG seed — same seed + same single-threaded file order
        reproduces the same stream.
      verify_crc: verify per-record CRCs (cheap: hardware CRC32C where
        available, slice-by-8 fallback; single pass).

    Note: records cross the FFI boundary in batches (up to 4x the
    producer bounds — ~1024 records / ~8 MB), so a
    :class:`RecordCorruptionError` surfaces at BATCH granularity — up to
    one batch later than the corrupt record itself, after earlier records
    in that window were already yielded.  The trade buys the ~5x
    batched-FFI throughput win over per-record ctypes calls.

    Shards must be IMMUTABLE while a reader is open: regular files are
    mmap-ed for speed, and a concurrent truncation faults (SIGBUS) the
    process instead of surfacing a read error.  (Appending a new shard
    file alongside is fine; rewriting one being read is not — the same
    contract as the reference's record readers.)
    """

    def __init__(
        self,
        paths: Sequence[str],
        *,
        num_threads: int = 1,
        shuffle_buffer: int = 0,
        seed: int = 0,
        verify_crc: bool = True,
    ):
        if not paths:
            raise ValueError("RecordReader needs at least one file")
        self._lib = load_native_library()
        arr = (ctypes.c_char_p * len(paths))(
            *[str(p).encode() for p in paths]
        )
        self._h = self._lib.dtf_reader_open(
            arr, len(paths), num_threads, shuffle_buffer, seed, int(verify_crc)
        )
        if not self._h:
            raise OSError(f"cannot open record files {list(paths)!r}")
        # Batched pulls: one FFI round-trip per ~batch of records (the
        # per-record ctypes path was ~5x slower than plain Python file
        # reads — bench_input.py).  _pending holds sliced-out records.
        self._pending: list[bytes] = []
        self._pending_ix = 0
        # GC safety net: a dropped, unexhausted reader still joins its C++
        # worker threads and frees queued records.
        self._finalizer = weakref.finalize(
            self, self._lib.dtf_reader_close, self._h
        )

    def __iter__(self) -> Iterator[bytes]:
        return self

    def __next__(self) -> bytes:
        if self._pending_ix < len(self._pending):
            rec = self._pending[self._pending_ix]
            self._pending_ix += 1
            return rec
        if self._h is None:
            raise StopIteration
        buf = ctypes.POINTER(ctypes.c_uint8)()
        lens = ctypes.POINTER(ctypes.c_uint64)()
        # Limits >= the producer's packing bounds (read from the C ABI so
        # the two can't drift apart) keep the handoff zero-copy in C.
        n = self._lib.dtf_reader_next_packed(
            self._h, ctypes.byref(buf), ctypes.byref(lens),
            4 * self._lib.dtf_reader_batch_records(),
            4 * self._lib.dtf_reader_batch_bytes(),
        )
        if n == 0:
            self.close()
            raise StopIteration
        if n == -2:
            self.close()
            raise RecordCorruptionError(
                "corrupt record encountered (bad CRC or framing)"
            )
        try:
            sizes = lens[:n]
            # One bulk copy, then C-speed bytes slicing.  (Measured faster
            # than per-record ctypes.string_at despite the extra copy: a
            # ctypes call costs ~1us while a ~KB memcpy costs ~50ns; the
            # <=8MB blob is transient.)
            blob = ctypes.string_at(buf, sum(sizes))
        finally:
            self._lib.dtf_free(buf)
            self._lib.dtf_free(lens)
        out, off = [], 0
        for size in sizes:
            out.append(blob[off:off + size])
            off += size
        self._pending = out
        self._pending_ix = 1
        return out[0]

    def read_batches(self):
        """Yield ``(payload, lengths)`` batch VIEWS — the zero-copy path.

        ``payload`` is a uint8 numpy view over the C batch buffer
        (concatenated record bytes); ``lengths`` a uint64 numpy view of
        per-record lengths (offsets = ``np.cumsum(lengths)``).  One FFI
        round-trip per producer batch (~256 records) and **no per-record
        Python object creation** — on a single core the per-record
        ``bytes`` construction is what pins the iterator API at
        pure-Python speed (bench_input.py), so fixed-shape/tokenized
        consumers that can slice numpy views should use this.

        Both views alias memory that is FREED when the generator advances
        or closes — copy (``payload.copy()``) anything that must outlive
        the iteration step.  Do not interleave with the per-record
        iterator on the same reader: both consume the same stream.
        """
        import numpy as np

        lib = self._lib
        while self._h is not None:
            buf = ctypes.POINTER(ctypes.c_uint8)()
            lens = ctypes.POINTER(ctypes.c_uint64)()
            # exact producer bounds -> every pull is a whole-batch handoff
            n = lib.dtf_reader_next_packed(
                self._h, ctypes.byref(buf), ctypes.byref(lens),
                lib.dtf_reader_batch_records(),
                lib.dtf_reader_batch_bytes(),
            )
            if n == 0:
                self.close()
                return
            if n == -2:
                self.close()
                raise RecordCorruptionError(
                    "corrupt record encountered (bad CRC or framing)"
                )
            try:
                lengths = np.ctypeslib.as_array(lens, shape=(n,))
                payload = np.ctypeslib.as_array(
                    buf, shape=(int(lengths.sum()),)
                )
                yield payload, lengths
            finally:
                lib.dtf_free(buf)
                lib.dtf_free(lens)

    def close(self) -> None:
        if self._h is not None:
            self._finalizer.detach()
            self._lib.dtf_reader_close(self._h)
            self._h = None

    def __enter__(self) -> "RecordReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
