"""Multi-host cluster bootstrap.

Replaces the reference stack's L4 layer (SURVEY.md §1): ``tf.train.Server`` +
``ClusterSpec`` + ``TFConfigClusterResolver`` + the C++ coordination service.
JAX bundles the same TSL-lineage coordination service; it is configured through
``jax.distributed.initialize`` — heartbeats, barriers, and error propagation
come with it, replacing the reference's gRPC server boot and Python
``_check_health`` thread (SURVEY.md §3.2, §5.3).

A ``TF_CONFIG``-compatible resolver shim is kept so `run_distributed.sh`-style
launchers (one process per task, cluster described by a JSON env var —
SURVEY.md §5.6) keep working unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os

import jax

logger = logging.getLogger(__name__)

_initialized = False


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Resolved cluster topology — the ``ClusterSpec`` equivalent.

    ``auto=True`` means "let ``jax.distributed.initialize`` discover the
    cluster itself" (Cloud TPU pod metadata path) — the other fields are then
    ignored.
    """

    coordinator_address: str | None = None  # "host:port" of process 0
    num_processes: int = 1
    process_id: int = 0
    auto: bool = False

    @property
    def is_multiprocess(self) -> bool:
        return self.auto or self.num_processes > 1


def parse_tf_config(tf_config_json: str) -> ClusterConfig:
    """Parse a ``TF_CONFIG`` JSON blob into a :class:`ClusterConfig`.

    Accepts the reference's format (SURVEY.md §5.6):
    ``{"cluster": {"worker": ["h0:p", "h1:p"], ...}, "task": {"type": "worker",
    "index": 0}}``.  The first worker is the coordinator (the reference's
    "collective leader" / chief convention).  ``chief`` and ``ps`` job names
    from the legacy ParameterServerStrategy launcher are folded into one flat
    process list, ordered chief → worker → ps, matching the reference's
    task-enumeration order.
    """
    cfg = json.loads(tf_config_json)
    cluster = cfg.get("cluster", {})
    task = cfg.get("task", {})
    ordered_jobs = [j for j in ("chief", "worker", "ps") if j in cluster]
    ordered_jobs += sorted(j for j in cluster if j not in ("chief", "worker", "ps", "evaluator"))
    flat: list[str] = []
    offsets: dict[str, int] = {}
    for job in ordered_jobs:
        offsets[job] = len(flat)
        flat.extend(cluster[job])
    if not flat:
        return ClusterConfig()
    task_type = task.get("type", "worker")
    task_index = int(task.get("index", 0))
    if task_type == "evaluator":
        # Evaluator is outside the training cluster in TF semantics; treat as
        # a standalone single process.
        return ClusterConfig()
    process_id = offsets.get(task_type, 0) + task_index
    return ClusterConfig(
        coordinator_address=flat[0],
        num_processes=len(flat),
        process_id=process_id,
    )


def resolve_cluster(env: dict[str, str] | None = None) -> ClusterConfig:
    """Resolve cluster topology from the environment.

    Priority order (mirrors the reference's resolver chain, SURVEY.md §2.3):

    1. JAX-native env vars (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES``
       / ``JAX_PROCESS_ID``) — the modern launcher path.
    2. ``TF_CONFIG`` — the reference's launcher contract.
    3. Cloud TPU metadata — handled inside ``jax.distributed.initialize``
       itself (args all None); we return an "auto" marker config.
    """
    env = dict(os.environ if env is None else env)
    if "JAX_COORDINATOR_ADDRESS" in env:
        return ClusterConfig(
            coordinator_address=env["JAX_COORDINATOR_ADDRESS"],
            num_processes=int(env.get("JAX_NUM_PROCESSES", "1")),
            process_id=int(env.get("JAX_PROCESS_ID", "0")),
        )
    if env.get("TF_CONFIG"):
        return parse_tf_config(env["TF_CONFIG"])
    # Cloud TPU pod: the libtpu/metadata env describes a multi-host slice;
    # jax.distributed.initialize(None, ...) self-discovers the cluster there.
    hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h]) > 1:
        return ClusterConfig(auto=True)
    return ClusterConfig()


def initialize(cluster: ClusterConfig | None = None) -> ClusterConfig:
    """Bring up the distributed runtime (idempotent).

    Single-process resolutions skip ``jax.distributed.initialize`` entirely so
    local runs never wait on a coordination service — the reference's
    "cluster_spec empty → local" branch (SURVEY.md §3.2).
    """
    global _initialized
    cluster = cluster or resolve_cluster()
    if _initialized:
        return cluster
    if cluster.auto:
        # Cloud TPU metadata self-discovery (SURVEY.md §5.6 build equivalent)
        jax.distributed.initialize()
        logger.info(
            "distributed runtime up (auto): process %d/%d",
            jax.process_index(), jax.process_count(),
        )
    elif cluster.is_multiprocess:
        jax.distributed.initialize(
            coordinator_address=cluster.coordinator_address,
            num_processes=cluster.num_processes,
            process_id=cluster.process_id,
        )
        logger.info(
            "distributed runtime up: process %d/%d, coordinator %s",
            cluster.process_id,
            cluster.num_processes,
            cluster.coordinator_address,
        )
    _initialized = True
    return cluster


def shutdown() -> None:
    global _initialized
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_chief() -> bool:
    """Chief-only convention for checkpoint/metric writing (SURVEY.md §5.5)."""
    return jax.process_index() == 0
