"""Multi-host cluster bootstrap.

Replaces the reference stack's L4 layer (SURVEY.md §1): ``tf.train.Server`` +
``ClusterSpec`` + ``TFConfigClusterResolver`` + the C++ coordination service.
JAX bundles the same TSL-lineage coordination service; it is configured through
``jax.distributed.initialize`` — heartbeats, barriers, and error propagation
come with it, replacing the reference's gRPC server boot and Python
``_check_health`` thread (SURVEY.md §3.2, §5.3).

A ``TF_CONFIG``-compatible resolver shim is kept so `run_distributed.sh`-style
launchers (one process per task, cluster described by a JSON env var —
SURVEY.md §5.6) keep working unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re

import jax

logger = logging.getLogger(__name__)

_initialized = False


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Resolved cluster topology — the ``ClusterSpec`` equivalent.

    ``auto=True`` means "let ``jax.distributed.initialize`` discover the
    cluster itself" (Cloud TPU pod metadata path) — the other fields are then
    ignored.
    """

    coordinator_address: str | None = None  # "host:port" of process 0
    num_processes: int = 1
    process_id: int = 0
    auto: bool = False

    @property
    def is_multiprocess(self) -> bool:
        return self.auto or self.num_processes > 1


def parse_tf_config(tf_config_json: str) -> ClusterConfig:
    """Parse a ``TF_CONFIG`` JSON blob into a :class:`ClusterConfig`.

    Accepts the reference's format (SURVEY.md §5.6):
    ``{"cluster": {"worker": ["h0:p", "h1:p"], ...}, "task": {"type": "worker",
    "index": 0}}``.  The first worker is the coordinator (the reference's
    "collective leader" / chief convention).  ``chief`` and ``ps`` job names
    from the legacy ParameterServerStrategy launcher are folded into one flat
    process list, ordered chief → worker → ps, matching the reference's
    task-enumeration order.
    """
    cfg = json.loads(tf_config_json)
    cluster = cfg.get("cluster", {})
    task = cfg.get("task", {})
    ordered_jobs = [j for j in ("chief", "worker", "ps") if j in cluster]
    ordered_jobs += sorted(j for j in cluster if j not in ("chief", "worker", "ps", "evaluator"))
    flat: list[str] = []
    offsets: dict[str, int] = {}
    for job in ordered_jobs:
        offsets[job] = len(flat)
        flat.extend(cluster[job])
    if not flat:
        return ClusterConfig()
    task_type = task.get("type", "worker")
    task_index = int(task.get("index", 0))
    if task_type == "evaluator":
        # Evaluator is outside the training cluster in TF semantics; treat as
        # a standalone single process.
        return ClusterConfig()
    process_id = offsets.get(task_type, 0) + task_index
    return ClusterConfig(
        coordinator_address=flat[0],
        num_processes=len(flat),
        process_id=process_id,
    )


def expand_nodelist(nodelist: str) -> list[str]:
    """Expand a Slurm compact nodelist: ``"n[001-003,07],login0"``.

    The subset of Slurm hostlist syntax the reference's
    ``SlurmClusterResolver`` handles (SURVEY.md §2.3): comma-separated
    entries, each optionally with one ``[...]`` range group of
    zero-padded ranges and scalars.
    """
    out: list[str] = []
    # Split on commas not inside brackets.
    entries, depth, cur = [], 0, []
    for ch in nodelist:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            entries.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        entries.append("".join(cur))
    def expand_entry(entry: str) -> list[str]:
        m = re.fullmatch(r"([^\[]*)\[([^\]]+)\](.*)", entry)
        if not m:
            return [entry]
        prefix, body, suffix = m.groups()
        expanded: list[str] = []
        for part in body.split(","):
            if "-" in part:
                lo, hi = part.split("-")
                width = len(lo)
                expanded.extend(
                    f"{prefix}{i:0{width}d}{tail}"
                    for i in range(int(lo), int(hi) + 1)
                    # multi-group names (Cray "c0c[0-1]n[0-3]"): recurse on
                    # the suffix so every group expands, not just the first
                    for tail in expand_entry(suffix)
                )
            else:
                expanded.extend(
                    f"{prefix}{part}{tail}" for tail in expand_entry(suffix)
                )
        return expanded

    for entry in entries:
        out.extend(expand_entry(entry))
    return out


def _coordinator_addr(
    env: dict[str, str], default_host: str, coordinator_port: int
) -> str:
    """Explicit ``JAX_COORDINATOR_ADDRESS`` wins; else ``default_host`` with
    ``JAX_COORDINATOR_PORT`` (or the resolver's default port)."""
    addr = env.get("JAX_COORDINATOR_ADDRESS")
    if addr:
        return addr
    port = int(env.get("JAX_COORDINATOR_PORT", str(coordinator_port)))
    return f"{default_host}:{port}"


def resolve_slurm(
    env: dict[str, str], *, coordinator_port: int = 12321
) -> ClusterConfig | None:
    """Resolve from Slurm step env (reference ``slurm_cluster_resolver.py``).

    One JAX process per Slurm task; the coordinator is the first node of the
    step nodelist.  Honors ``SLURM_STEP_NODELIST`` (srun step) with
    ``SLURM_JOB_NODELIST`` (sbatch allocation) as fallback.
    """
    if "SLURM_PROCID" not in env:
        return None
    ntasks = int(env.get("SLURM_STEP_NUM_TASKS", env.get("SLURM_NTASKS", "1")))
    if ntasks <= 1:
        # Not a multi-task launch: fall through (a Slurm-wrapped TPU pod job
        # with one task per host still needs the TPU metadata auto path).
        return None
    addr = env.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        nodelist = env.get(
            "SLURM_STEP_NODELIST", env.get("SLURM_JOB_NODELIST", "")
        )
        nodes = expand_nodelist(nodelist) if nodelist else []
        if not nodes:
            return None
        addr = _coordinator_addr(env, nodes[0], coordinator_port)
    return ClusterConfig(
        coordinator_address=addr,
        num_processes=ntasks,
        process_id=int(env["SLURM_PROCID"]),
    )


def resolve_mpi(env: dict[str, str]) -> ClusterConfig | None:
    """Resolve from an OpenMPI/mpirun launch (``OMPI_COMM_WORLD_*``).

    MPI gives rank/size but no coordinator address — that must come from
    ``JAX_COORDINATOR_ADDRESS`` (typically ``$(hostname -i)`` of rank 0,
    exported by the launch script, the ``run_distributed.sh`` pattern).
    """
    if "OMPI_COMM_WORLD_RANK" not in env:
        return None
    size = int(env.get("OMPI_COMM_WORLD_SIZE", "1"))
    if size <= 1:
        return None  # single rank: fall through (see resolve_slurm)
    addr = env.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return None
    return ClusterConfig(
        coordinator_address=addr,
        num_processes=size,
        process_id=int(env["OMPI_COMM_WORLD_RANK"]),
    )


def resolve_kubernetes(
    env: dict[str, str], *, coordinator_port: int = 12321
) -> ClusterConfig | None:
    """Resolve from Kubernetes pod env (reference ``kubernetes_cluster_resolver.py``).

    The reference resolver asks the K8s API for pod IPs by label selector;
    a JAX job instead uses the stable identities K8s already injects into
    every pod — no API credentials or network round-trip needed:

    - **Indexed Job** (``completionMode: Indexed``): rank comes from the
      ``JOB_COMPLETION_INDEX`` env var K8s sets on each pod.
    - **StatefulSet**: rank is the trailing ``-<n>`` ordinal of the pod
      hostname (``myjob-3``).

    World size comes from ``K8S_NUM_PODS`` (set it from
    ``spec.completions``/``spec.replicas`` via the downward API or the
    manifest).  The coordinator is pod 0 reached through the headless
    service: ``<base>-0.<K8S_HEADLESS_SERVICE>:port``, overridable with
    ``JAX_COORDINATOR_ADDRESS``.  Only activates inside a cluster
    (``KUBERNETES_SERVICE_HOST`` is set in every pod).
    """
    if "KUBERNETES_SERVICE_HOST" not in env:
        return None
    num = int(env.get("K8S_NUM_PODS", "0"))
    if num <= 1:
        return None
    hostname = env.get("HOSTNAME", "")
    m = re.fullmatch(r"(.*)-(\d+)", hostname)
    if "JOB_COMPLETION_INDEX" in env:  # Indexed Job
        rank = int(env["JOB_COMPLETION_INDEX"])
    elif m:  # StatefulSet ordinal
        rank = int(m.group(2))
    else:
        return None
    addr = env.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        svc = env.get("K8S_HEADLESS_SERVICE")
        if not svc or not m:
            # Without both a headless service and a `<base>-<n>` pod name
            # there is no pod-0 DNS name to construct — fall through rather
            # than hand jax.distributed a garbage address.
            return None
        addr = _coordinator_addr(env, f"{m.group(1)}-0.{svc}", coordinator_port)
    if not 0 <= rank < num:
        raise ValueError(
            f"K8s pod ordinal {rank} out of range for K8S_NUM_PODS={num}"
        )
    return ClusterConfig(
        coordinator_address=addr, num_processes=num, process_id=rank
    )


def resolve_gce(
    env: dict[str, str], *, coordinator_port: int = 12321
) -> ClusterConfig | None:
    """Resolve from a GCE instance group (reference ``gce_cluster_resolver.py``).

    The reference resolver lists the group's instances through the Compute
    API (credentials + network); here the launcher snapshots that list into
    ``GCE_INSTANCE_GROUP_HOSTS`` (comma-separated hostnames, group order —
    one ``gcloud compute instance-groups list-instances`` away), which keeps
    the resolver hermetic and testable.  Rank is ``GCE_TASK_INDEX`` if set,
    else this instance's position in the list (``GCE_INSTANCE_NAME`` /
    ``HOSTNAME``).  The first instance is the coordinator, the reference's
    task-0 convention.
    """
    hosts = [h for h in env.get("GCE_INSTANCE_GROUP_HOSTS", "").split(",") if h]
    if len(hosts) <= 1:
        return None
    if "GCE_TASK_INDEX" in env:
        rank = int(env["GCE_TASK_INDEX"])
    else:
        name = env.get("GCE_INSTANCE_NAME") or env.get("HOSTNAME", "")
        short = {h.split(".")[0]: i for i, h in enumerate(hosts)}
        rank = short.get(name.split(".")[0], -1)
        if rank < 0:
            return None
    if not 0 <= rank < len(hosts):
        raise ValueError(
            f"GCE_TASK_INDEX={rank} out of range for "
            f"{len(hosts)} instance-group hosts"
        )
    addr = _coordinator_addr(env, hosts[0], coordinator_port)
    return ClusterConfig(
        coordinator_address=addr, num_processes=len(hosts), process_id=rank
    )


def resolve_sagemaker(
    env: dict[str, str], *, coordinator_port: int = 12321
) -> ClusterConfig | None:
    """Resolve from SageMaker training env (reference ``sagemaker_cluster_resolver``
    semantics, SURVEY.md §2.3): ``SM_HOSTS`` is a JSON list of container
    hostnames, ``SM_CURRENT_HOST`` this container's.  The first host (sorted,
    SageMaker's algo-1 convention) is the coordinator.
    """
    raw = env.get("SM_HOSTS")
    if not raw:
        return None
    try:
        decoded = json.loads(raw)
    except json.JSONDecodeError:
        return None
    if not isinstance(decoded, list) or not all(
        isinstance(h, str) for h in decoded
    ):
        return None
    hosts = sorted(decoded)
    if len(hosts) <= 1:
        return None
    current = env.get("SM_CURRENT_HOST", "")
    if current not in hosts:
        return None
    return ClusterConfig(
        coordinator_address=_coordinator_addr(env, hosts[0], coordinator_port),
        num_processes=len(hosts),
        process_id=hosts.index(current),
    )


def resolve_cluster(env: dict[str, str] | None = None) -> ClusterConfig:
    """Resolve cluster topology from the environment.

    Priority order (mirrors the reference's resolver chain, SURVEY.md §2.3:
    TFConfig → Slurm/GCE/K8s resolvers):

    1. JAX-native env vars (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES``
       / ``JAX_PROCESS_ID``) — the modern launcher path.
    2. ``TF_CONFIG`` — the reference's launcher contract.
    3. Slurm step env (``SLURM_PROCID``/``SLURM_NTASKS``/nodelist).
    4. OpenMPI env (``OMPI_COMM_WORLD_RANK``/``SIZE``).
    5. Kubernetes pod identity (Indexed Job / StatefulSet ordinal).
    6. GCE instance-group snapshot (``GCE_INSTANCE_GROUP_HOSTS``).
    7. SageMaker training env (``SM_HOSTS``/``SM_CURRENT_HOST``).
    8. Cloud TPU metadata — handled inside ``jax.distributed.initialize``
       itself (args all None); we return an "auto" marker config.
    """
    env = dict(os.environ if env is None else env)
    saw_dangling_addr = False
    if env.get("JAX_COORDINATOR_ADDRESS"):
        # Rank precedence: JAX_PROCESS_ID, else a scheduler rank var (a
        # multi-task Slurm/MPI/K8s/GCE launch with the JAX vars exported),
        # else 0.  An explicit JAX_NUM_PROCESSES always selects this path —
        # even with stale scheduler vars in the env (e.g. an interactive
        # `srun --pty` shell has SLURM_PROCID=0), the user's explicit JAX
        # vars win.
        if "JAX_PROCESS_ID" in env or "JAX_NUM_PROCESSES" in env:
            rank = env.get("JAX_PROCESS_ID") or env.get(
                "SLURM_PROCID"
            ) or env.get("OMPI_COMM_WORLD_RANK") or env.get(
                "JOB_COMPLETION_INDEX"
            ) or env.get("GCE_TASK_INDEX") or "0"
            cfg = ClusterConfig(
                coordinator_address=env["JAX_COORDINATOR_ADDRESS"],
                num_processes=int(env.get("JAX_NUM_PROCESSES", "1")),
                process_id=int(rank),
            )
            if cfg.process_id >= cfg.num_processes:
                raise ValueError(
                    f"JAX_PROCESS_ID={cfg.process_id} out of range for "
                    f"JAX_NUM_PROCESSES={cfg.num_processes}; multi-process "
                    "launches must export JAX_NUM_PROCESSES on every rank"
                )
            if "JAX_PROCESS_ID" not in env and cfg.num_processes > 1:
                logger.warning(
                    "JAX_PROCESS_ID missing; derived process_id=%d (from "
                    "scheduler env, or 0). Every process in this job must "
                    "resolve a distinct rank or the job will not form.",
                    cfg.process_id,
                )
            return cfg
        saw_dangling_addr = True  # warn only if nothing downstream resolves
    if env.get("TF_CONFIG"):
        return parse_tf_config(env["TF_CONFIG"])
    for resolver in (resolve_slurm, resolve_mpi, resolve_kubernetes,
                     resolve_gce, resolve_sagemaker):
        cfg = resolver(env)
        if cfg is not None:
            return cfg
    # Cloud TPU pod: the libtpu/metadata env describes a multi-host slice;
    # jax.distributed.initialize(None, ...) self-discovers the cluster there.
    hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h]) > 1:
        return ClusterConfig(auto=True)
    if saw_dangling_addr:
        logger.warning(
            "JAX_COORDINATOR_ADDRESS set but JAX_PROCESS_ID/JAX_NUM_PROCESSES "
            "are absent and no scheduler env (TF_CONFIG/Slurm/MPI/K8s/GCE/"
            "SageMaker) "
            "resolved a cluster; treating as local"
        )
    return ClusterConfig()


def initialize(cluster: ClusterConfig | None = None) -> ClusterConfig:
    """Bring up the distributed runtime (idempotent).

    Single-process resolutions skip ``jax.distributed.initialize`` entirely so
    local runs never wait on a coordination service — the reference's
    "cluster_spec empty → local" branch (SURVEY.md §3.2).
    """
    global _initialized
    cluster = cluster or resolve_cluster()
    if _initialized:
        return cluster
    if cluster.auto:
        # Cloud TPU metadata self-discovery (SURVEY.md §5.6 build equivalent)
        jax.distributed.initialize()
        logger.info(
            "distributed runtime up (auto): process %d/%d",
            jax.process_index(), jax.process_count(),
        )
    elif cluster.is_multiprocess:
        jax.distributed.initialize(
            coordinator_address=cluster.coordinator_address,
            num_processes=cluster.num_processes,
            process_id=cluster.process_id,
        )
        logger.info(
            "distributed runtime up: process %d/%d, coordinator %s",
            cluster.process_id,
            cluster.num_processes,
            cluster.coordinator_address,
        )
    _initialized = True
    return cluster


def shutdown() -> None:
    global _initialized
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_chief() -> bool:
    """Chief-only convention for checkpoint/metric writing (SURVEY.md §5.5)."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cluster-wide barrier via the coordination service.

    The reference's coordination-service barrier (SURVEY.md §5.3,
    `coordination_service.h:67`); used e.g. to line all hosts up on the
    same checkpoint step.  No-op in single-process runs.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def broadcast_from_chief(pytree):
    """Ship a host-side pytree from process 0 to every process.

    The coordination-service KV-store pattern (chief decides, all agree):
    e.g. a dynamically chosen step count, eval split, or config dict.
    Arbitrary picklable leaves are supported (strings included — the raw
    ``broadcast_one_to_all`` is numeric-only): the chief's tree ships as a
    pickled uint8 payload.  Returns the chief's values on every process;
    no-op single-process.
    """
    if jax.process_count() <= 1:
        return pytree
    import pickle

    import numpy as np
    from jax.experimental import multihost_utils

    payload = pickle.dumps(pytree)
    n = int(
        multihost_utils.broadcast_one_to_all(np.int64(len(payload)))
    )
    buf = np.frombuffer(payload, dtype=np.uint8) if is_chief() else np.zeros(
        n, np.uint8
    )
    out = multihost_utils.broadcast_one_to_all(buf)
    return pickle.loads(np.asarray(out).tobytes())
