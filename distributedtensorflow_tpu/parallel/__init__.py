"""Parallelism core: mesh, bootstrap, collectives, sharding."""

from .mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_PIPE,
    AXIS_SEQ,
    BATCH_AXES,
    CANONICAL_AXES,
    MeshSpec,
    build_hybrid_mesh,
    build_mesh,
    data_axes,
    mirrored_mesh,
    multi_worker_mesh,
    one_device_mesh,
    slice_count,
    replica_count,
)
from .bootstrap import (  # noqa: F401
    ClusterConfig,
    barrier,
    broadcast_from_chief,
    expand_nodelist,
    initialize,
    is_chief,
    parse_tf_config,
    process_count,
    process_index,
    resolve_cluster,
    resolve_gce,
    resolve_kubernetes,
    resolve_mpi,
    resolve_sagemaker,
    resolve_slurm,
    shutdown,
)
from .coordinator import (  # noqa: F401
    ClosureAborted,
    Coordinator,
    PerWorker,
    RemoteValue,
    WorkerUnavailableError,
)
from .collectives import (  # noqa: F401
    Implementation,
    Options,
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    broadcast,
    pack_by_size,
    packed_all_reduce,
    permute,
    reduce_scatter,
    shift,
    tree_all_reduce,
)
from .ring_attention import (  # noqa: F401
    make_sequence_parallel_attention,
    ring_attention,
    sequence_parallel_attention_fn,
    ulysses_attention,
)
from .pipeline import (  # noqa: F401
    make_pipelined_fn,
    pipeline_apply,
    stack_stage_params,
)
from .moe import (  # noqa: F401
    expert_parallel_moe,
    init_expert_params,
    local_moe,
    make_moe_fn,
    make_moe_layer,
    top1_route,
    top2_route,
)
from .pipeline import gpipe_bubble_fraction  # noqa: F401
from .sharding import (  # noqa: F401
    FixedShardsPartitioner,
    LayoutMap,
    MaxSizePartitioner,
    MinSizePartitioner,
    Partitioner,
    auto_fsdp_spec,
    batch_spec,
    named_shardings,
    shard_batch,
    shard_tree,
    spec_for,
    specs_for_tree,
    tree_paths,
)
