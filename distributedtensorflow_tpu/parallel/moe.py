"""Expert parallelism: Switch-style MoE with all_to_all token dispatch.

New capability absent from the reference stack (SURVEY.md §2.4 EP row).
Experts are sharded over the ``expert`` mesh axis; tokens are routed top-1
with a capacity limit, dispatched to their expert's device via a pair of
``lax.all_to_all`` s (the MoE idiom on the ICI torus), processed by the
local experts, and combined back weighted by the router probability.

Everything is fixed-shape (dispatch/combine are one-hot einsum contractions,
dropped tokens pass through on the residual path), so the whole layer jits
into one SPMD program — no data-dependent shapes (XLA requirement).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

PyTree = Any


def _capacity_slots(pos: jax.Array, mask: jax.Array, capacity: int) -> jax.Array:
    """(T, E) 1-based queue positions + assignment mask → (T, E, C) one-hot
    dispatch, dropping assignments past ``capacity``."""
    keep = (pos <= capacity) & (mask > 0)
    slot = jnp.clip(pos - 1.0, 0, capacity - 1).astype(jnp.int32)
    return keep[..., None] * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)


def _masked_fracs(assign: jax.Array, probs: jax.Array,
                  token_mask: jax.Array | None):
    """(frac_tokens, frac_probs) per expert, averaged over VALID tokens
    only — with padding present, pads must not dilute the aux loss."""
    if token_mask is None:
        return jnp.mean(assign, axis=0), jnp.mean(probs, axis=0)
    w = token_mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w), 1.0)
    # assign is already zeroed at pad rows by the caller
    return jnp.sum(assign, axis=0) / denom, \
        jnp.sum(probs * w[:, None], axis=0) / denom


def top1_route(
    logits: jax.Array,  # (T, E) router logits
    capacity: int,
    token_mask: jax.Array | None = None,  # (T,) 1 = real token, 0 = pad
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing with capacity (Switch Transformer recipe).

    Returns ``(dispatch, combine, aux_loss)``:
    - dispatch: (T, E, C) one-hot — token t occupies slot c of expert e;
    - combine: (T, E, C) — dispatch weighted by the router probability;
    - aux_loss: scalar load-balancing loss (mean_frac_tokens · mean_probs · E).

    ``token_mask`` excludes padding: pad tokens consume NO capacity slot
    (they ride the residual path) and do not dilute the aux-loss means.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    expert_onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T, E)
    if token_mask is not None:
        expert_onehot = expert_onehot * token_mask.astype(jnp.float32)[:, None]
    # position of each token within its expert's queue
    pos_in_expert = jnp.cumsum(expert_onehot, axis=0) * expert_onehot  # 1-based
    dispatch = _capacity_slots(pos_in_expert, expert_onehot, capacity)
    gate = jnp.sum(probs * expert_onehot, axis=-1, keepdims=True)  # (T, 1)
    combine = dispatch * gate[..., None]
    # Switch aux loss: encourages uniform token/prob mass over experts
    frac_tokens, frac_probs = _masked_fracs(expert_onehot, probs, token_mask)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def top2_route(
    logits: jax.Array,  # (T, E) router logits
    capacity: int,
    token_mask: jax.Array | None = None,  # (T,) 1 = real token, 0 = pad
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-2 routing with capacity (GShard recipe).

    Each token goes to its two highest-probability experts; the two gates
    are renormalized to sum to 1.  Top-2 assignments queue AFTER all top-1
    assignments per expert (GShard's priority rule: second choices only
    take leftover capacity).  Same return contract (and the same
    pad-exclusion semantics for ``token_mask``) as :func:`top1_route`.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=jnp.float32)
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=jnp.float32)
    if token_mask is not None:
        w = token_mask.astype(jnp.float32)[:, None]
        mask1, mask2 = mask1 * w, mask2 * w

    g1 = jnp.sum(probs * mask1, axis=-1)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    # Queue positions: top-1 first, then top-2 behind ALL top-1 of that
    # expert (so capacity preempts second choices, never first choices).
    pos1 = jnp.cumsum(mask1, axis=0) * mask1  # 1-based
    count1 = jnp.sum(mask1, axis=0, keepdims=True)  # (1, E)
    pos2 = (jnp.cumsum(mask2, axis=0) + count1) * mask2

    d1 = _capacity_slots(pos1, mask1, capacity)  # (T, E, C)
    d2 = _capacity_slots(pos2, mask2, capacity)
    dispatch = d1 + d2
    combine = d1 * g1[:, None, None] + d2 * g2[:, None, None]
    # GShard aux loss over the FIRST choice (same form as Switch).
    frac_tokens, frac_probs = _masked_fracs(mask1, probs, token_mask)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def expert_choice_route(
    logits: jax.Array,  # (T, E) router logits
    capacity: int,
    token_mask: jax.Array | None = None,  # (T,) 1 = real token, 0 = pad
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Expert-choice routing (Zhou et al. 2022): each EXPERT selects its
    top-``capacity`` tokens by router probability — the inverted assignment.

    Load balance is perfect *by construction* (every expert processes
    exactly ``capacity`` tokens), so no auxiliary loss is needed:
    ``aux_loss`` is a constant 0.  Tokens may be chosen by zero experts
    (they ride the residual path) or by several (their outputs sum,
    weighted by the selecting experts' probabilities).  Same return
    contract as :func:`top1_route`.

    **Not causal**: whether token t is selected depends on every other
    token's router score — including future positions.  Use only in
    encoder / non-autoregressive settings (the EC paper's domain);
    ``models/gpt_moe.py`` rejects it for the causal LM —
    ``models/bert_moe.py`` is the encoder workload that uses it.

    **Pool semantics under expert parallelism**: inside ``make_moe_fn``'s
    shard_map region each token SHARD routes its own pool, so the top-k
    selection is per-shard (the EC paper's per-device setting), not a
    global top-k — EC outputs are therefore layout-DEPENDENT by design,
    unlike the per-token top1/top2 routers.
    """
    t, e = logits.shape
    capacity = min(capacity, t)  # an expert cannot pick more tokens than exist
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T, E)
    if token_mask is not None:
        # pads rank strictly below every real token (softmax probs are
        # strictly positive); any pad that still lands in a top-k (more
        # capacity than real tokens) is zeroed via the keep mask below.
        w = token_mask.astype(jnp.float32)[:, None]
        probs = probs * w - (1.0 - w)
    gates, token_idx = jax.lax.top_k(probs.T, capacity)  # (E, C) both
    keep = (gates > 0.0).astype(jnp.float32)  # (E, C)
    dispatch = jax.nn.one_hot(token_idx, t, dtype=jnp.float32) * keep[..., None]
    dispatch = dispatch.transpose(2, 0, 1)  # (T, E, C)
    combine = dispatch * jnp.maximum(gates, 0.0)[None, :, :]
    return dispatch, combine, jnp.zeros((), jnp.float32)


ROUTERS = {
    "top1": top1_route,
    "top2": top2_route,
    "expert_choice": expert_choice_route,
}
#: assignments per token, for capacity scaling (GShard: top-2 needs 2x slots;
#: expert-choice capacity is the EC paper's k = cf * T / E).
_ASSIGNMENTS = {"top1": 1, "top2": 2, "expert_choice": 1}


def expert_parallel_moe(
    tokens: jax.Array,  # (T, d) — this shard's tokens
    router_kernel: jax.Array,  # (d, E)
    expert_params: PyTree,  # leaves (E_local, ...) — local experts
    expert_fn: Callable[[PyTree, jax.Array], jax.Array],  # (params,(N,d))->(N,d)
    *,
    axis_name: str = mesh_lib.AXIS_EXPERT,
    capacity_factor: float = 1.25,
    router: str = "top1",
    token_mask: jax.Array | None = None,  # (T,) 1 = real token, 0 = pad
) -> tuple[jax.Array, jax.Array]:
    """MoE layer body (shard_map-internal). Returns (out, aux_loss).

    ``router``: "top1" (Switch), "top2" (GShard), or "expert_choice"
    (encoder-only — see :func:`expert_choice_route`).  ``expert_params``
    leading dim is the local expert count; global expert count
    E = E_local * axis_size.  Dropped-over-capacity tokens contribute 0
    here (caller keeps them on the residual path).
    """
    if router not in ROUTERS:
        raise ValueError(
            f"unknown router {router!r}; expected one of {list(ROUTERS)}"
        )
    n = lax.axis_size(axis_name)
    t, d = tokens.shape
    e = router_kernel.shape[-1]
    if e % n:
        raise ValueError(
            f"n_experts={e} not divisible by expert axis size {n}"
        )
    # Scale capacity by assignments-per-token: top-2 produces 2T assignments,
    # so capacity_factor=1.0 still means "room for every assignment" under a
    # uniform router (the GShard 2*cf*T/E convention).
    capacity = max(
        1, int(t * capacity_factor * _ASSIGNMENTS[router] / e)
    )

    logits = tokens.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    dispatch, combine, aux = ROUTERS[router](logits, capacity, token_mask)

    # (T, E, C) x (T, d) -> (E, C, d): expert-major send buffer
    send = jnp.einsum("tec,td->ecd", dispatch, tokens.astype(jnp.float32))
    # all_to_all: split experts across devices, gather every shard's slots
    # (E, C, d) -> (E_local, n*C, d)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
    out = jax.vmap(expert_fn)(expert_params, recv.astype(tokens.dtype))
    out = out.astype(jnp.float32)
    # route results back: (E_local, n*C, d) -> (E, C, d)
    back = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)
    combined = jnp.einsum("tec,ecd->td", combine, back)
    # aux loss is per-shard; mean over shards for a global scalar
    aux = lax.pmean(aux, axis_name)
    return combined.astype(tokens.dtype), aux


def init_expert_params(
    init_one: Callable[[jax.Array], PyTree],
    n_experts: int,
    rng: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = mesh_lib.AXIS_EXPERT,
) -> PyTree:
    """Stack per-expert params on a leading dim sharded over ``expert``."""
    rngs = jax.random.split(rng, n_experts)
    stacked = jax.vmap(init_one)(rngs)
    specs = jax.tree.map(lambda _: P(), jax.eval_shape(init_one, rng))
    sharding = jax.tree.map(
        lambda spec: NamedSharding(mesh, P(axis_name, *spec)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(stacked, sharding)


def make_moe_fn(
    mesh: Mesh,
    expert_fn: Callable[[PyTree, jax.Array], jax.Array],
    *,
    capacity_factor: float = 1.25,
    axis_name: str = mesh_lib.AXIS_EXPERT,
    router: str = "top1",
) -> Callable:
    """Un-jitted shard_map MoE region for use INSIDE a jitted model.

    ``fn(tokens (N, d), router_kernel, expert_params) -> (out, aux)`` —
    tokens are sharded over (batch axes + expert axis) so each expert shard
    routes its local tokens; expert params are expert-axis sharded.  The
    model-level embedding (``models/gpt_moe.py``) drops this into its MLP
    the same way ring attention drops into ``attn_fn``.
    """
    if router not in ROUTERS:  # eager: fail here, not inside the jit trace
        raise ValueError(
            f"unknown router {router!r}; expected one of {list(ROUTERS)}"
        )
    batch_axes = mesh_lib.data_axes(mesh)
    tok_axes = tuple(batch_axes) + (axis_name,)

    def run(tokens, router_kernel, expert_params, token_mask=None):
        if token_mask is None:  # keep the shard_map arity static
            token_mask = jnp.ones((tokens.shape[0],), jnp.float32)

        def body(toks, rk, ep, tmask):
            out, aux = expert_parallel_moe(
                toks, rk, ep, expert_fn=expert_fn, axis_name=axis_name,
                capacity_factor=capacity_factor, router=router,
                token_mask=tmask,
            )
            if batch_axes:  # make the aux loss a true global scalar
                aux = lax.pmean(aux, batch_axes)
            return out, aux

        param_specs = jax.tree.map(lambda _: P(axis_name), expert_params)
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(tok_axes), P(), param_specs, P(tok_axes)),
            out_specs=(P(tok_axes), P()),
            check_vma=False,
        )(tokens, router_kernel, expert_params, token_mask)

    return run


def make_moe_layer(
    mesh: Mesh,
    expert_fn: Callable[[PyTree, jax.Array], jax.Array],
    *,
    capacity_factor: float = 1.25,
    axis_name: str = mesh_lib.AXIS_EXPERT,
    router: str = "top1",
) -> Callable:
    """Jit-compiled global entry around :func:`make_moe_fn`."""
    return jax.jit(make_moe_fn(
        mesh, expert_fn, capacity_factor=capacity_factor,
        axis_name=axis_name, router=router,
    ))


def with_moe_layout(base) -> "LayoutMap":
    """``base`` layout rules + the expert-parallel sharding for MoEMLP
    params (expert stacks over the ``expert`` axis, router replicated) —
    THE single definition shared by every MoE model's layout."""
    from .sharding import LayoutMap  # noqa: PLC0415 (avoid cycle at import)

    rules = LayoutMap([
        (r".*moe_mlp/experts_in", P("expert", None, None)),
        (r".*moe_mlp/experts_out", P("expert", None, None)),
        (r".*moe_mlp/router", P()),
    ])
    for pat, spec in base._rules:
        rules._rules.append((pat, spec))
    return rules


def bind_expert_parallel_model(cfg, mesh: Mesh, model_ctor,
                               expert_fn) -> Any:
    """``model_ctor(cfg, moe_fn)`` with the all_to_all dispatch region
    bound when the mesh has a real ``expert`` axis; local (replicated)
    experts otherwise — the single bind used by every MoE model family."""
    if dict(mesh.shape).get(mesh_lib.AXIS_EXPERT, 1) > 1:
        moe_fn = make_moe_fn(
            mesh, expert_fn,
            capacity_factor=cfg.capacity_factor, router=cfg.router,
        )
        return model_ctor(cfg, moe_fn)
    return model_ctor(cfg, None)


def local_moe(
    tokens: jax.Array,  # (T, d)
    router_kernel: jax.Array,  # (d, E)
    expert_params: PyTree,  # leaves (E, ...) — ALL experts, replicated
    expert_fn: Callable[[PyTree, jax.Array], jax.Array],
    *,
    capacity_factor: float = 1.25,
    router: str = "top1",
    token_mask: jax.Array | None = None,  # (T,) 1 = real token, 0 = pad
) -> tuple[jax.Array, jax.Array]:
    """Single-device MoE (no collectives): every expert lives locally.

    Same routing/capacity math as :func:`expert_parallel_moe` with axis
    size 1 — the golden reference for EP tests and the fallback when the
    mesh has no real ``expert`` axis.
    """
    t, d = tokens.shape
    e = router_kernel.shape[-1]
    capacity = max(1, int(t * capacity_factor * _ASSIGNMENTS[router] / e))
    logits = tokens.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    dispatch, combine, aux = ROUTERS[router](logits, capacity, token_mask)
    send = jnp.einsum("tec,td->ecd", dispatch, tokens.astype(jnp.float32))
    out = jax.vmap(expert_fn)(expert_params, send.astype(tokens.dtype))
    combined = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))
    return combined.astype(tokens.dtype), aux
