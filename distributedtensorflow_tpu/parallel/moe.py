"""Expert parallelism: Switch-style MoE with all_to_all token dispatch.

New capability absent from the reference stack (SURVEY.md §2.4 EP row).
Experts are sharded over the ``expert`` mesh axis; tokens are routed top-1
with a capacity limit, dispatched to their expert's device via a pair of
``lax.all_to_all`` s (the MoE idiom on the ICI torus), processed by the
local experts, and combined back weighted by the router probability.

Everything is fixed-shape (dispatch/combine are one-hot einsum contractions,
dropped tokens pass through on the residual path), so the whole layer jits
into one SPMD program — no data-dependent shapes (XLA requirement).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

PyTree = Any


def top1_route(
    logits: jax.Array,  # (T, E) router logits
    capacity: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing with capacity (Switch Transformer recipe).

    Returns ``(dispatch, combine, aux_loss)``:
    - dispatch: (T, E, C) one-hot — token t occupies slot c of expert e;
    - combine: (T, E, C) — dispatch weighted by the router probability;
    - aux_loss: scalar load-balancing loss (mean_frac_tokens · mean_probs · E).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    expert_onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T, E)
    # position of each token within its expert's queue
    pos_in_expert = jnp.cumsum(expert_onehot, axis=0) * expert_onehot  # 1-based
    keep = (pos_in_expert <= capacity) & (expert_onehot > 0)
    slot = (pos_in_expert - 1.0).astype(jnp.int32)  # 0-based, valid where keep
    slot_onehot = jax.nn.one_hot(jnp.clip(slot, 0, capacity - 1), capacity,
                                 dtype=jnp.float32)
    dispatch = keep[..., None] * slot_onehot  # (T, E, C)
    gate = jnp.sum(probs * expert_onehot, axis=-1, keepdims=True)  # (T, 1)
    combine = dispatch * gate[..., None]
    # Switch aux loss: encourages uniform token/prob mass over experts
    frac_tokens = jnp.mean(expert_onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def expert_parallel_moe(
    tokens: jax.Array,  # (T, d) — this shard's tokens
    router_kernel: jax.Array,  # (d, E)
    expert_params: PyTree,  # leaves (E_local, ...) — local experts
    expert_fn: Callable[[PyTree, jax.Array], jax.Array],  # (params,(N,d))->(N,d)
    *,
    axis_name: str = mesh_lib.AXIS_EXPERT,
    capacity_factor: float = 1.25,
) -> tuple[jax.Array, jax.Array]:
    """Switch MoE layer body (shard_map-internal). Returns (out, aux_loss).

    ``expert_params`` leading dim is the local expert count; global expert
    count E = E_local * axis_size.  Dropped-over-capacity tokens contribute 0
    here (caller keeps them on the residual path).
    """
    n = lax.axis_size(axis_name)
    t, d = tokens.shape
    e = router_kernel.shape[-1]
    if e % n:
        raise ValueError(
            f"n_experts={e} not divisible by expert axis size {n}"
        )
    capacity = max(1, int(t * capacity_factor / e))

    logits = tokens.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    dispatch, combine, aux = top1_route(logits, capacity)

    # (T, E, C) x (T, d) -> (E, C, d): expert-major send buffer
    send = jnp.einsum("tec,td->ecd", dispatch, tokens.astype(jnp.float32))
    # all_to_all: split experts across devices, gather every shard's slots
    # (E, C, d) -> (E_local, n*C, d)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
    out = jax.vmap(expert_fn)(expert_params, recv.astype(tokens.dtype))
    out = out.astype(jnp.float32)
    # route results back: (E_local, n*C, d) -> (E, C, d)
    back = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)
    combined = jnp.einsum("tec,ecd->td", combine, back)
    # aux loss is per-shard; mean over shards for a global scalar
    aux = lax.pmean(aux, axis_name)
    return combined.astype(tokens.dtype), aux


def init_expert_params(
    init_one: Callable[[jax.Array], PyTree],
    n_experts: int,
    rng: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = mesh_lib.AXIS_EXPERT,
) -> PyTree:
    """Stack per-expert params on a leading dim sharded over ``expert``."""
    rngs = jax.random.split(rng, n_experts)
    stacked = jax.vmap(init_one)(rngs)
    specs = jax.tree.map(lambda _: P(), jax.eval_shape(init_one, rng))
    sharding = jax.tree.map(
        lambda spec: NamedSharding(mesh, P(axis_name, *spec)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(stacked, sharding)


def make_moe_layer(
    mesh: Mesh,
    expert_fn: Callable[[PyTree, jax.Array], jax.Array],
    *,
    capacity_factor: float = 1.25,
    axis_name: str = mesh_lib.AXIS_EXPERT,
) -> Callable:
    """Global entry: ``fn(tokens (N, d), router_kernel, expert_params)``.

    Tokens are sharded over (batch axes + expert axis) so each expert shard
    routes its local tokens; expert params are expert-axis sharded.
    """
    batch_axes = mesh_lib.data_axes(mesh)
    tok_axes = tuple(batch_axes) + (axis_name,)

    def run(tokens, router_kernel, expert_params):
        def body(toks, rk, ep):
            out, aux = expert_parallel_moe(
                toks, rk, ep, expert_fn=expert_fn, axis_name=axis_name,
                capacity_factor=capacity_factor,
            )
            if batch_axes:  # make the aux loss a true global scalar
                aux = lax.pmean(aux, batch_axes)
            return out, aux

        param_specs = jax.tree.map(lambda _: P(axis_name), expert_params)
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(tok_axes), P(), param_specs),
            out_specs=(P(tok_axes), P()),
            check_vma=False,
        )(tokens, router_kernel, expert_params)

    return jax.jit(run)
