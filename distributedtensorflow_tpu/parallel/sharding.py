"""Sharded parameter state: partitioners, layout rules, pytree sharding.

Replaces the reference's distributed-values layer (SURVEY.md §2.1):
``PerReplica`` / ``MirroredVariable`` wrappers become plain ``jax.Array`` s
with a ``NamedSharding``; ``ShardedVariable`` + partitioners
(``sharded_variable.py:47-176``) become :class:`Partitioner` rules producing
``PartitionSpec`` s; the save/restore integration lives in
:mod:`distributedtensorflow_tpu.checkpoint`.

There is no runtime wrapper-object machinery: sharding is metadata attached to
arrays, and the XLA partitioner does variable placement — the design the
reference's experimental DTensor layer and Keras 3 ``keras.distribution``
point toward (SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import re
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

logger = logging.getLogger("distributedtensorflow_tpu")

PyTree = Any


# --- Partitioners (reference parity: tf.distribute.experimental.partitioners,
#     sharded_variable.py:47-176). They decide HOW MANY shards a variable
#     gets; here that becomes a PartitionSpec on a named mesh axis.


class Partitioner:
    """Decide the number of shards for a variable of a given shape/dtype.

    Reference semantics: partition along axis 0 only (``sharded_variable``
    splits embedding rows).  ``num_shards`` is then clamped to the mesh axis
    size and to the dimension size by :func:`spec_for`.
    """

    def num_shards(self, shape: Sequence[int], dtype: np.dtype) -> int:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedShardsPartitioner(Partitioner):
    """Always ``num_shards`` (reference ``FixedShardsPartitioner``)."""

    shards: int

    def num_shards(self, shape, dtype) -> int:
        return self.shards


@dataclasses.dataclass(frozen=True)
class MinSizePartitioner(Partitioner):
    """As many shards as possible keeping each shard >= min_shard_bytes.

    Reference ``MinSizePartitioner`` (``sharded_variable.py:115``).
    """

    min_shard_bytes: int = 256 << 10
    max_shards: int = 1 << 30

    def num_shards(self, shape, dtype) -> int:
        total = math.prod(shape) * np.dtype(dtype).itemsize
        return max(1, min(self.max_shards, total // max(1, self.min_shard_bytes)))


@dataclasses.dataclass(frozen=True)
class MaxSizePartitioner(Partitioner):
    """As few shards as possible keeping each shard <= max_shard_bytes.

    Reference ``MaxSizePartitioner`` (``sharded_variable.py:176``).
    """

    max_shard_bytes: int

    def num_shards(self, shape, dtype) -> int:
        total = math.prod(shape) * np.dtype(dtype).itemsize
        return max(1, -(-total // max(1, self.max_shard_bytes)))


def spec_for(
    partitioner: Partitioner,
    shape: Sequence[int],
    dtype: np.dtype,
    mesh: Mesh,
    axis: str = mesh_lib.AXIS_MODEL,
    *,
    dim: int = 0,
) -> P:
    """Turn a partitioner decision into a PartitionSpec on ``axis``.

    A NamedSharding can only split a dim over the *whole* mesh axis, so the
    partitioner's shard count is interpreted against that constraint: the
    variable is sharded ``axis_size``-ways iff the partitioner asks for at
    least that many shards (so per-shard size constraints like
    ``MinSizePartitioner.min_shard_bytes`` still hold) and ``dim`` divides
    evenly; otherwise it is replicated (the reference falls back to one
    shard too).
    """
    n = partitioner.num_shards(shape, np.dtype(dtype))
    axis_size = mesh.shape[axis]
    if n < axis_size or axis_size <= 1 or shape[dim] % axis_size != 0:
        if n >= axis_size > 1 and shape[dim] % axis_size != 0:
            # The partitioner *wanted* this variable sharded but the dim
            # doesn't divide the mesh axis — a large embedding silently
            # replicating would defeat the Wide&Deep sharded-embedding
            # path this exists for, so say it loudly (pad the vocab to a
            # multiple of the axis size to shard it).
            logger.warning(
                "spec_for: %s-byte variable shape=%s wants >=%d shards but "
                "dim %d (size %d) does not divide mesh axis %r (size %d); "
                "REPLICATING instead. Pad the dimension to a multiple of "
                "%d to shard it.",
                math.prod(shape) * np.dtype(dtype).itemsize, tuple(shape),
                n, dim, shape[dim], axis, axis_size, axis_size,
            )
        return P()
    spec = [None] * len(shape)
    spec[dim] = axis
    return P(*spec)


# --- Layout rules: path-regex → PartitionSpec (the Keras-3 LayoutMap /
#     GSPMD-rule pattern, SURVEY.md §2.3 "keras.distribution").


class LayoutMap:
    """Ordered mapping of path regexes to ``PartitionSpec``.

    Paths are '/'-joined pytree key paths (e.g. ``"encoder/layers_0/mlp/kernel"``).
    First matching rule wins (``re.search`` semantics); no match → replicated.
    """

    def __init__(self, rules: Sequence[tuple[str, P]] = ()):
        self._rules: list[tuple[re.Pattern[str], P]] = [
            (re.compile(pat), spec) for pat, spec in rules
        ]

    def add(self, pattern: str, spec: P) -> "LayoutMap":
        self._rules.append((re.compile(pattern), spec))
        return self

    def spec(self, path: str) -> P:
        for pat, spec in self._rules:
            if pat.search(path):
                return spec
        return P()

    def __call__(self, path: str) -> P:
        return self.spec(path)


def path_str(key_path: tuple) -> str:
    """Render a jax.tree_util key path as a '/'-joined string."""
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def tree_paths(tree: PyTree) -> PyTree:
    """Pytree of '/'-joined path strings, same structure as ``tree``."""
    return jax.tree.map_with_path(lambda kp, _: path_str(kp), tree)


def auto_fsdp_spec(
    shape: Sequence[int],
    mesh: Mesh,
    *,
    axis: str = mesh_lib.AXIS_FSDP,
    min_size_to_shard: int = 2**14,
) -> P:
    """ZeRO-style weight sharding rule (SURVEY.md §7 step 3; PAPERS.md
    "Automatic Cross-Replica Sharding of Weight Update", arxiv 2004.13336).

    Shard the largest dimension divisible by the fsdp axis size; tiny params
    stay replicated (sharding them costs more in collectives than it saves).
    """
    axis_size = mesh.shape.get(axis, 1)
    if axis_size <= 1 or math.prod(shape) < min_size_to_shard:
        return P()
    candidates = [
        (dim_size, i)
        for i, dim_size in enumerate(shape)
        if dim_size % axis_size == 0 and dim_size > 1
    ]
    if not candidates:
        return P()
    _, dim = max(candidates)
    spec = [None] * len(shape)
    spec[dim] = axis
    return P(*spec)


def specs_for_tree(
    tree: PyTree,
    mesh: Mesh,
    rule: LayoutMap | Callable[[str, tuple[int, ...]], P] | None = None,
    *,
    fsdp: bool = False,
) -> PyTree:
    """PartitionSpec pytree for ``tree``.

    ``rule`` may be a LayoutMap (path-only) or a ``(path, shape) -> spec``
    callable.  With ``fsdp=True``, leaves that no rule shards fall back to
    :func:`auto_fsdp_spec`.
    """

    def leaf_spec(key_path, leaf) -> P:
        path = path_str(key_path)
        shape = tuple(getattr(leaf, "shape", ()))
        spec = P()
        if isinstance(rule, LayoutMap):
            spec = rule.spec(path)
        elif callable(rule):
            spec = rule(path, shape)
        if fsdp and spec == P():
            spec = auto_fsdp_spec(shape, mesh)
        return spec

    return jax.tree.map_with_path(leaf_spec, tree)


def named_shardings(mesh: Mesh, specs: PyTree) -> PyTree:
    """Map a PartitionSpec pytree to NamedShardings on ``mesh``."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_tree(tree: PyTree, mesh: Mesh, specs: PyTree) -> PyTree:
    """Place a pytree onto ``mesh`` with the given PartitionSpecs."""
    return jax.device_put(tree, named_shardings(mesh, specs))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_spec(mesh: Mesh, *, extra_dims: int = 0,
               leading_unsharded: int = 0) -> P:
    """PartitionSpec for a batch: leading dim sharded over all batch axes.

    ``leading_unsharded`` prepends that many replicated dims — e.g. the
    step dimension of a ``steps_per_call`` bundle ``(k, B, ...)``.
    """
    axes = mesh_lib.data_axes(mesh)
    return P(*([None] * leading_unsharded),
             axes if axes else None, *([None] * extra_dims))


def shard_batch(batch: PyTree, mesh: Mesh) -> PyTree:
    """Shard every leaf's leading (batch) dimension over the batch axes."""
    sharding = NamedSharding(mesh, batch_spec(mesh))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
