"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

New first-class capability absent from the reference stack (SURVEY.md §5.7):
TF-classic has only the generic ``all_to_all`` op; long-context training needs
attention over sequences sharded across devices.

Two schemes, both valid inside ``shard_map`` over the ``seq`` mesh axis:

- :func:`ring_attention` — K/V chunks rotate around the ring via
  ``lax.ppermute`` while each device's Q stays put; online-softmax
  accumulators merge each chunk's contribution.  Communication is
  neighbor-to-neighbor over ICI (the torus's cheapest pattern) and overlaps
  with the chunk matmuls.  Memory per device stays O(S/n).
- :func:`ulysses_attention` — two ``all_to_all`` s reshard seq↔heads so each
  device computes *full-sequence* attention for H/n heads (then swaps back).
  Cheaper compute structure (one big attention per device, can use the
  Pallas flash kernel), but needs heads % seq_axis == 0 and all-to-all
  bandwidth.

References: Ring Attention (Liu et al. 2023) / DeepSpeed-Ulysses patterns —
re-derived here for the jax/shard_map idiom.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib

NEG_INF = -1e9


def ring_attention(
    q: jax.Array,  # (B, S_loc, H, D) — this device's seq shard
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = mesh_lib.AXIS_SEQ,
    causal: bool = False,
    impl: str | None = None,  # None=auto | "flash" | "xla"
    segment_ids: jax.Array | None = None,  # (B, S_loc) this shard's segments
) -> jax.Array:
    """Ring attention over mesh axis ``axis_name`` (shard_map-internal).

    Devices are assumed to hold *contiguous* sequence chunks in mesh-axis
    order (chunk i on position i) — the layout ``PartitionSpec(..., "seq",
    ...)`` produces.

    Chunk compute dispatches to the Pallas flash-attention kernels
    (``ops/flash_attention.py``) whenever the chunk shape supports them
    (auto) — per SURVEY.md §5.7 "ring attention with Pallas kernel": no
    (S_loc, S_loc) score tile ever reaches HBM, in forward *or* backward.
    ``impl="xla"`` forces the einsum online-softmax fallback (odd chunk
    sizes / unsupported dtypes).
    """
    if impl is None:
        from ..ops import flash_attention as fa

        # Match ops-level supported(): only auto-pick flash on real TPU
        # hardware (off-TPU the interpret-mode kernel is orders of magnitude
        # slower than the einsum ring), and only once the PER-DEVICE chunk
        # is long enough that the kernel beats XLA's fused attention
        # (MIN_SEQ_FOR_PALLAS — the bench_attn.py-evidenced threshold).
        # Callers can always force impl="flash".
        ok = (
            fa._on_tpu()
            and q.shape == k.shape == v.shape
            and q.shape[1] >= fa.MIN_SEQ_FOR_PALLAS
            and fa._pick_block_q(q.shape[1]) is not None
            and q.dtype in (jnp.bfloat16, jnp.float32)
        )
        impl = "flash" if ok else "xla"
    if impl == "flash":
        from ..ops.flash_attention import _on_tpu

        return _ring_flash(q, k, v, segment_ids, axis_name, causal,
                           not _on_tpu())
    return _ring_attention_xla(q, k, v, axis_name=axis_name, causal=causal,
                               segment_ids=segment_ids)


# --- Flash-kernel ring (custom VJP) -----------------------------------------


def _ring_flash_fwd_impl(q, k, v, seg, axis_name, causal, interpret):
    """Ring forward: each chunk through the Pallas flash kernel, partials
    merged by their log-sum-exp.  Returns (out, global lse).

    ``seg`` (B, S_loc) or None: packed-segment ids; the K/V chunk's segment
    ids rotate with it, and each chunk pair is masked q-segment vs
    k-segment inside the kernel.  A chunk fully masked for some q row gets
    lse ~ -1e9 there, so the merge weights its (uniform-average) output by
    ~0 — the same mechanism that nullifies strictly-future causal chunks.
    """
    from ..ops.flash_attention import _flash_forward

    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    have_seg = seg is not None

    def chunk(step, kc, vc, seg_c):
        """(o_chunk fp32 (B,S,H,D), lse_chunk (B,H,S)) for this ring step."""
        kidx = (my - step) % n
        seg_kw = dict(segment_ids=seg, kv_segment_ids=seg_c) if have_seg \
            else dict(segment_ids=None)

        def diag(_):
            return _flash_forward(q, kc, vc, None, causal=True,
                                  interpret=interpret, **seg_kw)

        def past(_):
            return _flash_forward(q, kc, vc, None, causal=False,
                                  interpret=interpret, **seg_kw)

        if not causal:
            o, lse = past(None)
            return o.astype(jnp.float32), lse

        def future(_):
            # Strictly-future chunk: nothing to compute.  lse=-inf makes the
            # merge weight exp(lse - m) exactly 0.
            return (
                jnp.zeros((b, s_loc, h, d), q.dtype),
                jnp.full((b, h, s_loc), NEG_INF, jnp.float32),
            )

        o, lse = lax.cond(
            kidx > my,
            future,
            lambda _: lax.cond(kidx == my, diag, past, None),
            None,
        )
        return o.astype(jnp.float32), lse

    def merge(m, l, acc, o_c, lse_c):
        # o_c is chunk-softmax-normalized; exp(lse_c - m_new) restores the
        # un-normalized numerator so partials combine exactly.
        m_new = jnp.maximum(m, lse_c)  # (B, H, S)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(lse_c - m_new)
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + (
            o_c * beta.transpose(0, 2, 1)[..., None]
        )
        l = l * alpha + beta
        return m_new, l, acc

    def body(carry, step):
        m, l, acc, kc, vc, seg_c = carry
        o_c, lse_c = chunk(step, kc, vc, seg_c)
        m, l, acc = merge(m, l, acc, o_c, lse_c)
        # rotate K/V (+ segments) to the next device; XLA overlaps this
        # with the matmuls
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        if have_seg:
            seg_c = lax.ppermute(seg_c, axis_name, perm)
        return (m, l, acc, kc, vc, seg_c), None

    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    seg0 = seg if have_seg else jnp.zeros((), jnp.int32)
    # last chunk merged outside the scan: no wasted final K/V rotation
    (m, l, acc, kc, vc, seg_c), _ = lax.scan(
        body, (m0, l0, acc0, k, v, seg0), jnp.arange(n - 1)
    )
    o_c, lse_c = chunk(n - 1, kc, vc, seg_c)
    m, l, acc = merge(m, l, acc, o_c, lse_c)
    out = acc / l.transpose(0, 2, 1)[..., None]
    lse_global = m + jnp.log(l)
    return out.astype(q.dtype), lse_global


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ring_flash(q, k, v, seg, axis_name, causal, interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, seg, axis_name, causal, interpret)
    return out


def _ring_flash_fwd(q, k, v, seg, axis_name, causal, interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, seg, axis_name, causal,
                                    interpret)
    return out, (q, k, v, seg, out, lse)


def _ring_flash_bwd(axis_name, causal, interpret, res, g):
    """Backward ring: per-chunk Pallas dq/dk/dv kernels driven by the
    *global* LSE; dk/dv partials rotate with their K/V chunk so after a
    full cycle every chunk's gradient lands back on its home device."""
    from ..ops.flash_attention import _flash_backward_pallas_core

    q, k, v, seg, out, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    gf = g.astype(jnp.float32)
    delta = jnp.einsum("bqhd,bqhd->bhq", gf, out.astype(jnp.float32))
    have_seg = seg is not None

    def chunk_grads(step, kc, vc, seg_c):
        kidx = (my - step) % n
        seg_kw = dict(segment_ids=seg, kv_segment_ids=seg_c) if have_seg \
            else {}

        def run(causal_flag):
            def f(_):
                return _flash_backward_pallas_core(
                    q, kc, vc, None, g, lse, delta,
                    causal=causal_flag, interpret=interpret, **seg_kw,
                )
            return f

        if not causal:
            return run(False)(None)

        def future(_):
            return (
                jnp.zeros_like(q), jnp.zeros_like(kc), jnp.zeros_like(vc)
            )

        return lax.cond(
            kidx > my,
            future,
            lambda _: lax.cond(kidx == my, run(True), run(False), None),
            None,
        )

    def body(carry, step):
        dq_acc, kc, vc, seg_c, dk_ring, dv_ring = carry
        dq_c, dk_c, dv_c = chunk_grads(step, kc, vc, seg_c)
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_ring = dk_ring + dk_c.astype(jnp.float32)
        dv_ring = dv_ring + dv_c.astype(jnp.float32)
        # K/V and their gradient partials travel together; n rotations is a
        # full cycle, so dk/dv end the scan on their chunk's home device.
        kc, vc, dk_ring, dv_ring = (
            lax.ppermute(x, axis_name, perm)
            for x in (kc, vc, dk_ring, dv_ring)
        )
        if have_seg:
            seg_c = lax.ppermute(seg_c, axis_name, perm)
        return (dq_acc, kc, vc, seg_c, dk_ring, dv_ring), None

    zeros_q = jnp.zeros(q.shape, jnp.float32)
    zeros_k = jnp.zeros(k.shape, jnp.float32)
    seg0 = seg if have_seg else jnp.zeros((), jnp.int32)
    (dq, _, _, _, dk, dv), _ = lax.scan(
        body,
        (zeros_q, k, v, seg0, zeros_k, jnp.zeros(v.shape, jnp.float32)),
        jnp.arange(n),
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


# --- XLA einsum fallback ----------------------------------------------------


def _ring_attention_xla(
    q: jax.Array,  # (B, S_loc, H, D) — this device's seq shard
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = mesh_lib.AXIS_SEQ,
    causal: bool = False,
    segment_ids: jax.Array | None = None,  # (B, S_loc)
) -> jax.Array:
    """Einsum online-softmax ring (chunk-granular causal masking, uniform
    control flow).  Fallback for shapes/dtypes the flash kernels reject."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    have_seg = segment_ids is not None

    def merge_chunk(m, l, acc, kc, vc, seg_c, step):
        # kc holds the chunk originally on device (my - step) % n
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32)) * scale
        if causal:
            kidx = (my - step) % n
            q_pos = my * s_loc + jnp.arange(s_loc)
            k_pos = kidx * s_loc + jnp.arange(s_loc)
            keep = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(keep[None, None], s, NEG_INF)
        if have_seg:
            same = segment_ids[:, :, None] == seg_c[:, None, :]  # (B, Sq, Sk)
            s = jnp.where(same[:, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vc.astype(jnp.float32))
        acc_new = acc * alpha.transpose(0, 2, 1, 3) + pv
        return m_new, l_new, acc_new

    def body(carry, step):
        m, l, acc, kc, vc, seg_c = carry
        m, l, acc = merge_chunk(m, l, acc, kc, vc, seg_c, step)
        # rotate K/V to the next device; XLA overlaps this with the matmuls
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        if have_seg:
            seg_c = lax.ppermute(seg_c, axis_name, perm)
        return (m, l, acc, kc, vc, seg_c), None

    m0 = jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    acc0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    seg0 = segment_ids if have_seg else jnp.zeros((), jnp.int32)
    # scan runs only the n-1 steps that need a rotation afterwards; the last
    # chunk is merged outside so no wasted final ppermute of K and V
    (m, l, acc, kc, vc, seg_c), _ = lax.scan(
        body, (m0, l0, acc0, k, v, seg0), jnp.arange(n - 1)
    )
    m, l, acc = merge_chunk(m, l, acc, kc, vc, seg_c, n - 1)
    # l >= 1 always: the diagonal chunk contributes exp(0) per row, so no
    # division guard is needed (matches the full-attention softmax exactly)
    out = acc / l.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,  # (B, S_loc, H, D)
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = mesh_lib.AXIS_SEQ,
    causal: bool = False,
    attn_fn: Callable | None = None,
    segment_ids: jax.Array | None = None,  # (B, S_loc)
) -> jax.Array:
    """Ulysses sequence parallelism (shard_map-internal).

    all_to_all reshards (B, S/n, H, D) -> (B, S, H/n, D), runs full-sequence
    attention per device on its head subset (``attn_fn``, default the
    framework attention entry, which may pick the Pallas flash kernel), then
    reshards back.  Heads must divide the seq-axis size.  ``segment_ids``
    (packed sequences) are all-gathered along ``seq`` — each device sees the
    full-sequence ids its full-sequence attention needs (ids are int32 and
    tiny next to K/V).
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"heads={h} not divisible by seq axis size {n}")
    if attn_fn is None:
        from ..ops.attention import dot_product_attention

        attn_fn = functools.partial(dot_product_attention, causal=causal)
    if segment_ids is not None:
        seg_full = lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        attn_fn = functools.partial(attn_fn, segment_ids=seg_full)

    def seq_to_heads(x):  # (B, S_loc, H, D) -> (B, S, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # (B, S, H/n, D) -> (B, S_loc, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    out = attn_fn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v))
    return heads_to_seq(out)


def make_sequence_parallel_attention(
    mesh: Mesh,
    *,
    scheme: str = "ring",  # "ring" | "ulysses"
    causal: bool = False,
    axis_name: str = mesh_lib.AXIS_SEQ,
) -> Callable:
    """Jit-compiled global-array entry: (B, S, H, D) sharded on ``seq``.

    The batch dim is additionally sharded over the batch axes, so this
    composes dp x sp out of the box.
    """
    return jax.jit(
        sequence_parallel_attention_fn(
            mesh, scheme=scheme, causal=causal, axis_name=axis_name
        )
    )


def sequence_parallel_attention_fn(
    mesh: Mesh,
    *,
    scheme: str = "ring",  # "ring" | "ulysses"
    causal: bool = True,
    axis_name: str = mesh_lib.AXIS_SEQ,
) -> Callable:
    """Un-jitted shard_map attention for use *inside* a jitted model.

    The manual-collectives region embedded in a GSPMD program: models (e.g.
    ``models.gpt.GPTLM``) take this as their ``attn_fn`` so the surrounding
    train step stays one ``jit`` while attention runs ring/Ulysses over the
    ``seq`` axis.  Dropping it into a mesh without a real ``seq`` axis
    (size 1) degrades to plain blockwise attention — same program, no
    collectives — so the model code never branches.
    """
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[scheme]
    kernel = functools.partial(fn, axis_name=axis_name, causal=causal)
    batch_axes = mesh_lib.data_axes(mesh)
    # Heads stay sharded over the model axis INSIDE the region: ring
    # attention is per-head independent, so on a dp x tp x sp mesh the
    # Megatron head shards never gather — each device ring-rotates only its
    # own heads' K/V (size-1 model axis makes this a no-op).
    head_axis = (
        mesh_lib.AXIS_MODEL
        if scheme == "ring" and mesh.shape.get(mesh_lib.AXIS_MODEL, 1) > 1
        else None
    )
    spec = P(batch_axes if batch_axes else None, axis_name, head_axis, None)
    seg_spec = P(batch_axes if batch_axes else None, axis_name)
    plain = jax.shard_map(
        lambda q, k, v: kernel(q, k, v),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    packed = jax.shard_map(
        lambda q, k, v, seg: kernel(q, k, v, segment_ids=seg),
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec,
        check_vma=False,
    )

    def attention(q, k, v, segment_ids=None):
        if segment_ids is None:
            return plain(q, k, v)
        return packed(q, k, v, segment_ids)

    return attention
