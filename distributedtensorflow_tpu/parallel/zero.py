"""Cross-replica weight-update sharding (ZeRO-style, stage 1).

Implements PAPERS.md "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arxiv 2004.13336): under pure data parallelism the
optimizer state is fully replicated, so per-chip memory — not math — caps
the model size.  This module shards the optimizer state AND the weight
update itself across the data-parallel replicas:

- gradients are **reduce-scattered** over the batch axes (each replica
  receives the cross-replica sum of only its 1/N shard);
- each replica applies the optimizer update to only its shard of the
  parameters and optimizer state;
- updated parameters are **all-gathered** back before the next forward
  pass (the forward/backward math is unchanged — this is a memory and
  update-bandwidth optimization, not a model-parallel scheme).

Uneven shapes are handled per the paper: every parameter is flattened and
padded to a multiple of the shard count, then viewed as ``(degree,
padded_size // degree)`` so any shape shards evenly (the pad tail carries
zero gradients, so it is inert under elementwise optimizers).

Implementation note: on jax 0.4.37 the partial-manual ``shard_map`` path
hits the XLA ``PartitionId`` lowering ceiling (pinned by
tests/test_jax_workarounds.py; the pipeline went full-manual for the
same reason), so the
collectives here are expressed as GSPMD sharding *constraints* inside the
jitted step — XLA lowers the constraint on the summed gradient to a
reduce-scatter and the constraint back to the parameter layout to an
all-gather, with the same freedom to fuse/overlap it has for every other
collective in the program.  The constraint applications are routed through
:func:`..parallel.collectives.gspmd_reduce_scatter` /
:func:`~.collectives.gspmd_all_gather` so they land in the span tracer and
the ``collective_dispatch_seconds{op=reduce_scatter|all_gather}``
histogram like every other collective wrapper.

Composition: the sharder chunks over the mesh's batch axes
(``data`` × ``fsdp``), so it composes with the :mod:`.sharding` layout
machinery — tensor-parallel (``model``-axis) parameters keep their layout
(the all-gather constrains back to the bound parameter specs, not to full
replication), and ``fsdp=True`` states simply see their already-sharded
parameters rechunked for the update stage.

Correctness contract: exact (up to float reassociation) for *elementwise*
optimizers — sgd/momentum/adam/adamw/adagrad/lion
(:data:`..train.optimizers.ZERO_SAFE`).  Optimizers that compute
cross-parameter norms or shape-factored statistics (lamb, lars, adafactor)
would see per-shard values instead of per-parameter ones; ``train.py``
warns when ``--zero`` is combined with one of those.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collectives
from . import mesh as mesh_lib

logger = logging.getLogger("distributedtensorflow_tpu")

PyTree = Any

__all__ = [
    "ZeroSharder",
    "chunk_shape",
    "chunk_array",
    "unchunk_array",
    "map_param_slots",
    "saved_opt_layout",
    "restore_step_zero",
    "restore_latest_zero",
]


# --- chunk math (degree-only, shared with checkpoint rechunking) ------------


def chunk_shape(shape: Sequence[int], degree: int) -> tuple[int, int]:
    """The ``(degree, ceil(size / degree))`` view every parameter shards
    into — the paper's flatten-pad-split partitioning, valid for ANY shape
    (scalars included)."""
    size = math.prod(shape) if shape else 1
    return (degree, -(-size // degree))


def chunk_array(x: jax.Array, degree: int) -> jax.Array:
    """Flatten, zero-pad to a multiple of ``degree``, view as
    ``(degree, chunk)``.  Pure reshape/pad — valid under ``jit`` and
    ``eval_shape``."""
    d, c = chunk_shape(x.shape, degree)
    flat = jnp.ravel(x)
    pad = d * c - flat.shape[0]
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(d, c)


def unchunk_array(x: jax.Array, shape: Sequence[int]) -> jax.Array:
    """Inverse of :func:`chunk_array`: drop the pad tail, restore shape."""
    size = math.prod(shape) if shape else 1
    return x.reshape(-1)[:size].reshape(tuple(shape))


def _chunked_shapes(param_shapes: PyTree, degree: int) -> PyTree:
    """Abstract ``(degree, chunk)`` view of every param leaf — the ONE
    derivation the layout probe, rechunk slot-matching, and intermediate
    sharding all share (they must never disagree about the chunk layout)."""
    return jax.eval_shape(
        lambda p: jax.tree.map(lambda x: chunk_array(x, degree), p),
        param_shapes,
    )


def _shapes(tree: PyTree) -> list[tuple[int, ...]]:
    """Sorted leaf shapes — structure-insensitive comparison key (orbax
    metadata trees nest differently from live optax namedtuples)."""
    return sorted(
        tuple(int(d) for d in leaf.shape)
        for leaf in jax.tree.leaves(tree)
        if hasattr(leaf, "shape")
    )


def map_param_slots(
    opt_tree: PyTree,
    param_shapes: PyTree,
    slot_shapes: PyTree,
    slot_fn: Callable[[Any, Any], Any],
    other_fn: Callable[[Any], Any] = lambda leaf: leaf,
) -> PyTree:
    """Map ``slot_fn(slot_leaf, param_shape_leaf)`` over every
    optimizer-state subtree that mirrors the parameters.

    Optax states are (nested) tuples/namedtuples whose param-shaped nodes
    (momentum, variance, trace, ...) have the params' treedef with leaf
    shapes given by ``slot_shapes`` (the params' own shapes for an
    unchunked state, their :func:`chunk_shape` for a ZeRO state).  Nodes
    that don't match — step counters, schedule state — map through
    ``other_fn`` leafwise.  The same walk
    :func:`..train.state._opt_state_specs` uses, generalized so spec
    derivation and checkpoint rechunking cannot disagree about which
    leaves are slots.
    """
    param_treedef = jax.tree.structure(param_shapes)
    expected = [tuple(s.shape) for s in jax.tree.leaves(slot_shapes)]

    def map_subtree(sub: PyTree) -> PyTree:
        if jax.tree.structure(sub) == param_treedef:
            leaves = jax.tree.leaves(sub)
            if all(
                tuple(getattr(l, "shape", ())) == e
                for l, e in zip(leaves, expected)
            ):
                return jax.tree.unflatten(
                    jax.tree.structure(sub),
                    [
                        slot_fn(l, p)
                        for l, p in zip(leaves, jax.tree.leaves(param_shapes))
                    ],
                )
        return jax.tree.map(other_fn, sub)

    def walk(node):
        if isinstance(node, tuple) and not hasattr(node, "shape"):
            children = [walk(c) for c in node]
            if hasattr(node, "_fields"):  # namedtuple (optax state nodes)
                return type(node)(*children)
            return tuple(children)
        return map_subtree(node)

    return walk(opt_tree)


class ZeroSharder:
    """The weight-update sharding policy for one mesh.

    ``axes`` defaults to the mesh's batch axes (``data`` × ``fsdp``) — the
    data-parallel replicas the paper shards across; ``degree`` is their
    size product.  Create once per run and pass to
    :func:`..train.state.create_sharded_state`, which chunks the optimizer
    state at init and binds the parameter specs the post-update all-gather
    restores to.
    """

    def __init__(self, mesh: Mesh, axes: Sequence[str] | None = None):
        self.mesh = mesh
        self.axes: tuple[str, ...] = tuple(axes or mesh_lib.data_axes(mesh))
        if not self.axes:
            raise ValueError(
                f"mesh {dict(mesh.shape)} has no batch axes to shard the "
                "weight update over"
            )
        self.degree = math.prod(mesh.shape[a] for a in self.axes)
        if self.degree <= 1:
            raise ValueError(
                f"ZeRO degree {self.degree} (axes {self.axes} of mesh "
                f"{dict(mesh.shape)}): nothing to shard — run without --zero"
            )
        #: PartitionSpec of a chunked leaf: dim 0 over the batch axes.
        self.chunk_pspec = P(self.axes)
        self._param_specs: PyTree | None = None

    # --- layout -------------------------------------------------------------

    def bind(self, param_specs: PyTree) -> "ZeroSharder":
        """Record the parameters' PartitionSpecs — the layout the
        post-update all-gather constrains back to (replicated under pure
        DP; the tensor-parallel layout when one is in force)."""
        self._param_specs = param_specs
        return self

    def chunk_tree(self, params: PyTree) -> PyTree:
        return jax.tree.map(lambda x: chunk_array(x, self.degree), params)

    def unchunk_tree(self, chunked: PyTree, like: PyTree) -> PyTree:
        return jax.tree.map(
            lambda c, p: unchunk_array(c, p.shape), chunked, like
        )

    def chunk_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.chunk_pspec)

    def opt_state_specs(self, opt_shapes: PyTree,
                        param_shapes: PyTree) -> PyTree:
        """PartitionSpec pytree for a chunked optimizer state: slot leaves
        shard dim 0 over the batch axes, everything else replicates."""
        chunked = _chunked_shapes(param_shapes, self.degree)
        return map_param_slots(
            opt_shapes, param_shapes, chunked,
            slot_fn=lambda leaf, p: self.chunk_pspec,
            other_fn=lambda leaf: P(),
        )

    # --- the sharded update (inside the jitted train step) ------------------

    def apply_gradients(self, state, grads: PyTree):
        """reduce-scatter grads → shard-local optimizer update →
        all-gather params; the drop-in body behind
        ``TrainState.apply_gradients`` when a sharder is attached.

        The optimizer state enters and leaves in chunked ``(degree,
        chunk)`` layout; the parameters enter full/laid-out, are sliced to
        the local chunk for the update (a dynamic-slice of an
        already-replicated value — no communication), and leave full
        again via the all-gather constraint.
        """
        import optax  # noqa: PLC0415 — keep parallel/ import-light

        cshard = self.chunk_sharding()
        cgrads = collectives.gspmd_reduce_scatter(
            self.chunk_tree(grads), cshard
        )
        cparams = jax.tree.map(
            lambda p: jax.lax.with_sharding_constraint(
                chunk_array(p, self.degree), cshard
            ),
            state.params,
        )
        updates, new_opt_state = state.tx.update(
            cgrads, state.opt_state, cparams
        )
        new_cparams = optax.apply_updates(cparams, updates)
        param_specs = self._param_specs
        if param_specs is None:
            param_specs = jax.tree.map(lambda _: P(), state.params)
        new_params = collectives.gspmd_all_gather(
            self.unchunk_tree(new_cparams, state.params),
            jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), param_specs,
                is_leaf=lambda x: isinstance(x, P),
            ),
        )
        return state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )


# --- checkpoint interop: restore across ZeRO degrees ------------------------


def _opt_shapes_for_degree(tx, param_shapes: PyTree,
                           degree: int | None) -> PyTree:
    """Abstract optimizer-state tree for ``tx`` over params chunked at
    ``degree`` (``None`` = unchunked / pure data parallel)."""
    if degree is None:
        return jax.eval_shape(lambda p: tx.init(p), param_shapes)
    return jax.eval_shape(
        lambda p: tx.init(p), _chunked_shapes(param_shapes, degree)
    )


def saved_opt_layout(mgr, step: int, tx, param_shapes: PyTree) -> int | None:
    """The ZeRO degree checkpoint ``step``'s optimizer state was saved at.

    Reads the checkpoint's array *metadata* (shapes only — no tensor I/O)
    and matches it against the layouts ``tx`` could have produced: the
    unchunked layout (returns ``None``) or a chunked layout at any degree
    observed in the saved leading dims.  Raises ``ValueError`` when the
    saved shapes match no candidate (a different optimizer family — the
    same failure a plain restore would hit, reported before any I/O).
    """
    meta = mgr.item_metadata(step)
    opt_meta = meta.get("opt_state") if isinstance(meta, dict) else None
    if opt_meta is None:
        raise ValueError(f"checkpoint step {step} has no opt_state metadata")
    got = _shapes(opt_meta)
    if got == _shapes(_opt_shapes_for_degree(tx, param_shapes, None)):
        return None
    candidates = sorted({s[0] for s in got if len(s) == 2 and s[0] > 1})
    for d in candidates:
        if got == _shapes(_opt_shapes_for_degree(tx, param_shapes, d)):
            return d
    raise ValueError(
        f"checkpoint step {step} optimizer-state shapes {got[:4]}... match "
        "neither the unchunked layout nor any ZeRO degree in "
        f"{candidates} — was it saved with a different optimizer?"
    )


def _rechunk_opt_state(
    opt_state: PyTree,
    param_shapes: PyTree,
    from_degree: int | None,
    to_sharder: ZeroSharder | None,
) -> PyTree:
    """Convert an optimizer state between ZeRO layouts (host-side math:
    unchunk at the saved degree, rechunk at the target's).  Non-slot
    leaves pass through."""
    slot_shapes = (
        param_shapes if from_degree is None
        else _chunked_shapes(param_shapes, from_degree)
    )

    def convert(leaf, pshape):
        x = leaf if from_degree is None else unchunk_array(leaf, pshape.shape)
        return (
            chunk_array(x, to_sharder.degree) if to_sharder is not None else x
        )

    return map_param_slots(opt_state, param_shapes, slot_shapes, convert)


def _mesh_of(target) -> Mesh | None:
    """The mesh a TrainState's arrays live on (from their NamedShardings),
    or None for host-only/unsharded trees."""
    for leaf in jax.tree.leaves(target.params):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            return sh.mesh
    return None


def restore_step_zero(mgr, step: int, target, mesh: Mesh | None = None,
                      sharder: ZeroSharder | None = None):
    """Layout-aware restore of ONE checkpoint step into ``target``.

    Probes the saved ZeRO degree first; a matching layout restores
    directly with :meth:`~..checkpoint.CheckpointManager.restore`
    semantics (verifies, raises ``CheckpointCorruptError``, no fallback).
    A mismatched layout restores into an intermediate state shaped like
    the *saved* layout — so the CRC32 integrity manifest verifies the
    bytes exactly as written — then rechunks the verified slots into the
    target layout and placement.  ``mesh`` and ``sharder`` default from
    ``target`` (its attached sharder, its arrays' sharding), so callers
    holding only a state template — the sidecar evaluator — stay
    layout-safe across trainer/evaluator topology differences.

    Returns ``(restored_state, rechunked)`` where ``rechunked`` is None
    for a direct restore or ``{"from": degree, "to": degree}``.
    """
    if sharder is None:
        sharder = getattr(target, "zero", None)
    if mesh is None:
        mesh = sharder.mesh if sharder is not None else _mesh_of(target)
    param_shapes = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), target.params
    )
    target_degree = sharder.degree if sharder is not None else None
    try:
        saved_degree = saved_opt_layout(mgr, step, target.tx, param_shapes)
    except Exception as e:
        logger.warning(
            "checkpoint step %d: ZeRO layout probe failed (%s); "
            "attempting a direct restore", step, e,
        )
        saved_degree = target_degree
    if saved_degree == target_degree or mesh is None:
        # mesh is None: nowhere to place a rechunk intermediate — the
        # direct restore surfaces the same shape mismatch it always did.
        return mgr.restore(step, target), None
    logger.warning(
        "checkpoint step %d was saved at ZeRO degree %s; rechunking "
        "its optimizer state to degree %s on restore",
        step, saved_degree or 1,
        target_degree or 1,
    )
    repl = NamedSharding(mesh, P())
    mid_opt_shapes = _opt_shapes_for_degree(
        target.tx, param_shapes, saved_degree
    )
    # Shard the intermediate's slot leaves dim-0 over the batch axes
    # when the saved degree divides across them — a replicated
    # intermediate would transiently hold the full per-device
    # optimizer copy --zero exists to avoid.  (A saved UNCHUNKED
    # layout has no shardable leading dim; that direction replicates,
    # costing no more than the run it migrates from.)
    mid_shardings = jax.tree.map(lambda _: repl, mid_opt_shapes)
    if saved_degree is not None:
        axes = (
            sharder.axes if sharder is not None
            else tuple(mesh_lib.data_axes(mesh))
        )
        nshards = math.prod(mesh.shape[a] for a in axes) if axes else 1
        if nshards > 1 and saved_degree % nshards == 0:
            slot_shapes = _chunked_shapes(param_shapes, saved_degree)
            mid_shardings = map_param_slots(
                mid_opt_shapes, param_shapes, slot_shapes,
                slot_fn=lambda leaf, p: NamedSharding(mesh, P(axes)),
                other_fn=lambda leaf: repl,
            )
    mid_opt = jax.jit(
        lambda shapes=mid_opt_shapes: jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        ),
        out_shardings=mid_shardings,
    )()
    mid = target.replace(opt_state=mid_opt)
    restored = mgr.restore(step, mid)
    out_shardings = jax.tree.map(lambda a: a.sharding, target.opt_state)
    converted = jax.jit(
        lambda opt: _rechunk_opt_state(
            opt, param_shapes, saved_degree, sharder
        ),
        out_shardings=out_shardings,
    )(restored.opt_state)
    rechunked = {"from": saved_degree or 1, "to": target_degree or 1}
    return restored.replace(opt_state=converted), rechunked


def restore_latest_zero(mgr, target, mesh: Mesh | None = None,
                        sharder: ZeroSharder | None = None,
                        *, before_step: int | None = None):
    """Restore the newest *verified* checkpoint into ``target``, converting
    the optimizer state between ZeRO degrees when the saved layout differs
    from the target's.

    ``target`` is a fully-built TrainState whose opt_state layout reflects
    ``sharder`` (chunked at its degree, or unchunked when ``sharder`` is
    None; both default from ``target`` like :func:`restore_step_zero`).
    Every candidate step gets its OWN layout probe — a mixed-layout
    history must not re-try a differently-chunked step against this
    target and mistake the shape mismatch for corruption.  Corrupt steps
    fall back to the next-newest (``restore_latest`` semantics);
    ``before_step`` restricts candidates to strictly earlier steps (the
    supervisor's NaN-recovery contract).  Returns None when no usable
    checkpoint exists.
    """
    from ..checkpoint.integrity import CheckpointCorruptError  # noqa: PLC0415

    steps = sorted(mgr.all_steps(), reverse=True)
    if before_step is not None:
        steps = [s for s in steps if s < before_step]
    rejected: list[dict] = []
    for step in steps:
        try:
            restored, rechunked = restore_step_zero(
                mgr, step, target, mesh, sharder
            )
        except FileNotFoundError:
            continue
        except CheckpointCorruptError as e:
            rejected.append({"step": step, "reason": str(e)[:300]})
            continue
        report = {"restored_step": step, "rejected": rejected}
        if rechunked is not None:
            report["rechunked"] = rechunked
        mgr.last_restore_report = report
        if rejected:
            logger.warning(
                "restored VERIFIED checkpoint step %d after rejecting "
                "%s", step, [r["step"] for r in rejected],
            )
        return restored
    # Overwrite unconditionally (restore_latest semantics): a None return
    # with no candidates must not leave an EARLIER restore's rejections in
    # the report for callers — the supervisor's restart telemetry — to
    # misattribute to this attempt.
    mgr.last_restore_report = {"restored_step": None, "rejected": rejected}
    if rejected:
        logger.error(
            "no verifiable checkpoint left (rejected %s); cold start",
            [r["step"] for r in rejected],
        )
    return None
