"""MPMD pipeline parallelism: each stage is a separate *program*.

The SPMD schedules in ``parallel/pipeline.py`` run every stage inside one
jitted program on one mesh.  This module is the contrasting design from
"Scaling Deep Learning Training with MPMD Pipeline Parallelism"
(PAPERS.md, arxiv 2412.14374): each pipeline stage is its own OS process
with its own params, its own compiled programs, and its own optimizer —
driven through :class:`..parallel.coordinator.Coordinator`'s
process-backed workers, so stage death rides the coordinator's
retry/respawn machinery instead of killing the run.

Wire contract (the ``data/wire.py`` raw tensor frames, PR 9 idiom):

- stage ``i`` holds ONE persistent loopback TCP link to stage ``i+1``
  (``u64 LE frame length | DTW1 frame``); activations flow down the link,
  cotangents flow back up the same link;
- every frame is a raw tensor dict (optional CRC32C) whose header echoes
  the sender's trace context, so the receiver's ``pipeline.handoff`` span
  parents under the sender's step span and ``tools/timeline.py --fleet``
  stitches the per-stage ``trace.jsonl`` files into one cross-process
  schedule rendering;
- the sender may have at most ``window`` microbatches in flight per link
  (activation sent, cotangent not yet returned) — the credit window that
  bounds per-stage live activations exactly like the SPMD 1F1B slot ring;
- each link runs a reader and a writer thread, so stage compute overlaps
  the transfer in steady state (the socket drains while the next
  microbatch computes).

Training semantics: a GPT split layer-wise.  Stage 0 owns the embedding
and the first layers; the last stage owns the final layers, ``ln_f`` and
an UNTIED head (a tied head would need a cross-stage gradient exchange
for the shared table — exactly the coupling MPMD removes).  Backward is
save-the-stage-input + recompute (the 1F1B remat pattern): on a returned
cotangent the stage re-runs its forward under ``jax.grad``.  Gradients
are stage-local by construction, so each stage applies its own optimizer
step with NO cross-stage collective — the MPMD property that removes the
``PartitionId``-class single-program lowering ceilings entirely.

Failure contract: a killed stage severs its links; every peer's closure
raises :class:`..parallel.coordinator.WorkerUnavailableError`, the
coordinator re-queues all stage closures, the killed process respawns
(budget + backoff), and the run re-executes deterministically from its
seeds — completion-despite-kill is the smoke-test acceptance.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import socket
import struct
import threading
import time
from typing import Any

import numpy as np

from .. import obs
from ..data import wire
from ..obs.tracing import (
    TraceRecorder,
    current_context,
    new_trace_id,
    record_remote_span,
    remote_span,
)
from .coordinator import Coordinator, WorkerUnavailableError
from .pipeline import fb_schedule

_LEN = struct.Struct("<Q")

_H_HANDOFF = obs.histogram(
    "pipeline_handoff_seconds",
    "MPMD stage handoff latency: sender's frame stamp to receiver decode, "
    "labeled by the RECEIVING stage",
)
_H_STALL = obs.histogram(
    "pipeline_mpmd_stall_seconds",
    "seconds a stage spent blocked on its credit window (activations in "
    "flight == window) before the next cotangent freed a slot, by stage",
)


@dataclasses.dataclass(frozen=True)
class MPMDConfig:
    """Model + schedule shape for one MPMD pipeline run (picklable — it
    rides the coordinator's closure pipe into every stage process)."""

    n_stages: int = 2
    n_steps: int = 8
    n_microbatches: int = 4
    microbatch_size: int = 4
    seq_len: int = 32
    vocab_size: int = 256
    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 4
    #: credit window: activation microbatches in flight per link before
    #: the sender blocks (the per-stage live-activation bound)
    window: int = 2
    lr: float = 1e-2
    seed: int = 0
    crc: bool = True
    recv_timeout_s: float = 120.0
    connect_timeout_s: float = 60.0

    def validate(self) -> None:
        if self.n_stages < 2:
            raise ValueError("MPMD pipeline needs n_stages >= 2")
        if self.num_layers % self.n_stages:
            raise ValueError(
                f"num_layers={self.num_layers} not divisible by "
                f"n_stages={self.n_stages}"
            )
        if self.window < 1:
            raise ValueError("window must be >= 1")
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide into num_heads")


# --- framed link over one TCP socket -----------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the link")
        buf += chunk
    return bytes(buf)


class _Link:
    """One persistent stage-to-stage connection: reader + writer threads
    (compute/transfer overlap), framed raw-tensor payloads."""

    def __init__(self, sock: socket.socket, name: str, crc: bool):
        self._sock = sock
        self._name = name
        self._crc = crc
        self.rx: queue.Queue = queue.Queue()
        self._tx: queue.Queue = queue.Queue()
        self._dead: BaseException | None = None
        self._reader = threading.Thread(
            target=self._read_loop, name=f"{name}-rx", daemon=True
        )
        self._writer = threading.Thread(
            target=self._write_loop, name=f"{name}-tx", daemon=True
        )
        self._reader.start()
        self._writer.start()

    def _read_loop(self) -> None:
        try:
            while True:
                (ln,) = _LEN.unpack(_recv_exact(self._sock, _LEN.size))
                if ln > (1 << 31):
                    # The DTW1 CRC covers the payload, not this prefix: a
                    # desynced length must fail the link immediately, not
                    # allocate toward 2^64 bytes until the recv timeout
                    # (same bound as the data-service framing).
                    raise ConnectionError(f"oversized frame ({ln} bytes)")
                payload = _recv_exact(self._sock, ln)
                trace = wire.peek_trace(payload)
                tensors = wire.decode_tensors(payload)
                self.rx.put(("frame", tensors, trace))
        except BaseException as e:  # noqa: BLE001 — surfaced to the loop
            self._dead = e
            self.rx.put(("dead", e, None))

    def _write_loop(self) -> None:
        try:
            while True:
                payload = self._tx.get()
                if payload is None:
                    return
                self._sock.sendall(_LEN.pack(len(payload)) + payload)
        except BaseException as e:  # noqa: BLE001
            self._dead = e
            self.rx.put(("dead", e, None))

    def send(self, tensors: dict, trace: dict | None = None) -> None:
        if self._dead is not None:
            raise WorkerUnavailableError(
                f"link {self._name} is dead: {self._dead!r}"
            )
        self._tx.put(wire.encode_tensors(tensors, crc=self._crc, trace=trace))

    def poll(self, timeout: float) -> tuple[dict, dict | None] | None:
        """One frame, or None when nothing arrives within ``timeout``
        (raises on a severed link)."""
        try:
            if timeout > 0:
                kind, a, b = self.rx.get(timeout=timeout)
            else:
                kind, a, b = self.rx.get_nowait()
        except queue.Empty:
            return None
        if kind == "dead":
            raise WorkerUnavailableError(
                f"link {self._name} severed: {a!r}"
            )
        return a, b

    def recv(self, timeout: float) -> tuple[dict, dict | None]:
        got = self.poll(timeout)
        if got is None:
            raise WorkerUnavailableError(
                f"link {self._name}: no frame within {timeout:.0f}s "
                "(stalled or dead peer)"
            )
        return got

    def close(self) -> None:
        self._tx.put(None)
        # Drain the writer BEFORE severing the socket: the last cotangent
        # of a finishing stage may still be in the tx queue, and a
        # premature shutdown would cut it off mid-flight (the peer would
        # then read a severed link where a clean final frame was owed).
        self._writer.join(timeout=10.0)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


# --- loopback rendezvous ------------------------------------------------------


def _port_file(rdir: str, link: int) -> str:
    return os.path.join(rdir, f"link{link}.port")


def _serve_link(rdir: str, link: int, timeout_s: float) -> socket.socket:
    """Bind an ephemeral loopback listener, publish its port (atomic
    rename — a respawned server republishes a FRESH port and the client's
    connect-retry loop re-reads it), accept exactly one peer."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    os.makedirs(rdir, exist_ok=True)
    tmp = _port_file(rdir, link) + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(port))
    os.replace(tmp, _port_file(rdir, link))
    srv.settimeout(timeout_s)
    try:
        conn, _ = srv.accept()
    except socket.timeout:
        raise WorkerUnavailableError(
            f"link {link}: no upstream connection within {timeout_s:.0f}s"
        ) from None
    finally:
        srv.close()
    conn.settimeout(None)  # idleness policing lives at the queue level
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return conn


def _connect_link(rdir: str, link: int, timeout_s: float) -> socket.socket:
    """Dial the downstream peer's published port through the resilient
    substrate (``net.rpc.connect_with_retry``): exponential backoff +
    jitter instead of a fixed poll, per-link ``rpc_attempt_seconds`` /
    retry metrics, and a breaker that fast-fails a peer stuck refusing.
    Each attempt RE-READS the port file — a respawned server republishes
    a fresh port and the retry picks it up."""
    from ..net import rpc as netrpc  # noqa: PLC0415

    path = _port_file(rdir, link)

    def _dial() -> socket.socket:
        with open(path) as f:
            port = int(f.read().strip())
        sock = socket.create_connection(("127.0.0.1", port), timeout=2.0)
        sock.settimeout(None)  # connect-only timeout; reads block
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    try:
        return netrpc.connect_with_retry(
            _dial,
            endpoint=f"mpmd_link:{link}",
            deadline_s=timeout_s,
            policy=netrpc.RetryPolicy(
                deadline_s=timeout_s, backoff_base_s=0.05,
                backoff_max_s=0.5,
            ),
            retryable=(OSError, ValueError),
        )
    except (netrpc.DeadlineExceeded, ConnectionError) as e:
        raise WorkerUnavailableError(
            f"link {link}: could not connect within {timeout_s:.0f}s "
            f"({e})"
        ) from e


# --- per-stage model ---------------------------------------------------------


def _build_stage_fns(cfg: MPMDConfig, stage_id: int):
    """Compiled programs of one stage: ``(init, fwd, bwd | loss_grad)``.

    Backward is recompute-from-saved-input (``jax.grad`` of the stage
    forward), so in-flight memory per microbatch is one stage INPUT.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax

    from ..models.gpt import GPTBlock, GPTConfig
    from ..models.layers import FusedLayerNorm

    gcfg = GPTConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers, num_heads=cfg.num_heads,
        intermediate_size=4 * cfg.hidden_size, max_seq=cfg.seq_len,
        dtype=jnp.float32, remat=False,
    )
    lps = cfg.num_layers // cfg.n_stages
    first = stage_id == 0
    last = stage_id == cfg.n_stages - 1

    class Stage(nn.Module):
        @nn.compact
        def __call__(self, x):
            if first:
                x = nn.Embed(
                    gcfg.vocab_size, gcfg.hidden_size,
                    dtype=jnp.float32, name="wte",
                )(x)
            positions = jnp.broadcast_to(
                jnp.arange(x.shape[1]), x.shape[:2]
            )
            for i in range(lps):
                x = GPTBlock(gcfg, name=f"h{i}")(x, positions, True)
            if last:
                x = FusedLayerNorm(out_dtype=jnp.float32, name="ln_f")(x)
                x = nn.Dense(
                    gcfg.vocab_size, use_bias=False,
                    dtype=jnp.float32, name="head",
                )(x)
            return x

    module = Stage()
    sample = (
        jnp.zeros((1, cfg.seq_len), jnp.int32) if first
        else jnp.zeros((1, cfg.seq_len, cfg.hidden_size), jnp.float32)
    )
    params = module.init(
        jax.random.PRNGKey(cfg.seed * 7919 + stage_id), sample
    )["params"]
    tx = optax.adam(cfg.lr)
    opt_state = tx.init(params)

    fwd = jax.jit(lambda p, x: module.apply({"params": p}, x))

    if last:
        def _loss(p, x, ids):
            logits = module.apply({"params": p}, x)
            logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
            tgt = ids[:, 1:]
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
            return jnp.mean(nll)

        loss_grad = jax.jit(jax.value_and_grad(_loss, argnums=(0, 1)))
        bwd = None
    elif first:
        loss_grad = None

        def _vjp_first(p, x, dy):
            # x is the int token batch — params are the only diff input
            _, pull = jax.vjp(lambda p_: module.apply({"params": p_}, x), p)
            (gp,) = pull(dy)
            return gp

        bwd = jax.jit(_vjp_first)
    else:
        loss_grad = None

        def _vjp_apply(p, x, dy):
            _, pull = jax.vjp(lambda p_, x_: module.apply({"params": p_}, x_),
                              p, x)
            return pull(dy)

        bwd = jax.jit(_vjp_apply)

    update = jax.jit(
        lambda p, o, g: (lambda up, no: (optax.apply_updates(p, up), no))(
            *tx.update(g, o, p)
        )
    )
    return params, opt_state, fwd, bwd, loss_grad, update


def _make_ids(cfg: MPMDConfig, step: int, micro: int) -> np.ndarray:
    """Deterministic learnable LM microbatch (modular sequences — the
    test-suite make_batch idiom), identical across restart attempts."""
    r = np.random.default_rng(cfg.seed * 100003 + step * 1009 + micro)
    start = r.integers(0, cfg.vocab_size, (cfg.microbatch_size, 1))
    delta = r.integers(1, 7, (cfg.microbatch_size, 1))
    ids = (start + delta * np.arange(cfg.seq_len)) % cfg.vocab_size
    return ids.astype(np.int32)


def _observe_handoff(stage_id: int, tensors: dict, trace: dict | None,
                     trace_id: str) -> None:
    t_send = float(tensors["t_send"][()])
    dur = max(time.time() - t_send, 0.0)
    _H_HANDOFF.observe(dur, stage=str(stage_id))
    record_remote_span(
        "pipeline.handoff",
        t0=t_send, dur_s=dur,
        trace_id=(trace or {}).get("trace_id") or trace_id,
        parent_id=(trace or {}).get("span_id"),
        stage=stage_id,
        step=int(tensors["step"][()]),
        micro=int(tensors["micro"][()]),
    )


def _grads_add(acc, g):
    import jax

    if acc is None:
        return g
    return jax.tree.map(lambda a, b: a + b, acc, g)


def _stage_worker(cfg: MPMDConfig, stage_id: int, rdir: str, logdir: str,
                  trace_id: str):
    """One stage process's whole life: rendezvous, train loop, teardown.

    Runs inside a coordinator process worker; any link failure raises
    WorkerUnavailableError so the closure re-queues (all-stage restart).
    Returns the per-step mean losses from the LAST stage, None elsewhere.
    """
    import jax
    import jax.numpy as jnp

    cfg.validate()
    first = stage_id == 0
    last = stage_id == cfg.n_stages - 1
    stage_dir = os.path.join(logdir, f"stage{stage_id}")
    os.makedirs(stage_dir, exist_ok=True)
    recorder = TraceRecorder(
        os.path.join(stage_dir, "trace.jsonl"), chief_only=False
    ).install()
    up = down = None
    losses: list[float] = []
    step_seconds: list[float] = []
    # The stage's own metrics stream: one row per optimizer step, carrying
    # the pipeline_* stamps plus the flattened registry scalars (handoff/
    # stall histograms) — run_report's pipeline section and the schema
    # gates read stage dirs exactly like trainer logdirs.
    predicted_bubble = fb_schedule(
        cfg.n_stages, cfg.n_microbatches
    ).bubble_fraction()
    metrics_path = os.path.join(stage_dir, "metrics.jsonl")
    # Each attempt restarts training from scratch (deterministic seeds),
    # so the metrics stream restarts too — truncate rather than appending
    # a step-0 regression onto a dead attempt's rows.
    open(metrics_path, "w").close()

    def write_metrics_row(step: int, extra: dict) -> None:
        import json

        row = {
            "step": step,
            "t": time.time(),
            "pipeline_schedule": "mpmd",
            "pipeline_stages": cfg.n_stages,
            "pipeline_microbatches": cfg.n_microbatches,
            "pipeline_virtual": 1,
            "pipeline_bubble": predicted_bubble,
        }
        try:
            row.update(obs.default_registry().scalars())
        except Exception:
            pass
        row.update(extra)
        with open(metrics_path, "a") as f:
            f.write(json.dumps(row) + "\n")

    try:
        params, opt_state, fwd, bwd, loss_grad, update = _build_stage_fns(
            cfg, stage_id
        )
        # Rendezvous order: every stage serves its UPSTREAM link first
        # (stage i accepts from i-1 on link i-1), then dials downstream.
        # Stage 0 only dials, the last stage only serves — no cycles.
        if not first:
            up = _Link(
                _serve_link(rdir, stage_id - 1, cfg.connect_timeout_s),
                f"up{stage_id}", cfg.crc,
            )
        if not last:
            down = _Link(
                _connect_link(rdir, stage_id, cfg.connect_timeout_s),
                f"down{stage_id}", cfg.crc,
            )
        m_total = cfg.n_microbatches
        for step in range(cfg.n_steps):
            t_step0 = time.monotonic()
            grads = None
            if first:
                with remote_span("mpmd.step", step=step, stage=stage_id):
                    sent = done = 0
                    saved: dict[int, np.ndarray] = {}
                    while done < m_total:
                        if sent < m_total and (sent - done) < cfg.window:
                            ids = _make_ids(cfg, step, sent)
                            y = fwd(params, jnp.asarray(ids))
                            saved[sent] = ids
                            down.send(
                                {
                                    "x": np.asarray(y, np.float32),
                                    "ids": ids,
                                    "step": np.int32(step),
                                    "micro": np.int32(sent),
                                    "t_send": np.float64(time.time()),
                                },
                                trace=current_context(),
                            )
                            sent += 1
                            continue
                        window_blocked = sent < m_total
                        t0w = time.monotonic()
                        tens, _tr = down.recv(cfg.recv_timeout_s)
                        if window_blocked:
                            _H_STALL.observe(
                                time.monotonic() - t0w, stage=str(stage_id)
                            )
                        m = int(tens["micro"][()])
                        ids = saved.pop(m)
                        gp = bwd(
                            params, jnp.asarray(ids),
                            jnp.asarray(np.asarray(tens["dx"])),
                        )
                        grads = _grads_add(grads, gp)
                        done += 1
            elif not last:
                done = 0
                saved_x: dict[tuple[int, int], Any] = {}
                fwded = 0

                def process_cot(tens, tr):
                    m = int(tens["micro"][()])
                    x_in = saved_x.pop((int(tens["step"][()]), m))
                    gp, dx = bwd(
                        params, x_in,
                        jnp.asarray(np.asarray(tens["dx"])),
                    )
                    up.send(
                        {
                            "dx": np.asarray(dx, np.float32),
                            "step": tens["step"],
                            "micro": tens["micro"],
                            "t_send": np.float64(time.time()),
                        },
                        trace=tr,
                    )
                    return gp

                # Both directions are polled in one loop: blocking on the
                # upstream act alone would deadlock a >=3-stage pipeline
                # (the windowed sender upstream is itself waiting for the
                # cotangents parked in our downstream queue).
                idle_deadline = time.monotonic() + cfg.recv_timeout_s
                while done < m_total:
                    if fwded > done:
                        got = down.poll(0.0)  # prefer cotangents (1F1B)
                        if got is not None:
                            grads = _grads_add(grads, process_cot(*got))
                            done += 1
                            idle_deadline = (
                                time.monotonic() + cfg.recv_timeout_s
                            )
                            continue
                    if fwded < m_total:
                        got = up.poll(0.002)
                        if got is not None:
                            tens, tr = got
                            _observe_handoff(stage_id, tens, tr, trace_id)
                            x_in = jnp.asarray(np.asarray(tens["x"]))
                            y = fwd(params, x_in)
                            saved_x[(int(tens["step"][()]),
                                     int(tens["micro"][()]))] = x_in
                            down.send(
                                {
                                    "x": np.asarray(y, np.float32),
                                    "ids": np.asarray(tens["ids"]),
                                    "step": tens["step"],
                                    "micro": tens["micro"],
                                    "t_send": np.float64(time.time()),
                                },
                                trace=tr,
                            )
                            fwded += 1
                            idle_deadline = (
                                time.monotonic() + cfg.recv_timeout_s
                            )
                            continue
                    elif fwded > done:
                        got = down.poll(0.002)
                        if got is not None:
                            grads = _grads_add(grads, process_cot(*got))
                            done += 1
                            idle_deadline = (
                                time.monotonic() + cfg.recv_timeout_s
                            )
                            continue
                    if time.monotonic() > idle_deadline:
                        raise WorkerUnavailableError(
                            f"stage {stage_id}: no frames for "
                            f"{cfg.recv_timeout_s:.0f}s (dead pipeline?)"
                        )
            else:  # last stage: loss + immediate backward per microbatch
                step_losses = []
                for _ in range(m_total):
                    tens, tr = up.recv(cfg.recv_timeout_s)
                    _observe_handoff(stage_id, tens, tr, trace_id)
                    x_in = jnp.asarray(np.asarray(tens["x"]))
                    ids = jnp.asarray(np.asarray(tens["ids"]))
                    loss, (gp, dx) = loss_grad(params, x_in, ids)
                    up.send(
                        {
                            "dx": np.asarray(dx, np.float32),
                            "step": tens["step"],
                            "micro": tens["micro"],
                            "t_send": np.float64(time.time()),
                        },
                        trace=tr,
                    )
                    grads = _grads_add(grads, gp)
                    step_losses.append(float(loss))
                losses.append(float(np.mean(step_losses)))
            scale = 1.0 / m_total
            grads = jax.tree.map(lambda g: g * scale, grads)
            params, opt_state = update(params, opt_state, grads)
            step_seconds.append(time.monotonic() - t_step0)
            extra: dict = {"t_step": step_seconds[-1]}
            if last:
                extra["loss"] = losses[-1]
            write_metrics_row(step, extra)
        if last:
            return {"losses": losses, "step_seconds": step_seconds}
        return None
    except (ConnectionError, OSError, socket.timeout) as e:
        raise WorkerUnavailableError(
            f"stage {stage_id} link failure: {e!r}"
        ) from e
    finally:
        for link in (up, down):
            if link is not None:
                link.close()
        try:
            obs.default_registry().write_prometheus(
                os.path.join(stage_dir, "metrics.prom")
            )
        except Exception:
            pass
        recorder.uninstall()
        recorder.close()


def run_mpmd_pipeline(
    cfg: MPMDConfig,
    logdir: str,
    *,
    coordinator: Coordinator | None = None,
    join_timeout_s: float = 600.0,
) -> dict:
    """Drive an MPMD pipeline run to completion through the Coordinator.

    Schedules one stage closure per stage onto process-backed workers
    (pass ``coordinator=`` to share/kill-inject one; otherwise an owned
    ``Coordinator(num_workers=n_stages, use_processes=True)`` is built
    and shut down).  Returns ``{"losses": [per-step mean loss...],
    "trace_id", "stages", "logdir"}`` — losses come from the last stage's
    closure; a mid-run stage kill re-queues every stage closure and the
    run completes on the respawned pool.
    """
    cfg.validate()
    os.makedirs(logdir, exist_ok=True)
    rdir = os.path.join(logdir, "rendezvous")
    os.makedirs(rdir, exist_ok=True)
    trace_id = new_trace_id()
    owns = coordinator is None
    coord = coordinator or Coordinator(
        num_workers=cfg.n_stages, use_processes=True
    )
    try:
        rvs = [
            coord.schedule(
                _stage_worker, (cfg, i, rdir, logdir, trace_id)
            )
            for i in range(cfg.n_stages)
        ]
        coord.join(timeout=join_timeout_s)
        result = rvs[-1].fetch(timeout=30.0)
    finally:
        if owns:
            coord.shutdown()
    return {
        "losses": result["losses"],
        "step_seconds": result["step_seconds"],
        "trace_id": trace_id,
        "stages": cfg.n_stages,
        "logdir": logdir,
    }
