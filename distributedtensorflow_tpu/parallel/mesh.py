"""Device-mesh core: the TPU-native replacement for the tf.distribute strategy zoo.

In the reference stack, parallelism is chosen by picking a *strategy object*
(``OneDeviceStrategy`` / ``MirroredStrategy`` / ``MultiWorkerMirroredStrategy``
/ ``ParameterServerStrategyV2`` — see SURVEY.md §2.1).  On TPU the idiomatic
equivalent is a single SPMD program parameterized by a ``jax.sharding.Mesh``:
each strategy is *just a mesh shape* (SURVEY.md §7 step 1, §2.4 matrix).

Canonical mesh axes (slowest-varying first — outer axes ride DCN between
slices, inner axes ride ICI within a slice, so keep bandwidth-hungry axes
innermost):

=========  ===========================================================
``data``   pure data parallelism (gradient all-reduce; replaces the
           MirroredStrategy / MultiWorkerMirroredStrategy replica axis)
``fsdp``   data parallelism with sharded params/optimizer state
           (ZeRO-style weight-update sharding)
``pipe``   pipeline-parallel stage axis (GPipe-style; absent from the
           reference stack — new capability)
``seq``    sequence/context parallelism (ring attention / Ulysses;
           absent from the reference stack — new capability)
``expert`` expert parallelism for MoE (new capability)
``model``  tensor/model parallelism (Megatron-style; generalizes the
           reference's PS ShardedVariable embedding sharding)
=========  ===========================================================
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Mesh-major order. ``data`` outermost (can span DCN), ``model`` innermost
# (needs the fastest ICI links for per-layer collectives).
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_MODEL = "model"

CANONICAL_AXES: tuple[str, ...] = (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_EXPERT,
    AXIS_MODEL,
)

#: Axes over which gradients of replicated parameters are summed.
BATCH_AXES: tuple[str, ...] = (AXIS_DATA, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape over the canonical axes.

    Any single axis may be ``-1`` meaning "all remaining devices".  Axes of
    size 1 are kept in the mesh (size-1 collectives are no-ops that XLA
    removes), so downstream sharding rules can always name every canonical
    axis without caring which ones are active.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1
    model: int = 1

    def sizes(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.pipe, self.seq, self.expert, self.model)

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        """Concrete per-axis sizes for ``n_devices``, expanding a single -1."""
        sizes = list(self.sizes())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got spec {self}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh spec {self} needs {fixed} devices, have {n_devices}"
            )
        return tuple(sizes)

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        return build_mesh(self, devices)


def build_mesh(
    spec: MeshSpec, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with ICI-topology-aware device order.

    ``mesh_utils.create_device_mesh`` assigns devices so that innermost mesh
    axes map to nearest-neighbor ICI links on the TPU torus (the role
    NcclManager's topology detection plays in the reference stack —
    SURVEY.md §5.8).
    """
    if devices is None:
        devices = jax.devices()
    sizes = spec.sizes()
    if -1 not in sizes:
        # fully-fixed spec: take a prefix of the available devices, so e.g.
        # OneDeviceStrategy semantics (data=1) work on a multi-device host.
        # Single-process only: in a multi-host job a prefix mesh would contain
        # devices other processes can't address — that needs an explicit
        # device list from the caller.
        needed = math.prod(sizes)
        if needed < len(devices):
            if jax.process_count() > 1:
                raise ValueError(
                    f"mesh spec {spec} uses {needed} of {len(devices)} global "
                    "devices; sub-mesh selection is single-process only — "
                    "pass an explicit `devices` list (or use -1 axes) in "
                    "multi-host jobs"
                )
            devices = list(devices)[:needed]
    shape = spec.resolve(len(devices))
    if len(devices) == 1:
        dev_array = np.asarray(devices).reshape(shape)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=list(devices), allow_split_physical_axes=True
            )
        except (NotImplementedError, ValueError):
            # Non-TPU backends (CPU test meshes) have no physical topology.
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, CANONICAL_AXES)


# --- Strategy-zoo presets: each reference strategy is just a mesh shape. ---


def one_device_mesh(device: jax.Device | None = None) -> Mesh:
    """``OneDeviceStrategy`` equivalent: a 1×1×…×1 mesh on one device."""
    devices = [device] if device is not None else jax.local_devices()[:1]
    return build_mesh(MeshSpec(data=1), devices)


def mirrored_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """``MirroredStrategy`` equivalent: all *local* devices on the data axis."""
    return build_mesh(MeshSpec(data=-1), devices or jax.local_devices())


def multi_worker_mesh() -> Mesh:
    """``MultiWorkerMirroredStrategy`` equivalent: all *global* devices on data."""
    return build_mesh(MeshSpec(data=-1), jax.devices())


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """The batch axes present in ``mesh`` (for gradient psum / batch sharding)."""
    return tuple(a for a in BATCH_AXES if a in mesh.axis_names)


def replica_count(mesh: Mesh) -> int:
    """Number of data-parallel replicas (product of batch-axis sizes)."""
    return math.prod(mesh.shape[a] for a in data_axes(mesh))
