"""Device-mesh core: the TPU-native replacement for the tf.distribute strategy zoo.

In the reference stack, parallelism is chosen by picking a *strategy object*
(``OneDeviceStrategy`` / ``MirroredStrategy`` / ``MultiWorkerMirroredStrategy``
/ ``ParameterServerStrategyV2`` — see SURVEY.md §2.1).  On TPU the idiomatic
equivalent is a single SPMD program parameterized by a ``jax.sharding.Mesh``:
each strategy is *just a mesh shape* (SURVEY.md §7 step 1, §2.4 matrix).

Canonical mesh axes (slowest-varying first — outer axes ride DCN between
slices, inner axes ride ICI within a slice, so keep bandwidth-hungry axes
innermost):

=========  ===========================================================
``data``   pure data parallelism (gradient all-reduce; replaces the
           MirroredStrategy / MultiWorkerMirroredStrategy replica axis)
``fsdp``   data parallelism with sharded params/optimizer state
           (ZeRO-style weight-update sharding)
``pipe``   pipeline-parallel stage axis (GPipe-style; absent from the
           reference stack — new capability)
``seq``    sequence/context parallelism (ring attention / Ulysses;
           absent from the reference stack — new capability)
``expert`` expert parallelism for MoE (new capability)
``model``  tensor/model parallelism (Megatron-style; generalizes the
           reference's PS ShardedVariable embedding sharding)
=========  ===========================================================
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Mesh-major order. ``data`` outermost (can span DCN), ``model`` innermost
# (needs the fastest ICI links for per-layer collectives).
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_MODEL = "model"

CANONICAL_AXES: tuple[str, ...] = (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_EXPERT,
    AXIS_MODEL,
)

#: Axes over which gradients of replicated parameters are summed.
BATCH_AXES: tuple[str, ...] = (AXIS_DATA, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape over the canonical axes.

    Any single axis may be ``-1`` meaning "all remaining devices".  Axes of
    size 1 are kept in the mesh (size-1 collectives are no-ops that XLA
    removes), so downstream sharding rules can always name every canonical
    axis without caring which ones are active.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1
    model: int = 1

    def sizes(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.pipe, self.seq, self.expert, self.model)

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        """Concrete per-axis sizes for ``n_devices``, expanding a single -1."""
        sizes = list(self.sizes())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got spec {self}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh spec {self} needs {fixed} devices, have {n_devices}"
            )
        return tuple(sizes)

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        return build_mesh(self, devices)


def build_mesh(
    spec: MeshSpec, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with ICI-topology-aware device order.

    ``mesh_utils.create_device_mesh`` assigns devices so that innermost mesh
    axes map to nearest-neighbor ICI links on the TPU torus (the role
    NcclManager's topology detection plays in the reference stack —
    SURVEY.md §5.8).
    """
    if devices is None:
        devices = jax.devices()
    sizes = spec.sizes()
    if -1 not in sizes:
        # fully-fixed spec: take a prefix of the available devices, so e.g.
        # OneDeviceStrategy semantics (data=1) work on a multi-device host.
        # Single-process only: in a multi-host job a prefix mesh would contain
        # devices other processes can't address — that needs an explicit
        # device list from the caller.
        needed = math.prod(sizes)
        if needed < len(devices):
            if jax.process_count() > 1:
                raise ValueError(
                    f"mesh spec {spec} uses {needed} of {len(devices)} global "
                    "devices; sub-mesh selection is single-process only — "
                    "pass an explicit `devices` list (or use -1 axes) in "
                    "multi-host jobs"
                )
            devices = list(devices)[:needed]
    shape = spec.resolve(len(devices))
    if len(devices) == 1:
        dev_array = np.asarray(devices).reshape(shape)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=list(devices), allow_split_physical_axes=True
            )
        except (NotImplementedError, ValueError):
            # Non-TPU backends (CPU test meshes) have no physical topology.
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, CANONICAL_AXES)


def slice_count(devices: Sequence[jax.Device] | None = None) -> int:
    """Number of distinct TPU slices among ``devices`` (1 off-TPU).

    Multi-slice jobs see a ``slice_index`` on each device; collectives
    between slices ride DCN, within a slice ICI (SURVEY.md §5.8).
    """
    if devices is None:
        devices = jax.devices()
    return len({getattr(d, "slice_index", 0) for d in devices})


def build_hybrid_mesh(
    ici_spec: MeshSpec,
    dcn_spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Multi-slice mesh: ``dcn_spec`` axes span slices (DCN), ``ici_spec``
    axes stay within a slice (ICI torus).

    The resulting mesh's axis sizes are the per-axis product of the two
    specs; keep bandwidth-hungry axes (``model``, ``seq``) in ``ici_spec``
    and put ``data`` (one gradient all-reduce per step, latency-tolerant)
    across DCN — the multi-slice recipe the reference's NcclManager never
    had to express (single-slice GPUs).

    ``dcn_spec`` defaults to ``data=<n_slices>``.  With only one slice
    visible (CPU test meshes, single-slice pods) the per-axis product of
    the two specs is built over all devices via :func:`build_mesh` — the
    same combined shape as the multi-slice case, so elastic restore onto
    one slice keeps the mesh shape.
    """
    if devices is None:
        devices = jax.devices()
    slice_sizes: dict[int, int] = {}
    for d in devices:
        idx = getattr(d, "slice_index", 0)
        slice_sizes[idx] = slice_sizes.get(idx, 0) + 1
    n_slices = len(slice_sizes)
    if n_slices == 1:
        if dcn_spec is not None:
            # Keep the combined shape identical to the multi-slice case
            # (elastic restore onto one slice must not halve the mesh):
            # per-axis product, -1 wildcards preserved.
            merged = MeshSpec(*(
                -1 if -1 in (d, i) else d * i
                for d, i in zip(dcn_spec.sizes(), ici_spec.sizes())
            ))
            return build_mesh(merged, devices)
        return build_mesh(ici_spec, devices)
    if len(set(slice_sizes.values())) != 1:
        raise ValueError(
            f"slices have unequal device counts {slice_sizes}; a hybrid "
            "mesh needs uniform slices (whole slices lie along DCN axes)"
        )
    per_slice = len(devices) // n_slices
    dcn_spec = dcn_spec or MeshSpec(data=n_slices)
    dcn_shape = dcn_spec.resolve(n_slices)
    ici_shape = ici_spec.resolve(per_slice)
    try:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=list(devices),
            allow_split_physical_axes=True,
        )
    except (NotImplementedError, ValueError):
        # No physical-topology info.  Order devices slice-major, lay the
        # DCN axes over the slice dimension and the ICI axes within a
        # slice, then interleave (dcn_i, ici_i) per canonical axis — the
        # same layout create_hybrid_device_mesh produces, minus torus
        # awareness.  A plain reshape to the product shape would only be
        # correct when the DCN axes happen to be the outermost ones.
        n_axes = len(ici_shape)
        total = tuple(d * i for d, i in zip(dcn_shape, ici_shape))
        ordered = sorted(devices, key=lambda d: (getattr(d, "slice_index", 0),
                                                 getattr(d, "id", 0)))
        dev_array = np.empty(len(ordered), dtype=object)
        dev_array[:] = ordered
        dev_array = dev_array.reshape(*dcn_shape, *ici_shape)
        interleave = [ax for i in range(n_axes) for ax in (i, n_axes + i)]
        dev_array = dev_array.transpose(interleave).reshape(total)
    return Mesh(dev_array, CANONICAL_AXES)


# --- Strategy-zoo presets: each reference strategy is just a mesh shape. ---


def one_device_mesh(device: jax.Device | None = None) -> Mesh:
    """``OneDeviceStrategy`` equivalent: a 1×1×…×1 mesh on one device."""
    devices = [device] if device is not None else jax.local_devices()[:1]
    return build_mesh(MeshSpec(data=1), devices)


def mirrored_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """``MirroredStrategy`` equivalent: all *local* devices on the data axis."""
    return build_mesh(MeshSpec(data=-1), devices or jax.local_devices())


def multi_worker_mesh() -> Mesh:
    """``MultiWorkerMirroredStrategy`` equivalent: all *global* devices on
    ``data`` — slice-aware: on a multi-slice job the data axis is laid out
    with whole slices contiguous so the gradient all-reduce's intra-slice
    phase rides ICI and only the inter-slice phase touches DCN."""
    return build_hybrid_mesh(MeshSpec(data=-1), devices=jax.devices())


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """The batch axes present in ``mesh`` (for gradient psum / batch sharding)."""
    return tuple(a for a in BATCH_AXES if a in mesh.axis_names)


def replica_count(mesh: Mesh) -> int:
    """Number of data-parallel replicas (product of batch-axis sizes)."""
    return math.prod(mesh.shape[a] for a in data_axes(mesh))
