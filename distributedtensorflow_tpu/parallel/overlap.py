"""Collective–matmul overlap: bucketed backward-pass gradient sync.

Under GSPMD data parallelism the gradient all-reduce is implicit — XLA
materializes the cross-replica sum wherever the consuming op (the
optimizer update) forces it, which in practice parks the whole gradient
sync AFTER the backward pass: the ICI sits idle through the backward
matmuls and the MXU sits idle through the sync.  The classic fix (the
pjit LM scaling recipe, PAPERS.md 2204.06514; DDP gradient bucketing) is
to issue the collective for each layer group **as soon as its gradient
is produced**, so communication hides under the remaining backward
compute.

Mechanism — no scheduler, no side effects, exact numerics: every
parameter bucket is passed through a ``jax.custom_vjp`` **identity tag**
whose backward applies a GSPMD sharding constraint to the bucket's
cotangents (``collectives.gspmd_overlap_all_reduce`` /
``gspmd_overlap_reduce_scatter``).  The constraint pins the gradient
value's layout at that exact point of the backward graph, which forces
XLA to schedule the cross-replica reduction there — adjacent to the
producing matmuls, overlappable with everything still to run — instead
of deferring it to the update.  Because a sharding constraint is
numerically the identity, the bucketed step is bit-equivalent to the
unbucketed one (pinned by ``tests/test_overlap.py`` on an 8-device CPU
mesh, including composed with ``--zero``).

Buckets are **per-layer groups**: leaves grouped by their top-level
module path (``h0`` … ``h11``, ``wte``, …), with adjacent small groups
greedily merged up to ``bucket_bytes`` so tiny layers don't each pay a
collective launch.  One tag per bucket; tags are created once at plan
build so the jitted step's Python identities are stable across restarts
(the supervisor re-traces against the same plan).

Composition:

- **ZeRO** (``parallel/zero.py``): the backward hook chunks each
  gradient to the sharder's ``(degree, chunk)`` view and constrains it
  to the dim-0 batch-axes sharding — the reduce-scatter the weight
  update needs anyway, just issued early; ``ZeroSharder.apply_gradients``
  then finds the layout already satisfied.
- **Tensor parallelism**: the DP-flavor constraint targets each leaf's
  BOUND parameter spec, so model-axis-sharded gradients keep their
  layout and only the batch-axes reduction is forced early.
- **Gradient accumulation**: the tag fires once per microbatch, so
  ``accum_steps > 1`` trades ``accum_steps``× the collective volume for
  the overlap — worth it on DCN-free single-pod meshes, documented as
  the caveat it is (docs/API.md).

Telemetry: the bucket dispatches land in the span tracer and in
``collective_dispatch_seconds{op=..., overlapped="1"}``, so the PR-4
timeline and run_report's step-time section show the overlapped share;
the Trainer stamps ``overlap_buckets`` / ``overlap_coverage`` into every
metric record.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collectives
from . import zero as zero_lib

logger = logging.getLogger("distributedtensorflow_tpu")

PyTree = Any

__all__ = ["OverlapPlan", "plan_buckets"]


def _leaf_bytes(leaf) -> int:
    size = math.prod(leaf.shape) if leaf.shape else 1
    itemsize = getattr(leaf.dtype, "itemsize", None)
    if itemsize is None:
        itemsize = jax.numpy.dtype(leaf.dtype).itemsize
    return size * itemsize


def _group_key(path) -> str:
    """The per-layer-group key of one leaf path: its first path
    component (``h3/attn/qkv/kernel`` → ``h3``).  flax param trees put
    the block name first, so this is exactly "one bucket per transformer
    block" before merging."""
    if not path:
        return "<root>"
    p = path[0]
    key = getattr(p, "key", None)
    if key is None:
        key = getattr(p, "name", None)
    if key is None:
        key = getattr(p, "idx", p)
    return str(key)


def plan_buckets(
    param_shapes: PyTree, bucket_bytes: int
) -> list[list[int]]:
    """Group flattened-leaf indices into per-layer-group buckets.

    Leaves sharing a top-level module are never split; adjacent groups
    (in flatten order) merge greedily while the running size stays under
    ``bucket_bytes``.  Every leaf lands in exactly one bucket — coverage
    is total by construction."""
    leaves_with_path = jax.tree_util.tree_flatten_with_path(param_shapes)[0]
    groups: list[tuple[str, list[int], int]] = []
    for i, (path, leaf) in enumerate(leaves_with_path):
        key = _group_key(path)
        nbytes = _leaf_bytes(leaf)
        if groups and groups[-1][0] == key:
            groups[-1][1].append(i)
            groups[-1] = (key, groups[-1][1], groups[-1][2] + nbytes)
        else:
            groups.append((key, [i], nbytes))
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for _key, idxs, nbytes in groups:
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.extend(idxs)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


class OverlapPlan:
    """The compiled-in bucketing policy for one (mesh, model) pair.

    Build once per run with :meth:`build` and hand to
    ``train.make_train_step(..., overlap=plan)`` — the engine wraps the
    loss function so parameters flow through the bucket tags and every
    bucket's gradient sync is issued inside the backward pass.
    """

    def __init__(
        self,
        mesh: Mesh,
        buckets: Sequence[Sequence[int]],
        leaf_shardings: Sequence[NamedSharding],
        treedef,
        *,
        zero: "zero_lib.ZeroSharder | None" = None,
    ):
        self.mesh = mesh
        self.buckets = [list(b) for b in buckets]
        self.zero = zero
        self._leaf_shardings = list(leaf_shardings)
        self._treedef = treedef
        self._n_leaves = len(leaf_shardings)
        covered = sorted(i for b in self.buckets for i in b)
        if covered != list(range(self._n_leaves)):
            raise ValueError(
                f"buckets cover {len(covered)} leaf slots of "
                f"{self._n_leaves} (or cover one twice)"
            )
        #: Fraction of parameter BYTES whose gradient sync the plan
        #: issues in-backward.  1.0 by construction today; kept as data
        #: (not a constant) so a future skip-list shows up in telemetry.
        self.coverage = 1.0
        self._tags = [
            self._make_tag(list(bucket)) for bucket in self.buckets
        ]

    # --- construction -------------------------------------------------------

    @classmethod
    def build(
        cls,
        mesh: Mesh,
        param_shapes: PyTree,
        param_specs: PyTree,
        *,
        zero: "zero_lib.ZeroSharder | None" = None,
        bucket_bytes: int = 4 << 20,
    ) -> "OverlapPlan":
        """Plan buckets for a model.

        ``param_shapes``: abstract params (``jax.eval_shape`` of the
        init); ``param_specs``: their bound PartitionSpecs (the tree
        ``create_sharded_state`` returns) — the layout the DP-flavor
        constraint pins each gradient to.  ``zero`` switches the hook to
        the chunked reduce-scatter flavor at that sharder's degree.
        """
        leaves, treedef = jax.tree_util.tree_flatten(param_shapes)
        spec_leaves = jax.tree_util.tree_flatten(
            param_specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
        if len(spec_leaves) != len(leaves):
            raise ValueError(
                f"param_specs has {len(spec_leaves)} leaves, params have "
                f"{len(leaves)}"
            )
        shardings = [
            s if isinstance(s, NamedSharding) else NamedSharding(mesh, s)
            for s in spec_leaves
        ]
        buckets = plan_buckets(param_shapes, bucket_bytes)
        return cls(mesh, buckets, shardings, treedef, zero=zero)

    # --- the backward hook --------------------------------------------------

    def _sync_bucket(self, idxs: list[int], grads: list):
        """Issue one bucket's gradient sync (runs at TRACE time, inside
        the backward of the jitted step)."""
        if self.zero is not None:
            degree = self.zero.degree
            cshard = self.zero.chunk_sharding()
            chunked = [zero_lib.chunk_array(g, degree) for g in grads]
            chunked = collectives.gspmd_overlap_reduce_scatter(
                chunked, cshard
            )
            return [
                zero_lib.unchunk_array(c, g.shape)
                for c, g in zip(chunked, grads)
            ]
        shardings = [self._leaf_shardings[i] for i in idxs]
        return collectives.gspmd_overlap_all_reduce(grads, shardings)

    def _make_tag(self, idxs: list[int]) -> Callable:
        plan = self

        @jax.custom_vjp
        def tag(xs):
            return xs

        def fwd(xs):
            return xs, None

        def bwd(_, gs):
            return (plan._sync_bucket(idxs, list(gs)),)

        tag.defvjp(fwd, bwd)
        return tag

    # --- wiring -------------------------------------------------------------

    def tag_params(self, params: PyTree) -> PyTree:
        """Route every bucket of ``params`` through its identity tag; the
        forward is free (XLA elides it), the backward issues the sync."""
        leaves, treedef = jax.tree_util.tree_flatten(params)
        if len(leaves) != self._n_leaves:
            raise ValueError(
                f"params have {len(leaves)} leaves; the plan was built "
                f"for {self._n_leaves} — rebuild the OverlapPlan for "
                "this model"
            )
        out = list(leaves)
        for tag, idxs in zip(self._tags, self.buckets):
            tagged = tag([leaves[i] for i in idxs])
            for i, t in zip(idxs, tagged):
                out[i] = t
        return jax.tree_util.tree_unflatten(treedef, out)

    def wrap_loss_fn(self, loss_fn: Callable) -> Callable:
        """The engine hook: same LossFn contract, parameters tagged."""

        def wrapped(params, model_state, batch, rng):
            return loss_fn(self.tag_params(params), model_state, batch, rng)

        return wrapped

    def describe(self) -> dict:
        return {
            "buckets": len(self.buckets),
            "coverage": self.coverage,
            "mode": "reduce_scatter" if self.zero is not None
            else "all_reduce",
        }
