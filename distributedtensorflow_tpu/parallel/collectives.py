"""Typed collective wrappers over XLA's ICI/DCN collectives.

Replaces the reference stack's L0–L2 communication layers (SURVEY.md §2.2):
the C++ ring/NCCL collective executor plus the Python ``CrossDeviceOps`` /
``CollectiveReplicaLauncher`` dispatch.  Here the XLA compiler plays the role
of ``NcclManager`` — topology-aware algorithm selection, fusion, and
compute/communication overlap — so these wrappers stay thin: they add axis-name
typing, pytree conveniences, and the reference's gradient-packing policy
(``group_by_size``), and are valid inside ``jit`` / ``shard_map``.

No group/instance-key negotiation survives: XLA's static schedule makes the
reference's collective ordering tokens and launch-order deadlock workarounds
(SURVEY.md §5.2) unnecessary by construction.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .. import obs

PyTree = Any

AxisSpec = str | tuple[str, ...]

# Host-side dispatch timing (obs/): inside jit this measures trace/staging
# cost, called eagerly it measures the dispatch itself — either way it is
# the HOST's share of a collective, which is what lets a cross-host
# straggler row distinguish comms bookkeeping from compute.  Sub-ms
# buckets: dispatches are far below the step-time-oriented defaults.
_DISPATCH_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 0.001, 0.0025, 0.005,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)
_M_DISPATCH_S = obs.histogram(
    "collective_dispatch_seconds",
    "host-side dispatch/trace seconds of collective wrappers by op",
    buckets=_DISPATCH_BUCKETS,
)


def _timed_dispatch(fn=None, *, op: str | None = None,
                    overlapped: bool | None = None):
    """Route a collective wrapper's host-side time through the span tracer
    (``collective_<op>`` spans — children of the enclosing compile/step
    span when traced under jit) and the dispatch histogram.

    ``op`` overrides the histogram label (default: the function name) —
    the GSPMD constraint wrappers below use it so a reduce-scatter
    expressed as a sharding constraint lands under the same
    ``op=reduce_scatter`` label as the shard_map primitive.

    ``overlapped`` (non-None) adds an ``overlapped="0"|"1"`` label: the
    backward-pass bucketed gradient sync (``parallel/overlap.py``)
    dispatches through its own wrappers so the PR-4 timeline and the
    metric stream can tell an overlap-issued collective from the
    step-end one.  Wrappers without the flag keep their historical
    un-labeled series (field names in existing artifacts don't move).

    While a reactive-profiler window is open (``obs.capture``), the
    region is additionally labeled with a ``jax.profiler``
    ``TraceAnnotation`` so the captured host timeline names the
    collective being dispatched — the disambiguation a straggler-spread
    capture exists for.  The check is one module-attribute read, so the
    un-captured hot path pays nothing."""

    def decorate(f):
        label = op or f.__name__
        name = f"collective_{label}"
        hist_labels = {"op": label}
        if overlapped is not None:
            hist_labels["overlapped"] = "1" if overlapped else "0"

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            with obs.span(name):
                if obs.capture.capture_active():
                    with jax.profiler.TraceAnnotation(name):
                        out = f(*args, **kwargs)
                else:
                    out = f(*args, **kwargs)
            _M_DISPATCH_S.observe(time.perf_counter() - t0, **hist_labels)
            return out

        return wrapper

    return decorate(fn) if fn is not None else decorate


class ReduceOp(enum.Enum):
    """Reduction kinds, mirroring ``tf.distribute.ReduceOp`` (+ min/max)."""

    SUM = "sum"
    MEAN = "mean"
    MAX = "max"
    MIN = "min"


class Implementation(enum.Enum):
    """Reference-parity knob (``CommunicationImplementation`` — SURVEY.md §5.6).

    On TPU there is nothing to pick: XLA lowers to ICI/DCN automatically.
    Retained so reference configs parse; AUTO is the only honest value.
    """

    AUTO = "auto"
    RING = "ring"  # accepted, ignored (XLA chooses)
    NCCL = "nccl"  # accepted, ignored (no NCCL on TPU)


@dataclasses.dataclass(frozen=True)
class Options:
    """Collective tuning knobs (reference: ``tf.distribute.experimental
    .CommunicationOptions``, ``collective_util.py:117``).

    ``bytes_per_pack`` feeds :func:`packed_all_reduce`; ``timeout_seconds`` is
    honored by the watchdog in :mod:`distributedtensorflow_tpu.utils.watchdog`
    (XLA collectives cannot time out individually — a hang is surfaced by the
    coordination service / watchdog instead, SURVEY.md §5.2).
    """

    bytes_per_pack: int = 0  # 0 = one pack per leaf (no repacking)
    timeout_seconds: float | None = None
    implementation: Implementation = Implementation.AUTO


def _as_tuple(axis: AxisSpec) -> tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


@_timed_dispatch
def all_reduce(x: jax.Array, axis: AxisSpec, op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """All-reduce ``x`` over mesh axis/axes (inside shard_map/jit)."""
    axis = _as_tuple(axis)
    if op is ReduceOp.SUM:
        return lax.psum(x, axis)
    if op is ReduceOp.MEAN:
        return lax.pmean(x, axis)
    if op is ReduceOp.MAX:
        return lax.pmax(x, axis)
    if op is ReduceOp.MIN:
        return lax.pmin(x, axis)
    raise ValueError(f"unknown reduce op {op}")


def tree_all_reduce(
    tree: PyTree, axis: AxisSpec, op: ReduceOp = ReduceOp.SUM
) -> PyTree:
    """All-reduce every leaf of a pytree (the gradient-sync primitive).

    The XLA scheduler fuses/overlaps these; equivalent of the reference's
    ``batch_all_reduce`` (``cross_device_utils.py:407``).
    """
    return jax.tree.map(functools.partial(all_reduce, axis=axis, op=op), tree)


@_timed_dispatch
def all_gather(
    x: jax.Array, axis: AxisSpec, *, gather_axis: int = 0, tiled: bool = True
) -> jax.Array:
    """Gather shards along ``gather_axis`` from all devices on mesh ``axis``.

    Reference: ``Strategy.gather`` / ``collective_ops.all_gather_v2``
    (SURVEY.md §1 L1).
    """
    return lax.all_gather(x, _as_tuple(axis), axis=gather_axis, tiled=tiled)


@_timed_dispatch
def reduce_scatter(
    x: jax.Array, axis: AxisSpec, *, scatter_axis: int = 0
) -> jax.Array:
    """Sum-reduce then scatter shards along ``scatter_axis``.

    The ZeRO building block (reference analogue: ``NcclReduceScatterer``,
    ``collective_nccl_reducer.h:34``).
    """
    return lax.psum_scatter(x, _as_tuple(axis), scatter_dimension=scatter_axis, tiled=True)


def tree_reduce_scatter(
    tree: PyTree, axis: AxisSpec, *, scatter_axis: int = 0
) -> PyTree:
    """Reduce-scatter every leaf of a pytree — the ZeRO gradient-sync
    primitive (each replica receives the cross-replica sum of only its
    shard; shard_map/jit contexts with bound axis names)."""
    return jax.tree.map(
        functools.partial(reduce_scatter, axis=axis,
                          scatter_axis=scatter_axis),
        tree,
    )


def tree_all_gather(
    tree: PyTree, axis: AxisSpec, *, gather_axis: int = 0
) -> PyTree:
    """All-gather every leaf of a pytree — the ZeRO parameter
    re-assembly primitive (inverse of :func:`tree_reduce_scatter`)."""
    return jax.tree.map(
        functools.partial(all_gather, axis=axis, gather_axis=gather_axis),
        tree,
    )


def _constrain_tree(tree: PyTree, shardings) -> PyTree:
    """``with_sharding_constraint`` over a pytree; ``shardings`` is one
    ``Sharding`` applied to every leaf, or a matching pytree of them."""
    if isinstance(shardings, jax.sharding.Sharding):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, shardings), tree
        )
    return jax.tree.map(
        jax.lax.with_sharding_constraint, tree, shardings,
    )


@_timed_dispatch(op="reduce_scatter")
def gspmd_reduce_scatter(tree: PyTree, shardings) -> PyTree:
    """Constrain partial-sum gradients to a sharded layout inside a
    GSPMD-jitted program — XLA lowers the cross-replica sum feeding the
    constraint to a reduce-scatter (the ZeRO weight-update path on
    jax versions whose partial-manual shard_map lowering is limited; see
    parallel/zero.py).  Timed under ``op=reduce_scatter`` like the
    shard_map primitive above."""
    return _constrain_tree(tree, shardings)


@_timed_dispatch(op="all_gather")
def gspmd_all_gather(tree: PyTree, shardings) -> PyTree:
    """Constrain shard-local values back to their full layout inside a
    GSPMD-jitted program — XLA lowers the constraint to an all-gather
    (the ZeRO post-update parameter re-assembly).  Timed under
    ``op=all_gather``."""
    return _constrain_tree(tree, shardings)


@_timed_dispatch(op="all_reduce", overlapped=True)
def gspmd_overlap_all_reduce(tree: PyTree, shardings) -> PyTree:
    """Backward-pass bucketed gradient sync, data-parallel flavor: pin a
    gradient bucket to its bound parameter layout the moment the backward
    produces it, so XLA schedules the cross-replica sum (an all-reduce
    under pure DP; a reduce over the batch axes only, under TP layouts)
    DURING the remaining backward matmuls instead of after them
    (``parallel/overlap.py``).  Numerically an identity — it is a layout
    constraint on an already-global value.  Timed under
    ``op=all_reduce, overlapped=1``."""
    return _constrain_tree(tree, shardings)


@_timed_dispatch(op="reduce_scatter", overlapped=True)
def gspmd_overlap_reduce_scatter(tree: PyTree, shardings) -> PyTree:
    """Backward-pass bucketed gradient sync, ZeRO flavor: constrain a
    bucket's chunked ``(degree, chunk)`` gradient views to the dim-0
    batch-axes sharding inside the backward, so the reduce-scatter the
    weight-update sharding needs anyway is issued per layer group as the
    grads appear (``parallel/overlap.py``; composes with
    ``parallel/zero.py`` — the update-time constraint then finds the
    layout already satisfied).  Timed under
    ``op=reduce_scatter, overlapped=1``."""
    return _constrain_tree(tree, shardings)


@_timed_dispatch
def broadcast(x: jax.Array, axis: AxisSpec, *, src: int = 0) -> jax.Array:
    """Broadcast the value from mesh-position ``src`` on ``axis`` to all.

    Reference: hierarchical tree broadcast / ``broadcast_send_v2``
    (SURVEY.md §2.2).  XLA lowers the masked psum to an optimal broadcast.
    ``where`` (not multiply) masking: NaN/Inf garbage in non-source shards
    must not poison the sum.
    """
    axis = _as_tuple(axis)
    idx = lax.axis_index(axis[0]) if len(axis) == 1 else _linear_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def _linear_index(axes: tuple[str, ...]) -> jax.Array:
    idx = jnp.int32(0)
    for a in axes:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


@_timed_dispatch
def permute(
    x: jax.Array, axis: str, perm: Sequence[tuple[int, int]]
) -> jax.Array:
    """Point-to-point permutation (reference: ``Permuter``, ``permuter.h:45``)."""
    return lax.ppermute(x, axis, perm=list(perm))


@_timed_dispatch
def shift(x: jax.Array, axis: str, *, offset: int = 1) -> jax.Array:
    """Rotate shards around mesh ``axis`` — the ring-attention step primitive."""
    n = lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


@_timed_dispatch
def all_to_all(
    x: jax.Array, axis: str, *, split_axis: int, concat_axis: int, tiled: bool = True
) -> jax.Array:
    """All-to-all resharding (Ulysses head↔sequence swap; MoE token dispatch).

    Reference exposes only the generic op (``collective_ops.py:501``); here it
    is a first-class primitive (SURVEY.md §5.7).
    """
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


# --- Gradient packing (reference ``group_by_size``, cross_device_ops.py:1150).
#
# XLA already fuses small all-reduces, so packing is OFF by default; it exists
# for reference parity and for experiments on DCN-spanning meshes where fewer,
# larger collectives can win.


def pack_by_size(
    leaves: Sequence[jax.Array], bytes_per_pack: int
) -> list[list[int]]:
    """Greedy bucketing of leaf indices, preserving order within a pack.

    Mirrors the reference's ``group_by_size`` (leaves are packed in reverse
    gradient order there; order is the caller's concern here).  A pack never
    mixes dtypes: concatenating mixed-dtype leaves would silently promote
    (bf16 grads becoming fp32), changing output dtypes vs the unpacked path.
    """
    if bytes_per_pack <= 0:
        return [[i] for i in range(len(leaves))]
    packs: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * leaf.dtype.itemsize
        if cur and (cur_bytes + nbytes > bytes_per_pack or leaf.dtype != cur_dtype):
            packs.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = leaf.dtype
    if cur:
        packs.append(cur)
    return packs


def packed_all_reduce(
    tree: PyTree,
    axis: AxisSpec,
    *,
    options: Options | None = None,
    op: ReduceOp = ReduceOp.SUM,
) -> PyTree:
    """All-reduce a pytree with optional flatten-concat-reduce-split packing."""
    options = options or Options()
    leaves, treedef = jax.tree.flatten(tree)
    if options.bytes_per_pack <= 0:
        return treedef.unflatten(
            [all_reduce(leaf, axis, op) for leaf in leaves]
        )
    packs = pack_by_size(leaves, options.bytes_per_pack)
    out: list[jax.Array | None] = [None] * len(leaves)
    for pack in packs:
        if len(pack) == 1:
            i = pack[0]
            out[i] = all_reduce(leaves[i], axis, op)
            continue
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in pack])
        reduced = all_reduce(flat, axis, op)
        offset = 0
        for i in pack:
            n = leaves[i].size
            out[i] = reduced[offset : offset + n].reshape(leaves[i].shape)
            offset += n
    return treedef.unflatten(out)
