"""Async parameter server: true stale-gradient training (reference config #5).

Reference semantics (SURVEY.md §3.3, §2.1 ``ParameterServerStrategyV2``
``parameter_server_strategy_v2.py:77`` + ``ClusterCoordinator``
``coordinator/cluster_coordinator.py:1399``): variables are partitioned
across parameter-server tasks, every worker loops pull → grad → push with
**no synchronization against its peers** — gradients are applied to whatever
the current parameters are (stale gradients), and training continues through
worker loss because workers are stateless.

Rounds 1-2 replaced the *capability* (sparse models bigger than one host)
with sync sharded-embedding SPMD and replaced the dispatcher with
:mod:`.coordinator`; the async *update semantics* remained a documented gap
(PARITY.md "Known gaps").  This module closes it.

TPU-native stance: the device loop stays sync SPMD — there is no async
update on ICI, and pretending otherwise would fight XLA.  Async PS is a
**host-side training mode** for the sparse/recsys family the reference runs
on parameter servers (Wide&Deep): exactly where async PS is still the
published idiom (embedding-dominated models, update cost ≪ transfer cost,
tolerance to staleness).  Dense accelerator workloads keep the sync engine.

Architecture (all host-side, reusing the data-service wire format —
``uint64 LE length + JSON frame [+ npz frame]``):

- :class:`PSServer` — one PS task: owns a shard of the flat param dict plus
  the optimizer state *for that shard* (reference: optimizer slot variables
  live with their variable on the PS).  ``push`` applies the update
  immediately under the shard lock and bumps a version counter; the applied
  staleness (``version_at_apply − version_at_pull``) is recorded per push.
- :func:`partition_params` — round-robin-by-size placement of variables
  onto PS shards, with large axis-0-splittable variables first split by the
  sharded-variable partitioners (``sharding.Partitioner``) — the
  ``ShardedVariable`` layout (reference ``sharded_variable.py:843``).
- :class:`AsyncPSClient` — pull/reassemble the full tree, split/push grads.
- :class:`AsyncPSTrainer` — orchestration: PS servers as daemon threads in
  the chief, workers as OS processes (real death) computing grads with
  jitted CPU JAX; ``kill_worker`` is the fault-injection path and the
  surviving workers keep the global version advancing (elasticity).
- **Cluster launcher path** (the reference's legacy TF_CONFIG ps/worker
  tiers, SURVEY.md §1 L7): :func:`build_cluster_pieces` derives
  byte-identical shards + placement plan on every task from the shared CLI
  flags, a ``ps`` task serves its shard via :meth:`PSServer.serve_until`,
  and a ``worker``/``chief`` task runs :func:`worker_loop` against the
  ``cluster["ps"]`` addresses — wired in ``train.py`` job auto-detection.

Per-shard optimizer correctness: shards are applied independently, which is
exact for elementwise transforms (sgd/adagrad/adam/adamw without global-norm
clipping) — the same restriction the reference's PS placement imposes, where
each PS applies updates to its variables in isolation.  Global-norm clipping,
if wanted, must happen worker-side before the push (as the reference does);
an optax transform that mixes information across variables would silently
become per-shard here, so keep PS optimizers elementwise.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import multiprocessing as mp
import os
import socketserver
import threading
import time
from typing import Any, Callable, Iterator, Mapping, Sequence

import numpy as np

from ..data.service import (
    _recv_msg,
    _rpc,
    _send_msg,
    decode_batch,
    encode_batch,
)
from .sharding import Partitioner

logger = logging.getLogger("distributedtensorflow_tpu")

FlatParams = dict[str, np.ndarray]

#: Per-connection socket timeout inside the PS request handler: bounds how
#: long a wedged peer (half-open TCP) can occupy a handler thread.
_HANDLER_SOCKET_TIMEOUT_S = 30.0
#: Response-send timeout.  settimeout() is a TOTAL deadline for sendall
#: (not an idle bound), so a live-but-slow worker pulling a large shard
#: over a thin link needs far more than the receive bound; this only
#: exists to eventually unstick a truly dead peer.
_HANDLER_SEND_TIMEOUT_S = 600.0
#: serve_until's post-done drain cap: after the exit condition holds, wait
#: at most this long for inflight handlers before returning anyway.
_DRAIN_CAP_S = 5.0


# --- placement plan ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Piece:
    """One contiguous axis-0 slice of a variable living on one PS."""

    ps: int
    start: int
    stop: int  # 0/0 for unsplit (whole-array) placement

    def wire_key(self, key: str) -> str:
        if self.stop == 0:
            return key
        return f"{key}@{self.start}:{self.stop}"


@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    """Where every variable (piece) lives; JSON-serializable for workers."""

    num_ps: int
    pieces: dict[str, tuple[_Piece, ...]]

    def to_json(self) -> str:
        return json.dumps({
            "num_ps": self.num_ps,
            "pieces": {
                k: [[p.ps, p.start, p.stop] for p in v]
                for k, v in self.pieces.items()
            },
        })

    @staticmethod
    def from_json(s: str) -> "PlacementPlan":
        raw = json.loads(s)
        return PlacementPlan(
            num_ps=raw["num_ps"],
            pieces={
                k: tuple(_Piece(*p) for p in v)
                for k, v in raw["pieces"].items()
            },
        )


def partition_params(
    flat: FlatParams,
    num_ps: int,
    partitioner: Partitioner | None = None,
) -> tuple[list[FlatParams], PlacementPlan]:
    """Place variables on ``num_ps`` shards (reference §3.3 placement).

    Greedy round-robin by bytes onto the least-loaded PS; a variable the
    ``partitioner`` wants split (and whose axis 0 allows it) is first cut
    into up to ``num_ps`` axis-0 pieces — the ``ShardedVariable`` embedding
    split (``sharded_variable.py:84-176`` semantics: axis-0 only).
    """
    shards: list[FlatParams] = [{} for _ in range(num_ps)]
    loads = [0] * num_ps
    pieces: dict[str, tuple[_Piece, ...]] = {}
    # Big-first for better balance.
    for key, arr in sorted(flat.items(), key=lambda kv: -kv[1].nbytes):
        arr = np.asarray(arr)
        n_sub = 1
        if (
            partitioner is not None
            and arr.ndim >= 1
            and arr.shape[0] >= 2
        ):
            want = partitioner.num_shards(arr.shape, arr.dtype)
            n_sub = max(1, min(want, num_ps, arr.shape[0]))
        if n_sub == 1:
            ps = loads.index(min(loads))
            shards[ps][key] = arr
            loads[ps] += arr.nbytes
            pieces[key] = (_Piece(ps, 0, 0),)
            continue
        bounds = np.linspace(0, arr.shape[0], n_sub + 1).astype(int)
        plist = []
        for i in range(n_sub):
            start, stop = int(bounds[i]), int(bounds[i + 1])
            piece = arr[start:stop]
            ps = loads.index(min(loads))
            p = _Piece(ps, start, stop)
            shards[ps][p.wire_key(key)] = piece
            loads[ps] += piece.nbytes
            plist.append(p)
        pieces[key] = tuple(plist)
    return shards, PlacementPlan(num_ps=num_ps, pieces=pieces)


def reassemble(plan: PlacementPlan, per_ps: Sequence[FlatParams]) -> FlatParams:
    """Inverse of :func:`partition_params`: concat pieces along axis 0."""
    out: FlatParams = {}
    for key, plist in plan.pieces.items():
        if len(plist) == 1 and plist[0].stop == 0:
            out[key] = per_ps[plist[0].ps][key]
        else:
            out[key] = np.concatenate(
                [per_ps[p.ps][p.wire_key(key)] for p in plist], axis=0
            )
    return out


def split_like(plan: PlacementPlan, flat: FlatParams) -> list[FlatParams]:
    """Split a full flat tree (e.g. gradients) back into per-PS dicts."""
    per_ps: list[FlatParams] = [{} for _ in range(plan.num_ps)]
    for key, plist in plan.pieces.items():
        arr = flat[key]
        for p in plist:
            piece = arr if p.stop == 0 else arr[p.start:p.stop]
            per_ps[p.ps][p.wire_key(key)] = np.asarray(piece)
    return per_ps


# --- PS server --------------------------------------------------------------

class PSServer:
    """One parameter-server task: a param shard + its optimizer state.

    The push path is the async heart: apply-on-receipt under the shard
    lock, no cross-worker barrier, version counter + staleness histogram.
    """

    def __init__(
        self,
        shard: FlatParams,
        make_optimizer: Callable[[], Any],
        *,
        port: int = 0,
        bind: str = "127.0.0.1",
    ):
        import jax
        import jax.numpy as jnp

        # PS state lives on host CPU even when the chief also owns a TPU:
        # async PS is the host-side path; the device stays with the sync
        # engine.  (Under JAX_PLATFORMS=axon there is no cpu backend — fall
        # back to default placement, which is then the only backend.)
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            cpu = None
        self._lock = threading.Lock()
        self._params = {
            k: jax.device_put(jnp.asarray(v), cpu) for k, v in shard.items()
        }
        opt = make_optimizer()
        self._opt_state = opt.init(self._params)

        def _apply(grads, opt_state, params):
            updates, new_state = opt.update(grads, opt_state, params)
            import optax

            return optax.apply_updates(params, updates), new_state

        self._apply = jax.jit(_apply)
        self._cpu = cpu
        self._version = 0
        self._updates = 0
        self._inflight = 0  # requests mid-handler (serve_until drains)
        self._staleness: dict[int, int] = {}
        self._push_by_worker: dict[int, int] = {}
        self._stopping = threading.Event()

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one request per connection
                # Count the request from BEFORE the receive: if inflight
                # were only incremented after _recv_msg returned, a push
                # that has been fully received but not yet counted could
                # still be torn down by a stop() racing serve_until's
                # drain.  The socket timeout bounds how long a wedged peer
                # can hold the inflight count (serve_until additionally
                # caps its drain, so a dead client can never pin the task).
                self.request.settimeout(_HANDLER_SOCKET_TIMEOUT_S)
                with outer._lock:
                    outer._inflight += 1
                try:
                    try:
                        header, data = _recv_msg(self.request)
                    except (ConnectionError, json.JSONDecodeError, OSError):
                        return
                    # Request received — switch to the (much longer) send
                    # deadline before building/streaming the response.
                    self.request.settimeout(_HANDLER_SEND_TIMEOUT_S)
                    self._handle(header, data)
                except OSError:
                    return  # peer vanished mid-response; nothing to unwind
                finally:
                    with outer._lock:
                        outer._inflight -= 1

            def _handle(self, header, data) -> None:
                op = header.get("op")
                if op == "pull":
                    # _push REPLACES the params dict (never mutates), so a
                    # consistent snapshot is just the reference + version;
                    # the expensive encode runs outside the lock and never
                    # stalls concurrent pushes (the barrier-free property
                    # this module exists for).
                    with outer._lock:
                        version = outer._version
                        snapshot = outer._params
                    blob = encode_batch(
                        {k: np.asarray(v) for k, v in snapshot.items()}
                    )
                    _send_msg(self.request, {"version": version}, blob)
                elif op == "push":
                    grads = decode_batch(data)
                    try:
                        stale = outer._push(
                            grads, int(header["pulled_version"]),
                            int(header.get("worker", -1)),
                        )
                    except KeyError as e:
                        _send_msg(self.request, {"error": str(e)})
                        return
                    with outer._lock:
                        version = outer._version
                    _send_msg(
                        self.request,
                        {"version": version, "staleness": stale},
                    )
                elif op == "stats":
                    with outer._lock:
                        _send_msg(self.request, {
                            "version": outer._version,
                            "updates": outer._updates,
                            "staleness_hist": {
                                str(k): v for k, v in outer._staleness.items()
                            },
                            "pushes_by_worker": {
                                str(k): v
                                for k, v in outer._push_by_worker.items()
                            },
                            "keys": sorted(outer._params),
                        })
                elif op == "stop":
                    outer._stopping.set()
                    _send_msg(self.request, {"ok": True})
                    threading.Thread(
                        target=outer._server.shutdown, daemon=True
                    ).start()
                else:
                    _send_msg(self.request, {"error": f"unknown op {op!r}"})

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._last_push_t = time.monotonic()
        self._server = Server((bind, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"ps-server-{self.port}",
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _push(self, grads: FlatParams, pulled_version: int, worker: int) -> int:
        import jax
        import jax.numpy as jnp

        g = {
            k: jax.device_put(jnp.asarray(v), self._cpu)
            for k, v in grads.items()
        }
        with self._lock:
            if set(g) != set(self._params):
                raise KeyError(
                    f"push keys {sorted(g)[:3]}… do not match shard keys"
                )
            staleness = self._version - pulled_version
            self._params, self._opt_state = self._apply(
                g, self._opt_state, self._params
            )
            self._version += 1
            self._updates += 1
            self._staleness[staleness] = self._staleness.get(staleness, 0) + 1
            self._push_by_worker[worker] = self._push_by_worker.get(worker, 0) + 1
            self._last_push_t = time.monotonic()
        return staleness

    def serve_until(
        self,
        total_updates: int | None = None,
        *,
        idle_timeout_s: float | None = None,
        startup_grace_s: float | None = None,
        poll_s: float = 0.2,
    ) -> int:
        """Block this thread until the shard has absorbed ``total_updates``
        pushes, ``stop`` arrives, or no push for ``idle_timeout_s``.  The
        standalone-PS-task loop for the cluster launcher path (reference: a
        ps task blocks in ``server.join()``, SURVEY.md §1 L7
        run_distributed.sh / §5.6 TF_CONFIG).  Returns the final version.

        Before the FIRST push the clock uses ``startup_grace_s`` instead
        (None = idle_timeout_s): cluster tasks start unordered and the
        workers' interpreter/model startup can far exceed a reasonable
        steady-state idle bound — with one clock for both, the ps tier
        gives up exactly when slow workers are about to connect and the
        cluster deadlocks into "PS tasks unreachable" (observed three
        times under a loaded 1-core box, 2026-08-01, at every deadline
        tried: the race scales with the numbers).  A dead cluster still
        exits: the grace is finite, just sized for startup rather than
        steady-state idleness."""
        done_since: float | None = None
        with self._lock:
            first_version = self._version
        while True:
            with self._lock:
                version = self._version
                last = self._last_push_t
                inflight = self._inflight
            # Drain before returning: the budget-completing push's handler
            # may still be writing its response, and returning here lets
            # the caller stop()/exit and tear the daemon thread down
            # mid-send (the worker would see a connection reset).  The
            # drain is CAPPED: a peer that wedged mid-request (half-open
            # TCP, stalled host) must not pin the ps task forever — after
            # _DRAIN_CAP_S we return anyway and let stop() reset it.
            no_push_yet = version == first_version
            bound = (
                startup_grace_s
                if (no_push_yet and startup_grace_s is not None)
                else idle_timeout_s
            )
            done = (
                (total_updates is not None and version >= total_updates)
                or self._stopping.is_set()
                or (bound is not None and time.monotonic() - last > bound)
            )
            if done:
                if done_since is None:
                    done_since = time.monotonic()
                if (
                    inflight == 0
                    or time.monotonic() - done_since > _DRAIN_CAP_S
                ):
                    return version
            else:
                done_since = None
            time.sleep(poll_s if not done else 0.01)

    def params(self) -> FlatParams:
        with self._lock:
            snapshot = self._params
        return {k: np.asarray(v) for k, v in snapshot.items()}

    def stop(self) -> None:
        self._stopping.set()
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


# --- client -----------------------------------------------------------------


class PSUnavailableError(ConnectionError):
    """A PS task is unreachable — fatal, as in the reference (§3.3)."""


class AsyncPSClient:
    """Worker-side pull/push against the PS group."""

    def __init__(self, addrs: Sequence[str], plan: PlacementPlan,
                 *, worker_id: int = -1, timeout: float = 60.0):
        if len(addrs) != plan.num_ps:
            raise ValueError(f"{len(addrs)} addrs for {plan.num_ps}-PS plan")
        self._addrs = list(addrs)
        self._plan = plan
        self._worker_id = worker_id
        self._timeout = timeout

    def _rpc(self, ps: int, request: dict, data: bytes | None = None):
        try:
            if data is None:
                from ..net.rpc import RetryPolicy  # noqa: PLC0415

                # Single-shot with an honest endpoint identity: PS loss
                # is FATAL by contract (the reference's semantics) — the
                # net substrate's default retries would mask it, and the
                # default data_worker label would render PS traffic as
                # data-plane traffic in every rpc_* time series.
                return _rpc(
                    self._addrs[ps], request, timeout=self._timeout,
                    endpoint=f"peer:ps{ps}",
                    policy=RetryPolicy(deadline_s=self._timeout,
                                       max_attempts=1),
                )
            import socket as socket_mod

            host, port = self._addrs[ps].rsplit(":", 1)
            with socket_mod.create_connection(
                (host, int(port)), timeout=self._timeout
            ) as s:
                _send_msg(s, request, data)
                return _recv_msg(s)
        except (ConnectionError, OSError, TimeoutError) as e:
            raise PSUnavailableError(
                f"PS {ps} at {self._addrs[ps]}: {e!r}"
            ) from e

    def pull(self) -> tuple[FlatParams, list[int]]:
        """Fetch all shards; returns (full flat params, per-PS versions)."""
        per_ps, versions = [], []
        for ps in range(self._plan.num_ps):
            header, blob = self._rpc(ps, {"op": "pull"})
            per_ps.append(decode_batch(blob))
            versions.append(int(header["version"]))
        return reassemble(self._plan, per_ps), versions

    def push(self, flat_grads: FlatParams, versions: Sequence[int]) -> dict:
        """Push grads; applied immediately per shard (stale OK)."""
        stats = {"staleness": [], "version": []}
        for ps, shard in enumerate(split_like(self._plan, flat_grads)):
            header, _ = self._rpc(
                ps,
                {"op": "push", "pulled_version": versions[ps],
                 "worker": self._worker_id},
                encode_batch(shard),
            )
            if "error" in header:
                raise RuntimeError(f"PS {ps} rejected push: {header['error']}")
            stats["staleness"].append(int(header["staleness"]))
            stats["version"].append(int(header["version"]))
        return stats

    def stats(self) -> list[dict]:
        return [
            self._rpc(ps, {"op": "stats"})[0]
            for ps in range(self._plan.num_ps)
        ]


# --- worker process ---------------------------------------------------------


def _flatten(tree: Mapping) -> FlatParams:
    from flax import traverse_util

    return {
        "/".join(k): np.asarray(v)
        for k, v in traverse_util.flatten_dict(tree).items()
    }


def _unflatten(flat: Mapping[str, Any]) -> dict:
    from flax import traverse_util

    return traverse_util.unflatten_dict(
        {tuple(k.split("/")): v for k, v in flat.items()}
    )


def worker_loop(
    worker_id: int,
    num_workers: int,
    addrs: Sequence[str],
    plan: PlacementPlan,
    spec: dict,
) -> tuple[list[float], list[int]]:
    """The async-PS worker: pull → grad → push for ``spec["steps"]`` steps.

    Rebuilds the workload by name in-process (the same pattern the
    reference uses, where each worker re-traces the train fn against the
    PS-resident variables) and computes gradients with jitted JAX on the
    caller's current platform — force CPU before calling if this process
    must not claim an accelerator (see :func:`_async_worker_main`).
    Returns ``(per-step losses, per-push staleness)``.
    """
    import jax
    import jax.numpy as jnp

    from ..data.input_pipeline import InputContext
    from ..workloads import get_workload

    wl = get_workload(
        spec["workload"], test_size=spec.get("test_size", True),
        global_batch_size=spec["batch_size"] * num_workers,
    )
    ctx = InputContext(
        num_input_pipelines=num_workers,
        input_pipeline_id=worker_id,
        global_batch_size=spec["batch_size"] * num_workers,
    )
    data = wl.input_fn(ctx, spec.get("seed", 0))
    client = AsyncPSClient(addrs, plan, worker_id=worker_id)
    rng = jax.random.PRNGKey(1000 + worker_id)

    def loss_of(params, batch, rng):
        loss, _aux = wl.loss_fn(params, {}, batch, rng)
        return loss

    grad_fn = jax.jit(jax.value_and_grad(loss_of))

    losses: list[float] = []
    staleness: list[int] = []
    for _step in range(spec["steps"]):
        flat, versions = client.pull()
        params = jax.tree.map(jnp.asarray, _unflatten(flat))
        batch = next(data)
        rng, sub = jax.random.split(rng)
        loss, grads = grad_fn(params, batch, sub)
        stats = client.push(_flatten(grads), versions)
        losses.append(float(loss))
        staleness.extend(stats["staleness"])
        if spec.get("sleep_s"):
            time.sleep(spec["sleep_s"])
    return losses, staleness


def _async_worker_main(
    worker_id: int,
    num_workers: int,
    addrs: list[str],
    plan_json: str,
    spec: dict,
    queue,
) -> None:
    """Child main for spawned workers (module-level: spawn pickles it)."""
    # Workers compute grads on host CPU unconditionally: the TPU chip stays
    # with the sync engine, and the inherited JAX_PLATFORMS=axon (this
    # image's sitecustomize) must not claim the device from a grad worker —
    # same override the testing MultiProcessRunner applies to its children.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    losses, staleness = worker_loop(
        worker_id, num_workers, addrs,
        PlacementPlan.from_json(plan_json), spec,
    )
    queue.put((worker_id, losses, staleness))


def build_cluster_pieces(
    spec: dict,
    num_ps: int,
    num_workers: int,
    partitioner: Partitioner | None = None,
    make_optimizer: Callable[[], Any] | None = None,
    *,
    workload_obj=None,
):
    """Deterministic (workload, shards, plan, make_optimizer) for a PS job.

    Every task of a TF_CONFIG-launched PS cluster (ps tasks, worker tasks,
    the chief) calls this with the SAME CLI flags and seed and gets
    byte-identical initial shards and an identical placement plan — so no
    plan/params wire transfer is needed at bootstrap, exactly the
    launcher contract the reference's per-task TF_CONFIG scripts rely on
    (same flags on every task, SURVEY.md §5.6).
    """
    import jax

    if workload_obj is not None:
        wl = workload_obj  # caller already built it (same spec fields)
    else:
        from ..workloads import get_workload

        wl = get_workload(
            spec["workload"], test_size=spec.get("test_size", True),
            global_batch_size=spec["batch_size"] * num_workers,
        )
    variables = wl.init_fn(jax.random.PRNGKey(spec.get("seed", 0)))
    extra = set(variables) - {"params"}
    if extra:
        # Mutable collections (batch_stats etc.) have no PS placement
        # story — the reference's PS path is likewise params-only
        # (BN-free sparse/recsys models). Fail here, not in every worker.
        raise ValueError(
            f"async-PS supports params-only workloads; "
            f"{spec['workload']!r} also has collections {sorted(extra)} "
            "(e.g. batch norm) — use the sync engine for it"
        )
    flat = _flatten(variables["params"])
    shards, plan = partition_params(flat, num_ps, partitioner)
    return wl, shards, plan, (make_optimizer or wl.make_optimizer)


# --- orchestration ----------------------------------------------------------


class AsyncPSTrainer:
    """Drive async-PS training for a workload preset.

    Usage::

        t = AsyncPSTrainer("widedeep", num_ps=2, num_workers=2,
                           steps=40, batch_size=64)
        t.start()
        t.join()
        loss0, lossN = t.first_last_mean_loss()
        params = t.current_params()     # live (possibly mid-push) snapshot
        t.stop()

    Workers are real OS processes; :meth:`kill_worker` SIGKILLs one and the
    rest keep pushing (the reference's workers-are-stateless elasticity).
    PS tasks are daemon threads in this process — a PS death is fatal by
    design, as in the reference (``PSUnavailableError``).
    """

    def __init__(
        self,
        workload: str,
        *,
        num_ps: int = 2,
        num_workers: int = 2,
        steps: int = 20,
        batch_size: int = 64,
        test_size: bool = True,
        partitioner: Partitioner | None = None,
        make_optimizer: Callable[[], Any] | None = None,
        seed: int = 0,
        worker_sleep_s: float = 0.0,
    ):
        self._spec = {
            "workload": workload, "steps": steps, "batch_size": batch_size,
            "test_size": test_size, "seed": seed, "sleep_s": worker_sleep_s,
        }
        self._num_workers = num_workers
        wl, shards, self._plan, self._make_opt = build_cluster_pieces(
            self._spec, num_ps, num_workers, partitioner, make_optimizer
        )
        self._servers = [
            PSServer(shard, self._make_opt) for shard in shards
        ]
        self._addrs = [s.address for s in self._servers]
        self._workload = wl
        self._ctx = mp.get_context("spawn")
        self._queue = self._ctx.Queue()
        self._procs: dict[int, mp.Process] = {}
        self._results: dict[int, tuple[list[float], list[int]]] = {}
        self._killed: set[int] = set()

    # -- lifecycle

    def start(self) -> "AsyncPSTrainer":
        for i in range(self._num_workers):
            self._spawn(i)
        return self

    def _spawn(self, worker_id: int) -> None:
        p = self._ctx.Process(
            target=_async_worker_main,
            args=(worker_id, self._num_workers, self._addrs,
                  self._plan.to_json(), self._spec, self._queue),
            name=f"async-ps-worker-{worker_id}",
            daemon=True,
        )
        p.start()
        self._procs[worker_id] = p

    def kill_worker(self, worker_id: int) -> None:
        """Fault injection: the worker dies mid-loop; training continues."""
        self._killed.add(worker_id)
        self._procs[worker_id].kill()

    def respawn_worker(self, worker_id: int) -> None:
        """Elastic re-join: a replacement worker enters the pull/push loop."""
        self._procs[worker_id].join(timeout=5)
        self._spawn(worker_id)

    def join(self, timeout: float = 300.0) -> None:
        """Wait for all *live* workers to finish their step budget.

        Deliberately killed workers (:meth:`kill_worker`) are tolerated —
        that is the elasticity contract.  A worker that crashes on its own
        (nonzero exit without a kill) is an application error and raises,
        matching the coordinator's parked-error semantics: a run where
        every worker silently died must not report success.
        """
        deadline = time.monotonic() + timeout
        while True:
            self._drain()
            crashed = [
                i for i, p in self._procs.items()
                if i not in self._results and i not in self._killed
                and p.exitcode not in (0, None)
            ]
            if crashed:
                raise RuntimeError(
                    f"async-PS worker(s) {crashed} exited "
                    f"{[self._procs[i].exitcode for i in crashed]} without "
                    "being killed — check worker stderr"
                )
            expected = sum(
                1 for i, p in self._procs.items()
                if i not in self._results and i not in self._killed
            )
            if expected == 0:
                return
            if time.monotonic() > deadline:
                raise TimeoutError("async-PS join timed out")
            time.sleep(0.05)

    def _drain(self) -> None:
        while True:
            try:
                wid, losses, staleness = self._queue.get_nowait()
            except Exception:
                return
            self._results[wid] = (losses, staleness)

    # -- results / introspection

    @property
    def workload(self):
        return self._workload

    def worker_results(self) -> dict[int, tuple[list[float], list[int]]]:
        self._drain()
        return dict(self._results)

    def ps_stats(self) -> list[dict]:
        client = AsyncPSClient(self._addrs, self._plan)
        return client.stats()

    def global_version(self) -> int:
        """Total updates applied across PS shards (monotone progress)."""
        return sum(s["version"] for s in self.ps_stats())

    def current_params(self) -> dict:
        """Live snapshot of the full (nested) param tree."""
        client = AsyncPSClient(self._addrs, self._plan)
        flat, _ = client.pull()
        return _unflatten(flat)

    def evaluate(self, batches: int = 4, seed: int = 10_000) -> dict:
        """Run the workload's eval_fn on the *current* PS params."""
        import jax.numpy as jnp

        from ..data.input_pipeline import InputContext

        params = self.current_params()
        params = {k: jnp.asarray(v) for k, v in _flatten(params).items()}
        params = _unflatten(params)
        ctx = InputContext(1, 0, self._spec["batch_size"])
        data = self._workload.input_fn(ctx, seed)
        metrics: dict[str, float] = {}
        for _ in range(batches):
            m = self._workload.eval_fn(params, {}, next(data))
            for k, v in m.items():
                metrics[k] = metrics.get(k, 0.0) + float(v) / batches
        return metrics

    def first_last_mean_loss(self, k: int = 4) -> tuple[float, float]:
        """Mean of the first/last k losses across workers that finished."""
        self._drain()
        first, last = [], []
        for losses, _ in self._results.values():
            first.extend(losses[:k])
            last.extend(losses[-k:])
        if not first:  # every worker killed before finishing
            return float("nan"), float("nan")
        return float(np.mean(first)), float(np.mean(last))

    def stop(self) -> None:
        for p in self._procs.values():
            if p.is_alive():
                p.terminate()
        for p in self._procs.values():
            p.join(timeout=5)
        for s in self._servers:
            s.stop()

    def __enter__(self) -> "AsyncPSTrainer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
