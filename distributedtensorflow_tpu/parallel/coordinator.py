"""Coordinator: async closure dispatch with failure-transparent retry.

Replaces the reference's ``ClusterCoordinator`` engine (SURVEY.md §2.3,
§3.3: ``coordinator/cluster_coordinator.py`` — ``Closure`` :193,
``_CoordinatedClosureQueue`` :322, ``WorkerPreemptionHandler`` :841,
``Worker`` :1027, ``ClusterCoordinator`` :1399, ``schedule`` :1493,
``join`` :1565, ``create_per_worker_dataset`` :1604, ``fetch`` :1695).

TPU-native stance (SURVEY.md §7 "hard parts"): the *training* step on TPU is
sync SPMD — there is no async parameter server.  What survives of the
coordinator pattern is its genuinely useful half: a failure-transparent
dispatcher that fans closures out to a pool of workers (eval jobs, data
preprocessing, metric export, host-side side computations) while the main
thread keeps driving the device loop.  Semantics preserved from the
reference:

- ``schedule`` is non-blocking and returns a :class:`RemoteValue`;
- a worker failing with a *retryable* error re-queues the closure onto
  another worker (the reference's ``WorkerPreemptionHandler`` path, :841);
- a closure failing with an *application* error parks the error and
  re-raises it at ``schedule``/``join`` time (reference semantics: errors
  are reported "as soon as possible" at the next coordinator call);
- ``join`` barriers on queue drain; ``done`` polls it;
- ``create_per_worker_dataset`` + ``per_worker_value`` build one value per
  worker, resolved to the right worker's copy inside closures.
"""

from __future__ import annotations

import collections
import logging
import multiprocessing as mp
import os
import signal
import threading
import time
from typing import Any, Callable, Generic, Iterable, Iterator, TypeVar

from .. import obs

logger = logging.getLogger("distributedtensorflow_tpu")

# Registry metrics (obs/): dispatch health of every Coordinator in the
# process, one shared family with no per-instance labels — the queue-depth
# gauge is the "is host-side work backing up" signal.
_M_SCHEDULED = obs.counter(
    "coordinator_closures_scheduled_total", "closures accepted by schedule()"
)
_M_FINISHED = obs.counter(
    "coordinator_closures_finished_total", "closures completed successfully"
)
_M_RETRIED = obs.counter(
    "coordinator_closures_retried_total",
    "closure re-queues after a retryable worker failure",
)
_M_FAILED = obs.counter(
    "coordinator_closures_failed_total", "closures parked as application errors"
)
_M_QUEUE_DEPTH = obs.gauge(
    "coordinator_queue_depth", "closures waiting for a worker"
)
_M_WASTED_S = obs.histogram(
    "coordinator_wasted_seconds",
    "seconds a closure attempt ran before being discarded by a retry or "
    "failure (host-side badput; the goodput report counts the matching "
    "coordinator_retry/failure flight events per generation)",
)
_M_RESPAWNS = obs.counter(
    "worker_respawns_total",
    "process-backed worker respawns after a worker death, by worker id "
    "(a climbing single-worker rate = a crash-looping worker approaching "
    "its respawn budget)",
)

T = TypeVar("T")


class ClosureAborted(RuntimeError):
    """Raised by fetch() on closures cancelled after another closure failed."""


class WorkerUnavailableError(RuntimeError):
    """Retryable transport error — the reference's ``UnavailableError``.

    Raise this (or register other types via ``retryable_exceptions``) from a
    closure to signal "the worker died, not the computation": the closure is
    transparently re-scheduled on another worker.
    """


class RemoteValue(Generic[T]):
    """Future for a scheduled closure's result (reference :1695 ``fetch``)."""

    def __init__(self) -> None:
        self._ready = threading.Event()
        self._value: T | None = None
        self._error: BaseException | None = None

    def _set_value(self, value: T) -> None:
        self._value = value
        self._ready.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._ready.set()

    def fetch(self, timeout: float | None = None) -> T:
        if not self._ready.wait(timeout):
            raise TimeoutError("RemoteValue not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def done(self) -> bool:
        return self._ready.is_set()


class Closure:
    """A scheduled unit of work (reference ``Closure``, :193)."""

    __slots__ = ("fn", "args", "kwargs", "output", "attempts")

    def __init__(self, fn: Callable[..., Any], args: tuple, kwargs: dict):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.output: RemoteValue = RemoteValue()
        self.attempts = 0

    def execute(self, resolve: Callable[[Any], Any]) -> Any:
        args = tuple(resolve(a) for a in self.args)
        kwargs = {k: resolve(v) for k, v in self.kwargs.items()}
        return self.fn(*args, **kwargs)


class _ClosureQueue:
    """Bounded closure queue with in-flight tracking and error parking.

    Reference ``_CoordinatedClosureQueue`` (:322): ``put`` blocks when full
    (backpressure), ``wait`` barriers on drain, the first application error
    stops intake, cancels queued closures, and re-raises at the next
    coordinator call.
    """

    def __init__(self, maxsize: int = 256):
        self._queue: collections.deque[Closure] = collections.deque()
        self._maxsize = maxsize
        self._inflight = 0
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._drained = threading.Condition(self._lock)
        self._error: BaseException | None = None
        self._closed = False

    def put(self, closure: Closure) -> None:
        with self._not_full:
            self.raise_if_error()
            while len(self._queue) >= self._maxsize and not self._closed:
                self._not_full.wait()
                self.raise_if_error()
            if self._closed:
                raise RuntimeError("coordinator is shut down")
            self._queue.append(closure)
            _M_QUEUE_DEPTH.set(len(self._queue))
            self._not_empty.notify()

    def get(self, timeout: float = 0.1) -> Closure | None:
        with self._not_empty:
            if not self._queue:
                self._not_empty.wait(timeout)
            if not self._queue:
                return None
            closure = self._queue.popleft()
            self._inflight += 1
            _M_QUEUE_DEPTH.set(len(self._queue))
            self._not_full.notify()
            return closure

    def put_back(self, closure: Closure) -> None:
        """Re-queue a closure whose worker died (retry path)."""
        with self._lock:
            self._inflight -= 1
            if self._error is None and not self._closed:
                self._queue.appendleft(closure)
                _M_QUEUE_DEPTH.set(len(self._queue))
                self._not_empty.notify()
            else:
                closure.output._set_error(ClosureAborted("coordinator errored"))
                self._drained.notify_all()

    def mark_finished(self) -> None:
        with self._lock:
            self._inflight -= 1
            if not self._queue and self._inflight == 0:
                self._drained.notify_all()

    def mark_failed(self, err: BaseException) -> None:
        """Application error: park it, cancel everything queued."""
        with self._lock:
            self._inflight -= 1
            if self._error is None:
                self._error = err
            for closure in self._queue:
                closure.output._set_error(ClosureAborted("cancelled"))
            self._queue.clear()
            _M_QUEUE_DEPTH.set(0)
            self._not_full.notify_all()
            self._drained.notify_all()

    def raise_if_error(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def wait(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._drained:
            while (self._queue or self._inflight) and self._error is None:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    break
                self._drained.wait(remaining)
            self.raise_if_error()
            return not self._queue and self._inflight == 0

    def done(self) -> bool:
        with self._lock:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return not self._queue and self._inflight == 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for closure in self._queue:
                closure.output._set_error(ClosureAborted("coordinator shut down"))
            self._queue.clear()
            _M_QUEUE_DEPTH.set(0)
            self._not_empty.notify_all()
            self._not_full.notify_all()
            self._drained.notify_all()


class PerWorker(Generic[T]):
    """One value per worker; closures see their own worker's copy.

    Reference: per-worker datasets/values (``create_per_worker_dataset``
    :1604) — each worker builds its own iterator so data pipelines are not
    shared across workers.
    """

    def __init__(self, build_fn: Callable[[int], T], n_workers: int):
        self._build_fn = build_fn
        self._values: dict[int, T] = {}
        self._lock = threading.Lock()
        self._n = n_workers

    def _resolve(self, worker_id: int) -> T:
        with self._lock:
            if worker_id not in self._values:
                self._values[worker_id] = self._build_fn(worker_id)
            return self._values[worker_id]


def _subprocess_worker_main(conn, status_port: int | None = None) -> None:
    """Loop of a process-backed worker: recv (fn, args, kwargs), send result.

    ``status_port`` (0 = ephemeral) embeds an ``obs.StatusServer`` in the
    child so the chief's FleetAggregator can scrape its ``/varz`` — the
    bound port (or None on failure) is sent to the parent as a
    ``("status_port", port)`` handshake message BEFORE the closure loop
    starts, so it can never interleave with an execute round-trip."""
    server = None
    if status_port is not None:
        state = {"closures_done": 0, "pid": os.getpid()}
        try:
            from ..obs.server import StatusServer  # noqa: PLC0415

            server = StatusServer(
                status_port,
                status_fn=lambda: {"coordinator_worker": dict(state)},
            ).start()
            conn.send(("status_port", server.port))
        except Exception:  # bind failure — degrade, the worker still works
            conn.send(("status_port", None))
    else:
        state = {"closures_done": 0}
    while True:
        try:
            msg = conn.recv()
        except (EOFError, KeyboardInterrupt):
            return
        if msg is None:
            return
        fn, args, kwargs = msg
        try:
            result = fn(*args, **kwargs)
            state["closures_done"] += 1
            conn.send(("ok", result))
        except BaseException as e:  # noqa: BLE001 — shipped to the parent
            try:
                conn.send(("err", e))
            except Exception:  # unpicklable exception: ship the repr
                conn.send(("err", RuntimeError(repr(e))))


class _SubprocessExecutor:
    """A persistent worker OS process executing pickled closures.

    The process analogue of the reference's remote eager workers (§3.3):
    real isolation, real death.  A dead child surfaces as
    :class:`WorkerUnavailableError` — exactly the retryable signal the
    coordinator's re-queue path expects — and the executor respawns for the
    next closure.  Closures and their resolved args must be picklable
    (module-level functions; no PerWorker iterators).

    Respawns are BOUNDED (resilience satellite): a crash-looping worker —
    e.g. one whose host is out of memory, where every fresh process dies
    the same death — used to respawn forever at full speed.  A death now
    *schedules* the respawn behind an exponentially-backed-off deadline
    (``respawn_backoff_s`` base, doubling, clamped at
    ``respawn_backoff_max_s``); the actual spawn happens lazily at the
    next :meth:`execute` past the deadline, and executes arriving during
    the backoff fail fast with :class:`WorkerUnavailableError` — the
    dying worker must never stall the retry path that re-queues its
    closure onto healthy workers (nobody sleeps holding the executor
    lock).  Each scheduled respawn emits a ``worker_respawn`` flight
    event plus ``worker_respawns_total{worker=}``; after ``max_respawns``
    the executor goes permanently dead and its closures keep failing
    fast onto the surviving workers.
    """

    def __init__(self, worker_id: int, *, max_respawns: int = 8,
                 respawn_backoff_s: float = 0.5,
                 respawn_backoff_max_s: float = 30.0,
                 status_port: int | None = None,
                 defer_status_handshake: bool = False):
        self.worker_id = worker_id
        self._ctx = mp.get_context("spawn")
        self._lock = threading.Lock()
        self._max_respawns = max(0, int(max_respawns))
        self._backoff_s = max(0.0, float(respawn_backoff_s))
        self._backoff_max_s = max(0.0, float(respawn_backoff_max_s))
        self._status_port = status_port
        #: ``host:port`` of the child's embedded StatusServer (fleet
        #: scrape target), or None — refreshed on every (re)spawn.
        self.status_addr: str | None = None
        self.respawns = 0
        self.last_backoff_s = 0.0
        self._dead = False
        #: monotonic deadline of a scheduled-but-not-yet-performed respawn
        #: (None = a live process exists).
        self._spawn_not_before: float | None = None
        # defer_status_handshake: the Coordinator spawns ALL executors
        # first (children import obs/jax concurrently), then collects the
        # handshakes — otherwise startup serializes on N jax imports.
        self._spawn(wait_handshake=not defer_status_handshake)

    def _spawn(self, *, wait_handshake: bool = True) -> None:
        self._conn, child = self._ctx.Pipe()
        self._proc = self._ctx.Process(
            target=_subprocess_worker_main,
            args=(child, self._status_port), daemon=True,
            name=f"coordinator-proc-{self.worker_id}",
        )
        self._proc.start()
        child.close()
        self.status_addr = None
        if self._status_port is not None and wait_handshake:
            self.wait_status_handshake()

    def wait_status_handshake(self, timeout: float = 60.0) -> None:
        """Consume the child's ``("status_port", port)`` handshake (the
        spawn context re-imports this module — and obs/jax with it — in
        the child, so allow a generous import window).  A handshake that
        outlives the poll is consumed safely by execute()'s tag loop
        instead — results never shift by one message."""
        if self._status_port is None:
            return
        try:
            if self._conn.poll(timeout):
                tag, port = self._conn.recv()
                if tag == "status_port" and port:
                    self.status_addr = f"127.0.0.1:{int(port)}"
        except (EOFError, OSError):
            pass

    @property
    def pid(self) -> int:
        return self._proc.pid

    def backoff_remaining(self) -> float | None:
        """Seconds until this executor may respawn (0.0 = ready), or None
        when it is permanently dead.  Lock-free on purpose: the dispatch
        thread polls this while another thread may hold the executor lock
        inside a long closure; plain attribute reads are safe and a stale
        answer only shifts a pop by one poll."""
        if self._dead:
            return None
        t = self._spawn_not_before
        if t is None:
            return 0.0
        return max(t - time.monotonic(), 0.0)

    def execute(self, fn, args, kwargs):
        with self._lock:
            if self._dead:
                raise WorkerUnavailableError(
                    f"worker process {self.worker_id} is dead (respawn "
                    f"budget of {self._max_respawns} exhausted)"
                )
            if self._spawn_not_before is not None:
                # A death scheduled a respawn: spawn once the backoff
                # deadline passes; until then fail fast so the closure
                # re-queues onto a healthy worker immediately.
                if time.monotonic() < self._spawn_not_before:
                    raise WorkerUnavailableError(
                        f"worker process {self.worker_id} is respawning "
                        f"(backoff {self.last_backoff_s:.2f}s after death "
                        f"{self.respawns}/{self._max_respawns})"
                    )
                self._spawn_not_before = None
                # No handshake wait on the respawn path: execute's tag
                # loop below consumes it — blocking the failure path 60s
                # would stall exactly the retry the re-queue depends on.
                self._spawn(wait_handshake=False)
            try:
                self._conn.send((fn, args, kwargs))
                status, payload = self._conn.recv()
                while status == "status_port":
                    # Late status handshake (the spawn-time poll gave up
                    # before the child finished binding): consume it here
                    # so closure results can never shift by one message.
                    self.status_addr = (
                        f"127.0.0.1:{int(payload)}" if payload else None
                    )
                    status, payload = self._conn.recv()
            except (EOFError, OSError) as e:
                self._respawn()
                raise WorkerUnavailableError(
                    f"worker process {self.worker_id} died: {e!r}"
                ) from e
        if status == "err":
            raise payload
        return payload

    def _respawn(self) -> None:
        """Reap the dead process and SCHEDULE its replacement (or go
        permanently dead past the budget).  Never sleeps, never spawns —
        both would stall the caller's failure path, which healthy workers
        are waiting on to pick up the re-queued closure."""
        try:
            self._conn.close()
        except OSError:
            pass
        if self._proc.is_alive():
            self._proc.kill()
        self._proc.join(timeout=5)
        if self.respawns >= self._max_respawns:
            self._dead = True
            logger.error(
                "worker %d exhausted its respawn budget (%d); leaving it "
                "dead — closures re-queue onto surviving workers",
                self.worker_id, self._max_respawns,
            )
            return
        self.respawns += 1
        _M_RESPAWNS.inc(worker=str(self.worker_id))
        obs.record_event(
            "worker_respawn", worker=self.worker_id, respawn=self.respawns,
            budget=self._max_respawns,
        )
        self.last_backoff_s = min(
            self._backoff_s * (2 ** (self.respawns - 1)),
            self._backoff_max_s,
        )
        self._spawn_not_before = time.monotonic() + self.last_backoff_s
        logger.warning(
            "worker %d death %d/%d: respawn scheduled in %.2fs",
            self.worker_id, self.respawns, self._max_respawns,
            self.last_backoff_s,
        )

    def kill(self) -> None:
        """Fault injection: SIGKILL the worker process."""
        os.kill(self._proc.pid, signal.SIGKILL)

    def close(self) -> None:
        # Don't block shutdown behind a worker thread parked in recv() on a
        # long/hung closure: bounded lock wait, then escalate to kill.
        got = self._lock.acquire(timeout=1.0)
        try:
            if got:
                try:
                    self._conn.send(None)  # graceful: child loop exits
                    self._conn.close()
                except OSError:
                    pass
        finally:
            if got:
                self._lock.release()
        self._proc.join(timeout=5 if got else 0.1)
        if self._proc.is_alive():
            self._proc.kill()
            self._proc.join(timeout=5)


class _Worker(threading.Thread):
    """Dispatch thread (reference ``Worker``, :1027): pops and executes.

    A retryable failure re-queues the closure and "restarts" the worker
    (the reference re-establishes the remote connection; here the thread
    just clears its per-worker state and keeps serving).
    """

    def __init__(self, worker_id: int, coord: "Coordinator"):
        super().__init__(name=f"coordinator-worker-{worker_id}", daemon=True)
        self.worker_id = worker_id
        self._coord = coord
        self.failures = 0

    def run(self) -> None:
        queue = self._coord._queue
        while not self._coord._stopping.is_set():
            executor_state = self._coord._executor_for(self.worker_id)
            if executor_state is not None:
                rem = executor_state.backoff_remaining()
                if rem is None:
                    # Permanently dead executor: de-prioritize hard so
                    # surviving workers win every pop; if NO survivor
                    # exists the pop below still fails closures fast
                    # enough (bounded by max_retries) to surface the
                    # error instead of hanging the queue.
                    time.sleep(0.2)
                elif rem > 0:
                    # Respawn backoff window: do not pop AT ALL — a
                    # popped closure would insta-fail back into the
                    # queue, burning its retry budget against a worker
                    # that is known-down (healthy workers pick it up
                    # instead).
                    time.sleep(min(rem, 0.1))
                    continue
            closure = queue.get()
            if closure is None:
                continue
            if self._coord._failed_workers_see_unavailable(self.worker_id):
                # Fault injection: this worker is "preempted" — behave like a
                # dead remote: the closure must move to another worker.
                self.failures += 1
                closure.attempts += 1
                queue.put_back(closure)
                self._coord._recover_worker(self.worker_id)
                continue
            def resolve(v: Any) -> Any:
                if isinstance(v, PerWorker):
                    return v._resolve(self.worker_id)
                return v
            executor = self._coord._executor_for(self.worker_id)
            attempt_t0 = time.perf_counter()
            try:
                if executor is not None:
                    result = executor.execute(
                        closure.fn,
                        tuple(resolve(a) for a in closure.args),
                        {k: resolve(v) for k, v in closure.kwargs.items()},
                    )
                else:
                    result = closure.execute(resolve)
            except self._coord._retryable as e:
                self.failures += 1
                closure.attempts += 1
                _M_RETRIED.inc()
                _M_WASTED_S.observe(
                    time.perf_counter() - attempt_t0, outcome="retry"
                )
                if closure.attempts >= self._coord._max_retries:
                    err = RuntimeError(
                        f"closure failed {closure.attempts} retryable attempts"
                    )
                    err.__cause__ = e
                    closure.output._set_error(err)
                    queue.mark_failed(err)
                    _M_FAILED.inc()  # retry exhaustion is a permanent failure
                    obs.record_event(
                        "coordinator_failure", worker=self.worker_id,
                        attempts=closure.attempts, error="retries exhausted",
                    )
                    continue
                logger.warning(
                    "worker %d unavailable (%s); re-queueing closure "
                    "(attempt %d)", self.worker_id, e, closure.attempts,
                )
                # Flight marker: a retried closure is exactly the kind of
                # "what was happening before the hang" breadcrumb the
                # post-mortem wants (a dying worker pool precedes a stall).
                obs.record_event(
                    "coordinator_retry", worker=self.worker_id,
                    attempt=closure.attempts, error=repr(e)[:200],
                )
                queue.put_back(closure)
            except BaseException as e:  # noqa: BLE001 — parked, re-raised at join
                closure.output._set_error(e)
                queue.mark_failed(e)
                _M_FAILED.inc()
                _M_WASTED_S.observe(
                    time.perf_counter() - attempt_t0, outcome="failure"
                )
                obs.record_event(
                    "coordinator_failure", worker=self.worker_id,
                    error=repr(e)[:200],
                )
            else:
                closure.output._set_value(result)
                queue.mark_finished()
                _M_FINISHED.inc()


class Coordinator:
    """Failure-transparent closure dispatcher (reference :1399).

    Usage::

        coord = Coordinator(num_workers=4)
        rv = coord.schedule(eval_fn, (state,))
        ...            # main thread keeps training
        coord.join()   # barrier; re-raises any application error
        print(rv.fetch())
    """

    def __init__(
        self,
        num_workers: int = 1,
        *,
        queue_size: int = 256,
        retryable_exceptions: tuple[type[BaseException], ...] = (),
        max_retries: int = 16,
        use_processes: bool = False,
        max_respawns: int = 8,
        respawn_backoff_s: float = 0.5,
        respawn_backoff_max_s: float = 30.0,
        worker_status_ports: bool = False,
    ):
        """``use_processes=True`` backs each worker with a real OS process
        (the reference's remote-worker isolation): closures run out-of-
        process, a killed/crashed worker transparently re-queues its
        closure, and the pool respawns the process — at most
        ``max_respawns`` times per worker, with exponential backoff
        (``respawn_backoff_s`` base, ``respawn_backoff_max_s`` clamp), so a
        crash-looping worker cannot fork-bomb the host.  Requires picklable
        closures/args; PerWorker values stay thread-mode only.

        ``worker_status_ports=True`` (process mode only) embeds an
        ephemeral loopback ``obs.StatusServer`` in every worker process so
        the fleet aggregator can scrape them; the bound addresses are
        :meth:`worker_status_addrs`.
        """
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if worker_status_ports and not use_processes:
            raise ValueError(
                "worker_status_ports requires use_processes=True (thread "
                "workers share this process's own StatusServer)"
            )
        self._queue = _ClosureQueue(queue_size)
        self._max_retries = max_retries
        self._stopping = threading.Event()
        self._retryable = (WorkerUnavailableError, *retryable_exceptions)
        self._failed_workers: set[int] = set()
        self._failed_lock = threading.Lock()
        self._executors: list[_SubprocessExecutor] | None = (
            [
                _SubprocessExecutor(
                    i, max_respawns=max_respawns,
                    respawn_backoff_s=respawn_backoff_s,
                    respawn_backoff_max_s=respawn_backoff_max_s,
                    status_port=0 if worker_status_ports else None,
                    # spawn everything first; handshakes collected below
                    # so the children's obs/jax imports overlap instead
                    # of serializing Coordinator startup N-fold
                    defer_status_handshake=True,
                )
                for i in range(num_workers)
            ]
            if use_processes
            else None
        )
        if self._executors and worker_status_ports:
            for e in self._executors:
                e.wait_status_handshake()
        self._workers = [_Worker(i, self) for i in range(num_workers)]
        for w in self._workers:
            w.start()

    def _executor_for(self, worker_id: int) -> "_SubprocessExecutor | None":
        return self._executors[worker_id] if self._executors else None

    def worker_pids(self) -> list[int] | None:
        """PIDs of process-backed workers (None in thread mode)."""
        if not self._executors:
            return None
        return [e.pid for e in self._executors]

    def worker_status_addrs(self) -> list[str | None] | None:
        """Embedded StatusServer addresses of process-backed workers
        (``worker_status_ports=True``) — the fleet aggregator's scrape
        targets; None in thread mode, per-entry None where the child's
        server failed to bind."""
        if not self._executors:
            return None
        return [e.status_addr for e in self._executors]

    def kill_worker_process(self, worker_id: int) -> None:
        """Fault injection: SIGKILL a process-backed worker (its in-flight
        closure re-queues onto another worker; the process respawns)."""
        if not self._executors:
            raise RuntimeError("kill_worker_process needs use_processes=True")
        self._executors[worker_id].kill()

    @property
    def num_workers(self) -> int:
        return len(self._workers)

    def schedule(
        self, fn: Callable[..., Any], args: tuple = (), kwargs: dict | None = None
    ) -> RemoteValue:
        """Enqueue ``fn(*args)`` for some worker; non-blocking (:1493).

        Re-raises a previously failed closure's error, matching the
        reference's "error raised at the next schedule/join" contract.
        """
        closure = Closure(fn, args, kwargs or {})
        self._queue.put(closure)
        _M_SCHEDULED.inc()
        return closure.output

    def join(self, timeout: float | None = None) -> None:
        """Block until all scheduled closures finish (:1565)."""
        if not self._queue.wait(timeout):
            raise TimeoutError("coordinator join timed out")

    def done(self) -> bool:
        return self._queue.done()

    def fetch(self, values: Any) -> Any:
        """Resolve RemoteValues in a structure (:1695)."""
        if isinstance(values, RemoteValue):
            return values.fetch()
        if isinstance(values, (list, tuple)):
            return type(values)(self.fetch(v) for v in values)
        if isinstance(values, dict):
            return {k: self.fetch(v) for k, v in values.items()}
        return values

    def per_worker_value(self, build_fn: Callable[[int], T]) -> PerWorker[T]:
        return PerWorker(build_fn, len(self._workers))

    def create_per_worker_dataset(
        self, dataset_fn: Callable[[int], Iterable]
    ) -> PerWorker[Iterator]:
        """One iterator per worker (:1604); pass the result to closures."""
        return PerWorker(lambda i: iter(dataset_fn(i)), len(self._workers))

    # -- fault injection (the reference's MultiProcessRunner kill path is a
    #    process kill; for the in-process pool, preemption is simulated).

    def preempt_worker(self, worker_id: int) -> None:
        """Mark a worker dead: its next closures re-queue elsewhere."""
        with self._failed_lock:
            self._failed_workers.add(worker_id)

    def _failed_workers_see_unavailable(self, worker_id: int) -> bool:
        with self._failed_lock:
            return worker_id in self._failed_workers

    def _recover_worker(self, worker_id: int) -> None:
        with self._failed_lock:
            self._failed_workers.discard(worker_id)

    def shutdown(self) -> None:
        self._stopping.set()
        self._queue.close()
        for w in self._workers:
            w.join(timeout=5)
        if self._executors:
            for e in self._executors:
                e.close()

    def __enter__(self) -> "Coordinator":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
