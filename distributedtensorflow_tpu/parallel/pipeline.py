"""Pipeline parallelism: SPMD GPipe over the ``pipe`` mesh axis.

New capability absent from the reference stack (SURVEY.md §2.4: "no GPipe in
tf.distribute").  Design follows the single-program pipeline pattern
(SURVEY.md §7 step 9, PAPERS.md MPMD-pipeline entry chose the contrasting
design; SPMD is picked here for simplicity and jit-compatibility):

- stage s of the model lives on mesh position s of the ``pipe`` axis
  (stage-stacked params, leading dim sharded over ``pipe``);
- microbatches march through ticks; at each tick every device runs its stage
  on its current microbatch and hands the activation to the right neighbor
  via ``lax.ppermute`` (neighbor ICI transfer, overlapped by XLA);
- the whole schedule — warmup bubble, steady state, drain — is one
  ``lax.scan`` inside one jitted program; autodiff through it yields the
  reverse pipeline automatically.

Bubble fraction is the GPipe (n_stages-1)/(n_micro+n_stages-1); use
microbatch counts >= 4x stages.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

PyTree = Any


def gpipe_bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: (S-1)/(M+S-1).

    E.g. 4 stages × 16 microbatches → 15.8% bubble.  Keep microbatch counts
    >= 4× stages; 1F1B would shrink peak activation memory, not the bubble.
    """
    return (n_stages - 1) / (n_microbatches + n_stages - 1)


def _wire_ppermute(y, axis_name, perm, wire_dtype):
    """ppermute with an optional payload-only downcast (see wire_dtype in
    :func:`pipeline_apply`)."""
    if wire_dtype is None or jnp.dtype(wire_dtype) == y.dtype:
        return lax.ppermute(y, axis_name, perm)
    return lax.ppermute(
        y.astype(wire_dtype), axis_name, perm
    ).astype(y.dtype)


def pipeline_apply(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,
    microbatches: jax.Array,  # (n_micro, mb, ...) — same on every pipe rank
    *,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
    wire_dtype: object | None = None,
) -> jax.Array:
    """Run the microbatch pipeline (shard_map-internal).

    ``stage_fn(params, x) -> y`` must map activations to activations of the
    same shape (inter-stage handoff is a fixed-size buffer).  Returns the
    final outputs (n_micro, mb, ...) — valid on the *last* pipe rank and
    broadcast to all ranks so downstream (loss) code is uniform SPMD.

    ``remat=True`` checkpoints each stage invocation: the backward pass
    recomputes stage activations per (tick) instead of storing all
    ``n_micro + n_stages - 1`` of them — the activation-memory control that
    motivates 1F1B schedules, obtained here by rematerialization (GPipe's
    bubble fraction is unchanged; see :func:`gpipe_bubble_fraction`).

    ``wire_dtype`` casts ONLY the ppermute payload (cast down before the
    collective, back up after): with a bf16 model whose stage outputs are
    upcast bf16 values the roundtrip is bit-exact while the inter-stage
    wire traffic halves.  Scan carries, schedule buffers, and the region
    boundary keep the microbatches' dtype — jax 0.9's partial-manual
    partitioner aborts on bf16 region boundaries under autodiff
    (tests/test_jax_workarounds.py), which is why the cast lives HERE and
    not at the boundary.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    ticks = n_micro + n - 1
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        recv, outputs = carry
        mb_idx = jnp.clip(t - s, 0, n_micro - 1)
        x_first = lax.dynamic_index_in_dim(microbatches, jnp.clip(t, 0, n_micro - 1),
                                           keepdims=False)
        x = jnp.where(s == 0, x_first, recv)
        y = stage_fn(stage_params, x)
        active = (t - s >= 0) & (t - s < n_micro)
        # last stage banks its finished microbatch
        out_update = jnp.where(active & (s == n - 1), y, 0.0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            lax.dynamic_index_in_dim(outputs, mb_idx, keepdims=False)
            + out_update,
            mb_idx, axis=0,
        )
        recv = _wire_ppermute(y, axis_name, perm_fwd, wire_dtype)
        return (recv, outputs), None

    recv0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(ticks))
    # replicate the last stage's outputs to every rank (masked psum broadcast)
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def _make_wrapper(
    stage_fn, mesh, param_specs, *, n_microbatches, n_virtual,
    axis_name, remat,
):
    """Shared shard_map/jit wrapper for both schedules.

    ``n_virtual is None`` selects the GPipe path: param leaves are
    ``(n_stages, ...)`` with spec ``P(pipe, ...)``.  Otherwise circular:
    leaves ``(n_virtual, n_stages, ...)`` with spec ``P(None, pipe, ...)``.
    """
    circular = n_virtual is not None
    batch_axes = mesh_lib.data_axes(mesh)

    def run(stacked_params, batch):
        def inner(local_params, x):
            if x.shape[0] % n_microbatches:
                raise ValueError(
                    f"per-shard batch {x.shape[0]} not divisible by "
                    f"n_microbatches={n_microbatches}"
                )
            mb = x.reshape(n_microbatches, x.shape[0] // n_microbatches,
                           *x.shape[1:])
            if circular:
                params = jax.tree.map(lambda p: p[:, 0], local_params)
                out = circular_pipeline_apply(
                    stage_fn, params, mb, n_virtual=n_virtual,
                    axis_name=axis_name, remat=remat,
                )
            else:
                # shard_map leaves the size-1 stage dim on the leading axis
                params = jax.tree.map(lambda p: p[0], local_params)
                out = pipeline_apply(stage_fn, params, mb,
                                     axis_name=axis_name, remat=remat)
            return out.reshape(x.shape[0], *out.shape[2:])

        prefix = (None, axis_name) if circular else (axis_name,)
        in_param_specs = jax.tree.map(
            lambda spec: P(*prefix, *spec), param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        x_spec = P(batch_axes if batch_axes else None)
        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(in_param_specs, x_spec),
            out_specs=x_spec,
            check_vma=False,
        )(stacked_params, batch)

    return jax.jit(run)


def make_pipelined_fn(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    mesh: Mesh,
    param_specs: PyTree,
    *,
    n_microbatches: int,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
) -> Callable[[PyTree, jax.Array], jax.Array]:
    """Global-array entry: ``fn(stacked_params, batch) -> outputs``.

    ``stacked_params`` leaves carry a leading stage dim sharded over ``pipe``
    (spec prefix ``P("pipe", ...)`` — built by :func:`stack_stage_params`);
    ``batch`` (B, ...) is split into ``n_microbatches`` internally.
    ``remat`` forwards to :func:`pipeline_apply` (per-stage recompute).
    """
    return _make_wrapper(
        stage_fn, mesh, param_specs, n_microbatches=n_microbatches,
        n_virtual=None, axis_name=axis_name, remat=remat,
    )


def stack_stage_params(
    init_fn: Callable[[jax.Array], PyTree],
    n_stages: int,
    rng: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = mesh_lib.AXIS_PIPE,
) -> tuple[PyTree, PyTree]:
    """Initialize per-stage params stacked on a leading ``pipe``-sharded dim.

    Returns ``(stacked_params, per_stage_specs)`` — specs are for the
    *unstacked* leaves (the stage dim is added by :func:`make_pipelined_fn`).
    """
    rngs = jax.random.split(rng, n_stages)
    stacked = jax.vmap(init_fn)(rngs)
    specs = jax.tree.map(lambda _: P(), jax.eval_shape(init_fn, rng))
    sharding = jax.tree.map(
        lambda spec: NamedSharding(mesh, P(axis_name, *spec)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, sharding)
    return stacked, specs


# --- Circular (interleaved) schedule -----------------------------------------


def circular_bubble_fraction(
    n_stages: int, n_microbatches: int, n_virtual: int
) -> float:
    """Idle fraction of the circular schedule: (n-1)/(v*M + n-1).

    Each rank holds ``n_virtual`` non-adjacent stage chunks (stage k lives
    on rank ``k % n``, chunk ``k // n``), so the warmup/drain bubble is paid
    once per *ring*, not once per *stage* — a ``n_virtual``-fold reduction
    vs GPipe at equal microbatch count (Megatron interleaved-1F1B's bubble
    shape, obtained in SPMD form).
    """
    return (n_stages - 1) / (n_virtual * n_microbatches + n_stages - 1)


def circular_pipeline_apply(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,  # leaves (n_virtual, ...): this rank's chunk stack
    microbatches: jax.Array,  # (n_micro, mb, ...) — same on every pipe rank
    *,
    n_virtual: int,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
    wire_dtype: object | None = None,
) -> jax.Array:
    """Interleaved-pipeline microbatch loop (shard_map-internal).

    Schedule: stage ``k = c*n + p`` of microbatch ``m`` runs at tick
    ``c*M + m + p`` on rank ``p`` — microbatches stream around the ring
    ``n_virtual`` times; an activation leaving the last rank waits in a
    per-rank circular buffer for ``M - n`` ticks and re-enters rank 0 for
    its next chunk.  Requires ``n_micro >= n_ranks`` (the wrap-around
    arrives before its re-entry slot).  ``stage_fn`` must be
    shape-preserving and ``wire_dtype`` casts the ppermute payload only,
    both as in :func:`pipeline_apply`.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    if n_micro < n:
        raise ValueError(
            f"circular schedule needs n_micro >= n_ranks ({n_micro} < {n})"
        )
    ticks = n_virtual * n_micro + n - 1
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        recv, circ, outputs = carry
        rel = t - s
        c = jnp.clip(rel // n_micro, 0, n_virtual - 1)
        m = jnp.clip(rel, 0, n_virtual * n_micro - 1) % n_micro
        # Rank 0 writes the wrap-around it just received BEFORE reading its
        # input slot (write-then-read makes n_micro == n_ranks legal).
        wrap_slot = (t - n) % n_micro
        circ = lax.dynamic_update_index_in_dim(
            circ, jnp.where(t >= n, recv,
                            lax.dynamic_index_in_dim(circ, wrap_slot,
                                                     keepdims=False)),
            wrap_slot, axis=0,
        )
        x_new = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, n_micro - 1), keepdims=False
        )
        x_circ = lax.dynamic_index_in_dim(circ, m, keepdims=False)
        x0 = jnp.where(t < n_micro, x_new, x_circ)
        x = jnp.where(s == 0, x0, recv)
        params_c = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, c, keepdims=False),
            stage_params,
        )
        y = stage_fn(params_c, x)
        active = (rel >= 0) & (rel < n_virtual * n_micro)
        done = active & (s == n - 1) & (c == n_virtual - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            lax.dynamic_index_in_dim(outputs, m, keepdims=False)
            + jnp.where(done, y, 0.0),
            m, axis=0,
        )
        recv = _wire_ppermute(y, axis_name, perm_fwd, wire_dtype)
        return (recv, circ, outputs), None

    recv0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    circ0 = jnp.zeros_like(microbatches)
    outputs0 = jnp.zeros_like(microbatches)
    (_, _, outputs), _ = lax.scan(
        tick, (recv0, circ0, outputs0), jnp.arange(ticks)
    )
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def stack_circular_stage_params(
    init_fn: Callable[[jax.Array], PyTree],
    n_stages: int,
    n_virtual: int,
    rng: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = mesh_lib.AXIS_PIPE,
) -> tuple[PyTree, PyTree]:
    """Init ``n_stages * n_virtual`` stage params stacked ``(v, n, ...)``.

    Stage ``k`` (execution order) lands at ``[k // n, k % n]`` so the rank
    dim (sharded over ``pipe``) holds each rank's ``n_virtual`` chunk stack.
    Returns ``(stacked, per_stage_specs)`` like :func:`stack_stage_params`.
    """
    total = n_stages * n_virtual
    rngs = jax.random.split(rng, total)
    stacked = jax.vmap(init_fn)(rngs)  # (v*n, ...) in execution order
    stacked = jax.tree.map(
        lambda p: p.reshape(n_virtual, n_stages, *p.shape[1:]), stacked
    )
    specs = jax.tree.map(lambda _: P(), jax.eval_shape(init_fn, rng))
    sharding = jax.tree.map(
        lambda spec: NamedSharding(mesh, P(None, axis_name, *spec)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, sharding)
    return stacked, specs


def make_circular_pipelined_fn(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    mesh: Mesh,
    param_specs: PyTree,
    *,
    n_microbatches: int,
    n_virtual: int,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
) -> Callable[[PyTree, jax.Array], jax.Array]:
    """Global-array entry for the circular schedule.

    ``stacked_params`` leaves are ``(n_virtual, n_stages, ...)`` with the
    stage dim sharded over ``pipe`` (:func:`stack_circular_stage_params`).
    """
    if n_virtual < 1:
        raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
    n_stages = mesh.shape[axis_name]
    if n_microbatches < n_stages:
        raise ValueError(
            f"circular schedule needs n_microbatches >= n_stages "
            f"({n_microbatches} < {n_stages}): the wrap-around must arrive "
            "before its re-entry slot"
        )
    return _make_wrapper(
        stage_fn, mesh, param_specs, n_microbatches=n_microbatches,
        n_virtual=n_virtual, axis_name=axis_name, remat=remat,
    )
