"""Pipeline parallelism: SPMD GPipe over the ``pipe`` mesh axis.

New capability absent from the reference stack (SURVEY.md §2.4: "no GPipe in
tf.distribute").  Design follows the single-program pipeline pattern
(SURVEY.md §7 step 9, PAPERS.md MPMD-pipeline entry chose the contrasting
design; SPMD is picked here for simplicity and jit-compatibility):

- stage s of the model lives on mesh position s of the ``pipe`` axis
  (stage-stacked params, leading dim sharded over ``pipe``);
- microbatches march through ticks; at each tick every device runs its stage
  on its current microbatch and hands the activation to the right neighbor
  via ``lax.ppermute`` (neighbor ICI transfer, overlapped by XLA);
- the whole schedule — warmup bubble, steady state, drain — is one
  ``lax.scan`` inside one jitted program; autodiff through it yields the
  reverse pipeline automatically.

Bubble fraction is the GPipe (n_stages-1)/(n_micro+n_stages-1); use
microbatch counts >= 4x stages.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

PyTree = Any

#: Pipeline schedules.  ``gpipe`` (all-forward-then-autodiff; with
#: ``n_virtual > 1`` the circular/interleaved *forward* order) keeps
#: O(n_micro) microbatch activations live across the backward.  The
#: forward/backward-interleaved training schedules ``1f1b`` and
#: ``interleaved`` (:func:`fb_schedule` + :func:`pipeline_fb_step`) bound
#: live stage inputs at O(n_stages) / O(n_stages * n_virtual) slots.
SCHEDULES = ("gpipe", "1f1b", "interleaved")


def gpipe_bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: (S-1)/(M+S-1).

    E.g. 4 stages × 16 microbatches → 15.8% bubble.  Keep microbatch counts
    >= 4× stages; 1F1B would shrink peak activation memory, not the bubble.
    """
    return (n_stages - 1) / (n_microbatches + n_stages - 1)


def _wire_ppermute(y, axis_name, perm, wire_dtype):
    """ppermute with an optional payload-only downcast (see wire_dtype in
    :func:`pipeline_apply`)."""
    if wire_dtype is None or jnp.dtype(wire_dtype) == y.dtype:
        return lax.ppermute(y, axis_name, perm)
    return lax.ppermute(
        y.astype(wire_dtype), axis_name, perm
    ).astype(y.dtype)


def pipeline_apply(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,
    microbatches: jax.Array,  # (n_micro, mb, ...) — same on every pipe rank
    *,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
    wire_dtype: object | None = None,
) -> jax.Array:
    """Run the microbatch pipeline (shard_map-internal).

    ``stage_fn(params, x) -> y`` must map activations to activations of the
    same shape (inter-stage handoff is a fixed-size buffer).  Returns the
    final outputs (n_micro, mb, ...) — valid on the *last* pipe rank and
    broadcast to all ranks so downstream (loss) code is uniform SPMD.

    ``remat=True`` checkpoints each stage invocation: the backward pass
    recomputes stage activations per (tick) instead of storing all
    ``n_micro + n_stages - 1`` of them — the activation-memory control that
    motivates 1F1B schedules, obtained here by rematerialization (GPipe's
    bubble fraction is unchanged; see :func:`gpipe_bubble_fraction`).

    ``wire_dtype`` casts ONLY the ppermute payload (cast down before the
    collective, back up after): with a bf16 model whose stage outputs are
    upcast bf16 values the roundtrip is bit-exact while the inter-stage
    wire traffic halves.  Scan carries, schedule buffers, and the region
    boundary keep the microbatches' dtype — jax 0.9's partial-manual
    partitioner aborts on bf16 region boundaries under autodiff
    (tests/test_jax_workarounds.py), which is why the cast lives HERE and
    not at the boundary.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    ticks = n_micro + n - 1
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        recv, outputs = carry
        mb_idx = jnp.clip(t - s, 0, n_micro - 1)
        x_first = lax.dynamic_index_in_dim(microbatches, jnp.clip(t, 0, n_micro - 1),
                                           keepdims=False)
        x = jnp.where(s == 0, x_first, recv)
        y = stage_fn(stage_params, x)
        active = (t - s >= 0) & (t - s < n_micro)
        # last stage banks its finished microbatch
        out_update = jnp.where(active & (s == n - 1), y, 0.0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            lax.dynamic_index_in_dim(outputs, mb_idx, keepdims=False)
            + out_update,
            mb_idx, axis=0,
        )
        recv = _wire_ppermute(y, axis_name, perm_fwd, wire_dtype)
        return (recv, outputs), None

    recv0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(ticks))
    # replicate the last stage's outputs to every rank (masked psum broadcast)
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def _make_wrapper(
    stage_fn, mesh, param_specs, *, n_microbatches, n_virtual,
    axis_name, remat,
):
    """Shared shard_map/jit wrapper for both schedules.

    ``n_virtual is None`` selects the GPipe path: param leaves are
    ``(n_stages, ...)`` with spec ``P(pipe, ...)``.  Otherwise circular:
    leaves ``(n_virtual, n_stages, ...)`` with spec ``P(None, pipe, ...)``.
    """
    circular = n_virtual is not None
    batch_axes = mesh_lib.data_axes(mesh)

    def run(stacked_params, batch):
        def inner(local_params, x):
            if x.shape[0] % n_microbatches:
                raise ValueError(
                    f"per-shard batch {x.shape[0]} not divisible by "
                    f"n_microbatches={n_microbatches}"
                )
            mb = x.reshape(n_microbatches, x.shape[0] // n_microbatches,
                           *x.shape[1:])
            if circular:
                params = jax.tree.map(lambda p: p[:, 0], local_params)
                out = circular_pipeline_apply(
                    stage_fn, params, mb, n_virtual=n_virtual,
                    axis_name=axis_name, remat=remat,
                )
            else:
                # shard_map leaves the size-1 stage dim on the leading axis
                params = jax.tree.map(lambda p: p[0], local_params)
                out = pipeline_apply(stage_fn, params, mb,
                                     axis_name=axis_name, remat=remat)
            return out.reshape(x.shape[0], *out.shape[2:])

        prefix = (None, axis_name) if circular else (axis_name,)
        in_param_specs = jax.tree.map(
            lambda spec: P(*prefix, *spec), param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        x_spec = P(batch_axes if batch_axes else None)
        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(in_param_specs, x_spec),
            out_specs=x_spec,
            check_vma=False,
        )(stacked_params, batch)

    return jax.jit(run)


def make_pipelined_fn(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    mesh: Mesh,
    param_specs: PyTree,
    *,
    n_microbatches: int,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
) -> Callable[[PyTree, jax.Array], jax.Array]:
    """Global-array entry: ``fn(stacked_params, batch) -> outputs``.

    ``stacked_params`` leaves carry a leading stage dim sharded over ``pipe``
    (spec prefix ``P("pipe", ...)`` — built by :func:`stack_stage_params`);
    ``batch`` (B, ...) is split into ``n_microbatches`` internally.
    ``remat`` forwards to :func:`pipeline_apply` (per-stage recompute).
    """
    return _make_wrapper(
        stage_fn, mesh, param_specs, n_microbatches=n_microbatches,
        n_virtual=None, axis_name=axis_name, remat=remat,
    )


def stack_stage_params(
    init_fn: Callable[[jax.Array], PyTree],
    n_stages: int,
    rng: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = mesh_lib.AXIS_PIPE,
) -> tuple[PyTree, PyTree]:
    """Initialize per-stage params stacked on a leading ``pipe``-sharded dim.

    Returns ``(stacked_params, per_stage_specs)`` — specs are for the
    *unstacked* leaves (the stage dim is added by :func:`make_pipelined_fn`).
    """
    rngs = jax.random.split(rng, n_stages)
    stacked = jax.vmap(init_fn)(rngs)
    specs = jax.tree.map(lambda _: P(), jax.eval_shape(init_fn, rng))
    sharding = jax.tree.map(
        lambda spec: NamedSharding(mesh, P(axis_name, *spec)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, sharding)
    return stacked, specs


# --- Circular (interleaved) schedule -----------------------------------------


def circular_bubble_fraction(
    n_stages: int, n_microbatches: int, n_virtual: int
) -> float:
    """Idle fraction of the circular schedule: (n-1)/(v*M + n-1).

    Each rank holds ``n_virtual`` non-adjacent stage chunks (stage k lives
    on rank ``k % n``, chunk ``k // n``), so the warmup/drain bubble is paid
    once per *ring*, not once per *stage* — a ``n_virtual``-fold reduction
    vs GPipe at equal microbatch count (Megatron interleaved-1F1B's bubble
    shape, obtained in SPMD form).
    """
    return (n_stages - 1) / (n_virtual * n_microbatches + n_stages - 1)


def circular_pipeline_apply(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,  # leaves (n_virtual, ...): this rank's chunk stack
    microbatches: jax.Array,  # (n_micro, mb, ...) — same on every pipe rank
    *,
    n_virtual: int,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
    wire_dtype: object | None = None,
) -> jax.Array:
    """Interleaved-pipeline microbatch loop (shard_map-internal).

    Schedule: stage ``k = c*n + p`` of microbatch ``m`` runs at tick
    ``c*M + m + p`` on rank ``p`` — microbatches stream around the ring
    ``n_virtual`` times; an activation leaving the last rank waits in a
    per-rank circular buffer for ``M - n`` ticks and re-enters rank 0 for
    its next chunk.  Requires ``n_micro >= n_ranks`` (the wrap-around
    arrives before its re-entry slot).  ``stage_fn`` must be
    shape-preserving and ``wire_dtype`` casts the ppermute payload only,
    both as in :func:`pipeline_apply`.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    if n_micro < n:
        raise ValueError(
            f"circular schedule needs n_micro >= n_ranks ({n_micro} < {n})"
        )
    ticks = n_virtual * n_micro + n - 1
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        recv, circ, outputs = carry
        rel = t - s
        c = jnp.clip(rel // n_micro, 0, n_virtual - 1)
        m = jnp.clip(rel, 0, n_virtual * n_micro - 1) % n_micro
        # Rank 0 writes the wrap-around it just received BEFORE reading its
        # input slot (write-then-read makes n_micro == n_ranks legal).
        wrap_slot = (t - n) % n_micro
        circ = lax.dynamic_update_index_in_dim(
            circ, jnp.where(t >= n, recv,
                            lax.dynamic_index_in_dim(circ, wrap_slot,
                                                     keepdims=False)),
            wrap_slot, axis=0,
        )
        x_new = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, n_micro - 1), keepdims=False
        )
        x_circ = lax.dynamic_index_in_dim(circ, m, keepdims=False)
        x0 = jnp.where(t < n_micro, x_new, x_circ)
        x = jnp.where(s == 0, x0, recv)
        params_c = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, c, keepdims=False),
            stage_params,
        )
        y = stage_fn(params_c, x)
        active = (rel >= 0) & (rel < n_virtual * n_micro)
        done = active & (s == n - 1) & (c == n_virtual - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            lax.dynamic_index_in_dim(outputs, m, keepdims=False)
            + jnp.where(done, y, 0.0),
            m, axis=0,
        )
        recv = _wire_ppermute(y, axis_name, perm_fwd, wire_dtype)
        return (recv, circ, outputs), None

    recv0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    circ0 = jnp.zeros_like(microbatches)
    outputs0 = jnp.zeros_like(microbatches)
    (_, _, outputs), _ = lax.scan(
        tick, (recv0, circ0, outputs0), jnp.arange(ticks)
    )
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def stack_circular_stage_params(
    init_fn: Callable[[jax.Array], PyTree],
    n_stages: int,
    n_virtual: int,
    rng: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = mesh_lib.AXIS_PIPE,
) -> tuple[PyTree, PyTree]:
    """Init ``n_stages * n_virtual`` stage params stacked ``(v, n, ...)``.

    Stage ``k`` (execution order) lands at ``[k // n, k % n]`` so the rank
    dim (sharded over ``pipe``) holds each rank's ``n_virtual`` chunk stack.
    Returns ``(stacked, per_stage_specs)`` like :func:`stack_stage_params`.
    """
    total = n_stages * n_virtual
    rngs = jax.random.split(rng, total)
    stacked = jax.vmap(init_fn)(rngs)  # (v*n, ...) in execution order
    stacked = jax.tree.map(
        lambda p: p.reshape(n_virtual, n_stages, *p.shape[1:]), stacked
    )
    specs = jax.tree.map(lambda _: P(), jax.eval_shape(init_fn, rng))
    sharding = jax.tree.map(
        lambda spec: NamedSharding(mesh, P(None, axis_name, *spec)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, sharding)
    return stacked, specs


def make_circular_pipelined_fn(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    mesh: Mesh,
    param_specs: PyTree,
    *,
    n_microbatches: int,
    n_virtual: int,
    axis_name: str = mesh_lib.AXIS_PIPE,
    remat: bool = False,
) -> Callable[[PyTree, jax.Array], jax.Array]:
    """Global-array entry for the circular schedule.

    ``stacked_params`` leaves are ``(n_virtual, n_stages, ...)`` with the
    stage dim sharded over ``pipe`` (:func:`stack_circular_stage_params`).
    """
    if n_virtual < 1:
        raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
    n_stages = mesh.shape[axis_name]
    if n_microbatches < n_stages:
        raise ValueError(
            f"circular schedule needs n_microbatches >= n_stages "
            f"({n_microbatches} < {n_stages}): the wrap-around must arrive "
            "before its re-entry slot"
        )
    return _make_wrapper(
        stage_fn, mesh, param_specs, n_microbatches=n_microbatches,
        n_virtual=n_virtual, axis_name=axis_name, remat=remat,
    )


# --- 1F1B / interleaved-1F1B: forward/backward-interleaved schedules ----------
#
# GPipe above is "all forwards, then autodiff": jax reverses the tick scan,
# so every microbatch's stage input stays live until its backward runs —
# O(n_micro) live microbatch activations per rank.  The 1F1B family
# (PipeDream-flush; Megatron's interleaved variant — PAPERS.md 2412.14374
# positions both) interleaves: each tick runs ONE forward unit and ONE
# backward unit per rank, a microbatch's backward starts as soon as its
# forward clears the last stage, and its saved stage input is freed on the
# spot.  Live stage inputs are bounded by the schedule DEPTH — O(n_stages)
# slots for 1F1B, O(n_stages * n_virtual) for interleaved — independent of
# n_micro.  The backward is written BY HAND inside the same scan (per-unit
# jax.vjp with the saved stage input, i.e. per-stage rematerialization), so
# the loss head must be evaluated inside the loop at the last stage: the
# engine takes a ``head_fn`` and returns loss + gradients directly instead
# of being differentiated from outside.


@dataclasses.dataclass(frozen=True)
class FBSchedule:
    """Static schedule tables for :func:`pipeline_fb_step`.

    Each table is an int32 ``(ticks, n_stages)`` array; column ``s`` is
    rank ``s``'s program.  Per tick a rank runs at most one forward unit
    (``f_*``: chunk, microbatch, act-slot to save the stage input into,
    whether the input comes from the microbatch buffer) and one backward
    unit (``b_*``: chunk, microbatch, act-slot to restore, whether the
    cotangent comes from the in-loop loss head).  ``n_slots`` is the exact
    peak number of saved stage inputs any rank holds — the schedule's
    activation-memory bound, asserted by the generator.
    """

    n_stages: int
    n_micro: int
    n_virtual: int
    n_slots: int
    ticks: int
    tables: dict[str, np.ndarray]

    def bubble_fraction(self) -> float:
        """Idle fraction of the fb schedule's tick timeline: the warmup/
        drain ticks where a rank has no unit to run, over total ticks
        (both phases weighted equally — on real chips the backward unit
        costs ~2x the forward one, which shifts the fraction slightly in
        the schedule's favor)."""
        busy = 2 * self.n_virtual * self.n_micro
        total = 2 * self.ticks
        return (total - busy) / total


def _fb_units(n: int, m_total: int, v: int, forward: bool) -> list:
    """Unit execution order for one rank: ``[(chunk, microbatch), ...]``.

    Megatron's interleaved grouping: microbatches advance in groups of
    ``n`` per chunk, so the cross-chunk wrap-around (rank n-1 -> rank 0)
    always arrives exactly one tick before its consumer — both wraps ride
    the ppermute rings with zero extra buffering.  Backward mirrors the
    chunk order (last chunk first).
    """
    units = []
    for u in range(v * m_total):
        if v == 1:
            c, m = 0, u
        else:
            c = (u % (n * v)) // n
            m = (u // (n * v)) * n + (u % n)
        units.append((v - 1 - c, m) if (not forward and v > 1) else (c, m))
    return units


def fb_schedule(
    n_stages: int, n_microbatches: int, n_virtual: int = 1
) -> FBSchedule:
    """Build (and statically validate) a 1F1B / interleaved-1F1B schedule.

    ``n_virtual == 1`` is plain 1F1B; ``> 1`` is the interleaved variant
    (requires ``n_microbatches`` a positive multiple of ``n_stages``, the
    Megatron grouping constraint).  Every wire hop, act-slot reuse, and
    the peak-slot bound are checked here in plain Python — an off-by-one
    would otherwise surface as silently-wrong gradients.
    """
    n, m_total, v = n_stages, n_microbatches, n_virtual
    if n < 1 or m_total < 1 or v < 1:
        raise ValueError(
            f"need n_stages>=1, n_microbatches>=1, n_virtual>=1; got "
            f"{n}/{m_total}/{v}"
        )
    if v > 1 and (m_total % n or m_total < n):
        raise ValueError(
            f"interleaved schedule needs n_microbatches a positive "
            f"multiple of n_stages ({m_total} vs {n})"
        )
    fwd = _fb_units(n, m_total, v, forward=True)
    bwd = _fb_units(n, m_total, v, forward=False)
    b0 = (v - 1) * n + (n - 1)
    ticks = b0 + (n - 1) + v * m_total
    shape = (ticks, n)
    tabs = {
        k: np.zeros(shape, np.int32)
        for k in ("f_on", "f_c", "f_m", "f_slot", "f_inp",
                  "b_on", "b_c", "b_m", "b_slot", "b_head")
    }
    n_slots = 0
    for s in range(n):
        fwd_tick = {}
        slot_of = {}
        free: list[int] = []
        next_slot = 0
        high = 0
        for t in range(ticks):
            u = t - s
            if 0 <= u < v * m_total:
                c, m = fwd[u]
                fwd_tick[(c, m)] = t
                slot = free.pop() if free else next_slot
                if slot == next_slot:
                    next_slot += 1
                slot_of[(c, m)] = slot
                high = max(high, next_slot)
                tabs["f_on"][t, s] = 1
                tabs["f_c"][t, s] = c
                tabs["f_m"][t, s] = m
                tabs["f_slot"][t, s] = slot
                tabs["f_inp"][t, s] = int(s == 0 and c == 0)
            w = t - b0 - (n - 1 - s)
            if 0 <= w < v * m_total:
                c, m = bwd[w]
                assert (c, m) in slot_of, (
                    f"rank {s}: backward of {(c, m)} at tick {t} before "
                    f"its forward"
                )
                assert fwd_tick[(c, m)] <= t
                slot = slot_of.pop((c, m))
                free.append(slot)
                tabs["b_on"][t, s] = 1
                tabs["b_c"][t, s] = c
                tabs["b_m"][t, s] = m
                tabs["b_slot"][t, s] = slot
                tabs["b_head"][t, s] = int(s == n - 1 and c == v - 1)
        assert not slot_of, f"rank {s}: units never backwarded: {slot_of}"
        n_slots = max(n_slots, high)
    # Wire freshness: the engine keeps ONE recv buffer per direction, so
    # every consumed message must have been sent exactly one tick earlier
    # by the ring neighbor, carrying exactly the consumer's unit.
    for s in range(n):
        for t in range(ticks):
            if tabs["f_on"][t, s] and not tabs["f_inp"][t, s]:
                src = (s - 1) % n
                assert t >= 1 and tabs["f_on"][t - 1, src], (s, t)
                sent = (tabs["f_c"][t - 1, src], tabs["f_m"][t - 1, src])
                want = (tabs["f_c"][t, s], tabs["f_m"][t, s])
                if s > 0:
                    assert sent == want, (s, t, sent, want)
                else:  # wrap: rank n-1's chunk c-1 output feeds chunk c
                    assert sent == (want[0] - 1, want[1]), (s, t, sent, want)
            if tabs["b_on"][t, s] and not tabs["b_head"][t, s]:
                src = (s + 1) % n
                assert t >= 1 and tabs["b_on"][t - 1, src], (s, t)
                sent = (tabs["b_c"][t - 1, src], tabs["b_m"][t - 1, src])
                want = (tabs["b_c"][t, s], tabs["b_m"][t, s])
                if s < n - 1:
                    assert sent == want, (s, t, sent, want)
                else:  # wrap: rank 0's chunk c cotangent feeds chunk c-1
                    assert sent == (want[0] + 1, want[1]), (s, t, sent, want)
    return FBSchedule(
        n_stages=n, n_micro=m_total, n_virtual=v, n_slots=n_slots,
        ticks=ticks, tables=tabs,
    )


def pipeline_fb_step(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    head_fn: Callable[[PyTree, jax.Array, PyTree], jax.Array],
    stage_params: PyTree,  # leaves (n_virtual, lps, ...): this rank's chunks
    head_params: PyTree,
    microbatches: jax.Array,  # (n_micro, mb, ...) — this shard's batch slice
    labels: PyTree,  # leaves (n_micro, mb, ...)
    sched: FBSchedule,
    *,
    axis_name: str = mesh_lib.AXIS_PIPE,
    cotangent_scale: float = 1.0,
    wire_dtype: object | None = None,
) -> tuple[jax.Array, PyTree, PyTree, jax.Array]:
    """Run one fused forward+backward 1F1B pass (shard_map-internal).

    Per tick every rank runs (a) its forward unit — stage_fn on the
    recv'd/new microbatch, saving the stage INPUT into its act-slot ring —
    and (b) its backward unit — ``jax.vjp(stage_fn)`` on the saved input
    (per-stage rematerialization), with the cotangent either received
    from the right neighbor or, at the last stage, produced in-tick by
    ``jax.vjp(head_fn)`` seeded with ``cotangent_scale``.  Both phases are
    ``lax.cond``-gated (the predicate depends only on (tick, pipe rank),
    so model/seq peers inside ``stage_fn`` always agree — its collectives
    stay uniform; ``head_fn`` must be collective-free).

    Returns per-shard ``(loss_sum, stage_grads, head_grads, dx0)``: the
    caller applies the cross-shard psums that shard_map's own transpose
    would have inserted (grads of replicated inputs) and scales the loss.
    ``head_fn(head_params, y, labels_mb) -> scalar`` must be the mean loss
    of one microbatch.  Because this scan never gets differentiated from
    outside, XLA stores no per-tick residuals: live activation memory is
    exactly the ``sched.n_slots`` act ring plus carries.
    """
    n = sched.n_stages
    s = lax.axis_index(axis_name)
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]
    perm_bwd = [(i, (i - 1) % n) for i in range(n)]
    act_shape = microbatches.shape[1:]

    def pick_chunk(params, c):
        return jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, c, keepdims=False), params
        )

    def tick(carry, trow):
        recv_f, recv_b, acts, d_stage, d_head, dx0, loss_acc = carry

        def g(key):
            return lax.dynamic_index_in_dim(trow[key], s, keepdims=False)

        # ---- forward phase ----
        f_on = g("f_on") > 0
        f_c, f_m, f_slot = g("f_c"), g("f_m"), g("f_slot")
        x_new = lax.dynamic_index_in_dim(microbatches, f_m, keepdims=False)
        x = jnp.where(g("f_inp") > 0, x_new, recv_f)

        y = lax.cond(
            f_on,
            lambda opr: stage_fn(*opr),
            lambda opr: jnp.zeros(act_shape, x.dtype),
            (pick_chunk(stage_params, f_c), x),
        )
        old_slot = lax.dynamic_index_in_dim(acts, f_slot, keepdims=False)
        acts = lax.dynamic_update_index_in_dim(
            acts, jnp.where(f_on, x, old_slot), f_slot, axis=0
        )

        # ---- backward phase ----
        b_on = g("b_on") > 0
        b_c, b_m, b_slot = g("b_c"), g("b_m"), g("b_slot")
        b_head = g("b_head") > 0
        x_saved = lax.dynamic_index_in_dim(acts, b_slot, keepdims=False)
        lab = jax.tree.map(
            lambda v: lax.dynamic_index_in_dim(v, b_m, keepdims=False),
            labels,
        )
        params_b = pick_chunk(stage_params, b_c)

        def bwd_branch(opr):
            params_c, xx, rb, lab_ = opr
            yb, pull = jax.vjp(stage_fn, params_c, xx)

            def head_branch(o):
                hp, yy, ll = o
                loss_u, hpull = jax.vjp(
                    lambda hp_, y_: head_fn(hp_, y_, ll), hp, yy
                )
                d_hp, d_y = hpull(
                    jnp.asarray(cotangent_scale, loss_u.dtype)
                )
                return loss_u.astype(jnp.float32), d_hp, d_y

            def no_head(o):
                hp, yy, _ = o
                return (jnp.zeros((), jnp.float32),
                        jax.tree.map(jnp.zeros_like, hp),
                        jnp.zeros_like(yy))

            loss_u, d_hp, d_y = lax.cond(
                b_head, head_branch, no_head, (head_params, yb, lab_)
            )
            cot = jnp.where(b_head, d_y, rb.astype(yb.dtype))
            d_pc, dxx = pull(cot)
            return loss_u, d_hp, d_pc, dxx

        def bwd_zero(opr):
            params_c, xx, _, _ = opr
            return (jnp.zeros((), jnp.float32),
                    jax.tree.map(jnp.zeros_like, head_params),
                    jax.tree.map(jnp.zeros_like, params_c),
                    jnp.zeros_like(xx))

        loss_u, d_hp, d_pc, dx = lax.cond(
            b_on, bwd_branch, bwd_zero, (params_b, x_saved, recv_b, lab)
        )
        loss_acc = loss_acc + loss_u
        d_head = jax.tree.map(jnp.add, d_head, d_hp)
        d_stage = jax.tree.map(
            lambda acc, gl: lax.dynamic_update_index_in_dim(
                acc,
                lax.dynamic_index_in_dim(acc, b_c, keepdims=False)
                + gl.astype(acc.dtype),
                b_c, axis=0,
            ),
            d_stage, d_pc,
        )
        is_dx0 = b_on & (s == 0) & (b_c == 0)
        old0 = lax.dynamic_index_in_dim(dx0, b_m, keepdims=False)
        dx0 = lax.dynamic_update_index_in_dim(
            dx0,
            jnp.where(is_dx0, old0 + dx.astype(dx0.dtype), old0),
            b_m, axis=0,
        )

        recv_f = _wire_ppermute(y, axis_name, perm_fwd, wire_dtype)
        # Cotangents ride the reverse wire at FULL precision: unlike the
        # forward activations (bf16-upcast values for bf16 models, where
        # the wire_dtype roundtrip is bit-exact), gradient cotangents are
        # full-range fp32 — casting them would silently round every
        # gradient and break handoff_dtype's bit-exactness contract.
        recv_b = _wire_ppermute(
            jnp.where(b_on, dx, jnp.zeros_like(dx)).astype(
                microbatches.dtype
            ),
            axis_name, perm_bwd, None,
        )
        return (recv_f, recv_b, acts, d_stage, d_head, dx0, loss_acc), None

    init = (
        jnp.zeros(act_shape, microbatches.dtype),
        jnp.zeros(act_shape, microbatches.dtype),
        jnp.zeros((sched.n_slots, *act_shape), microbatches.dtype),
        jax.tree.map(jnp.zeros_like, stage_params),
        jax.tree.map(jnp.zeros_like, head_params),
        jnp.zeros((sched.n_micro, *act_shape), microbatches.dtype),
        jnp.zeros((), jnp.float32),
    )
    xs = {k: jnp.asarray(v) for k, v in sched.tables.items()}
    (_, _, _, d_stage, d_head, dx0, loss_sum), _ = lax.scan(tick, init, xs)
    return loss_sum, d_stage, d_head, dx0
