"""ResNets: ResNet-20 (CIFAR, config #2) and ResNet-50 (ImageNet, config #3 —
the headline benchmark model, BASELINE.json metric "ResNet-50/ImageNet
images/sec/chip").

TPU-first choices:
- compute in bfloat16 (MXU native), params and batch-norm stats in float32;
- NHWC layout (XLA TPU's preferred conv layout);
- no data-dependent control flow — the whole net is one traced graph.

Architecture follows the standard He et al. residual recipes (v1.5 bottleneck
for ResNet-50: stride on the 3x3, as in the common benchmark variant).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class ResidualBlock(nn.Module):
    """Basic 3x3+3x3 block (CIFAR ResNet-20)."""

    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
        )
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 padding="SAME")(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50), v1.5: stride on the 3x3."""

    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
        )
        residual = x
        y = nn.relu(norm()(conv(self.filters, (1, 1))(x)))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 padding="SAME")(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    """ResNet-6n+2 for CIFAR (n=3 -> ResNet-20)."""

    num_classes: int = 10
    n: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype, param_dtype=jnp.float32)(x))
        for stage, filters in enumerate((16, 32, 64)):
            for block in range(self.n):
                strides = 2 if stage > 0 and block == 0 else 1
                x = ResidualBlock(filters, strides, self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class ImageNetResNet(nn.Module):
    """Bottleneck ResNet for ImageNet; stage_sizes (3,4,6,3) -> ResNet-50.

    ``space_to_depth`` re-expresses the stem conv the MLPerf-TPU way
    (docs/RESNET_PERF.md §3 L2): the C=3 minor dim of the 224x224x3 input
    defeats the TPU's (8,128) register tiling (conv1 fwd measured at 480
    GB/s vs 758+ elsewhere).  Packing 2x2 spatial blocks into channels
    gives a 112x112x12 input, and the 7x7/s2 stem is equivalent to a
    4x4/s1 conv on it: output(i,j) = sum_{di,dj} W[di,dj] x[2i+di-3,
    2j+dj-3]; writing di-3 = 2p+a (a in {0,1}) maps every tap onto kernel
    position p in {-2..1} and packed channel (a,b,c) — a 4x4 kernel with
    asymmetric padding (2,1).  The 4x4x12x64 parameterization is a strict
    superset of the 7x7x3x64 stem (per axis, 1 of the 8 (p,a) pairs maps
    to tap di=-1 outside the 7-tap support — 15 of the 64 2-D combinations
    — and trains as free zeros), so the model class is unchanged up to
    that enlargement — the standard MLPerf treatment.  Equivalence is
    pinned by tests/test_models.py::test_space_to_depth_stem_equivalence.
    """

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    dtype: jnp.dtype = jnp.bfloat16
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):  # x: (B, 224, 224, 3)
        x = x.astype(self.dtype)
        if self.space_to_depth:
            b, h, w, c = x.shape
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
            x = nn.Conv(64, (4, 4), padding=[(2, 1), (2, 1)],
                        use_bias=False, dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype, param_dtype=jnp.float32)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, size in enumerate(self.stage_sizes):
            filters = 64 * 2**stage
            for block in range(size):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(filters, strides, self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def ResNet20(**kw) -> CifarResNet:
    return CifarResNet(n=3, **kw)


def ResNet50(**kw) -> ImageNetResNet:
    return ImageNetResNet(stage_sizes=(3, 4, 6, 3), **kw)
