"""Vision Transformer — an extra model family beyond the reference zoo.

The reference's image configs are all ConvNets (LeNet/ResNet —
BASELINE.json configs 1-3); a ViT exercises the framework's encoder
path on images: patch embedding as one strided conv (MXU-friendly),
pre-LN blocks over ``ops.attention.dot_product_attention`` (so the Pallas
flash kernel drops in at long patch sequences), Megatron TP layout over
the ``model`` axis, bf16 activations with fp32 LayerNorm — the same
TPU-first choices as the BERT/GPT implementations.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention
from ..parallel.sharding import LayoutMap
from .layers import FusedLayerNorm, dense


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 384      # ViT-S
    num_layers: int = 12
    num_heads: int = 6
    intermediate_size: int = 1536
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    #: Quantized compute (ops/quant.py): routes the block matmuls (qkv,
    #: proj, fc_in, fc_out) through the int8/fp8 quantized dot (STE
    #: backward).  The patch-embed conv, layer norms, pos embedding, and
    #: the fp32 classifier head stay high-precision.
    quant: str | None = None

    def __post_init__(self):
        from ..ops.quant import validate_mode

        validate_mode(self.quant)


def vit_s16() -> ViTConfig:
    return ViTConfig()


def vit_tiny() -> ViTConfig:
    """Test-size: 32px/8px patches, 2 layers, 128 hidden."""
    return ViTConfig(
        image_size=32, patch_size=8, num_classes=10,
        hidden_size=128, num_layers=2, num_heads=4, intermediate_size=256,
    )


class ViTBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        h = FusedLayerNorm(name="ln1")(x)
        # Fused QKV as one (D, 3H) matmul, like the GPT blocks: the flat 3H
        # output dim shards over `model` for any tp dividing 3*hidden (the
        # per-head layout would require tp | num_heads — ViT-S has 6).
        qkv = dense(
            3 * cfg.hidden_size, dtype=cfg.dtype, quant=cfg.quant,
            use_bias=False, name="qkv",
        )(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (*h.shape[:2], cfg.num_heads, head_dim)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        attn = dot_product_attention(q, k, v)  # bidirectional
        attn = attn.reshape(*h.shape[:2], cfg.hidden_size)
        attn = dense(
            cfg.hidden_size, dtype=cfg.dtype, quant=cfg.quant,
            use_bias=False, name="proj",
        )(attn)
        x = x + attn
        h = FusedLayerNorm(name="ln2")(x)
        h = dense(cfg.intermediate_size, dtype=cfg.dtype, quant=cfg.quant,
                  use_bias=False, name="fc_in")(h)
        h = nn.gelu(h)
        h = dense(cfg.hidden_size, dtype=cfg.dtype, quant=cfg.quant,
                  use_bias=False, name="fc_out")(h)
        if cfg.dropout_rate and not deterministic:
            h = nn.Dropout(cfg.dropout_rate)(h, deterministic=False)
        return x + h


class ViT(nn.Module):
    """ViT classifier; ``apply(variables, images, train=...)`` -> fp32 logits
    (the framework classification-loss contract — same as ResNet)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        cfg = self.cfg
        if tuple(images.shape[1:]) != (cfg.image_size, cfg.image_size, 3):
            raise ValueError(
                f"expected (B, {cfg.image_size}, {cfg.image_size}, 3) NHWC "
                f"input, got {images.shape}"
            )
        # Patchify = one strided conv: (B, H/P, W/P, D) in a single MXU op.
        x = nn.Conv(
            cfg.hidden_size,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            dtype=cfg.dtype, name="patch_embed",
        )(images.astype(cfg.dtype))
        b, ph, pw, d = x.shape
        x = x.reshape(b, ph * pw, d)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, ph * pw, cfg.hidden_size), jnp.float32,
        )
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = ViTBlock(cfg, name=f"block_{i}")(x, deterministic=not train)
        x = FusedLayerNorm(out_dtype=jnp.float32, name="ln_f")(x)
        x = jnp.mean(x, axis=1)  # global average pool (no cls token)
        return nn.Dense(
            cfg.num_classes, dtype=jnp.float32, name="head"
        )(x)


def vit_layout() -> LayoutMap:
    """Megatron TP rules over ``model``: QKV/fc_in column-parallel,
    proj/fc_out row-parallel (one all-reduce per block, inserted by XLA)."""
    return LayoutMap([
        (r".*qkv/kernel", P(None, "model")),
        (r".*proj/kernel", P("model", None)),
        (r".*fc_in/kernel", P(None, "model")),
        (r".*fc_out/kernel", P("model", None)),
    ])
