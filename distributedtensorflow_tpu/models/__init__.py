"""Model zoo: the reference's five workload models + a long-context decoder
LM, TPU-first flax modules."""

from .generate import decode_step, generate, prefill  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTLM,
    gpt_layout,
    gpt_medium,
    gpt_small,
    gpt_tiny,
    lm_eval,
    lm_loss,
    nan_taps,
)
from .lenet import LeNet5  # noqa: F401
from .resnet import (  # noqa: F401
    CifarResNet,
    ImageNetResNet,
    ResNet20,
    ResNet50,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertEncoder,
    BertForMLM,
    bert_base,
    bert_layout,
    bert_tiny,
    max_predictions_for,
    mlm_eval,
    mlm_loss,
)
from .vit import (  # noqa: F401
    ViT,
    ViTConfig,
    vit_layout,
    vit_s16,
    vit_tiny,
)
from .seq2seq import (  # noqa: F401
    Seq2SeqConfig,
    Seq2SeqLM,
    seq2seq_eval,
    seq2seq_generate,
    seq2seq_layout,
    seq2seq_loss,
    seq2seq_small,
    seq2seq_tiny,
)
from .widedeep import (  # noqa: F401
    WideDeep,
    WideDeepConfig,
    widedeep_layout,
    widedeep_eval,
    widedeep_loss,
    widedeep_test_config,
)


def make_nan_taps(model):
    """Best-effort NaN-provenance tap forward for ``obs.dynamics``:
    ``tap_fn(params, batch) -> {"NNN_module": nonfinite_count}`` with
    the forward position encoded in the key (``000_wte``, ``001_h0``,
    ... — jit canonicalizes dict outputs to sorted key order, so bare
    module names would lose forward order), or None for models without
    activation taps (provenance then falls back to the model-agnostic
    parameter/gradient censuses)."""
    if isinstance(model, GPTLM):
        return nan_taps(model)
    return None
