"""Encoder-decoder seq2seq LM — the T5-class model family, redesigned
TPU-first.

Reference anchor: the reference stack's encoder-decoder coverage is the
Keras seq2seq family (TF model-garden T5/transformer: encoder stack +
causal decoder stack + cross-attention + teacher forcing).  SURVEY.md
§2.3's zoo row names encoder-only (BERT), decoder-only (GPT), conv and
recsys families; this adds the remaining transformer family so a
reference user's seq2seq workloads have a home.

TPU-first deviations from the T5 paper (deliberate — this is a redesign,
not a port):

- **RoPE instead of relative-position bias buckets**: T5's learned
  bucketed bias adds a (H, Sq, Sk) tensor to every score matrix, which
  blocks the flash-attention kernels (they support masks/segments, not
  additive bias) and costs HBM at long sequence.  Rotary embeddings are
  position-relative too, compose with every kernel in ``ops/attention``,
  and add zero parameters.  Cross-attention uses each side's OWN
  positions (decoder positions rotate q, encoder positions rotate k) —
  relative offsets between the streams are meaningful.
- **Pre-RMSNorm** (fp32 math, like T5 1.1) everywhere; bf16 matmuls with
  the same dtype discipline as ``models/gpt.py``.
- **Tied embedding + chunked CE head**: one (V, D) table serves encoder
  input, decoder input, and the output head via
  :func:`..ops.xent.chunked_softmax_xent` — full (B, S, V) logits never
  materialize, and the table row-shards over ``model`` exactly like the
  GPT/BERT layouts (the head is TP-clean under GSPMD, ops/xent.py note).
- Attention kernels route through :func:`..ops.attention
  .dot_product_attention`, so Pallas flash drops in on TPU for the
  causal decoder self-attention.

Naming mirrors models/bert.py (``query``/``key``/``value``/``out``,
``mlp_in``/``mlp_out``) so :func:`seq2seq_layout` reuses the proven
Megatron column/row-parallel rules.
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention
from ..ops.xent import chunked_argmax, chunked_softmax_xent, tied_head_logits
from ..parallel.sharding import LayoutMap
from .gpt import cached_attention_with_vars, rope, rope_tables


@dataclasses.dataclass(frozen=True)
class Seq2SeqConfig:
    vocab_size: int = 32128
    hidden_size: int = 512
    num_heads: int = 8
    enc_layers: int = 6
    dec_layers: int = 6
    intermediate_size: int = 2048
    max_seq: int = 512
    dropout_rate: float = 0.0
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.bfloat16
    #: Grouped-query attention: K/V heads per attention (self AND cross);
    #: None = num_heads (MHA).  Same capability/convention as
    #: models.gpt.GPTConfig.num_kv_heads — shrinks the decoder's
    #: self-attention KV cache and the banked cross K/V by the group
    #: factor when serving.
    num_kv_heads: int | None = None
    #: id that starts every decoder input (teacher forcing shift-in).
    bos_id: int = 0
    #: padding id — excluded from the loss and from encoder attention.
    pad_id: int = 1

    def __post_init__(self):
        kv = self.num_kv_heads
        if kv is not None and (kv <= 0 or self.num_heads % kv):
            raise ValueError(
                f"num_kv_heads={kv} must divide num_heads={self.num_heads}"
            )

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads


def seq2seq_small() -> Seq2SeqConfig:
    """T5-small-scale (~60M params with a 32k vocab)."""
    return Seq2SeqConfig()


def seq2seq_tiny() -> Seq2SeqConfig:
    """Test-size config (2+2 layers, 128 hidden)."""
    return Seq2SeqConfig(
        vocab_size=512, hidden_size=128, num_heads=4, enc_layers=2,
        dec_layers=2, intermediate_size=256, max_seq=128,
    )


class _Attention(nn.Module):
    """Self- or cross-attention with per-stream RoPE.

    ``kv`` is the key/value source (== ``x`` for self-attention).
    ``q_positions``/``kv_positions`` rotate q and k with their own
    stream's positions; cross-attention passes encoder positions for k.

    ``decode=True`` selects the serving path:

    - causal self-attention runs the incremental KV cache (new keys/
      values land in the flax "cache" collection at ``cache_index``,
      attention reads the whole static cache with validity masking — the
      same idiom as ``models/gpt.py``);
    - cross-attention projects the encoder output to K/V exactly ONCE —
      the priming apply computes and stores them in the cache, and every
      later step reads the stored tensors without touching the key/value
      kernels (the encoder stream is frozen during decoding, so this is
      a pure dedup, bit-identical by the generate equivalence test).
    """

    cfg: Seq2SeqConfig
    causal: bool = False
    decode: bool = False

    @nn.compact
    def __call__(self, x, kv, *, q_positions, kv_positions, mask,
                 deterministic: bool, q_tabs=None, kv_tabs=None):
        cfg = self.cfg
        if kv is None:  # self-attention
            kv = x
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name, heads=cfg.num_heads: nn.DenseGeneral(
            (heads, head_dim), dtype=cfg.dtype, use_bias=False,
            name=name,
        )
        kv_dense = lambda name: dense(name, cfg.kv_heads)
        q = rope(dense("query")(x), q_positions, cfg.rope_theta, q_tabs)
        cross_decode = self.decode and not self.causal
        if cross_decode and self.has_variable("cache", "cross_key"):
            # Step apply: the projected encoder K/V were stored by the
            # priming apply — skip the key/value kernels entirely (this
            # branch is a distinct trace, so the matmuls never compile
            # into the step program).
            k = self.get_variable("cache", "cross_key")
            v = self.get_variable("cache", "cross_value")
        else:
            k = rope(kv_dense("key")(kv), kv_positions, cfg.rope_theta,
                     kv_tabs)
            v = kv_dense("value")(kv)
            if cross_decode and not self.is_initializing():
                # Bank the real projections for the step applies.  NOT
                # during .init(): the canonical flax cache-allocation
                # idiom inits with dummy inputs, and banking those would
                # make the presence check above serve dummy-derived K/V
                # on the real priming apply — by skipping the store here,
                # an init-created cache has no cross_key and the first
                # real (mutable) apply always primes from the real
                # encoder output.
                self.variable("cache", "cross_key", lambda: k)
                self.variable("cache", "cross_value", lambda: v)
        if self.decode and self.causal:
            out = self._cached_attention(q, k, v)
        else:
            out = dot_product_attention(
                q, k, v, mask=mask, causal=self.causal
            )
        out = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype, use_bias=False,
            name="out",
        )(out)
        if not deterministic:
            out = nn.Dropout(cfg.dropout_rate)(out, deterministic=False)
        return out

    def _cached_attention(self, q, k, v):
        """One decode step against the KV cache (the same shared helper
        as ``models/gpt.py`` — serving paths cannot diverge)."""
        return cached_attention_with_vars(self, q, k, v, self.cfg.max_seq)


class _MLP(nn.Module):
    cfg: Seq2SeqConfig

    @nn.compact
    def __call__(self, x, deterministic: bool):
        cfg = self.cfg
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, use_bias=False,
                     name="mlp_in")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, use_bias=False,
                     name="mlp_out")(h)
        if not deterministic:
            h = nn.Dropout(cfg.dropout_rate)(h, deterministic=False)
        return h


class EncoderBlock(nn.Module):
    cfg: Seq2SeqConfig

    @nn.compact
    def __call__(self, x, *, positions, mask, deterministic,
                 rope_tabs=None):
        cfg = self.cfg
        norm = lambda name: nn.RMSNorm(dtype=jnp.float32, name=name)
        x = x + _Attention(cfg, name="attention")(
            norm("ln_attn")(x).astype(cfg.dtype), None,
            q_positions=positions, kv_positions=positions, mask=mask,
            deterministic=deterministic, q_tabs=rope_tabs, kv_tabs=rope_tabs,
        )
        x = x + _MLP(cfg, name="mlp")(
            norm("ln_mlp")(x).astype(cfg.dtype), deterministic
        )
        return x


class DecoderBlock(nn.Module):
    cfg: Seq2SeqConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, enc_out, *, positions, enc_positions, cross_mask,
                 deterministic, rope_tabs=None, enc_rope_tabs=None):
        cfg = self.cfg
        norm = lambda name: nn.RMSNorm(dtype=jnp.float32, name=name)
        x = x + _Attention(cfg, causal=True, decode=self.decode,
                           name="attention")(
            norm("ln_attn")(x).astype(cfg.dtype), None,
            q_positions=positions, kv_positions=positions, mask=None,
            deterministic=deterministic, q_tabs=rope_tabs,
            kv_tabs=rope_tabs,
        )
        x = x + _Attention(cfg, decode=self.decode, name="cross_attention")(
            norm("ln_cross")(x).astype(cfg.dtype), enc_out,
            q_positions=positions, kv_positions=enc_positions,
            mask=cross_mask, deterministic=deterministic,
            q_tabs=rope_tabs, kv_tabs=enc_rope_tabs,
        )
        x = x + _MLP(cfg, name="mlp")(
            norm("ln_mlp")(x).astype(cfg.dtype), deterministic
        )
        return x


class Seq2SeqLM(nn.Module):
    """Tied-embedding encoder-decoder; ``__call__`` returns the decoder's
    final hidden states (the loss applies the chunked tied head).
    ``decode_cache=True`` switches the decoder self-attention to the
    KV-cache incremental path (:func:`seq2seq_generate`)."""

    cfg: Seq2SeqConfig
    #: KV-cache incremental decoding for the decoder self-attention
    #: (named to avoid shadowing the ``decode`` method).
    decode_cache: bool = False

    def setup(self):
        cfg = self.cfg
        self.shared_embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="shared"
        )
        self.enc_blocks = [
            EncoderBlock(cfg, name=f"enc_{i}") for i in range(cfg.enc_layers)
        ]
        self.dec_blocks = [
            DecoderBlock(cfg, decode=self.decode_cache, name=f"dec_{i}")
            for i in range(cfg.dec_layers)
        ]
        self.enc_norm = nn.RMSNorm(dtype=jnp.float32, name="enc_norm")
        self.dec_norm = nn.RMSNorm(dtype=jnp.float32, name="dec_norm")

    def _check_len(self, ids, stream: str):
        # RoPE itself is unbounded but extrapolates poorly past trained
        # lengths; max_seq is the declared training envelope and the
        # workload preset grows it with seq_len overrides.
        if ids.shape[-1] > self.cfg.max_seq:
            raise ValueError(
                f"{stream} length {ids.shape[-1]} exceeds "
                f"cfg.max_seq={self.cfg.max_seq}; raise max_seq (RoPE has "
                "no table to outgrow, but lengths beyond the trained "
                "envelope degrade)"
            )

    def encode(self, encoder_ids, deterministic: bool = True):
        cfg = self.cfg
        self._check_len(encoder_ids, "encoder")
        positions = jnp.broadcast_to(
            jnp.arange(encoder_ids.shape[-1]), encoder_ids.shape
        )
        pad = encoder_ids != cfg.pad_id  # (B, Senc) True = real token
        # keys masked everywhere a pad sits; every query row stays valid
        # (padded QUERY rows produce garbage that the loss never reads).
        mask = pad[:, None, None, :]
        x = self.shared_embed(encoder_ids).astype(jnp.float32)
        # Trig once per stream, shared by every block (same hoist as GPT).
        tabs = rope_tables(
            positions, cfg.hidden_size // cfg.num_heads, cfg.rope_theta,
            cfg.dtype,
        )
        for block in self.enc_blocks:
            x = block(x, positions=positions, mask=mask,
                      deterministic=deterministic, rope_tabs=tabs)
        return self.enc_norm(x), pad, positions

    def decode(self, decoder_ids, enc_out, enc_pad, enc_positions,
               deterministic: bool = True, positions=None):
        self._check_len(decoder_ids, "decoder")
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(decoder_ids.shape[-1]), decoder_ids.shape
            )
        cross_mask = enc_pad[:, None, None, :]
        x = self.shared_embed(decoder_ids).astype(jnp.float32)
        cfg = self.cfg
        tabs = rope_tables(
            positions, cfg.hidden_size // cfg.num_heads, cfg.rope_theta,
            cfg.dtype,
        )
        enc_tabs = rope_tables(
            enc_positions, cfg.hidden_size // cfg.num_heads, cfg.rope_theta,
            cfg.dtype,
        )
        for block in self.dec_blocks:
            x = block(x, enc_out.astype(self.cfg.dtype),
                      positions=positions, enc_positions=enc_positions,
                      cross_mask=cross_mask, deterministic=deterministic,
                      rope_tabs=tabs, enc_rope_tabs=enc_tabs)
        return self.dec_norm(x)

    def __call__(self, encoder_ids, decoder_ids, deterministic: bool = True):
        enc_out, enc_pad, enc_positions = self.encode(
            encoder_ids, deterministic
        )
        return self.decode(
            decoder_ids, enc_out, enc_pad, enc_positions, deterministic
        )


def shift_right(targets: jax.Array, bos_id: int) -> jax.Array:
    """Teacher-forcing decoder input: [BOS, t0, t1, ...] (drops the last)."""
    return jnp.concatenate(
        [jnp.full_like(targets[:, :1], bos_id), targets[:, :-1]], axis=1
    )


def seq2seq_loss(model: Seq2SeqLM):
    """Mean next-token NLL over non-pad target positions, tied chunked
    head (same reduction semantics as gpt.lm_loss)."""
    cfg = model.cfg

    def loss_fn(params, model_state, batch, rng):
        targets = batch["targets"]
        dec_in = shift_right(targets, cfg.bos_id)
        hidden = model.apply(
            {"params": params}, batch["encoder_ids"], dec_in,
            deterministic=not cfg.dropout_rate,
            rngs={"dropout": rng} if cfg.dropout_rate else None,
        )
        mask = (targets != cfg.pad_id).astype(jnp.float32)
        loss = chunked_softmax_xent(
            hidden, params["shared"]["embedding"], targets, mask,
            compute_dtype=cfg.dtype,
        )
        return loss, ({"perplexity": jnp.exp(loss)}, model_state)

    return loss_fn


def seq2seq_eval(model: Seq2SeqLM):
    """Teacher-forced token accuracy + loss/perplexity; the argmax
    streams token chunks (:func:`..ops.xent.chunked_argmax`) so eval,
    like training, never materializes (B, S, V) logits."""
    cfg = model.cfg

    def metric_fn(params, model_state, batch):
        targets = batch["targets"]
        dec_in = shift_right(targets, cfg.bos_id)
        hidden = model.apply(
            {"params": params}, batch["encoder_ids"], dec_in,
            deterministic=True,
        )
        mask = (targets != cfg.pad_id).astype(jnp.float32)
        loss = chunked_softmax_xent(
            hidden, params["shared"]["embedding"], targets, mask,
            compute_dtype=cfg.dtype,
        )
        pred = chunked_argmax(
            hidden, params["shared"]["embedding"], compute_dtype=cfg.dtype
        )
        correct = (pred == targets).astype(jnp.float32)
        acc = jnp.sum(correct * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return {"loss": loss, "accuracy": acc,
                "perplexity": jnp.exp(loss)}

    return metric_fn


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "greedy", "eos_token_id"),
)
def _s2s_generate_impl(params, encoder_ids, rng, temperature, *,
                       cfg: Seq2SeqConfig, max_new_tokens: int,
                       greedy: bool, eos_token_id: int):
    from .generate import _sample

    b = encoder_ids.shape[0]
    wte = params["shared"]["embedding"]

    enc_model = Seq2SeqLM(cfg)
    enc_out, enc_pad, enc_pos = enc_model.apply(
        {"params": params}, encoder_ids, method=enc_model.encode
    )

    model = Seq2SeqLM(cfg, decode_cache=True)
    tokens = jnp.full((b, max_new_tokens + 1), cfg.bos_id, jnp.int32)
    # Prime the cache with BOS at position 0.
    hidden0, vars0 = model.apply(
        {"params": params}, tokens[:, :1], enc_out, enc_pad, enc_pos,
        positions=jnp.zeros((b, 1), jnp.int32),
        method=model.decode, mutable=["cache"],
    )
    eos = eos_token_id

    def step(carry, t):
        tokens, cache, rng, hidden, done = carry
        rng, sub = jax.random.split(rng)
        logits = tied_head_logits(hidden[:, -1], wte, cfg.dtype)  # (B, V)
        nxt = _sample(logits, sub, temperature, greedy=greedy, top_k=0)
        if eos >= 0:
            nxt = jnp.where(done, eos, nxt)
            done = done | (nxt == eos)
        nxt = nxt.astype(jnp.int32)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, nxt[:, None], t + 1, axis=1
        )
        hidden, vars_out = model.apply(
            {"params": params, "cache": cache}, nxt[:, None],
            enc_out, enc_pad, enc_pos,
            positions=jnp.full((b, 1), t + 1, jnp.int32),
            method=model.decode, mutable=["cache"],
        )
        return (tokens, vars_out["cache"], rng, hidden, done), None

    (tokens, _, _, _, _), _ = jax.lax.scan(
        step,
        (tokens, vars0["cache"], rng, hidden0,
         jnp.zeros((b,), bool)),
        jnp.arange(max_new_tokens),
    )
    return tokens[:, 1:]


def seq2seq_generate(
    params,
    encoder_ids: jax.Array,  # (B, S_enc) with pad_id padding
    *,
    cfg: Seq2SeqConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    eos_token_id: int | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Autoregressive decoding: encode once, then KV-cache decoder steps.

    Returns (B, max_new_tokens) generated ids (BOS excluded).
    ``temperature=0`` is greedy; ``eos_token_id`` freezes a sequence from
    its first eos on (static shapes).  Mirrors ``models/generate.py``'s
    GPT loop: encoder forward, cache priming, and the whole decode scan
    compile as ONE jitted program — no host round-trips per token.
    """
    if cfg.max_seq < max_new_tokens + 1:
        raise ValueError(
            f"cfg.max_seq={cfg.max_seq} < 1+max_new_tokens="
            f"{max_new_tokens + 1}; raise max_seq"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _s2s_generate_impl(
        params, encoder_ids.astype(jnp.int32), rng,
        jnp.asarray(max(temperature, 0.0), jnp.float32),
        cfg=cfg, max_new_tokens=int(max_new_tokens),
        greedy=float(temperature) <= 0.0,
        eos_token_id=-1 if eos_token_id is None else int(eos_token_id),
    )


def seq2seq_layout(cfg: Seq2SeqConfig | None = None) -> LayoutMap:
    """Megatron TP rules over ``model`` — same column/row split as
    :func:`..models.bert.bert_layout`, applied to self-, cross-, and MLP
    kernels in both stacks; the shared table row-shards (vocab) so the
    chunked head partitions cleanly (ops/xent.py TP note).

    GQA (``cfg.num_kv_heads < num_heads``): the key/value kernels'
    heads axis may be smaller than the TP degree, so head-sharding them
    would fail at parameter placement — they stay replicated instead
    (the Megatron-GQA convention when tp > kv_heads; they are the
    smallest kernels in the block, E x Hkv x D)."""
    rules = [
        (r"(attention|cross_attention)/out/kernel", P("model", None, None)),
        (r"mlp_in/kernel", P(None, "model")),
        (r"mlp_out/kernel", P("model", None)),
        (r"shared/embedding", P("model", None)),
    ]
    if cfg is not None and cfg.kv_heads != cfg.num_heads:
        rules.insert(0, (r"query/kernel", P(None, "model", None)))
        # key/value: no rule -> replicated
    else:
        rules.insert(0, (r"(query|key|value)/kernel", P(None, "model", None)))
    return LayoutMap(rules)
