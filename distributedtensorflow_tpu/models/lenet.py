"""LeNet-5 for MNIST — reference workload config #1 (BASELINE.json:
"MNIST LeNet-5 single-worker, OneDeviceStrategy").

Classic LeCun-98 shape: two conv+pool stages, then 120-84-10 dense head.
Compute dtype defaults to float32 (the model is tiny; MXU gain is nil).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):  # x: (B, 28, 28, 1) or (B, 32, 32, 1)
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.tanh(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.tanh(nn.Dense(84, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
