"""Wide&Deep recommender — reference workload config #5 (BASELINE.json:
"Async parameter-server Wide&Deep, ParameterServerStrategy, sparse embeddings").

The reference shards its big embedding tables across parameter servers with
``ShardedVariable`` + partitioners and trains async (SURVEY.md §3.3).  The
TPU-native redesign (SURVEY.md §2.4 "Async PS" row, §7 hard parts):

- embedding tables are *model-parallel sharded* over the ``model`` mesh axis
  (rows split across devices, exactly the ``ShardedVariable`` layout) via
  :func:`widedeep_layout`; lookups become XLA gathers on sharded tables with
  automatic collective assembly;
- training is synchronous SPMD — the async-PS *capability* (scale sparse
  models past one host's memory) is preserved; the async *semantics* are
  documented as a gap and partially covered by the coordinator module
  (:mod:`distributedtensorflow_tpu.parallel.coordinator`).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import LayoutMap


@dataclasses.dataclass(frozen=True)
class WideDeepConfig:
    # one vocab size per categorical feature
    vocab_sizes: Sequence[int] = (100_000, 10_000, 1_000, 100)
    embed_dim: int = 64
    num_dense_features: int = 13
    mlp_dims: Sequence[int] = (1024, 512, 256)
    dtype: jnp.dtype = jnp.bfloat16


def widedeep_test_config() -> WideDeepConfig:
    return WideDeepConfig(
        vocab_sizes=(512, 128), embed_dim=8, num_dense_features=4,
        mlp_dims=(32, 16),
    )


class WideDeep(nn.Module):
    """Binary-classification Wide&Deep (Cheng et al. 2016 shape).

    Inputs: ``categorical`` (B, n_cat) int ids, ``dense`` (B, n_dense) floats.
    Output: logit (B,).
    """

    cfg: WideDeepConfig

    @nn.compact
    def __call__(self, categorical, dense, train: bool = True):
        cfg = self.cfg
        # Deep part: learned embeddings per categorical feature.
        embeds = []
        wide_logits = []
        for i, vocab in enumerate(cfg.vocab_sizes):
            ids = categorical[:, i]
            emb = nn.Embed(
                vocab, cfg.embed_dim, dtype=cfg.dtype, name=f"embed_{i}"
            )(ids)
            embeds.append(emb)
            # Wide part: per-id scalar weight = 1-dim embedding (the linear
            # model over sparse crosses in the reference).
            w = nn.Embed(vocab, 1, dtype=jnp.float32, name=f"wide_{i}")(ids)
            wide_logits.append(w[:, 0])
        deep = jnp.concatenate(embeds + [dense.astype(cfg.dtype)], axis=-1)
        for j, dim in enumerate(cfg.mlp_dims):
            deep = nn.relu(nn.Dense(dim, dtype=cfg.dtype, name=f"mlp_{j}")(deep))
        deep_logit = nn.Dense(1, dtype=jnp.float32, name="deep_out")(deep)[:, 0]
        wide_logit = sum(wide_logits) + nn.Dense(
            1, dtype=jnp.float32, name="wide_dense"
        )(dense.astype(jnp.float32))[:, 0]
        return deep_logit + wide_logit


def widedeep_layout() -> LayoutMap:
    """Shard embedding-table rows over ``model`` — the ShardedVariable layout."""
    return LayoutMap([
        (r"embed_\d+/embedding", P("model", None)),
        (r"wide_\d+/embedding", P("model", None)),
    ])


def _forward_metrics(model: WideDeep, params, batch):
    """Shared forward + metric math so train 'accuracy' and eval 'accuracy'
    can never drift (the --target-metric gate stops on these)."""
    import optax

    logits = model.apply(
        {"params": params}, batch["categorical"], batch["dense"]
    )
    labels = batch["label"].astype(jnp.float32)
    loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
    accuracy = jnp.mean(((logits > 0) == (labels > 0.5)).astype(jnp.float32))
    return loss, accuracy


def widedeep_loss(model: WideDeep):
    """Sigmoid cross-entropy LossFn for batches {categorical, dense, label}."""

    def loss_fn(params, model_state, batch, rng):
        loss, accuracy = _forward_metrics(model, params, batch)
        return loss, ({"accuracy": accuracy}, model_state)

    return loss_fn


def widedeep_eval(model: WideDeep):
    """Eval metrics: accuracy + mean log-loss on held-out batches."""

    def eval_fn(params, model_state, batch):
        del model_state
        loss, accuracy = _forward_metrics(model, params, batch)
        return {"accuracy": accuracy, "log_loss": loss}

    return eval_fn
