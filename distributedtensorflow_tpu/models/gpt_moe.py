"""GPT-MoE: the decoder LM with mixture-of-experts MLPs (expert parallel).

Round-1 verdict item #5: MoE existed only as a standalone layer — no zoo
model carried it, so expert parallelism never ran inside a real train step
with gradients through the router.  This model closes that: every
``moe_every_k``-th block replaces its dense MLP with a routed expert MLP
(top-2 GShard routing by default), the router's load-balancing aux loss is
folded into the LM loss, and the experts shard over the ``expert`` mesh
axis with ``all_to_all`` dispatch (``parallel/moe.py``).

No reference equivalent (SURVEY.md §2.4 EP row: absent from
tf.distribute) — this is new capability, built TPU-first: fixed-shape
dispatch (one-hot einsum + capacity), all collectives compiled onto ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel.moe import local_moe
from ..parallel.sharding import LayoutMap
from .gpt import (CausalSelfAttention, GPTBlock, GPTConfig, gpt_layout,
                  rope_tables)
from .layers import FusedLayerNorm

PyTree = Any
#: (tokens (T, d), router_kernel (d, E), expert_params, token_mask (T,)
#: or None) -> (out (T, d), aux loss) — the dispatch-region contract
#: produced by ``parallel.moe.make_moe_fn``.
MoEFn = Callable[
    [jax.Array, jax.Array, PyTree, "jax.Array | None"],
    tuple[jax.Array, jax.Array],
]


@dataclasses.dataclass(frozen=True)
class GPTMoEConfig(GPTConfig):
    n_experts: int = 8
    moe_every_k: int = 2  # every k-th block is MoE (1 = all blocks)
    capacity_factor: float = 1.25
    router: str = "top2"  # GShard default; "top1" = Switch.  "expert_choice"
    # is rejected: its per-expert top-k over the whole sequence reads future
    # tokens' router scores — invalid for a causal LM (encoder-only router).
    aux_loss_weight: float = 1e-2


def gpt_moe_small() -> GPTMoEConfig:
    return GPTMoEConfig()


def gpt_moe_tiny() -> GPTMoEConfig:
    """Test-size: 2 blocks (1 dense + 1 MoE), 4 experts."""
    return GPTMoEConfig(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        intermediate_size=256, max_seq=256, remat=False,
        n_experts=4, moe_every_k=2,
    )


def _expert_mlp(params: PyTree, x: jax.Array) -> jax.Array:
    """One expert's FFN: (N, d) -> (N, d); params = {"w_in", "w_out"}."""
    h = jax.nn.gelu(x @ params["w_in"].astype(x.dtype))
    return h @ params["w_out"].astype(x.dtype)


class MoEMLP(nn.Module):
    """Routed expert MLP.  ``moe_fn=None`` runs all experts locally
    (replicated — the golden/no-expert-axis path); a mesh-bound
    :func:`..parallel.moe.make_moe_fn` region makes it expert-parallel."""

    cfg: GPTMoEConfig
    moe_fn: MoEFn | None = None

    @nn.compact
    def __call__(self, x: jax.Array,
                 token_mask: jax.Array | None = None
                 ) -> tuple[jax.Array, jax.Array]:
        """``token_mask`` (B, S): 1 = real token — pads neither consume
        expert capacity nor dilute the aux loss (see parallel/moe.py
        routers).  None = all tokens real (the causal-LM presets)."""
        cfg = self.cfg
        router = self.param(
            "router", nn.initializers.normal(0.02),
            (cfg.hidden_size, cfg.n_experts), jnp.float32,
        )
        experts = {
            "w_in": self.param(
                "experts_in", nn.initializers.lecun_normal(),
                (cfg.n_experts, cfg.hidden_size, cfg.intermediate_size),
                jnp.float32,
            ),
            "w_out": self.param(
                "experts_out", nn.initializers.lecun_normal(),
                (cfg.n_experts, cfg.intermediate_size, cfg.hidden_size),
                jnp.float32,
            ),
        }
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)
        tmask = None if token_mask is None else token_mask.reshape(b * s)
        if self.moe_fn is not None:
            out, aux = self.moe_fn(tokens, router, experts, tmask)
        else:
            out, aux = local_moe(
                tokens, router, experts, _expert_mlp,
                capacity_factor=cfg.capacity_factor, router=cfg.router,
                token_mask=tmask,
            )
        return out.reshape(b, s, d), aux


class MoEGPTBlock(nn.Module):
    """Pre-LN decoder block with a routed-expert MLP; returns (x, aux)."""

    cfg: GPTMoEConfig
    moe_fn: MoEFn | None = None

    @nn.compact
    def __call__(self, x, positions, deterministic: bool, rope_tabs=None):
        cfg = self.cfg
        h = FusedLayerNorm(name="ln1")(x)
        attn_cls = CausalSelfAttention
        if cfg.remat_attn and not self.is_initializing():
            # same convention as gpt.GPTBlock: attention-only checkpoint
            attn_cls = nn.remat(CausalSelfAttention, static_argnums=(3,))
        x = x + attn_cls(cfg, None, False, name="attn")(
            h, positions, deterministic, rope_tabs
        )
        h = FusedLayerNorm(name="ln2")(x)
        m, aux = MoEMLP(cfg, self.moe_fn, name="moe_mlp")(h)
        return x + m, aux


class GPTMoELM(nn.Module):
    """Decoder LM with MoE MLPs every ``moe_every_k`` blocks.

    ``__call__`` returns ``(logits fp32, aux_loss)`` — the router
    load-balancing loss summed over MoE blocks, for the caller to weight
    into the training loss (``moe_lm_loss``).
    """

    cfg: GPTMoEConfig
    moe_fn: MoEFn | None = None

    def __post_init__(self):
        if self.cfg.router == "expert_choice":
            raise ValueError(
                "expert_choice routing is non-causal (each expert's top-k "
                "reads the whole sequence's router scores, future tokens "
                "included) — invalid for this autoregressive LM. Use it in "
                "encoder models; pick 'top1' or 'top2' here."
            )
        super().__post_init__()

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True,
                 return_hidden: bool = False):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="wte"
        )(input_ids)
        positions = jnp.broadcast_to(
            jnp.arange(input_ids.shape[1]), input_ids.shape
        )
        rope_tabs = rope_tables(
            positions, cfg.hidden_size // cfg.num_heads, cfg.rope_theta,
            cfg.dtype,
        )
        aux_total = jnp.zeros((), jnp.float32)
        dense_block = GPTBlock
        moe_block = MoEGPTBlock
        if cfg.remat:
            dense_block = nn.remat(GPTBlock, static_argnums=(3,))
            moe_block = nn.remat(MoEGPTBlock, static_argnums=(3,))
        for i in range(cfg.num_layers):
            # layer k-1, 2k-1, ... are MoE (last of each group of k)
            if (i + 1) % cfg.moe_every_k == 0:
                x, aux = moe_block(cfg, self.moe_fn, name=f"h{i}")(
                    x, positions, deterministic, rope_tabs
                )
                aux_total = aux_total + aux
            else:
                x = dense_block(cfg, None, False, name=f"h{i}")(
                    x, positions, deterministic, rope_tabs
                )
        x = FusedLayerNorm(out_dtype=jnp.float32, name="ln_f")(x)
        if return_hidden:
            return x, aux_total  # loss applies the chunked head (ops/xent)
        from ..ops.xent import tied_head_logits

        wte = self.variables["params"]["wte"]["embedding"]
        return tied_head_logits(x, wte, cfg.dtype), aux_total


def moe_lm_loss(model: GPTMoELM):
    """Next-token cross-entropy + weighted router aux loss.

    Cross-entropy uses the vocab-chunked head (``ops/xent.py``) like the
    dense GPT's ``lm_loss``: full-vocab fp32 logits never materialize.
    """
    from .gpt import _pick_xent

    aux_w = model.cfg.aux_loss_weight

    def loss_fn(params, model_state, batch, rng):
        hidden, aux = model.apply(
            {"params": params}, batch["input_ids"], deterministic=False,
            return_hidden=True,
        )
        lm = _pick_xent(model.cfg)(
            hidden[:, :-1],
            params["wte"]["embedding"],
            batch["input_ids"][:, 1:],
            compute_dtype=model.cfg.dtype,
        )
        loss = lm + aux_w * aux
        return loss, (
            {"perplexity": jnp.exp(lm), "aux_loss": aux}, model_state,
        )

    return loss_fn


def moe_lm_eval(model: GPTMoELM):
    """Eval metric_fn: deterministic forward, router aux reported but not
    folded into the eval loss (it is a training regularizer)."""
    from .gpt import _pick_xent

    def metric_fn(params, model_state, batch):
        hidden, aux = model.apply(
            {"params": params}, batch["input_ids"], deterministic=True,
            return_hidden=True,
        )
        lm = _pick_xent(model.cfg)(
            hidden[:, :-1],
            params["wte"]["embedding"],
            batch["input_ids"][:, 1:],
            compute_dtype=model.cfg.dtype,
        )
        return {"loss": lm, "perplexity": jnp.exp(lm), "aux_loss": aux}

    return metric_fn


def gpt_moe_layout() -> LayoutMap:
    """gpt_layout + the shared expert-parallel MoE rules (the router is
    tiny and stays replicated)."""
    from ..parallel.moe import with_moe_layout

    return with_moe_layout(gpt_layout())


def bind_expert_parallel(cfg: GPTMoEConfig, mesh: Mesh) -> GPTMoELM:
    """Build the model with the expert-parallel shard_map region when the
    mesh has a real ``expert`` axis; local (replicated) experts otherwise."""
    from ..parallel.moe import bind_expert_parallel_model

    return bind_expert_parallel_model(cfg, mesh, GPTMoELM, _expert_mlp)
