"""GPT-style decoder LM — the long-context flagship.

No decoder LM exists in the reference stack (its longest-sequence workload
is BERT-base MLM at 512 tokens — SURVEY.md §5.7); this model is the vehicle
for the framework's first-class long-context capability: its attention is
pluggable, so the same module runs

- dense causal attention (Pallas flash kernel via ``ops.attention``), or
- **sequence-parallel** ring / Ulysses attention over the ``seq`` mesh axis
  (``parallel.ring_attention.sequence_parallel_attention_fn``) for
  sequences too long for one device's HBM.

TPU-first choices: bfloat16 activations with float32 layer-norm/softmax,
rotary position embeddings (no learned position table to shard), pre-LN
blocks, Megatron-ready kernel names for the ``model``-axis layout in
:func:`gpt_layout`, and ``jax.checkpoint`` over blocks (remat) so long
sequences trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention
from ..parallel.sharding import LayoutMap
from .layers import FusedLayerNorm, dense, sow_nonfinite

AttnFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    #: >0 chunks the MLP over the sequence (ops.blockwise): the (B, S, d_ff)
    #: intermediate never materializes whole — the blockwise-FFN half of the
    #: long-context recipe (SURVEY.md §5.7). Must divide the sequence length.
    ffn_chunk_size: int = 0
    max_seq: int = 2048
    dropout_rate: float = 0.0
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = True
    #: Checkpoint ONLY the attention op inside each block (meaningful when
    #: ``remat`` is False): backward recomputes the (S, S) score/softmax
    #: tensors — the bulk of a short-seq block's activation memory — for
    #: ~5% extra FLOPs, so remat-free-speed training fits ~2x the batch.
    remat_attn: bool = False
    #: Attention kernel: "auto" (Pallas flash on TPU past the evidenced
    #: seq threshold), "pallas" (force the flash kernel — its backward
    #: stores no (S, S) tensors, so remat-free training fits much larger
    #: batches), or "xla".
    attn_impl: str = "auto"
    #: Sliding-window attention (Mistral-style): token i attends keys in
    #: ``(i - attn_window, i]``.  None = full causal.  The flash kernels
    #: skip out-of-band blocks (O(S*window) cost); the decode path masks
    #: the cache the same way, so training and serving agree.  New
    #: capability beyond the reference stack.
    attn_window: int | None = None
    #: Grouped-query attention: number of K/V heads; each group of
    #: ``num_heads // num_kv_heads`` query heads shares one K/V head.
    #: None = num_heads (MHA — every existing preset, param-tree
    #: unchanged).  Shrinks the decode KV cache and its per-step HBM
    #: stream by the group factor — the binding constraint of the serving
    #: decode step (ops.attention decode-perf history).  New capability
    #: beyond the reference stack (tf-classic predates GQA entirely).
    num_kv_heads: int | None = None
    #: LM-head loss kernel: "auto" (Pallas fused head on TPU — the fastest
    #: measured path, 111.3k vs 108.4k tok/s against chunked_bf16 at the
    #: 2026-08-01 headline A/B — and "chunked" elsewhere, keeping CPU
    #: tests on the fp32 golden path), "chunked" (lax.scan over token
    #: chunks, ops/xent.py), "chunked_bf16" (bf16 logits tiles), or
    #: "fused" (Pallas ops/fused_xent.py unconditionally — logits never
    #: leave VMEM; ~4.1x less head HBM traffic at equal FLOPs).
    xent_impl: str = "auto"
    #: Quantized compute (ops/quant.py): None/"none" = full-width; "int8"
    #: / "int8_stochastic" / "fp8" route every block dense matmul (qkv,
    #: proj, fc_in, fc_out) through the per-channel-absmax quantized
    #: dot with a straight-through-estimator backward.  Embeddings, layer
    #: norms, rope, and the fp32 tied head stay high-precision.  Param
    #: tree is unchanged, so checkpoints move between modes freely.
    quant: str | None = None

    def __post_init__(self):
        from ..ops.quant import validate_mode

        validate_mode(self.quant)
        kv = self.num_kv_heads
        if kv is not None and (kv <= 0 or self.num_heads % kv):
            raise ValueError(
                f"num_kv_heads={kv} must divide num_heads={self.num_heads}"
            )
        if self.attn_window is not None and self.attn_window < 1:
            raise ValueError(
                f"attn_window={self.attn_window} must be >= 1 (None = full)"
            )

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads


def gpt_small() -> GPTConfig:
    return GPTConfig()


def gpt_medium() -> GPTConfig:
    """GPT-2-medium (~350M params): 24 layers, hidden 1024, 16 heads.

    Wider matmuls (K=1024 = 8 full MXU passes vs small's 6) raise MXU
    efficiency; the measured single-chip MFU exceeds gpt_small's."""
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                     intermediate_size=4096)


def gpt_tiny() -> GPTConfig:
    """Test-size config (2 layers, 128 hidden, short context)."""
    return GPTConfig(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        intermediate_size=256, max_seq=256, remat=False,
    )


def cached_attention_with_vars(module: nn.Module, q, k, v,
                               max_seq: int,
                               window: int | None = None) -> jax.Array:
    """Flax "cache"-collection plumbing around
    :func:`..ops.attention.cached_decode_attention` — the ONE place the
    cache layout (cached_key/cached_value/cache_index) is defined, shared
    by every serving path (GPT and seq2seq decoder self-attention)."""
    from ..ops.attention import cached_decode_attention

    b, _, _, d = q.shape
    h_kv = k.shape[2]  # kv heads: < q heads under GQA (smaller cache)
    # (B, Hkv, S, D): per-step writes are contiguous (D,) rows and the
    # Pallas decode kernel streams (Hkv, S, D) tiles — see the decode-perf
    # history on ops.attention.cached_decode_attention.
    cached_k = module.variable(
        "cache", "cached_key",
        lambda: jnp.zeros((b, h_kv, max_seq, d), k.dtype)
    )
    cached_v = module.variable(
        "cache", "cached_value",
        lambda: jnp.zeros((b, h_kv, max_seq, d), v.dtype)
    )
    cache_ix = module.variable(
        "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
    )
    out, cached_k.value, cached_v.value, cache_ix.value = (
        cached_decode_attention(
            q, k, v, cached_k.value, cached_v.value, cache_ix.value,
            window=window,
        )
    )
    return out


def rope_tables(
    positions: jax.Array, d: int, theta: float, dtype
) -> tuple[jax.Array, jax.Array]:
    """Sign-folded (B, S, 1, D) cos/sin tables for :func:`rope`.

    Split out so the trunk can compute the trig ONCE per step and share
    the tables across every layer's q and k rotation (2 x num_layers
    calls otherwise; under block remat each call is also recomputed in
    the backward, whereas hoisted tables are saved residuals).  Trig in
    fp32, then cast to the compute ``dtype`` the combine runs at."""
    d_half = d // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # (B, S, Dh)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    cos_f = jnp.concatenate([cos, cos], axis=-1)[:, :, None, :]
    sin_f = jnp.concatenate([-sin, sin], axis=-1)[:, :, None, :]
    return cos_f.astype(dtype), sin_f.astype(dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float,
         tables: tuple[jax.Array, jax.Array] | None = None) -> jax.Array:
    """Rotary embedding, (B, S, H, D) with D even.

    Lane-friendly formulation (2026-08-01 retune): the textbook
    ``split -> 4 muls on (…, D/2) -> concat`` form cost ~31 ms/step in
    the GPT-2-small profile — every elementwise op ran on D/2=32-wide
    tensors (a quarter of the 128-lane VPU tile) and XLA materialized
    half-width copies around them (profile_lm_flash, fusions at
    (16,1024,12,32)).  Folding the signs into a full-width sin pattern
    turns it into ONE half-swap relayout plus two muls and an add at
    full D width; per-element arithmetic is bit-identical
    (x1*cos + x2*(-sin) == x1*cos - x2*sin in IEEE fp).

    The combine runs in ``x.dtype`` (round-4 retune): upcasting the
    already-bf16-rounded x to fp32 doubled the elementwise byte traffic
    for one extra rounding's worth of precision that the final
    cast-back discarded anyway.  fp32 inputs keep fully-fp32 math.
    ``tables`` are the precomputed :func:`rope_tables` (cast here if
    their dtype differs from x)."""
    d = x.shape[-1]
    d_half = d // 2
    if tables is None:
        tables = rope_tables(positions, d, theta, x.dtype)
    cos_f, sin_f = (t.astype(x.dtype) for t in tables)
    # Half-swap via a constant permutation matmul: the MXU moves the
    # halves (exact — R is 0/1), the VPU never runs a sub-lane relayout.
    r = jnp.block([
        [jnp.zeros((d_half, d_half), x.dtype),
         jnp.eye(d_half, dtype=x.dtype)],
        [jnp.eye(d_half, dtype=x.dtype),
         jnp.zeros((d_half, d_half), x.dtype)],
    ])  # x @ r == concat([x2, x1])
    x_rot = jnp.einsum("bshd,de->bshe", x, r)
    return x * cos_f + x_rot * sin_f


class CausalSelfAttention(nn.Module):
    cfg: GPTConfig
    attn_fn: AttnFn | None = None  # None = dense causal (flash-capable)
    decode: bool = False  # KV-cache incremental decoding (serving path)
    #: Manual tensor parallelism (the pipeline's full-manual shard_map
    #: region, where GSPMD cannot partition the kernels): ``n_heads`` /
    #: ``n_kv`` override the LOCAL head counts (this shard's slice of the
    #: fused qkv / proj kernels), and ``reduce_fn`` — typically
    #: ``lax.psum(., "model")`` — completes the row-parallel output
    #: projection.  Defaults (None) are exactly the historical behavior.
    n_heads: int | None = None
    n_kv: int | None = None
    reduce_fn: Any = None

    @nn.compact
    def __call__(self, x, positions, deterministic: bool, rope_tabs=None):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        nh = self.n_heads or cfg.num_heads
        n_kv = self.n_kv or cfg.kv_heads
        # Fused QKV projection: one large MXU matmul (column-parallel under
        # the model axis — gpt_layout shards the fused output dim).  Under
        # GQA (kv_heads < num_heads) the K/V column groups shrink; at the
        # MHA default the fused dim is exactly 3E and the split matches
        # the historical jnp.split(qkv, 3) — same param tree, same values.
        q_width = nh * head_dim
        kv_width = n_kv * head_dim
        qkv = dense(
            q_width + 2 * kv_width, dtype=cfg.dtype,
            quant=cfg.quant, use_bias=False, name="qkv",
        )(x)
        q = qkv[..., :q_width]
        k = qkv[..., q_width:q_width + kv_width]
        v = qkv[..., q_width + kv_width:]
        q = q.reshape(*x.shape[:2], nh, head_dim)
        k = k.reshape(*x.shape[:2], n_kv, head_dim)
        v = v.reshape(*x.shape[:2], n_kv, head_dim)
        q = rope(q, positions, cfg.rope_theta, rope_tabs)
        k = rope(k, positions, cfg.rope_theta, rope_tabs)
        if self.decode:
            if self.attn_fn is not None:
                raise ValueError(
                    "decode=True uses dense cached attention; a custom "
                    "attn_fn (e.g. sequence-parallel) is not supported in "
                    "decode mode — shard the batch, not the sequence, when "
                    "serving"
                )
            out = self._cached_attention(q, k, v)
        elif self.attn_fn is not None:
            if n_kv != nh:
                raise ValueError(
                    "GQA (kv_heads < num_heads) is not supported with a "
                    "custom attn_fn (ring/Ulysses sequence parallelism "
                    "resharding assumes equal q/kv head counts) — use the "
                    "dense/flash path or set kv_heads=num_heads"
                )
            if cfg.attn_window is not None:
                raise ValueError(
                    "attn_window is not supported with a custom attn_fn "
                    "(sequence-parallel attention masks per K/V chunk) — "
                    "use the dense/flash path"
                )
            out = self.attn_fn(q, k, v)
        else:
            out = dot_product_attention(
                q, k, v, causal=True, window=cfg.attn_window,
                implementation=cfg.attn_impl,
            )
        out = out.reshape(*x.shape[:2], q_width)
        # Row-parallel output projection (its input dim is head-sharded).
        out = dense(
            cfg.hidden_size, dtype=cfg.dtype, quant=cfg.quant,
            use_bias=False, name="proj",
        )(out)
        if self.reduce_fn is not None:
            out = self.reduce_fn(out)
        return out

    def _cached_attention(self, q, k, v):
        """One decode step against the KV cache (shared helper)."""
        return cached_attention_with_vars(self, q, k, v, self.cfg.max_seq,
                                          window=self.cfg.attn_window)


class GPTBlock(nn.Module):
    cfg: GPTConfig
    attn_fn: AttnFn | None = None
    decode: bool = False
    #: Manual tensor parallelism (see :class:`CausalSelfAttention`):
    #: per-shard head counts / MLP width, and the cross-shard reduction
    #: applied to the attention projection and MLP outputs (row-parallel
    #: psum).  Defaults are the historical single-shard behavior.
    n_heads: int | None = None
    n_kv: int | None = None
    ffn_size: int | None = None
    reduce_fn: Any = None

    @nn.compact
    def __call__(self, x, positions, deterministic: bool, rope_tabs=None):
        cfg = self.cfg
        h = FusedLayerNorm(name="ln1")(x)
        attn_cls = CausalSelfAttention
        if cfg.remat_attn and not self.decode and not self.is_initializing():
            # static_argnums counts __call__'s args including self:
            # deterministic is index 3 (same convention as the block remat;
            # rope_tabs at 4 is a traced array input, NOT static).
            attn_cls = nn.remat(CausalSelfAttention, static_argnums=(3,))
        x = x + attn_cls(
            cfg, self.attn_fn, self.decode, name="attn",
            n_heads=self.n_heads, n_kv=self.n_kv, reduce_fn=self.reduce_fn,
        )(h, positions, deterministic, rope_tabs)
        h = FusedLayerNorm(name="ln2")(x)
        # Column- then row-parallel MLP (Megatron split over `model`).
        fc_in = dense(self.ffn_size or cfg.intermediate_size,
                      dtype=cfg.dtype,
                      quant=cfg.quant, use_bias=False, name="fc_in")
        fc_out = dense(cfg.hidden_size, dtype=cfg.dtype, quant=cfg.quant,
                       use_bias=False, name="fc_out")

        def mlp(hc):
            return fc_out(nn.gelu(fc_in(hc)))

        if cfg.ffn_chunk_size > 0 and not self.decode:
            from ..ops.blockwise import blockwise_map

            if h.shape[1] % cfg.ffn_chunk_size:
                # silent dense fallback would materialize the full
                # (B, S, d_ff) intermediate exactly when the user asked
                # for the memory bound — fail loudly instead
                raise ValueError(
                    f"ffn_chunk_size={cfg.ffn_chunk_size} does not divide "
                    f"sequence length {h.shape[1]}; pick a divisor or pad"
                )

            # remat only outside init (param creation can't happen inside
            # jax.checkpoint); per-chunk recompute bounds backward memory
            # to one (B, chunk, d_ff) tile.
            m = blockwise_map(
                mlp, h, cfg.ffn_chunk_size,
                remat=not self.is_initializing(),
            )
        else:
            m = mlp(h)
        if self.reduce_fn is not None:
            # Completes the row-parallel fc_out (manual TP): each shard
            # holds F/tp of the intermediate, its fc_out output is a
            # partial sum.  Applied before dropout/residual, mirroring
            # where GSPMD inserts the all-reduce on auto meshes.
            m = self.reduce_fn(m)
        if cfg.dropout_rate:
            m = nn.Dropout(cfg.dropout_rate)(m, deterministic=deterministic)
        return x + m


class GPTLM(nn.Module):
    """Decoder-only LM head over token ids; logits in float32.

    ``decode=True`` switches every attention to KV-cache incremental mode
    (one-token steps against a ``max_seq`` cache in the "cache" variable
    collection) — the serving path used by :func:`generate`.
    """

    cfg: GPTConfig
    attn_fn: AttnFn | None = None
    decode: bool = False

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True,
                 positions=None, return_hidden: bool = False):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden_size,
            dtype=cfg.dtype, name="wte",
        )(input_ids)
        # NaN-provenance taps (obs/dynamics.py): per-module activation
        # isfinite counts sown into the "dynamics" collection.  Sown in
        # THIS scope — outside any remat'd block — so the taps are
        # remat-safe, and only when the collection is mutable (the
        # provenance re-forward), so training pays nothing.
        sow_nonfinite(self, "wte", x)
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1]), input_ids.shape
            )
        # One trig computation per step, shared by every layer's q and k
        # rotation (and saved as a residual under remat instead of being
        # recomputed per block in the backward).
        rope_tabs = rope_tables(
            positions, cfg.hidden_size // cfg.num_heads, cfg.rope_theta,
            cfg.dtype,
        )
        block = GPTBlock
        if cfg.remat and not self.decode:
            # Remat each block: activations recomputed in backward — the
            # jax.checkpoint HBM/FLOPs trade for long sequences.  For
            # nn.remat over a Module class, static_argnums counts
            # __call__'s args INCLUDING self: deterministic is index 3
            # (verified by tests/test_gpt.py::test_remat_path_trains).
            block = nn.remat(GPTBlock, static_argnums=(3,))
        for i in range(cfg.num_layers):
            x = block(cfg, self.attn_fn, self.decode, name=f"h{i}")(
                x, positions, deterministic, rope_tabs
            )
            sow_nonfinite(self, f"h{i}", x)
        x = FusedLayerNorm(out_dtype=jnp.float32, name="ln_f")(x)
        sow_nonfinite(self, "ln_f", x)
        if return_hidden:
            # Loss-side chunked head (ops/xent.py): the caller applies the
            # tied embedding per token chunk so full-vocab logits never
            # materialize.
            return x
        # Tied output head: reuse the embedding table (one less huge
        # vocab-sharded matrix; standard for decoder LMs).  Shared dtype
        # recipe (ops/xent.tied_head_logits): bf16 operands at MXU rate,
        # fp32 accumulation — identical to the chunked loss head.
        from ..ops.xent import tied_head_logits

        wte = self.variables["params"]["wte"]["embedding"]
        return tied_head_logits(x, wte, cfg.dtype)


def nan_taps(model: GPTLM):
    """The NaN-provenance tap forward for ``obs.dynamics``: a
    ``tap_fn(params, batch) -> {"NNN_module": nonfinite_count}`` whose
    keys embed the FORWARD position (``000_wte``, ``001_h0``, ...,
    ``00N_ln_f``) — jit canonicalizes dict outputs to sorted key order,
    so a bare module-name key would silently turn "first in the forward
    pass" into "first alphabetically"; with the index prefix, sorted
    order IS forward order and the provenance binary search names the
    first module that produced a non-finite activation.  jit-able; runs
    the deterministic no-dropout forward with only the ``dynamics``
    collection mutable."""
    order = (["wte"] + [f"h{i}" for i in range(model.cfg.num_layers)]
             + ["ln_f"])

    def tap_fn(params, batch):
        _, variables = model.apply(
            {"params": params},
            batch["input_ids"],
            deterministic=True,
            return_hidden=True,
            mutable=["dynamics"],
        )
        taps = variables.get("dynamics", {})
        return {f"{i:03d}_{name}": taps[f"{name}__nf"]
                for i, name in enumerate(order) if f"{name}__nf" in taps}

    return tap_fn


def lm_loss(model: GPTLM):
    """Next-token cross-entropy; ignores the final position's prediction.

    Uses the vocab-chunked head (``ops/xent.py``): the model returns final
    hidden states and the tied-embedding logits are built and reduced one
    token chunk at a time, so the fp32 ``(B, S, V)`` logits tensor never
    exists — measured +19% tokens/sec like-for-like on the v5e chip for
    GPT-2-small (BENCH_RESULTS/lm_*.json).
    """
    xent = _pick_xent(model.cfg)

    def loss_fn(params, model_state, batch, rng):
        hidden = model.apply(
            {"params": params},
            batch["input_ids"],
            deterministic=False,
            rngs={"dropout": rng},
            return_hidden=True,
        )
        targets = batch["input_ids"][:, 1:]
        mask = batch.get("mask")
        loss = xent(
            hidden[:, :-1],
            params["wte"]["embedding"],
            targets,
            mask[:, 1:] if mask is not None else None,
            compute_dtype=model.cfg.dtype,
        )
        return loss, ({"perplexity": jnp.exp(loss)}, model_state)

    return loss_fn


def _pick_xent(cfg: GPTConfig):
    """Head-loss kernel for ``cfg.xent_impl``: "auto" (fused on TPU,
    chunked elsewhere), "chunked" (fp32 logits tiles), "chunked_bf16"
    (bf16 tiles — half the head HBM traffic, ~1e-2 NLL tolerance), or
    "fused" (Pallas, logits never leave VMEM)."""
    impl = cfg.xent_impl
    if impl == "auto":
        from ..ops.flash_attention import _on_tpu

        impl = "fused" if _on_tpu() else "chunked"
    if impl == "fused":
        from ..ops.fused_xent import fused_softmax_xent

        return fused_softmax_xent
    if impl not in ("chunked", "chunked_bf16"):
        raise ValueError(
            f"xent_impl={cfg.xent_impl!r}: expected 'auto', 'chunked', "
            "'chunked_bf16', or 'fused'"
        )
    import functools

    from ..ops.xent import chunked_softmax_xent

    if impl == "chunked_bf16":
        return functools.partial(
            chunked_softmax_xent, logits_dtype=jnp.bfloat16
        )
    return chunked_softmax_xent


def lm_eval(model: GPTLM):
    """Eval metric_fn (params, model_state, batch) -> {loss, perplexity}.

    Deterministic forward (no dropout rng), same vocab-chunked head as
    ``lm_loss`` — wired into the ``gpt_lm`` preset so ``--eval-every`` and
    the sidecar evaluator work for LM workloads."""
    xent = _pick_xent(model.cfg)

    def metric_fn(params, model_state, batch):
        hidden = model.apply(
            {"params": params}, batch["input_ids"], deterministic=True,
            return_hidden=True,
        )
        mask = batch.get("mask")
        loss = xent(
            hidden[:, :-1],
            params["wte"]["embedding"],
            batch["input_ids"][:, 1:],
            mask[:, 1:] if mask is not None else None,
            compute_dtype=model.cfg.dtype,
        )
        return {"loss": loss, "perplexity": jnp.exp(loss)}

    return metric_fn


def gpt_layout() -> LayoutMap:
    """Megatron-style ``model``-axis sharding rules for :class:`GPTLM`.

    QKV and MLP-in are column-parallel (output dim sharded); proj and
    MLP-out are row-parallel (input dim sharded); the tied embedding is
    vocab-sharded.  Batch/seq sharding comes from the data/seq axes at the
    activation level, not the layout map.
    """
    return LayoutMap([
        (r".*wte/embedding", P("model", None)),
        (r".*attn/qkv/kernel", P(None, "model")),
        (r".*attn/proj/kernel", P("model", None)),
        (r".*fc_in/kernel", P(None, "model")),
        (r".*fc_out/kernel", P("model", None)),
    ])
