"""BERT-MoE encoder with EXPERT-CHOICE routing.

Expert-choice routing (Zhou et al. 2022) is acausal by construction —
each expert picks its top-k tokens across the whole sequence — so the
causal GPT-MoE rejects it (``models/gpt_moe.py``); THIS model is its
legitimate domain (bidirectional encoder), closing the round-2 advisor
note that the shipped EC router had no end-to-end workload.  Load balance
is perfect by construction (every expert processes exactly its capacity),
so the auxiliary loss is a constant zero; ``router`` can still be set to
``top1``/``top2`` for ablations, in which case the aux loss is live and
``moe_aux_loss`` shows up in the metrics stream.

Reference analogue: none (the reference stack has no MoE); this is a
new-capability row (SURVEY.md §2.4 EP) on the encoder side, sharing the
expert-parallel all_to_all dispatch region (``parallel/moe.py``) with
GPT-MoE.  Every ``moe_every``-th block swaps its dense MLP for the routed
expert MLP (the ST-MoE interleaving recipe); the rest stay dense.  The
embedding stack and MLM head are BERT's own (``BertEncoder`` block-factory
hook + ``mlm_head``), so encoder fixes propagate here automatically.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel.moe import bind_expert_parallel_model, with_moe_layout
from ..parallel.sharding import LayoutMap
from .layers import FusedLayerNorm
from .bert import (
    BertConfig,
    BertEncoder,
    SelfAttention,
    TransformerBlock,
    bert_layout,
    mlm_head,
)
from .gpt_moe import MoEFn, MoEMLP, _expert_mlp


@dataclasses.dataclass(frozen=True)
class BertMoEConfig(BertConfig):
    n_experts: int = 8
    capacity_factor: float = 1.25
    #: "expert_choice" (the EC paper's encoder setting, aux-free) or
    #: "top1"/"top2" (Switch/GShard, live aux loss) for ablations.
    router: str = "expert_choice"
    #: every k-th block carries the routed MLP (ST-MoE interleaving).
    moe_every: int = 2


def bert_moe_base() -> BertMoEConfig:
    return BertMoEConfig()


def bert_moe_tiny() -> BertMoEConfig:
    """Test-size config (2 layers, 1 routed, 4 experts)."""
    return BertMoEConfig(
        vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
        intermediate_size=512, max_position=128, n_experts=4,
    )


class MoETransformerBlock(nn.Module):
    """Post-LN encoder block with a routed-expert MLP; returns (x, aux).

    ``MoEMLP`` (models/gpt_moe.py) duck-types on the config fields it
    reads (hidden/intermediate size, n_experts, capacity_factor, router),
    all of which :class:`BertMoEConfig` provides.  Token validity is
    recovered from the broadcast attention mask's key dimension, so
    PADDING TOKENS neither consume expert capacity nor dilute the aux
    loss (see the routers in parallel/moe.py)."""

    cfg: BertMoEConfig
    moe_fn: MoEFn | None = None

    @nn.compact
    def __call__(self, x, mask, deterministic: bool, segment_ids=None):
        cfg = self.cfg
        ln = lambda name: FusedLayerNorm(out_dtype=jnp.float32, name=name)
        attn_out = SelfAttention(cfg, name="attention")(
            x, mask, deterministic, segment_ids
        )
        x = ln("ln_attn")(x + attn_out)
        # (B, 1, 1, S) broadcast attention mask -> (B, S) token validity
        token_mask = None if mask is None else mask[:, 0, 0, :]
        m, aux = MoEMLP(cfg, self.moe_fn, name="moe_mlp")(
            x.astype(cfg.dtype), token_mask
        )
        if not deterministic:
            m = nn.Dropout(cfg.dropout_rate)(m, deterministic=False)
        return ln("ln_mlp")(x + m), aux


class BertMoEForMLM(nn.Module):
    """MoE encoder + MLM head; ``__call__`` returns ``(logits, aux)``.

    Same call signature as :class:`bert.BertForMLM` (masked_positions
    gathered head included), so ``bert.mlm_loss``/``mlm_eval`` and the
    shared ``_mlm_metrics`` drive it unchanged — they detect the tuple
    return and surface ``moe_aux_loss`` in the metrics stream."""

    cfg: BertMoEConfig
    moe_fn: MoEFn | None = None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, segment_ids=None,
                 position_ids=None, masked_positions=None):
        cfg = self.cfg

        def block_fn(i: int) -> nn.Module:
            # routed MLP on blocks 1, 1+k, ... (never block 0: a dense
            # first block keeps tiny 2-layer test configs carrying exactly
            # one routed and one dense block)
            if i % cfg.moe_every == cfg.moe_every - 1:
                return MoETransformerBlock(cfg, self.moe_fn,
                                           name=f"layer_{i}")
            return TransformerBlock(cfg, name=f"layer_{i}")

        x, aux = BertEncoder(cfg, block_fn, name="encoder")(
            input_ids, token_type_ids, attention_mask, deterministic,
            segment_ids, position_ids,
        )
        return mlm_head(cfg, x, masked_positions), aux


def moe_mlm_loss(model: BertMoEForMLM, *, max_predictions: int | None = None,
                 aux_weight: float = 1e-2):
    """``bert.mlm_loss`` + the router aux loss weighted into the total.

    With the default expert-choice router the aux term is a constant zero
    (balance is structural); for the top1/top2 ablations it is live and
    ``aux_weight`` matches the Switch recipe's 1e-2."""
    from .bert import _mlm_metrics

    def loss_fn(params, model_state, batch, rng):
        loss, metrics = _mlm_metrics(model, max_predictions, params, batch,
                                     rng)
        loss = loss + aux_weight * metrics["moe_aux_loss"]
        return loss, (metrics, model_state)

    return loss_fn


def bert_moe_layout() -> LayoutMap:
    """bert_layout + the shared expert-parallel MoE rules."""
    return with_moe_layout(bert_layout())


def bind_expert_parallel_bert(
    cfg: BertMoEConfig, mesh: Mesh
) -> BertMoEForMLM:
    """Expert-parallel shard_map dispatch when the mesh has a real
    ``expert`` axis; local (replicated) experts otherwise — the same
    contract as ``gpt_moe.bind_expert_parallel``."""
    return bind_expert_parallel_model(cfg, mesh, BertMoEForMLM, _expert_mlp)
