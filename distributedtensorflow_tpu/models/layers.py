"""Shared flax layers for the model zoo."""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.layernorm import layer_norm
from ..ops.quant import quantized_matmul, validate_mode


def nonfinite_count(x) -> jax.Array:
    """Count of non-finite elements of ``x`` as an int32 scalar (fp32
    view, so bf16 Infs count too)."""
    return jnp.sum(~jnp.isfinite(x.astype(jnp.float32)), dtype=jnp.int32)


def sow_nonfinite(module: nn.Module, name: str, x):
    """NaN-provenance tap: sow ``x``'s non-finite count into the
    ``dynamics`` variable collection (obs/dynamics.py's activation
    census) and return ``x`` unchanged.

    Free in training: the collection is only mutable during the
    provenance re-forward (``mutable=["dynamics"]``), so the guarded
    branch traces nothing in the compiled train step.  Guarded off
    during ``init`` too — a sown count in the init variables would leak
    into ``model_state`` and change the checkpoint tree.

    The variable is stored as ``<name>__nf``: flax submodule and
    variable names share one scope namespace, so sowing under the
    module's own name ("wte", "h0", ...) is a duplicate-scope error.
    """
    if not module.is_initializing() \
            and module.is_mutable_collection("dynamics"):
        module.sow("dynamics", f"{name}__nf", nonfinite_count(x),
                   reduce_fn=lambda _prev, new: new)
    return x


class FusedLayerNorm(nn.Module):
    """Drop-in for ``nn.LayerNorm(dtype=float32)`` + output cast.

    Same parameter tree ("scale", "bias", both fp32, shape (D,)) so
    checkpoints written against the flax module restore unchanged; the
    computation routes through ``ops.layernorm.layer_norm`` (one-pass
    Pallas kernel on TPU, XLA reference elsewhere — identical fp32-stats
    semantics on both paths).

    ``out_dtype=None`` returns the input dtype (the pre-LN trunk case,
    replacing ``nn.LayerNorm(dtype=f32)(x).astype(cfg.dtype)``); pass
    ``jnp.float32`` for a final LN feeding an fp32 head.
    """

    epsilon: float = 1e-6  # flax nn.LayerNorm default (drop-in)
    out_dtype: jnp.dtype | None = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (d,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (d,), jnp.float32)
        return layer_norm(x, scale, bias, eps=self.epsilon,
                          out_dtype=self.out_dtype or x.dtype)


class QuantDenseGeneral(nn.Module):
    """``nn.DenseGeneral`` drop-in whose matmul runs the quantized path.

    Same parameter tree as the flax module it replaces — ``kernel`` of
    shape ``(*contracted_dims, *feature_dims)`` (fp32 param_dtype) and an
    optional ``bias`` — so checkpoints restore unchanged between quantized
    and full-width runs, and the tensor-parallel :class:`LayoutMap` rules
    keyed on ``.../kernel`` keep matching.  The forward flattens the
    contracted/feature dims to one 2-D ``(K, N)`` matmul through
    :func:`~..ops.quant.quantized_matmul` (int8/fp8 per-channel absmax,
    straight-through-estimator backward); quantization runs at the layer's
    compute ``dtype`` operands, so ``quant="none"`` reproduces the plain
    dense layer.

    ``"int8_stochastic"`` draws its rounding noise from the ``"dropout"``
    rng stream when the caller provides one (the training path — unique
    per module instance and step) and falls back to a fixed key for
    deterministic/eval applies.
    """

    features: int | tuple[int, ...]
    quant: str = "int8"
    axis: int | tuple[int, ...] = -1
    use_bias: bool = True
    dtype: jnp.dtype | None = None

    @nn.compact
    def __call__(self, x):
        mode = validate_mode(self.quant)
        feats = (
            (self.features,) if isinstance(self.features, int)
            else tuple(self.features)
        )
        axes = (self.axis,) if isinstance(self.axis, int) else tuple(self.axis)
        axes = tuple(a % x.ndim for a in axes)
        if axes != tuple(range(x.ndim - len(axes), x.ndim)):
            raise ValueError(
                f"QuantDenseGeneral contracts trailing axes only, got "
                f"axis={self.axis} for input rank {x.ndim}"
            )
        in_shape = tuple(x.shape[a] for a in axes)
        k = math.prod(in_shape)
        n = math.prod(feats)

        def kernel_init(key, shape, dtype):
            # lecun_normal over the FLATTENED (K, N) view — the same
            # fan-in statistics nn.DenseGeneral produces for these shapes.
            w = nn.initializers.lecun_normal()(key, (k, n), dtype)
            return w.reshape(shape)

        kernel = self.param("kernel", kernel_init, in_shape + feats,
                            jnp.float32)
        dtype = self.dtype or x.dtype
        x2 = x.reshape(*x.shape[: x.ndim - len(axes)], k).astype(dtype)
        w2 = kernel.reshape(k, n).astype(dtype)
        key = None
        if mode == "int8_stochastic":
            key = (
                self.make_rng("dropout") if self.has_rng("dropout")
                else jax.random.PRNGKey(0)
            )
        y = quantized_matmul(x2, w2, mode=mode, key=key)
        y = y.reshape(*x.shape[: x.ndim - len(axes)], *feats)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, feats,
                              jnp.float32)
            y = y + bias.astype(y.dtype)
        return y


class QuantDense(QuantDenseGeneral):
    """``nn.Dense`` drop-in over the quantized matmul (axis=-1, int
    features); see :class:`QuantDenseGeneral`."""


def dense(features: int, *, dtype, quant: str | None = None,
          use_bias: bool = True, name: str | None = None) -> nn.Module:
    """The model zoo's dense-layer picker: ``quant`` in (None, "none")
    returns a plain ``nn.Dense``; any other mode returns the
    checkpoint-compatible :class:`QuantDense`.  ONE switch shared by the
    GPT/BERT/ViT call sites so a new mode cannot be wired into one model
    family and silently ignored by another."""
    if not quant or quant == "none":
        return nn.Dense(features, dtype=dtype, use_bias=use_bias, name=name)
    return QuantDense(features, quant=quant, dtype=dtype,
                      use_bias=use_bias, name=name)
