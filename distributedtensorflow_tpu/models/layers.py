"""Shared flax layers for the model zoo."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..ops.layernorm import layer_norm


class FusedLayerNorm(nn.Module):
    """Drop-in for ``nn.LayerNorm(dtype=float32)`` + output cast.

    Same parameter tree ("scale", "bias", both fp32, shape (D,)) so
    checkpoints written against the flax module restore unchanged; the
    computation routes through ``ops.layernorm.layer_norm`` (one-pass
    Pallas kernel on TPU, XLA reference elsewhere — identical fp32-stats
    semantics on both paths).

    ``out_dtype=None`` returns the input dtype (the pre-LN trunk case,
    replacing ``nn.LayerNorm(dtype=f32)(x).astype(cfg.dtype)``); pass
    ``jnp.float32`` for a final LN feeding an fp32 head.
    """

    epsilon: float = 1e-6  # flax nn.LayerNorm default (drop-in)
    out_dtype: jnp.dtype | None = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (d,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (d,), jnp.float32)
        return layer_norm(x, scale, bias, eps=self.epsilon,
                          out_dtype=self.out_dtype or x.dtype)
