"""Autoregressive generation for the GPT decoder LM (serving path).

No serving/inference loop exists in the reference's training harness; this
completes the decoder-LM story: KV-cache incremental decoding
(``GPTLM(decode=True)`` — one-token steps against a static ``max_seq``
cache), greedy or temperature/top-k sampling, ragged right-padded prompts.

TPU-first: the whole generate loop is ONE ``lax.scan`` inside ``jit`` —
static shapes (prompt buffer padded to ``prompt_pad + max_new_tokens``),
the KV cache as scan carry, no host round-trips per token.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .gpt import GPTConfig, GPTLM


def _sample(logits, rng, temperature, *, greedy: bool, top_k: int,
            top_p: float = 1.0):
    """(B, V) logits -> (B,) token ids.  ``temperature`` is traced (no
    recompile per value); greedy/top_k/top_p change the compiled program."""
    if greedy:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    sorted_desc = None
    if top_k > 0:
        topv, _ = jax.lax.top_k(logits, top_k)  # O(V log k), no full sort
        kth = topv[:, -1][:, None]
        logits = jnp.where(logits < kth, -1e9, logits)
        sorted_desc = topv  # the only survivors; already descending
    if top_p < 1.0:
        # nucleus sampling: keep the smallest descending-prob prefix with
        # cumulative mass >= top_p (the first token is always kept).  After
        # top_k only the k survivors can be in the nucleus, so reuse them
        # instead of a full O(V log V) sort per decoded token; the -1e9
        # masked tail's softmax mass is ~0, so probs match the full-vocab
        # softmax over survivors.
        if sorted_desc is None:
            sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
        kept = exclusive_cum < top_p
        cutoff = jnp.min(
            jnp.where(kept, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, -1e9, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def prefill(params, tokens, positions, *, cfg: GPTConfig, cache=None):
    """Teacher-forced multi-token step through the KV cache.

    Runs the decode-mode model on a token chunk (causal within the chunk,
    attending everything already in ``cache``) and returns ``(logits,
    cache)`` with the chunk's K/V appended.  ``cache=None`` creates the
    cache collection (flax mutable-apply priming); pass the returned cache
    back to continue — chunked prefill is a loop of fixed-width calls, so
    one compiled program covers any prompt length.  This is the serving
    engine's prefill building block (``serve.engine``) as well as
    :func:`generate`'s priming step.  Pure function: traceable under jit
    and scan, caller owns the cache pytree.
    """
    model = GPTLM(cfg, decode=True)
    variables = {"params": params}
    if cache is not None:
        variables["cache"] = cache
    logits, vars_out = model.apply(
        variables, tokens, positions=positions, mutable=["cache"]
    )
    return logits, vars_out["cache"]


def decode_step(params, tokens, positions, cache, *, cfg: GPTConfig):
    """One-token decode step against an existing KV cache.

    ``tokens``/``positions`` are ``(B, 1)``; returns ``(logits, cache)``
    with the new token's K/V written at the cache index.  The single-step
    specialization of :func:`prefill` (the cache must already exist) —
    the body of :func:`generate`'s scan and the dense-cache counterpart of
    the serving engine's paged decode program.
    """
    return prefill(params, tokens, positions, cfg=cfg, cache=cache)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "greedy", "top_k", "top_p",
                     "eos_token_id"),
)
def _generate_impl(params, prompt, prompt_lens, rng, temperature, *,
                   cfg: GPTConfig, max_new_tokens: int, greedy: bool,
                   top_k: int, top_p: float, eos_token_id: int):
    b, prompt_pad = prompt.shape
    total = prompt_pad + max_new_tokens

    tokens = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1
    )

    # First token primes the cache (flax creates the cache collection on a
    # mutable apply); the scan then carries it functionally.
    logits0, cache = prefill(
        params, tokens[:, :1], jnp.zeros((b, 1), jnp.int32), cfg=cfg
    )

    done0 = jnp.zeros((b,), bool)

    def step(carry, t):
        tokens, cache, rng, logits, done = carry
        rng, sub = jax.random.split(rng)
        sampled = _sample(logits[:, -1], sub, temperature, greedy=greedy,
                          top_k=top_k, top_p=top_p)
        # While t+1 is still inside this sequence's prompt, feed the prompt
        # token; afterwards feed the sample (teacher-forced prefill and
        # decode in one uniform loop — no separate prefill program).
        in_prompt = (t + 1) < prompt_lens  # (B,)
        prompt_tok = jax.lax.dynamic_slice_in_dim(tokens, t + 1, 1, axis=1)[:, 0]
        if eos_token_id >= 0:
            # a finished sequence keeps emitting eos (shapes stay static;
            # "early stop" = the output is frozen from the eos on)
            sampled = jnp.where(done, eos_token_id, sampled)
        nxt = jnp.where(in_prompt, prompt_tok, sampled).astype(tokens.dtype)
        if eos_token_id >= 0:
            done = done | (~in_prompt & (nxt == eos_token_id))
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, nxt[:, None], t + 1, axis=1
        )
        logits, cache = decode_step(
            params, nxt[:, None], jnp.full((b, 1), t + 1, jnp.int32),
            cache, cfg=cfg,
        )
        return (tokens, cache, rng, logits, done), None

    (tokens, _, _, _, _), _ = jax.lax.scan(
        step, (tokens, cache, rng, logits0, done0), jnp.arange(total - 1)
    )
    return tokens


def generate(
    params,
    prompt: jax.Array,  # (B, P) right-padded token ids
    *,
    cfg: GPTConfig,
    max_new_tokens: int,
    prompt_lens: jax.Array | None = None,  # (B,) true lengths; default P
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_token_id: int | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Generate continuations; returns (B, P + max_new_tokens) token ids.

    ``temperature=0`` is greedy; otherwise softmax sampling at the given
    temperature, optionally truncated to the ``top_k`` highest logits
    and/or the ``top_p`` nucleus (smallest probability mass >= top_p).
    ``eos_token_id`` freezes a sequence once it samples that token (it
    keeps emitting eos; shapes stay static).
    The KV cache needs ``cfg.max_seq >= P + max_new_tokens``.
    """
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if eos_token_id is not None and eos_token_id < 0:
        raise ValueError(
            f"eos_token_id must be a valid token id, got {eos_token_id} "
            "(pass None to disable eos handling)"
        )
    b, p = prompt.shape
    total = p + max_new_tokens
    if cfg.max_seq < total:
        raise ValueError(
            f"cfg.max_seq={cfg.max_seq} < prompt+new={total}; raise max_seq"
        )
    if prompt_lens is None:
        prompt_lens = jnp.full((b,), p, jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_impl(
        params, prompt.astype(jnp.int32), prompt_lens.astype(jnp.int32), rng,
        jnp.asarray(temperature, jnp.float32),
        cfg=cfg, max_new_tokens=max_new_tokens,
        greedy=float(temperature) <= 0.0, top_k=int(top_k),
        top_p=float(top_p),
        eos_token_id=-1 if eos_token_id is None else int(eos_token_id),
    )
