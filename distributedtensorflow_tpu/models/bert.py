"""BERT-base masked-LM — reference workload config #4 (BASELINE.json:
"BERT-base MLM pretrain, gradient accumulation + CollectiveAllReduce").

TPU-first choices:
- bfloat16 activations, float32 params and layer-norm math;
- attention exposed behind ``ops.attention.dot_product_attention`` so the
  Pallas flash-attention kernel can drop in (SURVEY.md §7 step 9);
- tensor-parallel-ready: QKV/MLP kernels are named so the Megatron sharding
  rules in :func:`bert_layout` split heads / hidden over the ``model`` axis
  with one all-reduce per block.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention
from ..parallel.sharding import LayoutMap
from .layers import FusedLayerNorm, QuantDenseGeneral
from .layers import dense as dense_layer


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    #: Quantized compute (ops/quant.py): routes the block matmuls —
    #: query/key/value/out projections and the MLP pair — through the
    #: int8/fp8 per-channel quantized dot (STE backward).  Embeddings,
    #: layer norms, and the MLM head stay high-precision.  Same param
    #: tree either way (checkpoint-compatible).
    quant: str | None = None

    def __post_init__(self):
        from ..ops.quant import validate_mode

        validate_mode(self.quant)


def bert_base() -> "BertConfig":
    return BertConfig()


def bert_tiny() -> "BertConfig":
    """Test-size config (2 layers, 128 hidden)."""
    return BertConfig(
        vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
        intermediate_size=512, max_position=128,
    )


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic: bool, segment_ids=None):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        if cfg.quant and cfg.quant != "none":
            dense = lambda name: QuantDenseGeneral(
                (cfg.num_heads, head_dim), quant=cfg.quant,
                dtype=cfg.dtype, name=name,
            )
            out_proj = QuantDenseGeneral(
                cfg.hidden_size, quant=cfg.quant, axis=(-2, -1),
                dtype=cfg.dtype, name="out",
            )
        else:
            dense = lambda name: nn.DenseGeneral(
                (cfg.num_heads, head_dim), dtype=cfg.dtype, name=name
            )
            out_proj = nn.DenseGeneral(
                cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype, name="out"
            )
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        out = dot_product_attention(q, k, v, mask=mask, segment_ids=segment_ids)
        out = out_proj(out)
        if not deterministic:
            out = nn.Dropout(cfg.dropout_rate)(out, deterministic=False)
        return out


class TransformerBlock(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic: bool, segment_ids=None):
        cfg = self.cfg
        ln = lambda name: FusedLayerNorm(out_dtype=jnp.float32, name=name)
        attn_out = SelfAttention(cfg, name="attention")(
            x, mask, deterministic, segment_ids
        )
        x = ln("ln_attn")(x + attn_out)
        h = dense_layer(cfg.intermediate_size, dtype=cfg.dtype,
                        quant=cfg.quant, name="mlp_in")(x)
        h = nn.gelu(h)
        h = dense_layer(cfg.hidden_size, dtype=cfg.dtype,
                        quant=cfg.quant, name="mlp_out")(h)
        if not deterministic:
            h = nn.Dropout(cfg.dropout_rate)(h, deterministic=False)
        return ln("ln_mlp")(x + h)


class BertEncoder(nn.Module):
    """Embedding stack + block stack.

    ``block_fn`` (layer index -> block module) lets variants swap blocks
    without re-implementing the embedding stack — e.g. BERT-MoE
    (models/bert_moe.py) interleaves routed-expert blocks.  A block may
    return ``(x, aux)`` (aux losses are summed) or plain ``x``;
    ``__call__`` always returns ``(x, aux_total)``."""

    cfg: BertConfig
    block_fn: Any = None  # Callable[[int], nn.Module] | None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, segment_ids=None,
                 position_ids=None):
        """``segment_ids``/``position_ids`` enable packed pretraining:
        multiple short examples share one row; attention stays within a
        segment (flash kernel keeps it O(S) memory) and positions restart
        per packed example when the packer supplies ``position_ids``."""
        cfg = self.cfg
        seq_len = input_ids.shape[-1]
        tok = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       dtype=cfg.dtype, name="tok_embed")(input_ids)
        if position_ids is None:
            position_ids = jnp.arange(seq_len)
        pos = nn.Embed(cfg.max_position, cfg.hidden_size,
                       dtype=cfg.dtype, name="pos_embed")(position_ids)
        x = tok + pos
        if token_type_ids is not None:
            x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                             dtype=cfg.dtype, name="type_embed")(token_type_ids)
        x = FusedLayerNorm(out_dtype=jnp.float32, name="ln_embed")(x)
        if not deterministic:
            x = nn.Dropout(cfg.dropout_rate)(x, deterministic=False)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.num_layers):
            block = (self.block_fn(i) if self.block_fn is not None
                     else TransformerBlock(cfg, name=f"layer_{i}"))
            out = block(x, mask, deterministic, segment_ids)
            if isinstance(out, tuple):
                x, aux = out
                aux_total = aux_total + aux
            else:
                x = out
        return x, aux_total


def mlm_head(cfg: BertConfig, x, masked_positions=None):
    """Transform + LayerNorm + vocab projection — call from inside a
    parent module's ``@nn.compact`` (submodules attach to the caller).
    The single MLM-head definition shared by BertForMLM and the MoE
    variant so head changes cannot diverge."""
    if masked_positions is not None:
        x = jnp.take_along_axis(x, masked_positions[..., None], axis=1)
    x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_transform")(x)
    x = nn.gelu(x)
    x = FusedLayerNorm(out_dtype=jnp.float32, name="mlm_ln")(x)
    return nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="mlm_out")(x)


class BertForMLM(nn.Module):
    """Encoder + tied-embedding MLM head."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, segment_ids=None,
                 position_ids=None, masked_positions=None):
        """``masked_positions`` (B, P): run the MLM head only at those
        positions (returns (B, P, V) instead of (B, S, V)).  The standard
        BERT-pretraining optimization — ~15% of positions are masked, so
        the transform/projection head does 6-7x less work and the logits
        tensor shrinks the same factor.  Param tree is identical either
        way."""
        cfg = self.cfg
        encoder = BertEncoder(cfg, name="encoder")
        x, _ = encoder(input_ids, token_type_ids, attention_mask,
                       deterministic, segment_ids, position_ids)
        return mlm_head(cfg, x, masked_positions)


def max_predictions_for(seq_len: int) -> int:
    """Gathered-head size for a sequence length: 20% of positions (mask
    rate is 15%; rows with more masked positions drop the excess).  The
    single definition shared by the workload presets and the benches."""
    return seq_len // 5 + 1


def _mlm_metrics(model: BertForMLM, max_predictions: int | None,
                 params, batch, rng):
    """Shared head dispatch + weighted loss/accuracy for mlm_loss/mlm_eval.

    ``max_predictions`` set: the P first masked positions per row (found
    with a static-shape ``top_k`` on the validity mask) are gathered
    *before* the MLM head, so transform/projection and the (.., V) logits
    run on P positions instead of S — the reference BERT-pretraining
    recipe's ``masked_lm_positions`` idea, recovered from the -100
    convention inside the compiled step.  ``rng=None`` = deterministic
    (eval) forward.
    """
    import optax

    labels = batch["labels"]
    valid = labels >= 0
    kwargs = dict(
        attention_mask=batch.get("attention_mask"),
        segment_ids=batch.get("segment_ids"),
        position_ids=batch.get("position_ids"),
        deterministic=rng is None,
    )
    if rng is not None:
        kwargs["rngs"] = {"dropout": rng}
    extra = {}
    if max_predictions:
        p = min(max_predictions, labels.shape[1])
        w, pos = jax.lax.top_k(valid.astype(jnp.int32), p)  # (B, P)
        # Rows with more than P masked positions silently lose the excess
        # supervision (the reference recipe's max_predictions_per_seq cap);
        # surface the fraction so user-supplied data masked above ~20%
        # shows up in the metrics stream instead of quietly changing the
        # loss vs the dense head.
        extra["mlm_clipped_rows"] = jnp.mean(
            (valid.sum(axis=1) > p).astype(jnp.float32)
        )
        logits = model.apply(
            {"params": params}, batch["input_ids"],
            masked_positions=pos, **kwargs,
        )  # (B, P, V)
        safe_labels = jnp.take_along_axis(
            jnp.where(valid, labels, 0), pos, axis=1
        )
        w = w.astype(jnp.float32)
    else:
        logits = model.apply(
            {"params": params}, batch["input_ids"], **kwargs
        )  # (B, S, V)
        safe_labels = jnp.where(valid, labels, 0)
        w = valid.astype(jnp.float32)
    if isinstance(logits, tuple):  # MoE encoders return (logits, router aux)
        logits, extra["moe_aux_loss"] = logits
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), safe_labels
    )
    denom = jnp.maximum(w.sum(), 1.0)
    loss = (per_tok * w).sum() / denom
    acc = ((jnp.argmax(logits, -1) == safe_labels) * w).sum() / denom
    return loss, {"mlm_accuracy": acc.astype(jnp.float32), **extra}


def mlm_loss(model: BertForMLM, *, max_predictions: int | None = None):
    """LossFn for masked-LM batches: {input_ids, labels, attention_mask}.

    ``labels`` uses -100 (ignore) convention at unmasked positions; see
    :func:`_mlm_metrics` for the ``max_predictions`` gathered-head path.
    """

    def loss_fn(params, model_state, batch, rng):
        loss, metrics = _mlm_metrics(model, max_predictions, params, batch,
                                     rng)
        return loss, (metrics, model_state)

    return loss_fn


def mlm_eval(model: BertForMLM, *, max_predictions: int | None = None):
    """Eval metric_fn: deterministic forward (rng=None), same shared head
    dispatch as :func:`mlm_loss`."""

    def metric_fn(params, model_state, batch):
        loss, metrics = _mlm_metrics(model, max_predictions, params, batch,
                                     None)
        return {"loss": loss, **metrics}

    return metric_fn


def bert_layout() -> LayoutMap:
    """Megatron-style tensor-parallel rules over the ``model`` mesh axis.

    QKV and MLP-in shard their *output* features (column parallel); attention
    out and MLP-out shard their *input* features (row parallel), so each
    block needs exactly one all-reduce in forward — inserted automatically by
    XLA from these shardings.  Embeddings shard rows (vocab), the sharded-
    embedding capability of the reference's PS path (SURVEY.md §2.4 TP row).
    """
    return LayoutMap([
        (r"(query|key|value)/kernel", P(None, "model", None)),
        (r"attention/out/kernel", P("model", None, None)),
        (r"mlp_in/kernel", P(None, "model")),
        (r"mlp_out/kernel", P("model", None)),
        (r"(tok|pos|type)_embed/embedding", P("model", None)),
        (r"(query|key|value)/bias", P("model", None)),
    ])
