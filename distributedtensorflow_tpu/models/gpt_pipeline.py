"""Pipeline-parallel GPT: the GPipe schedule wrapped around real blocks.

Round-1 verdict item: the pipeline engine (``parallel/pipeline.py``) only
ever ran a toy Dense stage.  This module makes a *real model* train through
it, with the heterogeneous structure a decoder LM needs:

- **embed** (token table) and **head** (final LN + tied projection) run
  OUTSIDE the pipeline, sharded over the batch axes — they are one matmul
  each, far cheaper than the block stack, and keeping them out preserves
  the pipeline's shape-preserving handoff invariant.  The table itself is
  row-sharded over ``pipe`` when the vocab divides (see :meth:`layout`):
  compute stays outside the pipeline, but storage (+ optimizer slots) is
  split ZeRO-style instead of replicated n_stages-fold;
- the **transformer blocks** — where the FLOPs are — are stacked
  ``(n_stages, layers_per_stage, ...)`` with the leading dim sharded over
  ``pipe``; each stage scans its ``layers_per_stage`` blocks locally, and
  microbatches march stage-to-stage via the ``lax.ppermute`` GPipe schedule
  in :func:`..parallel.pipeline.pipeline_apply`;
- autodiff through the scanned schedule yields the reverse pipeline; remat
  (``jax.checkpoint`` per block) keeps activation memory flat.

No reference equivalent exists (SURVEY.md §2.4: tf.distribute has no
GPipe); this is the framework's own new-capability bar.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from ..parallel.pipeline import (
    circular_bubble_fraction,
    circular_pipeline_apply,
    gpipe_bubble_fraction,
    pipeline_apply,
)
from .gpt import GPTBlock, GPTConfig, rope_tables
from .layers import FusedLayerNorm

PyTree = Any


@dataclasses.dataclass
class PipelinedGPT:
    """Functional pipeline-parallel GPT (not an nn.Module: its params carry
    an explicit stage dimension that flax's module tree cannot express).

    ``init(rng) -> {"params": ...}`` and ``apply(params, input_ids) ->
    logits`` mirror the flax calling convention used by the workloads.
    """

    cfg: GPTConfig
    mesh: Mesh
    n_microbatches: int
    axis_name: str = mesh_lib.AXIS_PIPE
    #: >1 selects the circular (interleaved) schedule: each rank holds
    #: n_virtual non-adjacent stage chunks, shrinking the bubble
    #: n_virtual-fold (`circular_bubble_fraction`).
    n_virtual: int = 1
    #: Sequence-parallel attention inside the stages when the mesh has a
    #: real ``seq`` axis: "ring" (ppermute KV rotation) or "ulysses"
    #: (all_to_all head<->sequence reshard).
    sp_scheme: str = "ring"
    #: Dtype of the inter-stage ppermute PAYLOAD (the wire).  None = the
    #: fp32 schedule dtype end to end.  "bfloat16" halves the per-handoff
    #: ICI traffic by casting down just before the collective and back up
    #: after; with a bf16 model the stage output is an upcast bf16 value,
    #: so the roundtrip is BIT-EXACT (asserted by test) — requires
    #: cfg.dtype=bfloat16 for that reason.  Scan carries, schedule
    #: buffers, and the region boundary stay fp32: jax 0.9's
    #: partial-manual partitioner hard-aborts on bf16 region boundaries
    #: under autodiff (the wire cast is the safe subset of the
    #: optimization; see :meth:`apply`).
    handoff_dtype: str | None = None

    def __post_init__(self):
        cfg = self.cfg
        if self.n_virtual < 1:
            raise ValueError(
                f"n_virtual must be >= 1, got {self.n_virtual} "
                "(--pp-virtual on the CLI)"
            )
        # pipe x seq composition: with a real seq axis on the mesh, every
        # activation is additionally sharded over seq and each stage's
        # attention runs the K/V ring across it (direct lax collectives —
        # the pipeline's shard_map already makes every axis manual).
        self.seq_axis = mesh_lib.AXIS_SEQ
        self.seq_parallel = dict(self.mesh.shape).get(self.seq_axis, 1) > 1
        if self.sp_scheme not in ("ring", "ulysses"):
            # validated regardless of mesh shape, so a typo surfaces at
            # construction, not when the config is later scaled to seq > 1
            raise ValueError(
                f"sp_scheme must be ring|ulysses, got {self.sp_scheme!r}"
            )
        self.n_stages = self.mesh.shape[self.axis_name]
        total_stages = self.n_stages * self.n_virtual
        if cfg.num_layers % total_stages:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by "
                f"pipe={self.n_stages} x n_virtual={self.n_virtual} stages"
            )
        if self.n_virtual > 1 and self.n_microbatches < self.n_stages:
            raise ValueError(
                f"circular schedule needs n_microbatches >= n_stages "
                f"({self.n_microbatches} < {self.n_stages})"
            )
        if cfg.dropout_rate:
            raise NotImplementedError(
                "dropout inside the pipeline needs per-stage rng plumbing; "
                "set dropout_rate=0 for pipeline parallelism"
            )
        if self.handoff_dtype is None:
            self._wire = None
        elif self.handoff_dtype in ("bfloat16", "bf16"):
            if cfg.dtype != jnp.bfloat16:
                raise ValueError(
                    "handoff_dtype=bfloat16 requires cfg.dtype=bfloat16 — "
                    "a bf16 wire under an fp32 model would silently round "
                    "every cross-stage residual (with a bf16 model the "
                    "cast is exact)"
                )
            self._wire = jnp.bfloat16
        else:
            raise ValueError(
                f"handoff_dtype must be None or 'bfloat16', "
                f"got {self.handoff_dtype!r}"
            )
        self.layers_per_stage = cfg.num_layers // total_stages
        self._embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="wte"
        )
        # _block initializes params (dense attention; attn_fn carries no
        # params, so the tree is identical either way).  _apply_block is
        # what stages execute: under seq parallelism it swaps in ring
        # attention, whose lax collectives only trace inside the shard_map.
        self._block = GPTBlock(cfg)
        if self.seq_parallel:
            import functools

            from ..parallel.ring_attention import (
                ring_attention,
                ulysses_attention,
            )

            sp_fn = {"ring": ring_attention,
                     "ulysses": ulysses_attention}[self.sp_scheme]
            self._apply_block = GPTBlock(
                cfg,
                functools.partial(
                    sp_fn, axis_name=self.seq_axis, causal=True
                ),
            )
        else:
            self._apply_block = self._block
        self._ln_f = FusedLayerNorm(out_dtype=jnp.float32, name="ln_f")
        self._region = None  # jitted pipeline region, built on first apply

    # --- init ---------------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        r_embed, r_blocks, r_ln = jax.random.split(rng, 3)
        ids = jnp.zeros((1, 8), jnp.int32)
        embed_params = self._embed.init(r_embed, ids)["params"]

        x = jnp.zeros((1, 8, cfg.hidden_size), cfg.dtype)
        positions = jnp.zeros((1, 8), jnp.int32)

        def init_one(r):
            return self._block.init(r, x, positions, True)["params"]

        # Execution-order layer k lands at [k // lps] of the stage stack;
        # circular: stage c*n + p -> blocks[c, p] (chunk-major, rank dim
        # second so the pipe sharding stays on one leading-ish axis).
        if self.n_virtual > 1:
            block_rngs = jax.random.split(
                r_blocks,
                self.n_virtual * self.n_stages * self.layers_per_stage,
            ).reshape(self.n_virtual, self.n_stages, self.layers_per_stage, -1)
            blocks = jax.vmap(jax.vmap(jax.vmap(init_one)))(block_rngs)
        else:
            block_rngs = jax.random.split(
                r_blocks, self.n_stages * self.layers_per_stage
            ).reshape(self.n_stages, self.layers_per_stage, -1)
            blocks = jax.vmap(jax.vmap(init_one))(block_rngs)

        ln_params = self._ln_f.init(
            r_ln, jnp.zeros((1, cfg.hidden_size))
        )["params"]
        return {"params": {
            "wte": embed_params, "blocks": blocks, "ln_f": ln_params,
        }}

    # --- layout -------------------------------------------------------------

    def layout(self) -> Callable[[str, tuple], P]:
        """(path, shape) -> spec rule: stage dim of block leaves on ``pipe``,
        plus Megatron ``model``-axis sharding of the per-layer kernels when
        the mesh has a real model axis (pipe x tp: the model axis stays
        *auto* inside the pipeline's hybrid shard_map, so GSPMD partitions
        the stage matmuls and inserts the row-parallel all-reduce exactly
        as on an unpipelined mesh)."""
        axis = self.axis_name
        circular = self.n_virtual > 1
        tp = dict(self.mesh.shape).get(mesh_lib.AXIS_MODEL, 1) > 1

        n_stages = self.n_stages
        vocab = self.cfg.vocab_size

        def rule(path: str, shape: tuple) -> P:
            if not (path.startswith("blocks/") or "/blocks/" in path):
                # The embedding table is the one big non-block tensor
                # (vocab x hidden; at real scale it IS the per-rank memory
                # ceiling once the blocks are split pipe-ways).  Shard its
                # rows over pipe — embed/head run OUTSIDE the manual
                # region on auto axes, so GSPMD inserts the gather, and
                # the table + its optimizer slots stop being replicated
                # n_stages-fold (ZeRO-style placement, not a semantics
                # change).  ln_f stays replicated (two vectors).
                if path.endswith("wte/embedding") and vocab % n_stages == 0:
                    return P(axis, None)
                return P()
            # stage-stack prefix: (n_stages, lps, ...) or (v, n_stages, lps, ...)
            tail = [None] * (len(shape) - (2 if circular else 1))
            if tp and path.endswith("/kernel"):
                # per-layer kernels are 2D (in, out) at tail[-2:]:
                # column-parallel shards out, row-parallel shards in
                if "attn/qkv" in path or "fc_in" in path:
                    tail[-1] = mesh_lib.AXIS_MODEL
                elif "attn/proj" in path or "fc_out" in path:
                    tail[-2] = mesh_lib.AXIS_MODEL
            if circular:  # (v, n_stages, lps, ...): pipe on dim 1
                return P(None, axis, *tail)
            return P(axis, *tail)

        return rule

    # --- apply --------------------------------------------------------------

    def _stage_fn(self, stage_params: PyTree, x: jax.Array) -> jax.Array:
        """Apply this stage's ``layers_per_stage`` blocks (scan over the
        layer dim of the local param stack)."""
        if self.seq_parallel:
            # x holds this device's contiguous sequence chunk: positions
            # carry the global offset (RoPE and the ring's causal masking
            # both key off absolute position).
            s_loc = x.shape[1]
            positions = jnp.broadcast_to(
                lax.axis_index(self.seq_axis) * s_loc + jnp.arange(s_loc),
                x.shape[:2],
            )
        else:
            positions = jnp.broadcast_to(
                jnp.arange(x.shape[1]), x.shape[:2]
            )
        # Trig once per stage, shared across the layer scan (and saved as
        # a residual under remat) — same hoist as GPTLM's trunk.
        cfg = self.cfg
        rope_tabs = rope_tables(
            positions, cfg.hidden_size // cfg.num_heads, cfg.rope_theta,
            cfg.dtype,
        )

        def one(x, layer_params):
            # fp32 across the schedule, cfg.dtype inside the block (the
            # block's pre-LN casts do the rest)
            y = self._apply_block.apply(
                {"params": layer_params}, x.astype(self.cfg.dtype),
                positions, True, rope_tabs,
            )
            return y.astype(jnp.float32), None

        if self.cfg.remat:
            one = jax.checkpoint(one)
        x, _ = lax.scan(one, x, stage_params)
        return x

    def apply(self, variables: dict, input_ids: jax.Array, *,
              return_hidden: bool = False) -> jax.Array:
        params = variables["params"] if "params" in variables else variables
        cfg = self.cfg
        x = self._embed.apply({"params": params["wte"]}, input_ids)

        # Hybrid shard_map: only the axes whose collectives the pipeline
        # emits by hand (pipe ppermute, seq ring) are manual; data and
        # model stay AUTO — GSPMD shards the batch and partitions the
        # Megatron kernels (incl. the row-parallel all-reduce) inside the
        # region exactly as it would outside it.
        manual = {self.axis_name}
        if self.seq_parallel:
            manual.add(self.seq_axis)
        x_spec = P(
            None,  # batch dim: auto (data/fsdp sharding propagates)
            self.seq_axis if self.seq_parallel else None,
            None,
        )
        circular = self.n_virtual > 1
        if circular:
            block_specs = jax.tree.map(
                lambda p: P(None, self.axis_name, *([None] * (p.ndim - 2))),
                params["blocks"],
            )
        else:
            block_specs = jax.tree.map(
                lambda p: P(self.axis_name, *([None] * (p.ndim - 1))),
                params["blocks"],
            )
        n_micro = self.n_microbatches
        n_virtual = self.n_virtual

        def inner(block_params, xl):
            # xl stays fp32 through the pipeline machinery (scan carries,
            # ppermute handoffs); _stage_fn casts to cfg.dtype internally.
            # xl's batch dim is GLOBAL here (data is an auto axis)
            if xl.shape[0] % n_micro:
                raise ValueError(
                    f"global batch {xl.shape[0]} not divisible by "
                    f"n_microbatches={n_micro}"
                )
            mb = xl.reshape(
                n_micro, xl.shape[0] // n_micro, *xl.shape[1:]
            )
            if circular:
                local = jax.tree.map(lambda p: p[:, 0], block_params)
                out = circular_pipeline_apply(
                    self._stage_fn, local, mb, n_virtual=n_virtual,
                    axis_name=self.axis_name, wire_dtype=self._wire,
                )
            else:
                local = jax.tree.map(lambda p: p[0], block_params)
                out = pipeline_apply(
                    self._stage_fn, local, mb, axis_name=self.axis_name,
                    wire_dtype=self._wire,
                )
            return out.reshape(xl.shape)

        # Everything crossing or carried by the partial-manual region is
        # fp32: jax 0.9's partial-manual shard_map partitioner crashed on
        # bf16 copies ("invalid binary instruction opcode copy") when the
        # region composes with GSPMD-auto tensor-parallel kernels inside
        # (pipe x model), and hard-ABORTS the process under autodiff of a
        # bf16-boundary region on every composition (probed round 4).
        # Plain data x pipe bf16 FORWARD regions do compile
        # (tests/test_jax_workarounds.py pins the facts), but training is
        # the product, so the boundary stays fp32 unconditionally; the
        # safe subset of the bf16 optimization is the ppermute PAYLOAD
        # cast (``handoff_dtype="bfloat16"`` -> pipeline wire_dtype),
        # which is bit-exact for bf16 models.  Stage compute is still
        # cfg.dtype (see _stage_fn); fp32 handoffs are (mb, S, D)
        # residuals — tiny next to the stage matmuls — and ln_f upcasts
        # the output anyway.
        # The jit wrapper is load-bearing: partial-manual shard_map has no
        # eager impl path in jax 0.9 (_unmatch_spec only supports
        # all-manual), and grad-of-eager interprets the region the same
        # broken way.  Under an outer jit this inlines.  Cached on self so
        # eager callers don't pay a retrace per apply() (the specs depend
        # only on construction-time state; `inner` closes over nothing
        # call-specific).
        if self._region is None:
            self._region = jax.jit(jax.shard_map(
                inner, mesh=self.mesh,
                in_specs=(block_specs, x_spec), out_specs=x_spec,
                axis_names=frozenset(manual),
                check_vma=False,
            ))
        x = self._region(params["blocks"], x.astype(jnp.float32))

        x = self._ln_f.apply({"params": params["ln_f"]}, x)
        if return_hidden:
            return x  # loss applies the chunked head (ops/xent.py)
        from ..ops.xent import tied_head_logits

        wte = params["wte"]["embedding"]
        return tied_head_logits(x, wte, self.cfg.dtype)

    def bubble_fraction(self) -> float:
        if self.n_virtual > 1:
            return circular_bubble_fraction(
                self.n_stages, self.n_microbatches, self.n_virtual
            )
        return gpipe_bubble_fraction(self.n_stages, self.n_microbatches)


def pipelined_lm_loss(model: PipelinedGPT):
    """Next-token cross-entropy through the pipeline (same math as
    ``gpt.lm_loss`` incl. the vocab-chunked head; rng unused — dropout is
    rejected at construction)."""
    from ..ops.xent import chunked_softmax_xent

    def loss_fn(params, model_state, batch, rng):
        hidden = model.apply(
            {"params": params}, batch["input_ids"], return_hidden=True
        )
        loss = chunked_softmax_xent(
            hidden[:, :-1],
            params["wte"]["embedding"],
            batch["input_ids"][:, 1:],
            compute_dtype=model.cfg.dtype,
        )
        return loss, ({"perplexity": jnp.exp(loss)}, model_state)

    return loss_fn


def pipelined_lm_eval(model: PipelinedGPT):
    """Eval metric_fn through the pipeline (dropout is rejected at
    construction, so forward is already deterministic)."""
    from ..ops.xent import chunked_softmax_xent

    def metric_fn(params, model_state, batch):
        hidden = model.apply(
            {"params": params}, batch["input_ids"], return_hidden=True
        )
        loss = chunked_softmax_xent(
            hidden[:, :-1],
            params["wte"]["embedding"],
            batch["input_ids"][:, 1:],
            compute_dtype=model.cfg.dtype,
        )
        return {"loss": loss, "perplexity": jnp.exp(loss)}

    return metric_fn


def params_to_dense(
    pipe_params: dict, cfg: GPTConfig, *, n_virtual: int = 1
) -> dict:
    """Re-arrange pipeline params into the dense :class:`GPTLM` tree
    (``h{i}`` per layer) — for parity tests and for serving a
    pipeline-trained checkpoint on an unpipelined mesh.  ``n_virtual > 1``
    reads the circular ``(v, n_stages, lps, ...)`` block layout (execution
    order: stage ``c*n + p`` holds layers ``(c*n+p)*lps ...``)."""
    leaf = jax.tree.leaves(pipe_params["blocks"])[0]
    dense = {"wte": pipe_params["wte"], "ln_f": pipe_params["ln_f"]}
    if n_virtual > 1:
        v, n_stages, lps = leaf.shape[:3]
        if v != n_virtual:
            raise ValueError(
                f"params have {v} virtual chunks, caller said {n_virtual}"
            )
        for c in range(v):
            for p_ in range(n_stages):
                for j in range(lps):
                    k = (c * n_stages + p_) * lps + j
                    dense[f"h{k}"] = jax.tree.map(
                        lambda q: q[c][p_][j], pipe_params["blocks"]
                    )
        return dense
    n_stages = leaf.shape[0]
    layers_per_stage = cfg.num_layers // n_stages
    for s in range(n_stages):
        for j in range(layers_per_stage):
            dense[f"h{s * layers_per_stage + j}"] = jax.tree.map(
                lambda p: p[s][j], pipe_params["blocks"]
            )
    return dense
