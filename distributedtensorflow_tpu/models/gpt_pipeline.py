"""Pipeline-parallel GPT: the GPipe schedule wrapped around real blocks.

Round-1 verdict item: the pipeline engine (``parallel/pipeline.py``) only
ever ran a toy Dense stage.  This module makes a *real model* train through
it, with the heterogeneous structure a decoder LM needs:

- **embed** (token table) and **head** (final LN + tied projection) run
  OUTSIDE the pipeline, sharded over the batch axes — they are one matmul
  each, far cheaper than the block stack, and keeping them out preserves
  the pipeline's shape-preserving handoff invariant.  The table itself is
  row-sharded over ``pipe`` when the vocab divides (see :meth:`layout`):
  compute stays outside the pipeline, but storage (+ optimizer slots) is
  split ZeRO-style instead of replicated n_stages-fold;
- the **transformer blocks** — where the FLOPs are — are stacked
  ``(n_stages, layers_per_stage, ...)`` with the leading dim sharded over
  ``pipe``; each stage scans its ``layers_per_stage`` blocks locally, and
  microbatches march stage-to-stage via the ``lax.ppermute`` GPipe schedule
  in :func:`..parallel.pipeline.pipeline_apply`;
- autodiff through the scanned schedule yields the reverse pipeline; remat
  (``jax.checkpoint`` per block) keeps activation memory flat.

No reference equivalent exists (SURVEY.md §2.4: tf.distribute has no
GPipe); this is the framework's own new-capability bar.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import numpy as np

from ..parallel import mesh as mesh_lib
from ..parallel.pipeline import (
    SCHEDULES,
    circular_bubble_fraction,
    circular_pipeline_apply,
    fb_schedule,
    gpipe_bubble_fraction,
    pipeline_apply,
    pipeline_fb_step,
)
from .gpt import GPTBlock, GPTConfig, rope_tables
from .layers import FusedLayerNorm

PyTree = Any


@dataclasses.dataclass
class PipelinedGPT:
    """Functional pipeline-parallel GPT (not an nn.Module: its params carry
    an explicit stage dimension that flax's module tree cannot express).

    ``init(rng) -> {"params": ...}`` and ``apply(params, input_ids) ->
    logits`` mirror the flax calling convention used by the workloads.
    """

    cfg: GPTConfig
    mesh: Mesh
    n_microbatches: int
    axis_name: str = mesh_lib.AXIS_PIPE
    #: >1 selects the circular (interleaved) schedule: each rank holds
    #: n_virtual non-adjacent stage chunks, shrinking the bubble
    #: n_virtual-fold (`circular_bubble_fraction`).
    n_virtual: int = 1
    #: Training schedule: "gpipe" (all forwards, then autodiff — O(n_micro)
    #: live microbatch activations; with n_virtual>1 the circular forward
    #: order), "1f1b" (forward/backward interleaved, O(n_stages) live
    #: stage inputs; n_virtual must be 1), or "interleaved"
    #: (interleaved-1F1B over n_virtual>=2 chunks per rank,
    #: O(n_stages*n_virtual) live stage inputs; n_microbatches must be a
    #: multiple of n_stages).  The fb schedules compute the LM head loss
    #: in-loop at the last stage (parallel.pipeline.pipeline_fb_step), so
    #: they apply to the training loss_fn; apply()/eval always run the
    #: forward-only schedule.
    schedule: str = "gpipe"
    #: Sequence-parallel attention inside the stages when the mesh has a
    #: real ``seq`` axis: "ring" (ppermute KV rotation) or "ulysses"
    #: (all_to_all head<->sequence reshard).
    sp_scheme: str = "ring"
    #: Dtype of the inter-stage ppermute PAYLOAD (the wire).  None = the
    #: fp32 schedule dtype end to end.  "bfloat16" halves the per-handoff
    #: ICI traffic by casting down just before the collective and back up
    #: after; with a bf16 model the stage output is an upcast bf16 value,
    #: so the roundtrip is BIT-EXACT (asserted by test) — requires
    #: cfg.dtype=bfloat16 for that reason.  Scan carries, schedule
    #: buffers, and the region boundary stay fp32 (numerics: cross-stage
    #: residuals accumulate in fp32; the wire cast is the safe subset of
    #: the bf16 optimization; see :meth:`apply`).
    handoff_dtype: str | None = None

    def __post_init__(self):
        cfg = self.cfg
        if self.n_virtual < 1:
            raise ValueError(
                f"n_virtual must be >= 1, got {self.n_virtual} "
                "(--pp-virtual on the CLI)"
            )
        # pipe x seq composition: with a real seq axis on the mesh, every
        # activation is additionally sharded over seq and each stage's
        # attention runs the K/V ring across it (direct lax collectives —
        # the pipeline's shard_map already makes every axis manual).
        self.seq_axis = mesh_lib.AXIS_SEQ
        self.seq_parallel = dict(self.mesh.shape).get(self.seq_axis, 1) > 1
        if self.sp_scheme not in ("ring", "ulysses"):
            # validated regardless of mesh shape, so a typo surfaces at
            # construction, not when the config is later scaled to seq > 1
            raise ValueError(
                f"sp_scheme must be ring|ulysses, got {self.sp_scheme!r}"
            )
        self.n_stages = self.mesh.shape[self.axis_name]
        total_stages = self.n_stages * self.n_virtual
        if cfg.num_layers % total_stages:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by "
                f"pipe={self.n_stages} x n_virtual={self.n_virtual} stages"
            )
        if self.n_virtual > 1 and self.n_microbatches < self.n_stages:
            raise ValueError(
                f"circular schedule needs n_microbatches >= n_stages "
                f"({self.n_microbatches} < {self.n_stages})"
            )
        if self.schedule not in SCHEDULES:
            raise ValueError(
                f"schedule must be one of {SCHEDULES}, got {self.schedule!r}"
            )
        if self.schedule == "1f1b" and self.n_virtual != 1:
            raise ValueError(
                "schedule='1f1b' runs one chunk per rank; use "
                "schedule='interleaved' for n_virtual > 1"
            )
        if self.schedule == "interleaved":
            if self.n_virtual < 2:
                raise ValueError(
                    "schedule='interleaved' needs n_virtual >= 2 "
                    "(--pp-virtual on the CLI); with one chunk per rank "
                    "use schedule='1f1b'"
                )
            if self.n_microbatches % self.n_stages:
                raise ValueError(
                    f"interleaved schedule needs n_microbatches a multiple "
                    f"of n_stages ({self.n_microbatches} vs {self.n_stages})"
                )
        if self.schedule != "gpipe" and self.seq_parallel:
            raise NotImplementedError(
                "1f1b/interleaved compute the LM-head loss inside the "
                "pipeline region, and the next-token shift crosses seq "
                "shards there — use schedule='gpipe' with sequence "
                "parallelism"
            )
        if cfg.dropout_rate:
            raise NotImplementedError(
                "dropout inside the pipeline needs per-stage rng plumbing; "
                "set dropout_rate=0 for pipeline parallelism"
            )
        if self.handoff_dtype is None:
            self._wire = None
        elif self.handoff_dtype in ("bfloat16", "bf16"):
            if cfg.dtype != jnp.bfloat16:
                raise ValueError(
                    "handoff_dtype=bfloat16 requires cfg.dtype=bfloat16 — "
                    "a bf16 wire under an fp32 model would silently round "
                    "every cross-stage residual (with a bf16 model the "
                    "cast is exact)"
                )
            self._wire = jnp.bfloat16
        else:
            raise ValueError(
                f"handoff_dtype must be None or 'bfloat16', "
                f"got {self.handoff_dtype!r}"
            )
        self.layers_per_stage = cfg.num_layers // total_stages
        self._embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="wte"
        )
        # Manual Megatron tensor parallelism: the pipeline region is
        # FULL-manual shard_map (this jax's partial-manual lowering
        # hard-aborts — see apply()), so GSPMD cannot partition the stage
        # kernels inside it.  The stage block instead runs with per-shard
        # head counts / MLP width and an explicit row-parallel psum over
        # ``model`` (reduce_fn), against kernels sliced by the region's
        # in_specs.
        self.tp = dict(self.mesh.shape).get(mesh_lib.AXIS_MODEL, 1)
        tp_kwargs = {}
        if self.tp > 1:
            if (cfg.num_heads % self.tp or cfg.kv_heads % self.tp
                    or cfg.intermediate_size % self.tp):
                raise ValueError(
                    f"manual tensor parallelism needs num_heads="
                    f"{cfg.num_heads}, kv_heads={cfg.kv_heads} and "
                    f"intermediate_size={cfg.intermediate_size} divisible "
                    f"by model={self.tp}"
                )
            tp_kwargs = dict(
                n_heads=cfg.num_heads // self.tp,
                n_kv=cfg.kv_heads // self.tp,
                ffn_size=cfg.intermediate_size // self.tp,
                reduce_fn=lambda y: lax.psum(y, mesh_lib.AXIS_MODEL),
            )
        # _block initializes params (dense attention; attn_fn carries no
        # params, so the tree is identical either way).  _apply_block is
        # what stages execute: under seq parallelism it swaps in ring
        # attention, whose lax collectives only trace inside the shard_map.
        self._block = GPTBlock(cfg)
        if self.seq_parallel:
            import functools

            from ..parallel.ring_attention import (
                ring_attention,
                ulysses_attention,
            )

            sp_fn = {"ring": ring_attention,
                     "ulysses": ulysses_attention}[self.sp_scheme]
            self._apply_block = GPTBlock(
                cfg,
                functools.partial(
                    sp_fn, axis_name=self.seq_axis, causal=True
                ),
                **tp_kwargs,
            )
        elif self.tp > 1:
            self._apply_block = GPTBlock(cfg, **tp_kwargs)
        else:
            self._apply_block = self._block
        self._ln_f = FusedLayerNorm(out_dtype=jnp.float32, name="ln_f")
        self._region = None  # jitted pipeline region, built on first apply
        self._fb = None  # cached custom_vjp fb-region (1f1b/interleaved)

    # --- init ---------------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        r_embed, r_blocks, r_ln = jax.random.split(rng, 3)
        ids = jnp.zeros((1, 8), jnp.int32)
        embed_params = self._embed.init(r_embed, ids)["params"]

        x = jnp.zeros((1, 8, cfg.hidden_size), cfg.dtype)
        positions = jnp.zeros((1, 8), jnp.int32)

        def init_one(r):
            return self._block.init(r, x, positions, True)["params"]

        # Execution-order layer k lands at [k // lps] of the stage stack;
        # circular: stage c*n + p -> blocks[c, p] (chunk-major, rank dim
        # second so the pipe sharding stays on one leading-ish axis).
        if self.n_virtual > 1:
            block_rngs = jax.random.split(
                r_blocks,
                self.n_virtual * self.n_stages * self.layers_per_stage,
            ).reshape(self.n_virtual, self.n_stages, self.layers_per_stage, -1)
            blocks = jax.vmap(jax.vmap(jax.vmap(init_one)))(block_rngs)
        else:
            block_rngs = jax.random.split(
                r_blocks, self.n_stages * self.layers_per_stage
            ).reshape(self.n_stages, self.layers_per_stage, -1)
            blocks = jax.vmap(jax.vmap(init_one))(block_rngs)

        ln_params = self._ln_f.init(
            r_ln, jnp.zeros((1, cfg.hidden_size))
        )["params"]
        return {"params": {
            "wte": embed_params, "blocks": blocks, "ln_f": ln_params,
        }}

    # --- layout -------------------------------------------------------------

    def layout(self) -> Callable[[str, tuple], P]:
        """(path, shape) -> spec rule: stage dim of block leaves on ``pipe``,
        plus Megatron ``model``-axis sharding of the per-layer kernels when
        the mesh has a real model axis (pipe x tp: the region is
        full-manual, so apply() re-slices the stored kernels head-major at
        the boundary and the stage block runs per-shard Megatron math with
        explicit row-parallel psums — see ``_split_tp_blocks``)."""
        axis = self.axis_name
        circular = self.n_virtual > 1
        tp = dict(self.mesh.shape).get(mesh_lib.AXIS_MODEL, 1) > 1

        n_stages = self.n_stages
        vocab = self.cfg.vocab_size

        def rule(path: str, shape: tuple) -> P:
            if not (path.startswith("blocks/") or "/blocks/" in path):
                # The embedding table is the one big non-block tensor
                # (vocab x hidden; at real scale it IS the per-rank memory
                # ceiling once the blocks are split pipe-ways).  Shard its
                # rows over pipe — embed/head run OUTSIDE the manual
                # region on auto axes, so GSPMD inserts the gather, and
                # the table + its optimizer slots stop being replicated
                # n_stages-fold (ZeRO-style placement, not a semantics
                # change).  ln_f stays replicated (two vectors).
                if path.endswith("wte/embedding") and vocab % n_stages == 0:
                    return P(axis, None)
                return P()
            # stage-stack prefix: (n_stages, lps, ...) or (v, n_stages, lps, ...)
            tail = [None] * (len(shape) - (2 if circular else 1))
            if tp and path.endswith("/kernel"):
                # per-layer kernels are 2D (in, out) at tail[-2:]:
                # column-parallel shards out, row-parallel shards in
                if "attn/qkv" in path or "fc_in" in path:
                    tail[-1] = mesh_lib.AXIS_MODEL
                elif "attn/proj" in path or "fc_out" in path:
                    tail[-2] = mesh_lib.AXIS_MODEL
            if circular:  # (v, n_stages, lps, ...): pipe on dim 1
                return P(None, axis, *tail)
            return P(axis, *tail)

        return rule

    # --- apply --------------------------------------------------------------

    def _stage_fn(self, stage_params: PyTree, x: jax.Array) -> jax.Array:
        """Apply this stage's ``layers_per_stage`` blocks (scan over the
        layer dim of the local param stack)."""
        if self.seq_parallel:
            # x holds this device's contiguous sequence chunk: positions
            # carry the global offset (RoPE and the ring's causal masking
            # both key off absolute position).
            s_loc = x.shape[1]
            positions = jnp.broadcast_to(
                lax.axis_index(self.seq_axis) * s_loc + jnp.arange(s_loc),
                x.shape[:2],
            )
        else:
            positions = jnp.broadcast_to(
                jnp.arange(x.shape[1]), x.shape[:2]
            )
        # Trig once per stage, shared across the layer scan (and saved as
        # a residual under remat) — same hoist as GPTLM's trunk.
        cfg = self.cfg
        rope_tabs = rope_tables(
            positions, cfg.hidden_size // cfg.num_heads, cfg.rope_theta,
            cfg.dtype,
        )

        def one(x, layer_params):
            # fp32 across the schedule, cfg.dtype inside the block (the
            # block's pre-LN casts do the rest)
            y = self._apply_block.apply(
                {"params": layer_params}, x.astype(self.cfg.dtype),
                positions, True, rope_tabs,
            )
            return y.astype(jnp.float32), None

        if self.cfg.remat:
            one = jax.checkpoint(one)
        x, _ = lax.scan(one, x, stage_params)
        return x

    # --- manual-TP kernel plumbing ------------------------------------------

    def _split_tp_blocks(self, blocks: PyTree, nh: int | None = None,
                         nkv: int | None = None) -> PyTree:
        """Re-key the fused qkv kernel head-major for manual TP slicing.

        The fused qkv out dim is laid out ``[q | k | v]``: a contiguous
        ``model``-axis slice of it would cross the q/k/v boundaries, so a
        per-shard slice would NOT be "this shard's heads".  Outside the
        region the kernel is split into head-major leaves
        ``(..., D, heads, head_dim)`` whose head dim the region's in_specs
        shard; inside, each shard re-fuses ITS slice back into the local
        fused layout the block expects (:meth:`_fuse_tp_blocks`).  Pure
        slices/reshapes — autodiff carries kernel gradients back through
        them into the stored fused layout.  ``nh``/``nkv`` override the
        head counts for splitting a per-shard (local) fused tree — the fb
        engine's gradient un-fusing path.
        """
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        nh = nh if nh is not None else cfg.num_heads
        nkv = nkv if nkv is not None else cfg.kv_heads
        attn = dict(blocks["attn"])
        qkv = dict(attn["qkv"])
        kern = qkv["kernel"]
        *lead, d, _ = kern.shape
        qkv["kernel"] = {
            "q": kern[..., :nh * hd].reshape(*lead, d, nh, hd),
            "k": kern[..., nh * hd:(nh + nkv) * hd].reshape(
                *lead, d, nkv, hd),
            "v": kern[..., (nh + nkv) * hd:].reshape(*lead, d, nkv, hd),
        }
        attn["qkv"] = qkv
        out = dict(blocks)
        out["attn"] = attn
        return out

    @staticmethod
    def _fuse_tp_blocks(blocks: PyTree) -> PyTree:
        """Inverse of :meth:`_split_tp_blocks` on a per-shard slice."""
        attn = dict(blocks["attn"])
        qkv = dict(attn["qkv"])
        parts = qkv["kernel"]

        def flat(a):  # (..., D, h_local, hd) -> (..., D, h_local*hd)
            return a.reshape(*a.shape[:-2], a.shape[-2] * a.shape[-1])

        qkv["kernel"] = jnp.concatenate(
            [flat(parts["q"]), flat(parts["k"]), flat(parts["v"])], axis=-1
        )
        attn["qkv"] = qkv
        out = dict(blocks)
        out["attn"] = attn
        return out

    def _block_specs(self, blocks_t: PyTree) -> PyTree:
        """in_specs for the (possibly TP-split) stacked block tree."""
        prefix = ((None, self.axis_name) if self.n_virtual > 1
                  else (self.axis_name,))
        tp = self.tp

        def rule(path, leaf):
            pstr = "/".join(str(getattr(k, "key", k)) for k in path)
            tail = [None] * (leaf.ndim - len(prefix) - 1)
            if tp > 1:
                if "qkv/kernel" in pstr:
                    tail[-2] = mesh_lib.AXIS_MODEL  # (..., D, heads, hd)
                elif "proj/kernel" in pstr or "fc_out/kernel" in pstr:
                    tail[-2] = mesh_lib.AXIS_MODEL  # row-parallel: in dim
                elif "fc_in/kernel" in pstr:
                    tail[-1] = mesh_lib.AXIS_MODEL  # column-parallel: out
            return P(*prefix, None, *tail)

        return jax.tree.map_with_path(rule, blocks_t)

    # --- 1f1b / interleaved training loss -----------------------------------

    def _head_fn(self, head_ps, y, ids_mb):
        """In-loop loss head for the fb schedules: ln_f + tied chunked
        next-token xent on ONE microbatch (mean over its tokens) — the
        same math the gpipe path applies outside the region, per unit.
        Collective-free by construction (the ``pipeline_fb_step``
        contract: it runs under a rank-local ``lax.cond``)."""
        from ..ops.xent import chunked_softmax_xent

        h = self._ln_f.apply({"params": head_ps["ln_f"]}, y)
        return chunked_softmax_xent(
            h[:, :-1], head_ps["wte"]["embedding"], ids_mb[:, 1:],
            compute_dtype=self.cfg.dtype,
        )

    def _build_fb(self, blocks_t: PyTree, head_ps: PyTree):
        """Build the cached custom_vjp fb-region callable.

        The region runs the hand-scheduled forward+backward
        (:func:`..parallel.pipeline.pipeline_fb_step`) and returns loss
        AND gradients; the custom_vjp wrapper exposes the loss with the
        precomputed gradients as its backward, so ``jax.value_and_grad``
        of the workload loss_fn — and everything stacked on it: gradient
        accumulation, ``--zero``, ``--overlap`` — works unchanged.  The
        embedding lookup stays OUTSIDE: its cotangent is the region's
        ``dx0`` output, and jax transposes the lookup (and the tied
        table's double use) automatically.
        """
        cfg = self.cfg
        mesh = self.mesh
        sched = fb_schedule(
            self.n_stages, self.n_microbatches,
            self.n_virtual if self.schedule == "interleaved" else 1,
        )
        batch_axes = mesh_lib.data_axes(mesh)
        replicas = mesh_lib.replica_count(mesh)
        scale = 1.0 / (replicas * self.n_microbatches)
        n_micro = self.n_microbatches
        circular = self.n_virtual > 1
        tp = self.tp
        x_spec = P(batch_axes if batch_axes else None, None, None)
        ids_spec = P(batch_axes if batch_axes else None, None)
        block_specs = self._block_specs(blocks_t)
        head_specs = jax.tree.map(lambda _: P(), head_ps)

        def psum_axes(spec):
            """Mesh axes a leaf with this in_spec is replicated over —
            exactly the psums shard_map's own transpose inserts for the
            autodiff (gpipe) path, reproduced by hand here because the fb
            backward is hand-scheduled."""
            named = set()
            for entry in spec:
                if entry is None:
                    continue
                named.update(
                    entry if isinstance(entry, tuple) else (entry,)
                )
            return tuple(
                a for a in mesh.axis_names
                if mesh.shape[a] > 1 and a not in named
            )

        # Cotangent convention: jax transposes ``lax.psum`` to ``lax.psum``
        # and seeds the cotangent of a replicated output at ct/rep per
        # shard — the interior psum-transposes (the row-parallel
        # reduce_fn) restore full scale at each reduce point.  The
        # hand-seeded head cotangent must follow the same convention, so
        # it is divided by the replication factor of the non-batch,
        # non-pipe axes (model TP), and EVERY gradient is psum'd over the
        # axes its in_spec leaves unmapped — including the head over
        # ``model``, whose per-shard value carries the 1/rep seed.
        loss_reduce = tuple(
            a for a in (*batch_axes, self.axis_name) if mesh.shape[a] > 1
        )
        head_reduce = psum_axes(P())
        rep = 1
        for a in head_reduce:
            if a not in loss_reduce:
                rep *= mesh.shape[a]
        spec_leaves = jax.tree.leaves(
            block_specs, is_leaf=lambda x: isinstance(x, P)
        )

        def region(blocks_in, head_in, x0l, idsl):
            if tp > 1:
                blocks_in = self._fuse_tp_blocks(blocks_in)
            if circular:
                stacks = jax.tree.map(lambda p: p[:, 0], blocks_in)
            else:
                stacks = blocks_in  # (1, lps, ...): rank dim = chunk dim
            mb = x0l.reshape(
                n_micro, x0l.shape[0] // n_micro, *x0l.shape[1:]
            )
            labs = idsl.reshape(
                n_micro, idsl.shape[0] // n_micro, *idsl.shape[1:]
            )
            loss_sum, d_stage, d_head, dx0 = pipeline_fb_step(
                self._stage_fn, self._head_fn, stacks, head_in, mb, labs,
                sched, axis_name=self.axis_name,
                cotangent_scale=scale / rep,
                wire_dtype=self._wire,
            )
            loss = loss_sum * jnp.float32(scale)
            if loss_reduce:
                loss = lax.psum(loss, loss_reduce)
            if head_reduce:
                d_head = jax.tree.map(
                    lambda g: lax.psum(g, head_reduce), d_head
                )
            if circular:
                d_stage = jax.tree.map(lambda g: g[:, None], d_stage)
            if tp > 1:
                d_stage = self._split_tp_blocks(
                    d_stage, nh=cfg.num_heads // tp,
                    nkv=cfg.kv_heads // tp,
                )
            flat_g, treedef = jax.tree.flatten(d_stage)
            d_stage = jax.tree.unflatten(treedef, [
                lax.psum(g, ax) if (ax := psum_axes(sp)) else g
                for g, sp in zip(flat_g, spec_leaves)
            ])
            dx0 = dx0.reshape(x0l.shape)
            dx_axes = psum_axes(x_spec)
            if dx_axes:
                dx0 = lax.psum(dx0, dx_axes)
            return loss, d_stage, d_head, dx0

        region_sm = jax.jit(jax.shard_map(
            region, mesh=mesh,
            in_specs=(block_specs, head_specs, x_spec, ids_spec),
            out_specs=(P(), block_specs, head_specs, x_spec),
            check_vma=False,
        ))

        @jax.custom_vjp
        def fb(blocks_in, head_in, x0, ids):
            return region_sm(blocks_in, head_in, x0, ids)[0]

        def fb_fwd(blocks_in, head_in, x0, ids):
            loss, gb, gh, dx0 = region_sm(blocks_in, head_in, x0, ids)
            return loss, (gb, gh, dx0, ids)

        def fb_bwd(res, ct):
            gb, gh, dx0, ids = res

            def sc(tree):
                return jax.tree.map(lambda g: (g * ct).astype(g.dtype),
                                    tree)

            ids_ct = np.zeros(ids.shape, jax.dtypes.float0)
            return sc(gb), sc(gh), (dx0 * ct).astype(dx0.dtype), ids_ct

        fb.defvjp(fb_fwd, fb_bwd)
        self._fb = fb

    def fb_train_loss(self, params: PyTree, input_ids: jax.Array):
        """Scalar LM loss via the fb (1f1b/interleaved) schedule, with
        gradients precomputed in-region (see :meth:`_build_fb`)."""
        x0 = self._embed.apply(
            {"params": params["wte"]}, input_ids
        ).astype(jnp.float32)
        head_ps = {"ln_f": params["ln_f"], "wte": params["wte"]}
        blocks_t = (self._split_tp_blocks(params["blocks"])
                    if self.tp > 1 else params["blocks"])
        if self._fb is None:
            self._build_fb(blocks_t, head_ps)
        return self._fb(blocks_t, head_ps, x0, input_ids)

    def apply(self, variables: dict, input_ids: jax.Array, *,
              return_hidden: bool = False) -> jax.Array:
        params = variables["params"] if "params" in variables else variables
        cfg = self.cfg
        x = self._embed.apply({"params": params["wte"]}, input_ids)

        # FULL-manual shard_map: every mesh axis is manual inside the
        # region.  This jax's (0.4.37) partial-manual lowering goes
        # through `PartitionId`, which XLA's SPMD partitioner rejects
        # outright ("meaning is ambiguous"), and the grad path hard-aborts
        # on `IsManualSubgroup` — probed by tests/test_jax_workarounds.py.
        # Full-manual sidesteps the partitioner entirely: the batch is
        # manually sharded over the data axes, the stage kernels are
        # manually sliced over ``model`` with the block running per-shard
        # Megatron math + explicit row-parallel psums (__post_init__),
        # and the seq axis was always manual (ring/Ulysses collectives).
        # Embed and head stay OUTSIDE the region on GSPMD-auto axes, so
        # the pipe-sharded vocab table partitions exactly as before.
        batch_axes = mesh_lib.data_axes(self.mesh)
        x_spec = P(
            batch_axes if batch_axes else None,
            self.seq_axis if self.seq_parallel else None,
            None,
        )
        circular = self.n_virtual > 1
        blocks_t = (self._split_tp_blocks(params["blocks"])
                    if self.tp > 1 else params["blocks"])
        block_specs = self._block_specs(blocks_t)
        n_micro = self.n_microbatches
        n_virtual = self.n_virtual

        def inner(block_params, xl):
            # xl is this shard's LOCAL batch; it stays fp32 through the
            # pipeline machinery (scan carries, ppermute handoffs) —
            # _stage_fn casts to cfg.dtype internally.
            if xl.shape[0] % n_micro:
                raise ValueError(
                    f"per-replica batch {xl.shape[0]} not divisible by "
                    f"n_microbatches={n_micro}"
                )
            if self.tp > 1:
                block_params = self._fuse_tp_blocks(block_params)
            mb = xl.reshape(
                n_micro, xl.shape[0] // n_micro, *xl.shape[1:]
            )
            if circular:
                local = jax.tree.map(lambda p: p[:, 0], block_params)
                out = circular_pipeline_apply(
                    self._stage_fn, local, mb, n_virtual=n_virtual,
                    axis_name=self.axis_name, wire_dtype=self._wire,
                )
            else:
                local = jax.tree.map(lambda p: p[0], block_params)
                out = pipeline_apply(
                    self._stage_fn, local, mb, axis_name=self.axis_name,
                    wire_dtype=self._wire,
                )
            return out.reshape(xl.shape)

        # The region boundary and schedule buffers stay fp32: stage
        # compute is still cfg.dtype (_stage_fn), the fp32 handoffs are
        # (mb, S, D) residuals — tiny next to the stage matmuls — and the
        # safe half of the bf16-wire optimization is the ppermute PAYLOAD
        # cast (``handoff_dtype="bfloat16"`` -> wire_dtype), bit-exact for
        # bf16 models.  The jit wrapper is cached on self so eager callers
        # don't pay a retrace per apply() (specs depend only on
        # construction-time state; `inner` closes over nothing
        # call-specific).
        if self._region is None:
            self._region = jax.jit(jax.shard_map(
                inner, mesh=self.mesh,
                in_specs=(block_specs, x_spec), out_specs=x_spec,
                check_vma=False,
            ))
        x = self._region(blocks_t, x.astype(jnp.float32))

        x = self._ln_f.apply({"params": params["ln_f"]}, x)
        if return_hidden:
            return x  # loss applies the chunked head (ops/xent.py)
        from ..ops.xent import tied_head_logits

        wte = params["wte"]["embedding"]
        return tied_head_logits(x, wte, self.cfg.dtype)

    def bubble_fraction(self) -> float:
        if self.schedule in ("1f1b", "interleaved"):
            return fb_schedule(
                self.n_stages, self.n_microbatches,
                self.n_virtual if self.schedule == "interleaved" else 1,
            ).bubble_fraction()
        if self.n_virtual > 1:
            return circular_bubble_fraction(
                self.n_stages, self.n_microbatches, self.n_virtual
            )
        return gpipe_bubble_fraction(self.n_stages, self.n_microbatches)


def pipelined_lm_loss(model: PipelinedGPT):
    """Next-token cross-entropy through the pipeline (same math as
    ``gpt.lm_loss`` incl. the vocab-chunked head; rng unused — dropout is
    rejected at construction).  For the fb schedules (1f1b/interleaved)
    the head loss is computed INSIDE the scheduled loop and the gradients
    ride a custom_vjp (:meth:`PipelinedGPT.fb_train_loss`), so this
    loss_fn still plugs into ``jax.value_and_grad`` unchanged."""
    if model.schedule != "gpipe":
        def fb_loss_fn(params, model_state, batch, rng):
            loss = model.fb_train_loss(
                params, jnp.asarray(batch["input_ids"])
            )
            return loss, ({"perplexity": jnp.exp(loss)}, model_state)

        return fb_loss_fn

    from ..ops.xent import chunked_softmax_xent

    def loss_fn(params, model_state, batch, rng):
        hidden = model.apply(
            {"params": params}, batch["input_ids"], return_hidden=True
        )
        loss = chunked_softmax_xent(
            hidden[:, :-1],
            params["wte"]["embedding"],
            batch["input_ids"][:, 1:],
            compute_dtype=model.cfg.dtype,
        )
        return loss, ({"perplexity": jnp.exp(loss)}, model_state)

    return loss_fn


def pipelined_lm_eval(model: PipelinedGPT):
    """Eval metric_fn through the pipeline (dropout is rejected at
    construction, so forward is already deterministic)."""
    from ..ops.xent import chunked_softmax_xent

    def metric_fn(params, model_state, batch):
        hidden = model.apply(
            {"params": params}, batch["input_ids"], return_hidden=True
        )
        loss = chunked_softmax_xent(
            hidden[:, :-1],
            params["wte"]["embedding"],
            batch["input_ids"][:, 1:],
            compute_dtype=model.cfg.dtype,
        )
        return {"loss": loss, "perplexity": jnp.exp(loss)}

    return metric_fn


def params_to_dense(
    pipe_params: dict, cfg: GPTConfig, *, n_virtual: int = 1
) -> dict:
    """Re-arrange pipeline params into the dense :class:`GPTLM` tree
    (``h{i}`` per layer) — for parity tests and for serving a
    pipeline-trained checkpoint on an unpipelined mesh.  ``n_virtual > 1``
    reads the circular ``(v, n_stages, lps, ...)`` block layout (execution
    order: stage ``c*n + p`` holds layers ``(c*n+p)*lps ...``)."""
    leaf = jax.tree.leaves(pipe_params["blocks"])[0]
    dense = {"wte": pipe_params["wte"], "ln_f": pipe_params["ln_f"]}
    if n_virtual > 1:
        v, n_stages, lps = leaf.shape[:3]
        if v != n_virtual:
            raise ValueError(
                f"params have {v} virtual chunks, caller said {n_virtual}"
            )
        for c in range(v):
            for p_ in range(n_stages):
                for j in range(lps):
                    k = (c * n_stages + p_) * lps + j
                    dense[f"h{k}"] = jax.tree.map(
                        lambda q: q[c][p_][j], pipe_params["blocks"]
                    )
        return dense
    n_stages = leaf.shape[0]
    layers_per_stage = cfg.num_layers // n_stages
    for s in range(n_stages):
        for j in range(layers_per_stage):
            dense[f"h{s * layers_per_stage + j}"] = jax.tree.map(
                lambda p: p[s][j], pipe_params["blocks"]
            )
    return dense
