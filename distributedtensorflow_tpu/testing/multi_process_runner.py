"""Multi-process cluster runner for distributed tests.

Replaces the reference's ``MultiProcessRunner``
(``tf/python/distribute/multi_process_runner.py:107``, SURVEY.md §4): forks
one OS process per cluster task, wires the cluster env (here: the JAX
coordination-service env instead of ``TF_CONFIG`` — though callers may pass
any env, including ``TF_CONFIG``, to exercise the resolver chain), collects
per-task return values, enforces timeouts, and injects failures by killing
tasks mid-run (``SubprocessTimeoutError`` :1173,
``UnexpectedSubprocessExitError`` :1191 equivalents).

Children run on the CPU platform so multi-host tests need no hardware —
the JAX analogue of the reference's in-process fake clusters
(``multi_worker_test_base.py:123``); real collectives still run (Gloo
cross-process), so this tests the actual distributed runtime, not a mock.
"""

from __future__ import annotations

import dataclasses
import multiprocessing as mp
import os
import queue as queue_lib
import socket
import time
from typing import Any, Callable, Mapping, Sequence

_mp = mp.get_context("spawn")  # children must re-init JAX from scratch


class SubprocessTimeoutError(RuntimeError):
    """join() timed out; stragglers were killed."""

    def __init__(self, msg: str, result: "MultiProcessResult"):
        super().__init__(msg)
        self.result = result


class UnexpectedSubprocessExitError(RuntimeError):
    """A task exited nonzero (and was not an expected kill)."""

    def __init__(self, msg: str, result: "MultiProcessResult"):
        super().__init__(msg)
        self.result = result


@dataclasses.dataclass
class MultiProcessResult:
    """Per-task outcomes.

    ``return_values[i]`` holds task i's return value (missing if it died or
    raised); ``failures[i]`` holds the ``repr`` of the exception a failed
    task raised (missing if it succeeded or was killed before reporting).
    """

    return_values: dict[int, Any]
    failures: dict[int, str]
    exit_codes: dict[int, int | None]


_handed_out_ports: set[int] = set()


def pick_unused_port() -> int:
    """Pick a free localhost port, never repeating within this process.

    The socket closes before the caller binds the port, so an unrelated
    process could still steal it (inherent to port-picking); the dedupe set
    closes the much more likely race of two consecutive calls getting the
    same ephemeral port back from the kernel.
    """
    while True:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        if port not in _handed_out_ports:
            _handed_out_ports.add(port)
            return port


def _child_main(
    fn: Callable,
    task_id: int,
    num_processes: int,
    env: Mapping[str, str],
    init_distributed: bool,
    args: tuple,
    kwargs: dict,
    result_queue,
) -> None:
    # Env must be in place before JAX initializes a backend in this process.
    # The platform is forced (default: cpu) — the parent may run under a
    # TPU-selecting env (JAX_PLATFORMS=axon) that children must not inherit:
    # N children cannot share the one real chip.
    os.environ.update(env)
    os.environ["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    try:
        if init_distributed:
            from ..parallel import bootstrap

            bootstrap.initialize()
        value = fn(task_id, *args, **kwargs)
        result_queue.put((task_id, True, value))
    except BaseException as e:  # noqa: BLE001 — reported to the parent
        result_queue.put((task_id, False, repr(e)))
        raise


class MultiProcessRunner:
    """Run ``fn(task_id, *args)`` in ``num_processes`` cluster tasks.

    By default each child calls ``bootstrap.initialize()`` — resolving the
    cluster from the env this runner wrote (or any env the caller injected),
    which exercises the real resolver chain + coordination service.
    """

    def __init__(
        self,
        fn: Callable,
        num_processes: int,
        *,
        args: tuple = (),
        kwargs: dict | None = None,
        env: Mapping[str, str] | None = None,
        per_task_env: Sequence[Mapping[str, str]] | None = None,
        init_distributed: bool = True,
        timeout: float = 300.0,
    ):
        self._fn = fn
        self._n = num_processes
        self._args = args
        self._kwargs = kwargs or {}
        self._timeout = timeout
        self._queue = _mp.Queue()
        self._expected_kills: set[int] = set()
        port = pick_unused_port()
        base_env = {
            "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
            "JAX_NUM_PROCESSES": str(num_processes),
        }
        base_env.update(env or {})
        self._procs: list[mp.Process] = []
        for i in range(num_processes):
            child_env = dict(base_env, JAX_PROCESS_ID=str(i))
            if per_task_env:
                child_env.update(per_task_env[i])
            self._procs.append(
                _mp.Process(
                    target=_child_main,
                    args=(fn, i, num_processes, child_env, init_distributed,
                          self._args, self._kwargs, self._queue),
                    name=f"cluster-task-{i}",
                )
            )

    def start(self) -> "MultiProcessRunner":
        for p in self._procs:
            p.start()
        return self

    def terminate(self, task_id: int, *, expected: bool = True) -> None:
        """Fault injection: SIGKILL a task (reference process-kill path)."""
        if expected:
            self._expected_kills.add(task_id)
        self._procs[task_id].kill()

    def join(self, timeout: float | None = None) -> MultiProcessResult:
        timeout = self._timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        values: dict[int, Any] = {}
        failures: dict[int, str] = {}
        # Drain while waiting: a child whose return value exceeds the queue's
        # pipe buffer blocks in its feeder thread until the parent reads, so
        # joining before draining would deadlock (then falsely time out).
        while (
            any(p.is_alive() for p in self._procs)
            and time.monotonic() < deadline
        ):
            self._drain(values, failures, wait=0.05)
        for p in self._procs:
            p.join(max(0.0, deadline - time.monotonic()))
        timed_out = [p for p in self._procs if p.is_alive()]
        for p in timed_out:
            p.kill()
            p.join(10)
        self._drain(values, failures)
        result = MultiProcessResult(
            return_values=values,
            failures=failures,
            exit_codes={i: p.exitcode for i, p in enumerate(self._procs)},
        )
        if timed_out:
            raise SubprocessTimeoutError(
                f"tasks {[p.name for p in timed_out]} timed out after "
                f"{timeout}s", result,
            )
        bad = {
            i: code
            for i, code in result.exit_codes.items()
            if code != 0 and i not in self._expected_kills
        }
        if bad:
            raise UnexpectedSubprocessExitError(
                f"tasks exited nonzero: {bad}; failures: {failures}", result,
            )
        return result

    def _drain(
        self,
        values: dict[int, Any],
        failures: dict[int, str],
        wait: float = 0.0,
    ) -> None:
        block = wait > 0
        while True:
            try:
                task_id, ok, value = self._queue.get(block, wait or None)
            except queue_lib.Empty:
                return
            block = False  # only the first read waits
            if ok:
                values[task_id] = value
            else:
                failures[task_id] = value


def run(
    fn: Callable,
    num_processes: int,
    *,
    args: tuple = (),
    timeout: float = 300.0,
    env: Mapping[str, str] | None = None,
    per_task_env: Sequence[Mapping[str, str]] | None = None,
    init_distributed: bool = True,
) -> MultiProcessResult:
    """One-shot convenience (reference ``multi_process_runner.run``, :1245)."""
    return MultiProcessRunner(
        fn, num_processes, args=args, timeout=timeout, env=env,
        per_task_env=per_task_env, init_distributed=init_distributed,
    ).start().join()
