"""Test harness: multi-process cluster runner + fault injection."""

from .multi_process_runner import (  # noqa: F401
    MultiProcessResult,
    MultiProcessRunner,
    SubprocessTimeoutError,
    UnexpectedSubprocessExitError,
    pick_unused_port,
    run,
)
