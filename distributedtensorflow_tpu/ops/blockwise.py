"""Blockwise (chunked) token-wise computation for long sequences.

The feed-forward half of the blockwise-transformer recipe (SURVEY.md §5.7:
"ring attention ... blockwise feed-forward"; the attention half is
``ops/flash_attention.py`` + ``parallel/ring_attention.py``): a token-wise
function applied over sequence chunks so the (B, S, d_ff) intermediate never
materializes at once — with per-chunk rematerialization the backward pass
peaks at one (B, chunk, d_ff) tile instead of the full sequence.

Chunks are a compile-time Python loop (no ``lax.map``): each chunk is an
independent matmul pair XLA schedules back-to-back, and flax module calls
stay legal inside it (lifted transforms not required).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def blockwise_map(
    fn: Callable[[jax.Array], jax.Array],
    x: jax.Array,
    chunk_size: int,
    *,
    axis: int = 1,
    remat: bool = True,
) -> jax.Array:
    """Apply token-wise ``fn`` over ``chunk_size`` slices of ``axis``.

    ``fn`` must be elementwise over ``axis`` (each output position depends
    only on the same input position — true for MLPs/normalizations, NOT for
    attention).  ``remat=True`` checkpoints each chunk: backward recomputes
    that chunk's intermediates instead of storing all of them.  The axis
    length must divide evenly (callers pad, or pick a divisor chunk).
    """
    length = x.shape[axis]
    if chunk_size <= 0:
        raise ValueError(f"chunk_size must be positive, got {chunk_size}")
    if length % chunk_size:
        raise ValueError(
            f"axis {axis} length {length} not divisible by "
            f"chunk_size {chunk_size}"
        )
    if chunk_size == length:
        return fn(x)
    chunk_fn = jax.checkpoint(fn) if remat else fn
    parts = [
        chunk_fn(jax.lax.slice_in_dim(x, i, i + chunk_size, axis=axis))
        for i in range(0, length, chunk_size)
    ]
    return jnp.concatenate(parts, axis=axis)
