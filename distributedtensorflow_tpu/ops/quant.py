"""Quantized matmuls: int8 (and fp8-ready) dot_general with QAT hooks.

The step-time lever the pjit LM scaling recipe (PAPERS.md 2204.06514) and
the MLPerf TPU-pod study (1909.09756) both pull first: run the parameter
matmuls at a narrower width than the activation dtype.  On TPU an int8
contraction runs the MXU at ~2x the bf16 rate and halves the weight-side
HBM stream; on CPU (this sandbox's verification backend) the same program
is numerically exercised end to end, so quantized-vs-reference parity is
CI-checkable without the chip.

Scheme — symmetric per-channel absmax, the standard W8A8 recipe:

- both operands are quantized to int8 with a per-channel scale
  ``absmax / 127`` (lhs: per row of the contraction; rhs: per output
  column), accumulated in **int32**, and rescaled in fp32 — one
  ``s_row * s_col`` outer-product correction, exactly the factorization
  the MXU path needs;
- rounding is round-to-nearest by default; ``stochastic=True`` rounds
  ``floor(x/s + u)`` with ``u ~ U[0, 1)`` so the quantizer is *unbiased*
  (the accumulation-over-steps property QAT wants for weight gradients);
- the public :func:`quantized_matmul` carries a **straight-through
  estimator** custom VJP: the backward is the exact fp gradient of the
  un-quantized matmul (``dx = g @ w.T``, ``dw = x.T @ g`` at full
  precision), so training through quantized layers ("QAT-safe") follows
  the fp loss surface while the forward pays int8 prices;
- ``mode="fp8"`` quantizes to ``float8_e4m3fn`` with the same per-channel
  scale machinery (absmax / 448) when the installed jax exposes the dtype
  — the fp8-ready path; it raises a clear error otherwise instead of
  silently degrading.

Dynamic loss scaling (:class:`DynamicLossScale`) rides along for recipes
whose narrow-width gradients underflow: multiply the loss by ``scale``,
un-scale the grads, and :func:`loss_scale_update` grows the scale 2x every
``growth_interval`` finite steps / halves it on overflow — the standard
mixed-precision controller, expressed as a pure pytree so it lives inside
the jitted step.

Layer surface: :class:`~..models.layers.QuantDense` /
``QuantDenseGeneral`` (models/layers.py) wrap this module behind the same
parameter tree as ``nn.Dense`` / ``nn.DenseGeneral`` so checkpoints move
freely between quantized and full-width runs; ``GPTConfig.quant`` /
``BertConfig.quant`` / ``ViTConfig.quant`` (and ``train.py --quant``)
switch the dense/einsum call sites per model while embeddings, layer
norms, and the fp32 heads stay high-precision.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "QUANT_MODES",
    "quantize",
    "dequantize",
    "int8_dot",
    "quantized_matmul",
    "DynamicLossScale",
    "scale_loss",
    "unscale_grads",
    "grads_finite",
    "loss_scale_update",
]

#: The recognized quantized-compute modes ("none" = full-width passthrough).
QUANT_MODES = ("none", "int8", "int8_stochastic", "fp8")

_INT8_MAX = 127.0
_FP8_MAX = 448.0  # float8_e4m3fn finite max


def _fp8_dtype():
    dt = getattr(jnp, "float8_e4m3fn", None)
    if dt is None:
        raise NotImplementedError(
            "quant mode 'fp8' needs jnp.float8_e4m3fn, which this jax "
            "build does not expose — use 'int8' or upgrade jax"
        )
    return dt


def validate_mode(mode: str | None) -> str:
    mode = mode or "none"
    if mode not in QUANT_MODES:
        raise ValueError(
            f"unknown quant mode {mode!r}; expected one of {QUANT_MODES}"
        )
    return mode


def _absmax_scale(x32: jax.Array, axis: int, qmax: float) -> jax.Array:
    """Per-channel symmetric scale ``absmax / qmax`` (fp32, keepdims).

    A zero channel gets scale ``1/qmax`` (any positive value works: the
    channel quantizes to all-zeros either way and the rescale multiplies
    zeros) — never 0, which would NaN the divide."""
    amax = jnp.max(jnp.abs(x32), axis=axis, keepdims=True)
    return jnp.where(amax > 0, amax, 1.0) / qmax


def quantize(
    x: jax.Array,
    *,
    axis: int = -1,
    mode: str = "int8",
    key: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Quantize ``x`` along ``axis`` (the contraction axis).

    Returns ``(q, scale)`` with ``q`` int8 (or fp8) and ``scale`` the fp32
    per-channel absmax scale, keepdims over ``axis`` so ``q * scale``
    broadcasts back to ``x``'s shape.  ``mode="int8_stochastic"`` (or any
    mode with a ``key``) rounds stochastically — unbiased:
    ``E[q * scale] == x``.
    """
    mode = validate_mode(mode)
    if mode == "none":
        raise ValueError("quantize called with mode='none'")
    x32 = x.astype(jnp.float32)
    if mode == "fp8":
        scale = _absmax_scale(x32, axis, _FP8_MAX)
        return (x32 / scale).astype(_fp8_dtype()), scale
    scale = _absmax_scale(x32, axis, _INT8_MAX)
    y = x32 / scale
    if mode == "int8_stochastic" or key is not None:
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        y = jnp.floor(y + jax.random.uniform(key, y.shape, jnp.float32))
    else:
        y = jnp.round(y)
    q = jnp.clip(y, -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale


def int8_dot(
    x: jax.Array,
    w: jax.Array,
    *,
    mode: str = "int8",
    key: jax.Array | None = None,
) -> jax.Array:
    """``x @ w`` through the quantized path, fp32 result.

    ``x`` is ``(..., K)``; ``w`` is ``(K, N)``.  lhs rows and rhs columns
    each get their own absmax scale; the contraction accumulates in int32
    (fp32 for fp8 operands) and the two scale vectors rescale the
    accumulator — the only fp work outside the quantizers.
    """
    mode = validate_mode(mode)
    kx = kw = None
    if mode == "int8_stochastic":
        if key is None:
            raise ValueError("mode 'int8_stochastic' needs a PRNG key")
        kx, kw = jax.random.split(key)
    xq, sx = quantize(x, axis=-1, mode=mode, key=kx)   # (..., K), (..., 1)
    wq, sw = quantize(w, axis=0, mode=mode, key=kw)    # (K, N),  (1, N)
    acc_t = jnp.float32 if mode == "fp8" else jnp.int32
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=acc_t,
    )
    return acc.astype(jnp.float32) * sx * jnp.squeeze(sw, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _qmatmul(x, w, key, mode):
    return int8_dot(x, w, mode=mode, key=key).astype(x.dtype)


def _qmatmul_fwd(x, w, key, mode):
    return _qmatmul(x, w, key, mode), (x, w, np.shape(key))


def _qmatmul_bwd(mode, res, g):
    # Straight-through estimator: the exact gradient of the UN-quantized
    # matmul, computed at full precision from the saved fp operands — the
    # QAT contract (forward pays int8, backward follows the fp surface).
    x, w, key_shape = res
    g32 = g.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    dx = jax.lax.dot_general(
        g32, w32, (((g.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    g2 = g32.reshape(-1, g.shape[-1])
    dw = jax.lax.dot_general(
        x2, g2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(w.dtype)
    dkey = np.zeros(key_shape, jax.dtypes.float0)  # PRNG keys carry no grad
    return dx, dw, dkey


_qmatmul.defvjp(_qmatmul_fwd, _qmatmul_bwd)


def quantized_matmul(
    x: jax.Array,
    w: jax.Array,
    *,
    mode: str = "int8",
    key: jax.Array | None = None,
) -> jax.Array:
    """Differentiable quantized ``x @ w`` (straight-through estimator).

    ``x``: ``(..., K)`` activations; ``w``: ``(K, N)`` weights; output
    ``(..., N)`` in ``x.dtype``.  ``mode`` is one of :data:`QUANT_MODES`
    (``"none"`` falls through to the plain matmul — one call site, no
    branching at the layer); ``"int8_stochastic"`` requires ``key``.
    """
    mode = validate_mode(mode)
    if mode == "none":
        return jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (0,)), ((), ())),
        )
    if mode == "fp8":
        _fp8_dtype()  # fail loudly before tracing the custom_vjp
    if mode == "int8_stochastic" and key is None:
        raise ValueError("mode 'int8_stochastic' needs a PRNG key")
    if key is None:
        # a concrete dummy so the custom_vjp signature stays uniform; the
        # deterministic path never folds it in
        key = jax.random.PRNGKey(0)
    return _qmatmul(x, w, key, mode)


# --- dynamic loss scaling (the mixed-precision controller) -------------------


class DynamicLossScale(NamedTuple):
    """Pure-pytree loss-scale state; lives inside the jitted step.

    ``scale`` multiplies the loss (and divides the grads back);
    ``good_steps`` counts consecutive finite-gradient steps since the last
    change.  Defaults follow the classic AMP recipe: start at 2^15, double
    every 2000 clean steps, halve on overflow, never below 1.
    """

    scale: jax.Array
    good_steps: jax.Array

    @classmethod
    def init(cls, initial: float = 2.0 ** 15) -> "DynamicLossScale":
        return cls(
            scale=jnp.asarray(initial, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
        )


def scale_loss(loss: jax.Array, state: DynamicLossScale) -> jax.Array:
    return loss * state.scale.astype(loss.dtype)


def unscale_grads(grads, state: DynamicLossScale):
    inv = (1.0 / state.scale).astype(jnp.float32)
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)


def grads_finite(grads) -> jax.Array:
    """Scalar bool: every leaf of ``grads`` is entirely finite."""
    leaves = [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
    if not leaves:
        return jnp.asarray(True)
    return functools.reduce(jnp.logical_and, leaves)


def loss_scale_update(
    state: DynamicLossScale,
    finite: jax.Array,
    *,
    growth_interval: int = 2000,
    factor: float = 2.0,
    min_scale: float = 1.0,
) -> DynamicLossScale:
    """Next controller state: grow after ``growth_interval`` consecutive
    finite steps, shrink immediately on a non-finite one (that step's
    update should be skipped by the caller — the grads are garbage)."""
    good = jnp.where(finite, state.good_steps + 1, 0)
    grow = finite & (good >= growth_interval)
    scale = jnp.where(
        grow, state.scale * factor,
        jnp.where(finite, state.scale,
                  jnp.maximum(state.scale / factor, min_scale)),
    )
    return DynamicLossScale(
        scale=scale, good_steps=jnp.where(grow, 0, good)
    )
