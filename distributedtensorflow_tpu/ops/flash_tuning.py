"""On-disk autotune cache for the flash-attention block tiling.

``ops/flash_attention.py`` historically picked its (block_q, block_k)
tiling from a hand-retuned constant plus a divide-the-sequence fallback
chain — one number for every shape, refreshed only when someone re-ran
``tools/sweep_flash_blocks.py`` on a live chip and edited the source.
This module replaces that with a **runtime-consulted cache**: a JSON file
keyed on (shape, dtype, platform) whose entries are produced either by
``tools/autotune_flash.py``'s timing microbench sweep or from a
CaptureEngine XPlane, and looked up by the kernel at trace time.

Resolution order inside the kernel (``flash_attention._resolve_blocks``):

1. explicit ``block_q=`` / ``block_k=`` arguments (the sweep driver);
2. ``DTFT_FLASH_BLOCK_Q/K`` env overrides (the on-chip A/B knob);
3. a cache entry matching (platform, dtype, seq, depth) — preferring an
   exact (batch, heads) match — whose blocks divide the sequence;
4. the retuned default chain.

Cache location: ``DTFT_FLASH_TUNE_CACHE`` env var, else
``~/.cache/distributedtensorflow_tpu/flash_blocks.json``.  Set the env
var to ``off`` to disable consultation entirely (tests pin tilings that
way).  The file is read at most once per mtime (an in-process memo), so
the per-trace cost is a couple of stat calls.

Schema (validated by ``tools/check_metrics_schema.py``)::

    {"version": 1,
     "entries": [{"platform": "tpu", "dtype": "bfloat16",
                  "batch": 16, "heads": 12, "seq": 4096, "depth": 64,
                  "block_q": 1024, "block_k": 1024,
                  "ms": 17.1, "source": "sweep",
                  "timestamp": "2026-08-03T00:00:00"}, ...]}

``store()`` replaces any prior entry with the same key (newest
measurement wins) and writes atomically (tmp + rename).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any

logger = logging.getLogger("distributedtensorflow_tpu")

__all__ = [
    "cache_path",
    "load",
    "lookup",
    "store",
    "clear",
    "validate_doc",
    "SOURCES",
]

#: Provenance tags an entry may carry.
SOURCES = ("sweep", "xplane")

_ENV = "DTFT_FLASH_TUNE_CACHE"
_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "distributedtensorflow_tpu",
    "flash_blocks.json",
)

_memo_lock = threading.Lock()
_memo: dict[str, tuple[float, dict]] = {}  # path -> (mtime, doc)


def cache_path(path: str | None = None) -> str | None:
    """The effective cache file path; None when consultation is off."""
    if path is not None:
        return path
    env = os.environ.get(_ENV)
    if env == "off":
        return None
    return env or _DEFAULT


def load(path: str | None = None) -> dict:
    """The parsed cache document ({} when absent/off/corrupt) — mtime-
    memoized so the kernel's per-trace consult is cheap."""
    p = cache_path(path)
    if p is None:
        return {}
    try:
        mtime = os.stat(p).st_mtime
    except OSError:
        return {}
    with _memo_lock:
        hit = _memo.get(p)
        if hit is not None and hit[0] == mtime:
            return hit[1]
    try:
        with open(p) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("flash tuning cache %s unreadable (%s); ignoring",
                       p, e)
        doc = {}
    if not isinstance(doc, dict):
        doc = {}
    with _memo_lock:
        _memo[p] = (mtime, doc)
    return doc


def _entry_key(e: dict) -> tuple:
    return (e.get("platform"), e.get("dtype"), e.get("batch"),
            e.get("heads"), e.get("seq"), e.get("depth"))


def lookup(
    *,
    platform: str,
    dtype: str,
    seq: int,
    depth: int,
    batch: int | None = None,
    heads: int | None = None,
    path: str | None = None,
) -> tuple[int, int] | None:
    """The cached (block_q, block_k) for a shape, or None.

    Matching is on (platform, dtype, seq, depth); an entry that also
    matches (batch, heads) exactly beats a shape-generic one (batch and
    heads only scale the grid's embarrassingly-parallel axes, so a
    different-batch measurement of the same (seq, depth) is still the
    best available prior).  Entries whose blocks don't divide ``seq``
    are skipped — a corrupt or hand-edited cache must never turn into a
    Mosaic compile error."""
    doc = load(path)
    entries = doc.get("entries")
    if not isinstance(entries, list):
        return None
    best = None
    best_rank = -1
    for e in entries:
        if not isinstance(e, dict):
            continue
        if (e.get("platform") != platform or e.get("dtype") != dtype
                or e.get("seq") != seq or e.get("depth") != depth):
            continue
        bq, bk = e.get("block_q"), e.get("block_k")
        if not (isinstance(bq, int) and isinstance(bk, int)
                and bq > 0 and bk > 0 and seq % bq == 0 and seq % bk == 0):
            continue
        rank = int(e.get("batch") == batch) + int(e.get("heads") == heads)
        if rank > best_rank:
            best, best_rank = (bq, bk), rank
    return best


def store(entry: dict[str, Any], path: str | None = None) -> str:
    """Insert/replace one measurement; returns the file path written.

    Required keys: platform, dtype, seq, depth, block_q, block_k.
    ``source`` defaults to "sweep"; a timestamp is stamped when absent.
    Atomic write; an existing entry with the same
    (platform, dtype, batch, heads, seq, depth) key is replaced.
    """
    p = cache_path(path)
    if p is None:
        raise ValueError(
            f"flash tuning cache is disabled ({_ENV}=off); pass an "
            "explicit path"
        )
    missing = [k for k in ("platform", "dtype", "seq", "depth",
                           "block_q", "block_k") if entry.get(k) is None]
    if missing:
        raise ValueError(f"cache entry missing keys: {missing}")
    if entry["seq"] % entry["block_q"] or entry["seq"] % entry["block_k"]:
        raise ValueError(
            f"blocks ({entry['block_q']}, {entry['block_k']}) do not "
            f"divide seq {entry['seq']}"
        )
    entry = dict(entry)
    entry.setdefault("source", "sweep")
    if entry["source"] not in SOURCES:
        raise ValueError(
            f"source {entry['source']!r} not in {SOURCES}"
        )
    entry.setdefault("timestamp", time.strftime("%Y-%m-%dT%H:%M:%S"))
    doc = load(p)
    entries = [
        e for e in doc.get("entries", [])
        if isinstance(e, dict) and _entry_key(e) != _entry_key(entry)
    ]
    entries.append(entry)
    doc = {"version": 1, "entries": entries}
    os.makedirs(os.path.dirname(os.path.abspath(p)), exist_ok=True)
    tmp = f"{p}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, p)
    with _memo_lock:
        _memo.pop(p, None)
    return p


def clear(path: str | None = None) -> None:
    """Invalidate: remove the cache file (and its memo entry)."""
    p = cache_path(path)
    if p is None:
        return
    try:
        os.unlink(p)
    except FileNotFoundError:
        pass
    with _memo_lock:
        _memo.pop(p, None)


def validate_doc(doc: Any) -> list[str]:
    """Schema errors for a parsed cache document (shared logic for tests;
    ``tools/check_metrics_schema.py`` carries its own stdlib copy)."""
    errors: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, not an object"]
    if doc.get("version") != 1:
        errors.append(f"version {doc.get('version')!r} != 1")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        return errors + ["'entries' is missing or not a list"]
    for i, e in enumerate(entries):
        where = f"entries[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        for k in ("platform", "dtype"):
            if not isinstance(e.get(k), str) or not e.get(k):
                errors.append(f"{where}: {k!r} is not a non-empty string")
        for k in ("seq", "depth", "block_q", "block_k"):
            v = e.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                errors.append(f"{where}: {k!r} {v!r} is not a positive int")
        if (isinstance(e.get("seq"), int) and isinstance(e.get("block_q"), int)
                and isinstance(e.get("block_k"), int)
                and e["block_q"] > 0 and e["block_k"] > 0):
            if e["seq"] % e["block_q"] or e["seq"] % e["block_k"]:
                errors.append(
                    f"{where}: blocks ({e['block_q']}, {e['block_k']}) do "
                    f"not divide seq {e['seq']}"
                )
        if e.get("source") is not None and e["source"] not in SOURCES:
            errors.append(
                f"{where}: source {e['source']!r} not in {SOURCES}"
            )
        ms = e.get("ms")
        if ms is not None and (
            isinstance(ms, bool) or not isinstance(ms, (int, float))
            or not (ms >= 0)
        ):
            errors.append(f"{where}: 'ms' {ms!r} is not a non-negative "
                          "number")
    return errors
