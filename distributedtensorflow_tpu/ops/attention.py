"""Attention ops: XLA reference implementation + kernel dispatch point.

All attention in the framework routes through :func:`dot_product_attention`
so fused kernels (Pallas flash attention, ring attention over the ``seq``
axis — SURVEY.md §5.7) can replace the reference path without touching
models.  The plain-XLA path is itself MXU-friendly: one batched matmul per
score/value contraction, softmax in float32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative in bf16-safe range (bf16 max ~3.4e38; 1e9 fine)


def dot_product_attention(
    q: jax.Array,  # (B, S, H, D)
    k: jax.Array,  # (B, S, H, D)
    v: jax.Array,  # (B, S, H, D)
    *,
    mask: jax.Array | None = None,  # broadcastable to (B, H, Sq, Sk); True=keep
    segment_ids: jax.Array | None = None,  # int (B, S): packed sequences
    causal: bool = False,
    implementation: str = "auto",  # "auto" | "xla" | "pallas"
) -> jax.Array:
    """Multi-head scaled dot-product attention, BSHD layout.

    ``implementation="auto"`` picks the Pallas flash kernel on TPU when the
    shapes allow, else the XLA path.  ``segment_ids`` restricts attention to
    within packed segments (BERT-style example packing); on the XLA path it
    lowers to a block-diagonal mask, on the Pallas path it stays O(S) memory.
    """
    if implementation in ("auto", "pallas"):
        from . import flash_attention  # noqa: PLC0415 (lazy: pallas optional)

        if (
            flash_attention.supported(q, k, v, mask=mask, segment_ids=segment_ids)
            or implementation == "pallas"
        ):
            return flash_attention.flash_attention(
                q, k, v, mask=mask, segment_ids=segment_ids, causal=causal
            )
    if segment_ids is not None:
        seg = (segment_ids[:, :, None] == segment_ids[:, None, :])[:, None, :, :]
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    return xla_attention(q, k, v, mask=mask, causal=causal)


def cached_decode_attention(
    q: jax.Array,         # (B, s_new, H, D) new queries
    k_new: jax.Array,     # (B, s_new, H, D) new keys
    v_new: jax.Array,     # (B, s_new, H, D) new values
    cached_k: jax.Array,  # (B, H, D, max_seq) cache — S on LANES
    cached_v: jax.Array,  # (B, H, D, max_seq)
    cache_index: jax.Array,  # () int32 — next write slot
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One KV-cache decode step, shared by every serving path.

    Pure function (caller owns the cache state, e.g. a flax "cache"
    collection): writes the new K/V at ``cache_index``, attends the new
    queries against the whole static-shape cache with validity masking —
    a query at absolute position ``ix+i`` sees keys at positions
    ``<= ix+i``, which is also correct for multi-token chunked prefill —
    and returns ``(out, cached_k, cached_v, cache_index)`` updated.

    Layout + dtype discipline (2026-08-01 decode profiles): the cache is
    stored **(B, H, D, S)** — the long S axis on TPU LANES (a multiple
    of 128, zero pad waste) and D on sublanes — and the einsums keep
    native operand dtype with fp32 ACCUMULATION
    (``preferred_element_type``; an earlier ``.astype(f32)`` form
    materialized full fp32 cache copies every step).  Honest measured
    outcome: three formulations (fp32-cast + (B,S,H,D), S-contiguous
    (B,H,S,D), and this lane-major one) all timed ~9.6 ms/step at
    GPT-2-small bs16 — the multiply-reduce gemv lowering itself is the
    bound, invariant to logical layout, so the next decode-perf lever is
    a dedicated Pallas kernel, not more layout work.  This layout is
    kept as the principled default (no pad waste, contiguous stream).
    Softmax runs fp32 (matching :func:`xla_attention`).  New K/V arrive
    BSHD from the projections; the per-step transpose touches only
    (B, s_new, H, D).
    """
    b, s_new, h, d = q.shape
    max_seq = cached_k.shape[3]
    ix = cache_index
    cached_k = jax.lax.dynamic_update_slice(
        cached_k, k_new.transpose(0, 2, 3, 1), (0, 0, 0, ix)
    )
    cached_v = jax.lax.dynamic_update_slice(
        cached_v, v_new.transpose(0, 2, 3, 1), (0, 0, 0, ix)
    )
    q_pos = ix + jnp.arange(s_new)
    k_idx = jnp.arange(max_seq)
    valid = k_idx[None, :] <= q_pos[:, None]  # (s_new, max_seq)
    scores = jnp.einsum(
        "bqhd,bhdk->bhqk", q, cached_k,
        preferred_element_type=jnp.float32,
    ) / (d ** 0.5)
    scores = jnp.where(valid[None, None, :, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bhdk->bqhd", weights.astype(q.dtype), cached_v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    return out, cached_k, cached_v, ix + s_new


def xla_attention(q, k, v, *, mask=None, causal=False):
    orig_dtype = q.dtype
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(jnp.float32)
    # (B, H, Sq, Sk) scores; contraction in input dtype (bf16 MXU), softmax fp32
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    scores = scores.astype(jnp.float32)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(causal_mask, scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(orig_dtype), v)
    return out
