"""Attention ops: XLA reference implementation + kernel dispatch point.

All attention in the framework routes through :func:`dot_product_attention`
so fused kernels (Pallas flash attention, ring attention over the ``seq``
axis — SURVEY.md §5.7) can replace the reference path without touching
models.  The plain-XLA path is itself MXU-friendly: one batched matmul per
score/value contraction, softmax in float32.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative in bf16-safe range (bf16 max ~3.4e38; 1e9 fine)

#: Decode-step kernel selection: "auto" (Pallas single-token kernel where
#: platform/VMEM allow) or "xla" (force the einsum lowering).  Seeded from
#: the env; deliberately a MUTABLE module global, re-read at every trace:
#: bench_generate._xla_relative swaps it between back-to-back compiles for
#: the XLA-relative A/B (the decode claim hierarchy's primary axis), and
#: tests monkeypatch it.  Do not cache or freeze it at import time.
DECODE_IMPL = os.environ.get("DTF_DECODE_IMPL", "auto")


def dot_product_attention(
    q: jax.Array,  # (B, S, H, D)
    k: jax.Array,  # (B, S, H, D)
    v: jax.Array,  # (B, S, H, D)
    *,
    mask: jax.Array | None = None,  # broadcastable to (B, H, Sq, Sk); True=keep
    segment_ids: jax.Array | None = None,  # int (B, S): packed sequences
    causal: bool = False,
    window: int | None = None,  # sliding window (requires causal)
    implementation: str = "auto",  # "auto" | "xla" | "pallas"
) -> jax.Array:
    """Multi-head scaled dot-product attention, BSHD layout.

    ``implementation="auto"`` picks the Pallas flash kernel on TPU when the
    shapes allow, else the XLA path.  ``segment_ids`` restricts attention to
    within packed segments (BERT-style example packing); on the XLA path it
    lowers to a block-diagonal mask, on the Pallas path it stays O(S) memory.
    ``window`` enables causal sliding-window attention (token i sees keys
    in ``(i - window, i]``); the Pallas path skips out-of-band blocks so
    cost is O(S * window).
    """
    if implementation in ("auto", "pallas"):
        from . import flash_attention  # noqa: PLC0415 (lazy: pallas optional)

        if (
            flash_attention.supported(q, k, v, mask=mask, segment_ids=segment_ids)
            or implementation == "pallas"
        ):
            return flash_attention.flash_attention(
                q, k, v, mask=mask, segment_ids=segment_ids, causal=causal,
                window=window,
            )
    if segment_ids is not None:
        seg = (segment_ids[:, :, None] == segment_ids[:, None, :])[:, None, :, :]
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    return xla_attention(q, k, v, mask=mask, causal=causal, window=window)


def cached_decode_attention(
    q: jax.Array,         # (B, s_new, H, D) new queries
    k_new: jax.Array,     # (B, s_new, Hkv, D) new keys (Hkv <= H: GQA)
    v_new: jax.Array,     # (B, s_new, Hkv, D) new values
    cached_k: jax.Array,  # (B, Hkv, max_seq, D) cache
    cached_v: jax.Array,  # (B, Hkv, max_seq, D)
    cache_index: jax.Array,  # () int32 — next write slot
    window: int | None = None,  # sliding window (matches training masking)
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One KV-cache decode step, shared by every serving path.

    Pure function (caller owns the cache state, e.g. a flax "cache"
    collection): writes the new K/V at ``cache_index``, attends the new
    queries against the whole static-shape cache with validity masking —
    a query at absolute position ``ix+i`` sees keys at positions
    ``<= ix+i``, which is also correct for multi-token chunked prefill —
    and returns ``(out, cached_k, cached_v, cache_index)`` updated.

    Decode perf history (2026-08-01, GPT-2-small bs16 max_seq 1024, all
    measured in BENCH_RESULTS/generate_20260801_*.json): XLA's gemv
    lowering costs ~9.6 ms/step INVARIANT to cache layout and operand
    dtype (three formulations tied); a per-(b, h) Pallas kernel cut it
    to 7.1 ms but paid ~2.2 ms of strided cache WRITES in (B, H, D, S)
    plus DMA latency on 192 tiny tiles; the shipped form — cache
    (B, H, S, D) so the per-step write is a contiguous row, single-token
    steps dispatched to the head-blocked Pallas kernel — measures
    **7.3 ms/step (1.34x the XLA lowering)**.  The remaining gap to the
    ~1 ms memory floor is kernel-internal (half-empty lanes at D=64 and
    per-head softmax passes); further cuts need Mosaic-level work, not
    layout changes.  Softmax runs fp32 (matching :func:`xla_attention`);
    the multi-token (prefill) path keeps the XLA einsums with native
    operand dtype + fp32 accumulation.
    """
    b, s_new, h, d = q.shape
    max_seq = cached_k.shape[2]
    ix = cache_index
    cached_k = jax.lax.dynamic_update_slice(
        cached_k, k_new.transpose(0, 2, 1, 3), (0, 0, ix, 0)
    )
    cached_v = jax.lax.dynamic_update_slice(
        cached_v, v_new.transpose(0, 2, 1, 3), (0, 0, ix, 0)
    )
    q_pos = ix + jnp.arange(s_new)
    k_idx = jnp.arange(max_seq)
    valid = k_idx[None, :] <= q_pos[:, None]  # (s_new, max_seq)
    if window is not None:
        # sliding window: only the last `window` positions stay visible
        valid &= k_idx[None, :] > q_pos[:, None] - window
    # Kernel blocks are whole-axis in (S, D) (always tile-legal); the
    # head-block picker bounds VMEM, so the only fallback case is a
    # single head's (S, D) temporaries exceeding the budget.  Platform
    # routing: compiled kernel on TPU; interpret-mode kernel on CPU so
    # tests exercise the same code path; any OTHER backend (e.g. GPU)
    # keeps the compiled XLA einsum path below — interpret emulation
    # there would serve real traffic at Python speed.
    platform = jax.devices()[0].platform
    if (DECODE_IMPL != "xla" and s_new == 1
            and platform in ("tpu", "axon", "cpu")
            and max_seq * d * _decode_bytes_per_elem(cached_k.dtype.itemsize)
            <= _DECODE_VMEM_BUDGET):
        out = _pallas_decode_attention(
            q, cached_k, cached_v, valid.astype(jnp.int32),
            interpret=platform == "cpu",
        )
        return out, cached_k, cached_v, ix + s_new
    h_kv = cached_k.shape[1]
    if h != h_kv:  # GQA: grouped einsums, cache never broadcast to H
        g = h // h_kv
        qg = q.reshape(b, s_new, h_kv, g, d)
        scores = jnp.einsum(
            "bqhgd,bhkd->bhgqk", qg, cached_k,
            preferred_element_type=jnp.float32,
        ).reshape(b, h, s_new, max_seq) / (d ** 0.5)
    else:
        scores = jnp.einsum(
            "bqhd,bhkd->bhqk", q, cached_k,
            preferred_element_type=jnp.float32,
        ) / (d ** 0.5)
    scores = jnp.where(valid[None, None, :, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    if h != h_kv:
        wg = weights.astype(q.dtype).reshape(b, h_kv, g, s_new, max_seq)
        out = jnp.einsum(
            "bhgqk,bhkd->bqhgd", wg, cached_v,
            preferred_element_type=jnp.float32,
        ).reshape(b, s_new, h, d).astype(q.dtype)
    else:
        out = jnp.einsum(
            "bhqk,bhkd->bqhd", weights.astype(q.dtype), cached_v,
            preferred_element_type=jnp.float32,
        ).astype(q.dtype)
    return out, cached_k, cached_v, ix + s_new


def paged_decode_attention(
    q: jax.Array,             # (B, H, D) one new query per serving slot
    k_pool: jax.Array,        # (num_blocks, block_size, Hkv, D) shared pool
    v_pool: jax.Array,        # (num_blocks, block_size, Hkv, D)
    block_tables: jax.Array,  # (B, max_blocks) int32 physical block ids
    seq_lens: jax.Array,      # (B,) int32 valid tokens incl. this step's
) -> jax.Array:
    """Single-token decode attention against a paged (block-pool) KV cache.

    The serving engine's counterpart of :func:`cached_decode_attention`:
    instead of one dense ``(B, Hkv, max_seq, D)`` buffer per slot, K/V
    live in a pool of fixed-size blocks shared by every slot and each
    slot's ``block_tables`` row names the blocks that hold its sequence —
    so a finished or short sequence pins only the blocks it actually
    used (``serve.kv_cache`` owns allocation).  Blockwise layout per
    ``ops/blockwise.py``'s chunking idiom: the sequence axis is tiled in
    ``block_size`` chunks, here scattered through the pool.

    Each slot gathers its blocks to a ``(max_blocks * block_size, Hkv,
    D)`` view, masks positions ``>= seq_lens`` (and whatever a scratch /
    unallocated table entry points at), and runs the same fp32-softmax
    scaled dot product as the dense decode path — so paged and dense
    decode agree bit-for-bit up to reduction order (tests pin this).
    Reference XLA formulation (gather + einsum); a Mosaic kernel that
    streams blocks without materializing the gather is future work, so
    compute cost is O(max_blocks * block_size) per slot while *residency*
    is O(allocated blocks).
    """
    b, h, d = q.shape
    nb, block_size, h_kv, _ = k_pool.shape
    cap = block_tables.shape[1] * block_size
    # (B, max_blocks, bs, Hkv, D) -> (B, Hkv, cap, D); the gather is the
    # page-table walk.
    k = k_pool[block_tables].reshape(b, cap, h_kv, d).transpose(0, 2, 1, 3)
    v = v_pool[block_tables].reshape(b, cap, h_kv, d).transpose(0, 2, 1, 3)
    valid = jnp.arange(cap)[None, :] < seq_lens[:, None]  # (B, cap)
    if h != h_kv:  # GQA: grouped einsums, pool never broadcast to H
        g = h // h_kv
        qg = q.reshape(b, h_kv, g, d)
        scores = jnp.einsum(
            "bhgd,bhkd->bhgk", qg, k, preferred_element_type=jnp.float32,
        ).reshape(b, h, cap) / (d ** 0.5)
    else:
        scores = jnp.einsum(
            "bhd,bhkd->bhk", q, k, preferred_element_type=jnp.float32,
        ) / (d ** 0.5)
    scores = jnp.where(valid[:, None, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    if h != h_kv:
        wg = weights.astype(q.dtype).reshape(b, h_kv, g, cap)
        out = jnp.einsum(
            "bhgk,bhkd->bhgd", wg, v, preferred_element_type=jnp.float32,
        ).reshape(b, h, d)
    else:
        out = jnp.einsum(
            "bhk,bhkd->bhd", weights.astype(q.dtype), v,
            preferred_element_type=jnp.float32,
        )
    return out.astype(q.dtype)


def paged_verify_attention(
    q: jax.Array,             # (B, T, H, D) draft-window queries per slot
    k_pool: jax.Array,        # (num_blocks, block_size, Hkv, D) shared pool
    v_pool: jax.Array,        # (num_blocks, block_size, Hkv, D)
    block_tables: jax.Array,  # (B, max_blocks) int32 physical block ids
    attend_lens: jax.Array,   # (B,) int32 valid tokens for query 0
) -> jax.Array:
    """Multi-token decode attention against the paged pool (speculative
    verification).

    The ``T > 1`` generalization of :func:`paged_decode_attention`:
    each slot carries a window of ``T`` query positions — its last
    committed token followed by ``T - 1`` draft tokens — whose K/V this
    step wrote at consecutive positions, and query ``t`` attends
    ``attend_lens + t`` positions (causal masking *inside the draft
    window*: draft ``t`` sees everything committed plus the drafts
    before it, exactly what a sequential decode would have seen — which
    is why accepted drafts are token-for-token what the one-token path
    would have produced).  Same gather-through-page-table walk, same
    fp32-softmax scaled dot product, same GQA grouping; at ``T = 1``
    with ``attend_lens = seq_lens`` it reduces to the decode path.
    Returns ``(B, T, H, D)``.
    """
    b, t, h, d = q.shape
    nb, block_size, h_kv, _ = k_pool.shape
    cap = block_tables.shape[1] * block_size
    k = k_pool[block_tables].reshape(b, cap, h_kv, d).transpose(0, 2, 1, 3)
    v = v_pool[block_tables].reshape(b, cap, h_kv, d).transpose(0, 2, 1, 3)
    # (B, T, cap): query t of slot b sees positions < attend_lens[b] + t
    valid = (jnp.arange(cap)[None, None, :]
             < (attend_lens[:, None] + jnp.arange(t)[None, :])[:, :, None])
    if h != h_kv:  # GQA: grouped einsums, pool never broadcast to H
        g = h // h_kv
        qg = q.reshape(b, t, h_kv, g, d)
        scores = jnp.einsum(
            "bthgd,bhkd->bhgtk", qg, k,
            preferred_element_type=jnp.float32,
        ).reshape(b, h, t, cap) / (d ** 0.5)
    else:
        scores = jnp.einsum(
            "bthd,bhkd->bhtk", q, k, preferred_element_type=jnp.float32,
        ) / (d ** 0.5)
    scores = jnp.where(valid[:, None, :, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    if h != h_kv:
        wg = weights.astype(q.dtype).reshape(b, h_kv, g, t, cap)
        out = jnp.einsum(
            "bhgtk,bhkd->bthgd", wg, v, preferred_element_type=jnp.float32,
        ).reshape(b, t, h, d)
    else:
        out = jnp.einsum(
            "bhtk,bhkd->bthd", weights.astype(q.dtype), v,
            preferred_element_type=jnp.float32,
        )
    return out.astype(q.dtype)


def _decode_attn_kernel(q_ref, k_ref, v_ref, valid_ref, o_ref, *, scale):
    """A block of heads of one batch row's single-token decode attention.

    XLA lowers the decode gemv as separate multiply-reduce fusions that
    measured ~9.6 ms/step at GPT-2-small bs16 regardless of cache layout
    (see :func:`cached_decode_attention`).  This kernel fuses
    scores -> masked softmax -> weighted-V for ``hb`` heads per grid
    step over (hb, S, D) K/V tiles: the only HBM traffic is one read of
    each.

    Lane-major formulation (round-4 rework of the first measured kernel):
    the original computed per-head scores as an (S, 1) COLUMN — every
    softmax/mask pass used 1 of 128 lanes, and the score and weighted-V
    contractions ran as VPU multiply+lane-reduce over fp32-cast (S, D)
    tiles, which is exactly the "half-empty lanes and per-head softmax
    passes" gap its 7.3 ms measurement recorded.  Here both contractions
    are MXU dot_generals on the native-dtype tiles (fp32 accumulation)
    and every elementwise temporary is a lane-major (8, S) row tile —
    the 8 sublanes carry the q broadcast the block layout ships anyway,
    so each pass is 8 full vregs instead of 128 nearly-empty ones, and
    the (S, D) fp32 cast passes disappear entirely.
    """
    # q/out ride with an 8-deep broadcast sublane dim — (1, hb, 8, d)
    # blocks keep the head block on an UNTILED leading dim, so any hb is
    # tile-legal (a (hb, d) trailing block is only legal for hb % 8 == 0
    # or hb == H, and Mosaic cannot reshape lanes to sublanes in-kernel;
    # both found on-chip at hb=4).  Same trick as fused_xent's _SUB
    # scratch.  The head loop is a STATIC unroll.
    hb = q_ref.shape[1]
    # GQA: the kv block carries hb // group heads; q head hi reads kv
    # head hi // group — the group shares one streamed (S, D) tile, so
    # the cache read (the decode step's binding HBM cost) shrinks by
    # the group factor.
    group = hb // k_ref.shape[1]
    valid_row = valid_ref[...] != 0                     # (1, S)
    for hi in range(hb):
        q_h = q_ref[0, hi, :, :]                        # (8, D), rows equal
        k_h = k_ref[0, hi // group, :, :]               # (S, D)
        s = jax.lax.dot_general(
            q_h, k_h, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                       # (8, S)
        s = jnp.where(valid_row, s, NEG_INF)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        w = (p / jnp.sum(p, axis=1, keepdims=True)).astype(v_ref.dtype)
        o_ref[0, hi] = jax.lax.dot_general(
            w, v_ref[0, hi // group, :, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)                           # (8, D), rows equal


def _decode_bytes_per_elem(kv_itemsize: int) -> int:
    """VMEM bytes per cache element in the decode kernel: the
    double-buffered K and V blocks (2 operands x 2 buffers x itemsize)
    plus slack for the small lane-major temporaries.  Scales with the
    cache dtype — a flat bf16 constant under-counted fp32 caches ~2x
    and could pick a block over the 16 MB VMEM limit.  The lane-major
    kernel holds no fp32 (S, D) casts (the old formulation's flat
    24 B/elem), so more heads fit one grid step."""
    return 4 * kv_itemsize + 2


_DECODE_VMEM_BUDGET = 12 * 2**20


def _pick_decode_head_block(h: int, s: int, d: int, kv_itemsize: int,
                            group: int = 1) -> int:
    """q-heads per grid step: a multiple of ``group`` (so every step's
    kv block holds whole GQA groups) whose kv-side tile fits the VMEM
    budget.  At group=1 this is the original picker."""
    import os

    o = os.environ.get("DTFT_DECODE_HEAD_BLOCK")  # on-chip sweep override
    if o:
        n = int(o)
        if n > 0 and h % n == 0 and n % group == 0:
            return n
        import sys

        print(f"decode_attention: DTFT_DECODE_HEAD_BLOCK={o} invalid for "
              f"{h} heads / group {group}; using the auto-picked block",
              file=sys.stderr)
    for hb_kv in (16, 12, 8, 6, 4, 3, 2, 1):
        hb = hb_kv * group
        if h % hb == 0 and hb_kv * s * d * _decode_bytes_per_elem(kv_itemsize) \
                <= _DECODE_VMEM_BUDGET:
            return hb
    return group


def _pallas_decode_attention(q, cached_k, cached_v, valid, *, interpret):
    """Single-token decode attention over the (B, Hkv, S, D) cache.

    ``q`` (B, 1, H, D); ``valid`` (1, S) int32 (1 = attend).  Returns
    (B, 1, H, D).  Grid (B, H/hb): each step streams hb/group kv heads'
    K/V (GQA shares each kv tile across its query-head group).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, _, h, d = q.shape
    h_kv, s = cached_k.shape[1], cached_k.shape[2]
    group = h // h_kv
    hb = _pick_decode_head_block(h, s, d, cached_k.dtype.itemsize, group)
    hb_kv = hb // group
    mem = pl.ANY if interpret else pltpu.VMEM
    q8 = jnp.broadcast_to(
        q.transpose(0, 2, 1, 3), (b, h, 8, d)
    )  # (B, H, 8, D): 8-deep sublane broadcast (see kernel note)
    out = pl.pallas_call(
        functools.partial(_decode_attn_kernel, scale=1.0 / (d ** 0.5)),
        grid=(b, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, 8, d), lambda i, j: (i, j, 0, 0),
                         memory_space=mem),
            pl.BlockSpec((1, hb_kv, s, d), lambda i, j: (i, j, 0, 0),
                         memory_space=mem),
            pl.BlockSpec((1, hb_kv, s, d), lambda i, j: (i, j, 0, 0),
                         memory_space=mem),
            pl.BlockSpec((1, s), lambda i, j: (0, 0), memory_space=mem),
        ],
        out_specs=pl.BlockSpec((1, hb, 8, d), lambda i, j: (i, j, 0, 0),
                               memory_space=mem),
        out_shape=jax.ShapeDtypeStruct((b, h, 8, d), q.dtype),
        interpret=interpret,
    )(q8, cached_k, cached_v, valid)
    return out[:, :, 0, :][:, None, :, :]  # (B, 1, H, D)


def xla_attention(q, k, v, *, mask=None, causal=False, window=None):
    """BSHD attention; supports GQA (k/v with fewer heads than q, heads
    grouped ``g = Hq // Hkv``) via grouped einsums — the (Hkv, g) <->
    (Hq,) reshapes are over adjacent dims, so they are free relayouts,
    and K/V are never materialized at Hq width."""
    orig_dtype = q.dtype
    b, sq, hq, depth = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    scale = 1.0 / jnp.sqrt(depth).astype(jnp.float32)
    # (B, H, Sq, Sk) scores; contraction in input dtype (bf16 MXU), softmax fp32
    if hq != hkv:
        g = hq // hkv
        qg = q.reshape(b, sq, hkv, g, depth)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).reshape(
            b, hq, sq, sk) * scale
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    scores = scores.astype(jnp.float32)
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires causal")
    if causal:
        causal_mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        if window is not None:
            # band lower edge in absolute positions (q offset for Sq < Sk)
            qp = jnp.arange(sq)[:, None] + (sk - sq)
            causal_mask &= jnp.arange(sk)[None, :] > qp - window
        scores = jnp.where(causal_mask, scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    if hq != hkv:
        wg = weights.astype(orig_dtype).reshape(b, hkv, g, sq, sk)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", wg, v).reshape(b, sq, hq, depth)
    else:
        out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(orig_dtype), v)
    return out
