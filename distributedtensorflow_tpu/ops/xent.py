"""Memory-efficient softmax cross-entropy for large-vocabulary LM heads.

The naive LM loss materializes fp32 logits ``(B, S, V)`` plus a
``log_softmax`` copy — for GPT-2-small at B=16, S=1024, V=50257 that is
~3.3 GB *per copy*, and the train step becomes HBM-bandwidth-bound on
tensors that are immediately reduced away (measured on the v5e chip:
see BENCH_RESULTS/lm_*.json before/after).  The reference stack has no
equivalent (Keras ``SparseCategoricalCrossentropy`` materializes logits
the same way); this is TPU-first design, not a port.

:func:`chunked_softmax_xent` computes the same loss streaming over token
chunks inside a ``lax.scan`` whose body is ``jax.checkpoint``-ed:

- forward: per chunk, logits ``(C, V)`` are built, reduced to
  ``logsumexp`` and the target logit, then discarded — peak extra memory
  is ``C x V`` fp32 instead of ``B x S x V``;
- backward: the chunk's logits are *recomputed*, so the full logits
  tensor never exists in the residual set either.

Gradients match the naive loss exactly (same math, same reduction
order up to fp associativity); ``tests/test_gpt.py`` asserts equivalence.

Tensor-parallel note: under a vocab-sharded table (``gpt_layout`` puts
``model`` on wte dim 0) GSPMD partitions this head cleanly — verified on
an 8-way model mesh that the compiled fwd+bwd HLO contains ZERO
all-gathers, only per-chunk ``(C,)``-sized all-reduces for the logsumexp
and target-gather combines.  No hand-written vocab-parallel (shard_map)
head is needed; see also ``ops/fused_xent.py`` for the single-shard
Pallas fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

#: Tokens per scan chunk.  4096 keeps the transient logits tile at
#: 4096 x V fp32 (~0.8 GB for GPT-2's vocab) — large enough for full MXU
#: tiles, small enough to never pressure HBM.
DEFAULT_CHUNK_TOKENS = 4096


def chunked_argmax(
    hidden: jax.Array,   # (B, S, D) final hidden states
    wte: jax.Array,      # (V, D) tied table
    *,
    chunk_tokens: int = DEFAULT_CHUNK_TOKENS,
    compute_dtype: jnp.dtype | None = None,
) -> jax.Array:
    """Greedy token ids from the tied head WITHOUT full logits.

    The eval-side sibling of :func:`chunked_softmax_xent`: argmax needs
    the whole vocab row per token but not the whole (B, S, V) tensor —
    streaming (C, V) tiles through a scan keeps eval's peak memory at the
    training step's level (a sidecar evaluator must never OOM where the
    trainer fits).  Returns int32 (B, S).
    """
    b, s, d = hidden.shape
    n = b * s
    x = hidden.reshape(n, d)
    op_dtype = compute_dtype or jnp.result_type(hidden, wte)
    wte_t = wte.T.astype(op_dtype)

    c = min(chunk_tokens, n)
    n_chunks = -(-n // c)
    pad = n_chunks * c - n
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))

    def body(_, x_c):
        logits = jnp.matmul(
            x_c.astype(op_dtype), wte_t,
            preferred_element_type=jnp.float32,
        )
        return None, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    _, ids = lax.scan(body, None, x.reshape(n_chunks, c, d))
    return ids.reshape(n_chunks * c)[:n].reshape(b, s)


def tied_head_logits(
    x: jax.Array,    # (..., D) hidden states (fp32 post-ln_f)
    wte: jax.Array,  # (V, D) tied embedding table
    compute_dtype: jnp.dtype | None = None,
) -> jax.Array:
    """Full logits for a tied-embedding head, fp32 output.

    THE dtype recipe for every vocab matmul in the framework — operands in
    ``compute_dtype`` (bf16 = full MXU rate; an fp32 x fp32 vocab matmul
    runs at a fraction of it), fp32 accumulation via
    ``preferred_element_type``.  :func:`chunked_softmax_xent` uses the
    identical path per chunk, so the dense and chunked heads agree; model
    files must call this rather than hand-rolling the matmul."""
    dt = compute_dtype or jnp.result_type(x, wte)
    return jnp.matmul(
        x.astype(dt), wte.T.astype(dt),
        preferred_element_type=jnp.float32,
    )


def chunked_softmax_xent(
    hidden: jax.Array,   # (B, S, D) final hidden states (post-ln_f)
    wte: jax.Array,      # (V, D) tied embedding / output head
    targets: jax.Array,  # (B, S) int labels
    mask: jax.Array | None = None,  # (B, S) 1 = count this position
    *,
    chunk_tokens: int = DEFAULT_CHUNK_TOKENS,
    compute_dtype: jnp.dtype | None = None,
    logits_dtype: jnp.dtype | None = None,
) -> jax.Array:
    """Mean masked next-token NLL without materializing full logits.

    Returns the scalar mean of ``logsumexp(h @ wte.T) - logit[target]``
    over unmasked positions.  ``targets`` outside ``[0, V)`` (e.g. a
    -100-style ignore label a caller forgot to mask) contribute ZERO
    weight — matching optax's integer-label xent — rather than being
    silently attributed to a clipped token id.

    ``logits_dtype=bfloat16`` materializes each chunk's ``(C, V)`` logits
    tile in bf16 (the cast fuses into the matmul epilogue), HALVING the
    head's HBM traffic — the dominant cost of the chunked head on TPU.
    Reductions still run fp32 (logsumexp upcasts on read).  Logit
    magnitudes are O(10), so bf16's ~3 significant digits cost ~1e-2 in
    the per-token NLL — the standard LM-training trade (most stacks emit
    bf16 logits); keep the fp32 default where exact parity matters.
    """
    b, s, d = hidden.shape
    n = b * s
    v = wte.shape[0]
    x = hidden.reshape(n, d)
    t_raw = targets.reshape(n)
    t = jnp.clip(t_raw, 0, v - 1)
    w = (
        mask.reshape(n).astype(jnp.float32)
        if mask is not None
        else jnp.ones((n,), jnp.float32)
    )
    w = w * ((t_raw >= 0) & (t_raw < v)).astype(jnp.float32)

    c = min(chunk_tokens, n)
    n_chunks = -(-n // c)
    pad = n_chunks * c - n
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        t = jnp.pad(t, (0, pad))
        w = jnp.pad(w, (0, pad))  # padded rows weigh 0

    # compute_dtype picks the MATMUL operand dtype for the (C, V) logits
    # tile; accumulation/reductions stay fp32 via preferred_element_type.
    # Pass the model's compute dtype (bf16) here: hidden arrives fp32 from
    # the fp32 ln_f, and an fp32 x fp32 matmul runs at a fraction of the
    # MXU's bf16 rate — on the v5e this head was the single largest cost
    # of the GPT-2-small step (the 50k-vocab matmul is ~30% of model
    # FLOPs).  None = the operands' own dtypes (exact-parity tests).
    op_dtype = compute_dtype or jnp.result_type(hidden, wte)
    out_dtype = logits_dtype or jnp.float32
    wte_t = wte.T.astype(op_dtype)

    def body(carry, inp):
        nll_sum, w_sum = carry
        x_c, t_c, w_c = inp
        logits = jnp.matmul(
            x_c.astype(op_dtype), wte_t,
            preferred_element_type=jnp.float32,
        ).astype(out_dtype)  # (C, V); fp32 accumulate, out_dtype store
        # Upcasts fuse into the reductions (no fp32 copy of the tile).
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[:, None], axis=1)[:, 0]
        nll = lse - tgt.astype(jnp.float32)
        return (nll_sum + jnp.sum(nll * w_c), w_sum + jnp.sum(w_c)), None

    xs = (
        x.reshape(n_chunks, c, d),
        t.reshape(n_chunks, c),
        w.reshape(n_chunks, c),
    )
    (nll_sum, w_sum), _ = lax.scan(
        jax.checkpoint(body), (jnp.zeros((), jnp.float32),) * 2, xs
    )
    return nll_sum / jnp.maximum(w_sum, 1.0)
