"""Pallas fused linear + softmax cross-entropy: the LM-head hot op.

:func:`ops.xent.chunked_softmax_xent` already keeps the full ``(B*S, V)``
logits out of the *residual* set, but every chunk's ``(C, V)`` logits tile
still round-trips HBM — materialized by the matmul, re-read by logsumexp,
re-materialized and re-read twice more in the checkpointed backward.  On
the v5e that is ~20 GB of HBM traffic per GPT-2-small step (B=16, S=1024:
the single largest non-matmul cost of the step — see docs/LM_PERF.md).

This module fuses the head end-to-end in Pallas so logits live only in
VMEM, tile by tile, and HBM sees just ``x``, ``wte``, and the O(N)
outputs (~4.2 GB/step for the same shapes at the on-chip-validated tile
sizes — 4.1x less than chunked; see ``estimate_hbm_bytes``):

- **forward** — grid (vocab-blocks OUTER, token-blocks inner): the weight
  tile is fetched once per vocab block and stays in VMEM for the whole
  token sweep; per-token online-logsumexp state (m, s) and the gathered
  target logit accumulate in VMEM scratch sized (n_token_blocks, block_n)
  across the outer sweeps.  Logits are computed TRANSPOSED — (block_v,
  block_n), vocab on sublanes, tokens on lanes — so every per-token
  reduction lands as a lane-major (1, block_n) row that indexes straight
  into the scratch with no relayout.
- **backward** — two kernels, mirroring the flash-attention dq/dkv split
  (`ops/flash_attention.py`): ``dx`` with token-blocks outer (dx tile
  accumulates in scratch over the vocab sweep), ``dwte`` with vocab-blocks
  outer (accumulating directly into its output block, which is revisited
  consecutively across the inner token sweep — the only revisit pattern
  Pallas TPU guarantees stays resident in VMEM).  Both recompute the
  logits tile from the saved (x, wte, lse): softmax probabilities are
  ``exp(logit - lse)``, no renormalization pass needed.

Semantics match :func:`ops.xent.chunked_softmax_xent` exactly (same
masked-mean reduction; out-of-range targets contribute zero weight);
``tests/test_fused_xent.py`` asserts value and gradient equivalence in
interpret mode.

Reference anchor: the reference stack has no such op — Keras
``SparseCategoricalCrossentropy`` materializes full logits (SURVEY.md
§2.3 Keras trainer row).  This is the TPU-first "Pallas kernels for the
hot ops" obligation (SURVEY.md §2.4 native-code notes) applied to the
LM head.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF

def _env_int(name: str, default: int) -> int:
    """Bench/debug override for a tile size (read once at import).

    The defaults below are VMEM-budget reasoning, not measurements; the
    ``DTFT_XENT_*`` envs let an on-chip sweep retune them without code
    edits mid-tunnel-window."""
    import os

    return int(os.environ.get(name, default))


#: Default tile sizes.  The binding constraint is Mosaic's 16 MB scoped-
#: VMEM stack: the (block_v, block_n) fp32 logits tile plus its
#: elementwise temporaries (iota/mask/exp) dominate, alongside the
#: double-buffered operand blocks.  Measured on the v5e 2026-08-01:
#: block_v=2048 x block_n=512 compiled to a 16.71 MB stack — 724 KB OVER
#: the limit; 1024 x 512 fits with ~2x headroom.  The trade is NOT free:
#: the w table streams once per token chunk regardless of block_v, but x
#: restreams once PER VOCAB BLOCK (vocab-outer sweep), so halving block_v
#: doubles the fwd/dw x-restream — estimate_hbm_bytes puts the move at
#: 2.92 -> 4.18 GB/step at the headline config, ~1.5 ms @ 819 GB/s,
#: against a kernel that otherwise does not compile at all.
BLOCK_TOKENS = _env_int("DTFT_XENT_BLOCK_TOKENS", 512)
BLOCK_VOCAB = _env_int("DTFT_XENT_BLOCK_VOCAB", 1024)
#: dx backward uses a bigger token tile: its dominant HBM cost is the full
#: weight-table re-read per token block, so fewer/bigger token sweeps win.
#: Its vocab tile is the smallest: the dx kernel carries the most live
#: fp32 temporaries (p, dlog, the fp32-cast weight tile, the fp32 dx
#: accumulator), so it hits the same 16 MB stack wall soonest.
#: On-chip sweep 2026-08-01 (bs16 seq1024 headline): token tile 2048
#: first measured 118.7k tok/s vs 116.8k at 1024, but (a) 2048's ~18 MB
#: Mosaic stack only fits in SOME surrounding programs — it compiled
#: inside the seq-1024 train step yet fails in isolation AND inside the
#: seq-8192 step with the SAME padded (16384, 768) operands (scoped-
#: stack accounting is context-dependent), and (b) a re-measure of the
#: 1024 default landed 118.6k: the apparent tile win was mostly run
#: variance.  1024 is robust everywhere and costs nothing measurable.
BLOCK_TOKENS_DX = _env_int("DTFT_XENT_BLOCK_TOKENS_DX", 1024)
BLOCK_VOCAB_DX = _env_int("DTFT_XENT_BLOCK_VOCAB_DX", 512)


def _blocks_for_dim(d: int) -> tuple[int, int, int, int]:
    """(block_tokens, block_vocab, block_tokens_dx, block_vocab_dx) for
    hidden size ``d``.

    Every kernel tile is (block, d)- or (block_v, block_n)-shaped, so the
    VMEM stack scales with d: the d<=768 defaults above (on-chip-tuned at
    GPT-2-small) VMEM-OOM at d=1024 (GPT-2-medium), where the measured
    fitting set is 512 across the board (46.0k tok/s, MFU 0.566 —
    still ahead of the chunked_bf16 head's 44.1k).  Env overrides win
    unconditionally at every d."""
    if d <= 768:
        # The module constants above ARE the d<=768 defaults (env already
        # applied at import) — single source of truth for the tuned set.
        defaults = (BLOCK_TOKENS, BLOCK_VOCAB, BLOCK_TOKENS_DX,
                    BLOCK_VOCAB_DX)
    else:
        defaults = (512, 512, 512, 512)
    names = ("DTFT_XENT_BLOCK_TOKENS", "DTFT_XENT_BLOCK_VOCAB",
             "DTFT_XENT_BLOCK_TOKENS_DX", "DTFT_XENT_BLOCK_VOCAB_DX")
    return tuple(_env_int(n, v) for n, v in zip(names, defaults))


def _transposed_logits(w_ref, x_ref):
    """(block_v, block_n) fp32 logits tile: rows = vocab, cols = tokens."""
    return jax.lax.dot_general(
        w_ref[...], x_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


#: Sublane depth of the forward scratch accumulators.  The per-token-block
#: state lives in (n_token_blocks, _SUB, block_n) scratch: the dynamically
#: indexed dimension is the UNTILED leading one (tiling applies to the
#: trailing (_SUB, block_n) = (8, lanes) pair), so ``pl.ds(i, 1)`` never
#: asks Mosaic for an unaligned dynamic sublane slice — which interpret
#: mode would happily accept and the real TPU lowering may not.
_SUB = 8


def _fwd_kernel(x_ref, w_ref, t_ref, lse_ref, tgt_ref, m_sc, s_sc, g_sc,
                *, block_v, v_true):
    j = pl.program_id(0)   # vocab block (outer)
    i = pl.program_id(1)   # token block (inner)
    n_j = pl.num_programs(0)

    def read(sc):          # (1, block_n) row of token-block i's state
        return sc[pl.ds(i, 1)][0, :1, :].reshape(1, -1)

    def write(sc, val):    # broadcast the (1, block_n) row over _SUB
        sc[pl.ds(i, 1)] = jnp.broadcast_to(val, (1, _SUB, val.shape[-1]))

    @pl.when(j == 0)
    def _init():
        write(m_sc, jnp.full((1, m_sc.shape[-1]), NEG_INF, m_sc.dtype))
        write(s_sc, jnp.zeros((1, s_sc.shape[-1]), s_sc.dtype))
        write(g_sc, jnp.zeros((1, g_sc.shape[-1]), g_sc.dtype))

    logits = _transposed_logits(w_ref, x_ref)  # (block_v, block_n)
    row = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
    logits = jnp.where(row < v_true, logits, NEG_INF)

    t = t_ref[...]                      # (1, block_n) int32
    match = row == t                    # broadcasts over sublanes
    # Out-of-range targets (ignore labels) match no row of any block: the
    # gathered logit stays 0 and the caller's weight for the row is 0.
    g_part = jnp.sum(jnp.where(match, logits, 0.0), axis=0, keepdims=True)

    m_prev = read(m_sc)                 # (1, block_n)
    s_prev = read(s_sc)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=0, keepdims=True))
    s_new = s_prev * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(logits - m_new), axis=0, keepdims=True
    )
    write(m_sc, m_new)
    write(s_sc, s_new)
    write(g_sc, read(g_sc) + g_part)

    @pl.when(j == n_j - 1)
    def _finalize():
        lse_ref[...] = read(m_sc) + jnp.log(read(s_sc))
        tgt_ref[...] = read(g_sc)


def _bwd_dx_kernel(x_ref, w_ref, t_ref, lse_ref, c_ref, dx_ref, acc_sc,
                   *, block_v, v_true):
    i = pl.program_id(0)   # token block (outer)
    j = pl.program_id(1)   # vocab block (inner)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    logits = _transposed_logits(w_ref, x_ref)
    row = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
    logits = jnp.where(row < v_true, logits, NEG_INF)
    p = jnp.exp(logits - lse_ref[...])          # (block_v, block_n)
    match = row == t_ref[...]
    dlog = c_ref[...] * (p - match.astype(jnp.float32))
    # dx_i += sum_j dlogits_ji * wte_j : contract the vocab sublanes.
    # dlog drops to the operand compute dtype (bf16 in training) so the
    # matmul runs native MXU passes instead of the ~4x-slower fp32
    # emulation — profiled at 46% MXU with the old fp32 operands
    # (docs/LM_PERF.md round-4 anatomy); accumulation stays fp32.  This
    # matches standard mixed-precision (dlogits are bf16 wherever logits
    # are), and bf16's fp32-sized exponent keeps the tiny c*(p-match)
    # magnitudes exact in scale.  fp32 operands are left untouched.
    acc_sc[...] += jax.lax.dot_general(
        dlog.astype(w_ref.dtype), w_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_j - 1)
    def _finalize():
        dx_ref[...] = acc_sc[...]


def _bwd_dw_kernel(x_ref, w_ref, t_ref, lse_ref, c_ref, dw_ref,
                   *, block_v, v_true):
    j = pl.program_id(0)   # vocab block (outer)
    i = pl.program_id(1)   # token block (inner)
    n_i = pl.num_programs(1)

    logits = _transposed_logits(w_ref, x_ref)
    row = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
    logits = jnp.where(row < v_true, logits, NEG_INF)
    p = jnp.exp(logits - lse_ref[...])
    match = row == t_ref[...]
    dlog = c_ref[...] * (p - match.astype(jnp.float32))
    # dwte_j += sum_i dlogits_ji * x_i : contract the token lanes.  The
    # output block's index depends only on j (outer), so the accumulation
    # target stays resident across the whole inner sweep.  dlog in the
    # compute dtype for the same native-MXU reason as the dx kernel.
    part = jax.lax.dot_general(
        dlog.astype(x_ref.dtype), x_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == 0)
    def _first():
        dw_ref[...] = part

    @pl.when(i != 0)
    def _rest():
        dw_ref[...] = dw_ref[...] + part


def _pad_to(x, mult, axis):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


#: VMEM budget for the forward's per-token-block scratch accumulators.
#: The three (n_i, _SUB, block_n) fp32 buffers cost 96 B per token, i.e.
#: O(N) — unbounded, a 64x8192-token long-context head would ask for
#: ~48 MB of VMEM and fail to compile.  Token super-chunks of at most
#: ``budget // (3*_SUB*block_n*4)`` blocks keep scratch bounded; each
#: extra chunk re-reads the weight table once (~77 MB bf16 at GPT-2
#: vocab), which at the default 4 MiB budget (~43k tokens/chunk) stays
#: far below the ~20 GB logits round-trip the kernel exists to avoid.
#: Override: ``DTFT_XENT_FWD_SCRATCH_BYTES`` (read per call, testable).
FWD_SCRATCH_BUDGET_BYTES = 4 * 2**20


def _max_fwd_token_blocks(block_n: int) -> int:
    import os

    budget = int(
        os.environ.get("DTFT_XENT_FWD_SCRATCH_BYTES", FWD_SCRATCH_BUDGET_BYTES)
    )
    return max(1, budget // (3 * _SUB * block_n * 4))


def _fused_fwd_arrays(x, w, t, *, block_n, block_v, v_true, interpret):
    """Run the forward kernel on padded 2-D operands.

    x (N, D) compute-dtype, w (Vp, D) compute-dtype, t (N,) int32; N, Vp
    already padded to the block sizes.  Returns (lse, tgt) fp32 (N,).

    Token super-chunking: the per-token-block online-softmax state lives
    in VMEM scratch, so one pallas_call is bounded to
    :func:`_max_fwd_token_blocks` token blocks; larger N runs as a host
    loop of identical calls (at most two distinct shapes, so at most two
    kernel compiles) whose outputs concatenate.
    """
    n, d = x.shape
    vp = w.shape[0]
    n_j = vp // block_v
    mem = pl.ANY if interpret else pltpu.VMEM

    def one_call(xc, tc):
        n_c = xc.shape[0]
        n_i = n_c // block_n
        # Row operands/outputs are laid out (1, N) with block (1, block_n):
        # a (1, block_n) block over an (n_i, block_n) array would put a
        # sublane block of 1 over an array dim > 1, which the real Mosaic
        # lowering rejects ("block shape ... divisible by 8 and 128") even
        # though interpret mode accepts it — found on-chip 2026-08-01.
        lse, tgt = pl.pallas_call(
            functools.partial(_fwd_kernel, block_v=block_v, v_true=v_true),
            grid=(n_j, n_i),
            in_specs=[
                pl.BlockSpec((block_n, d), lambda j, i: (i, 0),
                             memory_space=mem),
                pl.BlockSpec((block_v, d), lambda j, i: (j, 0),
                             memory_space=mem),
                pl.BlockSpec((1, block_n), lambda j, i: (0, i),
                             memory_space=mem),
            ],
            out_specs=[
                pl.BlockSpec((1, block_n), lambda j, i: (0, i),
                             memory_space=mem),
                pl.BlockSpec((1, block_n), lambda j, i: (0, i),
                             memory_space=mem),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, n_c), jnp.float32),
                jax.ShapeDtypeStruct((1, n_c), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((n_i, _SUB, block_n), jnp.float32)] * 3,
            interpret=interpret,
        )(xc, w, tc.reshape(1, n_c))
        return lse.reshape(n_c), tgt.reshape(n_c)

    chunk_tokens = _max_fwd_token_blocks(block_n) * block_n
    if n <= chunk_tokens:
        return one_call(x, t)
    lses, tgts = [], []
    for s in range(0, n, chunk_tokens):
        lse_c, tgt_c = one_call(x[s:s + chunk_tokens], t[s:s + chunk_tokens])
        lses.append(lse_c)
        tgts.append(tgt_c)
    return jnp.concatenate(lses), jnp.concatenate(tgts)


def _fused_bwd_arrays(x, w, t, lse, c, *, block_n_dx, block_v_dx,
                      block_n_dw, block_v_dw, v_true, interpret):
    """dx (N, D) and dw (Vp, D), both fp32, from padded operands."""
    n, d = x.shape
    vp = w.shape[0]
    mem = pl.ANY if interpret else pltpu.VMEM

    def common_specs(block_n, block_v, idx_x, idx_w, idx_row):
        return [
            pl.BlockSpec((block_n, d), idx_x, memory_space=mem),
            pl.BlockSpec((block_v, d), idx_w, memory_space=mem),
            pl.BlockSpec((1, block_n), idx_row, memory_space=mem),
            pl.BlockSpec((1, block_n), idx_row, memory_space=mem),
            pl.BlockSpec((1, block_n), idx_row, memory_space=mem),
        ]

    # Row operands ride as (1, N) for the same Mosaic sublane-tiling
    # reason as the forward (see one_call above).
    n_i, n_j = n // block_n_dx, vp // block_v_dx
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, block_v=block_v_dx, v_true=v_true),
        grid=(n_i, n_j),
        in_specs=common_specs(
            block_n_dx, block_v_dx,
            lambda i, j: (i, 0), lambda i, j: (j, 0), lambda i, j: (0, i),
        ),
        out_specs=pl.BlockSpec((block_n_dx, d), lambda i, j: (i, 0),
                               memory_space=mem),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_n_dx, d), jnp.float32)],
        interpret=interpret,
    )(x, w, t.reshape(1, n), lse.reshape(1, n), c.reshape(1, n))

    n_i, n_j = n // block_n_dw, vp // block_v_dw
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, block_v=block_v_dw, v_true=v_true),
        grid=(n_j, n_i),
        in_specs=common_specs(
            block_n_dw, block_v_dw,
            lambda j, i: (i, 0), lambda j, i: (j, 0), lambda j, i: (0, i),
        ),
        out_specs=pl.BlockSpec((block_v_dw, d), lambda j, i: (j, 0),
                               memory_space=mem),
        out_shape=jax.ShapeDtypeStruct((vp, d), jnp.float32),
        interpret=interpret,
    )(x, w, t.reshape(1, n), lse.reshape(1, n), c.reshape(1, n))
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused(hidden2d, wte, t, w_row, compute_dtype, block_sizes, interpret):
    out, _ = _fused_fwd(hidden2d, wte, t, w_row, compute_dtype, block_sizes,
                        interpret)
    return out


def _fused_fwd(hidden2d, wte, t, w_row, compute_dtype, block_sizes,
               interpret):
    block_n, block_v = block_sizes[0], block_sizes[1]
    n, _ = hidden2d.shape
    v = wte.shape[0]
    xc = _pad_to(hidden2d.astype(compute_dtype), block_n, 0)
    wc = _pad_to(wte.astype(compute_dtype), block_v, 0)
    tp = _pad_to(t, block_n, 0)
    lse, tgt = _fused_fwd_arrays(
        xc, wc, tp, block_n=block_n, block_v=block_v, v_true=v,
        interpret=interpret,
    )
    lse, tgt = lse[:n], tgt[:n]
    w_sum = jnp.maximum(jnp.sum(w_row), 1.0)
    loss = jnp.sum((lse - tgt) * w_row) / w_sum
    return loss, (hidden2d, wte, t, w_row, lse, w_sum)


def _fused_bwd(compute_dtype, block_sizes, interpret, res, g):
    hidden2d, wte, t, w_row, lse, w_sum = res
    block_n_dx, block_v_dx = block_sizes[2], block_sizes[3]
    # dw uses the forward's tiling (vocab outer); dx its own.
    block_n_dw, block_v_dw = block_sizes[0], block_sizes[1]
    block_n_pad = math.lcm(block_n_dx, block_n_dw)
    n, _ = hidden2d.shape
    v = wte.shape[0]
    xc = _pad_to(hidden2d.astype(compute_dtype), block_n_pad, 0)
    wc = _pad_to(wte.astype(compute_dtype),
                 math.lcm(block_v_dx, block_v_dw), 0)
    tp = _pad_to(t, block_n_pad, 0)
    c = g * w_row / w_sum                       # (N,) fp32
    cp = _pad_to(c.astype(jnp.float32), block_n_pad, 0)
    lsep = _pad_to(lse, block_n_pad, 0)
    dx, dw = _fused_bwd_arrays(
        xc, wc, tp, lsep, cp,
        block_n_dx=block_n_dx, block_v_dx=block_v_dx,
        block_n_dw=block_n_dw, block_v_dw=block_v_dw,
        v_true=v, interpret=interpret,
    )
    dx = dx[:n].astype(hidden2d.dtype)
    dw = dw[:v].astype(wte.dtype)
    # d(loss)/d(w_row) = g * (lse - tgt - loss)/w_sum; training never
    # differentiates wrt the mask, so skip the extra tgt residual and
    # return a zero cotangent of the right shape.
    return dx, dw, None, jnp.zeros_like(w_row)


_fused.defvjp(_fused_fwd, _fused_bwd)


def _walk_fetches(grid, index_map) -> int:
    """Block (re)fetches of one operand across a row-major grid walk.

    Pallas TPU keeps exactly the current block of each operand resident:
    consecutive grid steps with the SAME block index reuse it (no HBM
    traffic); an index change is one block fetch.  Counting index changes
    over the kernel's actual grid order therefore gives the kernel's HBM
    read traffic in blocks — the same model the module docstring's
    "~4.2 GB/step" claim rests on, now computed instead of asserted.
    """
    import itertools

    fetches = 0
    prev = None
    for idx in itertools.product(*[range(g) for g in grid]):
        bi = index_map(*idx)
        if bi != prev:
            fetches += 1
            prev = bi
    return fetches


def estimate_hbm_bytes(
    n_tokens: int,
    d: int,
    v: int,
    *,
    block_tokens: int | None = None,
    block_vocab: int | None = None,
    block_tokens_dx: int | None = None,
    block_vocab_dx: int | None = None,
    compute_bytes: int = 2,  # bf16 operands
) -> dict:
    """Analytic HBM traffic of one fused fwd+bwd head pass, in bytes.

    Derived by replaying each kernel's (grid, index_map) pairs — the same
    shapes handed to ``pl.pallas_call`` — through :func:`_walk_fetches`,
    so the number moves if the kernel's tiling or loop order changes.
    Outputs are counted symmetrically (an output-block index change =
    one block flush).  Token super-chunking (the VMEM scratch budget,
    :func:`_max_fwd_token_blocks`) is modeled: every extra forward chunk
    re-reads the weight table once.

    Returns a dict with per-kernel and total byte counts plus
    ``chunked_head_bytes``, the corresponding traffic of the chunked
    (logits-materializing) head for the same shapes: logits tiles are
    written+read in fwd, and the checkpointed bwd recomputes (write) and
    reads them twice more (softmax grad + matmul operands) → 5 passes
    over an (N, V) fp32 array, plus the same x/w streams the fused path
    pays.  ``tests/test_fused_xent.py`` pins the headline-config ratio.

    Block defaults resolve through :func:`_blocks_for_dim` — the SAME
    selection ``fused_softmax_xent`` makes — so the estimate models the
    tiling the kernel actually runs at this ``d`` (the d=768 defaults
    would describe a nonexistent, VMEM-OOM config at d=1024).
    """
    _dt, _dv, _dtx, _dvx = _blocks_for_dim(d)
    block_tokens = block_tokens or _dt
    block_vocab = block_vocab or _dv
    block_tokens_dx = block_tokens_dx or _dtx
    block_vocab_dx = block_vocab_dx or _dvx

    def pad(x, m):
        return x + (-x) % m

    # Padding mirrors the real call path exactly: forward pads to ITS
    # block sizes only (`_fused_fwd` -> `_pad_to(..., block_n)`), while
    # backward pads to the lcm of the dx and dw tilings (`_fused_bwd`).
    n_fwd = pad(n_tokens, block_tokens)
    vp_fwd = pad(v, block_vocab)
    n = pad(n_tokens, math.lcm(block_tokens_dx, block_tokens))
    vp = pad(v, math.lcm(block_vocab_dx, block_vocab))
    row_b = 4  # fp32 (1, block_n) rows: t/lse/tgt/c
    out = {}

    # forward (per token super-chunk): grid (n_j, n_i), j outer
    chunk_tokens = _max_fwd_token_blocks(block_tokens) * block_tokens
    fwd = 0
    for s in range(0, n_fwd, chunk_tokens):
        n_c = min(chunk_tokens, n_fwd - s)
        n_i, n_j = n_c // block_tokens, vp_fwd // block_vocab
        grid = (n_j, n_i)
        x_f = _walk_fetches(grid, lambda j, i: (i, 0))
        w_f = _walk_fetches(grid, lambda j, i: (j, 0))
        t_f = _walk_fetches(grid, lambda j, i: (0, i))
        o_f = _walk_fetches(grid, lambda j, i: (0, i))  # lse and tgt
        fwd += (
            x_f * block_tokens * d * compute_bytes
            + w_f * block_vocab * d * compute_bytes
            + t_f * block_tokens * row_b
            + 2 * o_f * block_tokens * row_b
        )
    out["fwd_bytes"] = fwd

    # backward dx: grid (n_i, n_j), i outer
    n_i, n_j = n // block_tokens_dx, vp // block_vocab_dx
    grid = (n_i, n_j)
    out["bwd_dx_bytes"] = (
        _walk_fetches(grid, lambda i, j: (i, 0)) * block_tokens_dx * d
        * compute_bytes
        + _walk_fetches(grid, lambda i, j: (j, 0)) * block_vocab_dx * d
        * compute_bytes
        + 3 * _walk_fetches(grid, lambda i, j: (0, i)) * block_tokens_dx
        * row_b                                        # t, lse, c rows
        + _walk_fetches(grid, lambda i, j: (i, 0)) * block_tokens_dx * d * 4
    )                                                  # dx out, fp32

    # backward dw: grid (n_j, n_i), j outer (forward's tiling)
    n_i, n_j = n // block_tokens, vp // block_vocab
    grid = (n_j, n_i)
    out["bwd_dw_bytes"] = (
        _walk_fetches(grid, lambda j, i: (i, 0)) * block_tokens * d
        * compute_bytes
        + _walk_fetches(grid, lambda j, i: (j, 0)) * block_vocab * d
        * compute_bytes
        + 3 * _walk_fetches(grid, lambda j, i: (0, i)) * block_tokens * row_b
        + _walk_fetches(grid, lambda j, i: (j, 0)) * block_vocab * d * 4
    )                                                  # dw out, fp32

    out["total_bytes"] = fwd + out["bwd_dx_bytes"] + out["bwd_dw_bytes"]
    # chunked head: 5 full passes over fp32 logits + one x/w stream each
    # for fwd, recompute, and the two bwd matmuls (dx, dw).
    out["chunked_head_bytes"] = (
        5 * n * vp * 4
        + 4 * (n * d + vp * d) * compute_bytes
    )
    return out


def fused_softmax_xent(
    hidden: jax.Array,   # (B, S, D) or (N, D) final hidden states
    wte: jax.Array,      # (V, D) tied embedding / output head
    targets: jax.Array,  # (B, S) / (N,) int labels
    mask: jax.Array | None = None,  # same shape as targets; 1 = count
    *,
    compute_dtype: jnp.dtype | None = None,
    block_tokens: int | None = None,
    block_vocab: int | None = None,
    block_tokens_dx: int | None = None,
    block_vocab_dx: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Mean masked next-token NLL; logits never leave VMEM.

    Drop-in for :func:`ops.xent.chunked_softmax_xent` — same reduction,
    same out-of-range-target semantics, Pallas execution.  ``interpret``
    defaults to auto (interpreter off-TPU so CPU tests and the virtual
    mesh work).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    v = wte.shape[0]
    d = hidden.shape[-1]
    x2 = hidden.reshape(-1, d)
    n = x2.shape[0]
    t = targets.reshape(n).astype(jnp.int32)
    w_row = (
        mask.reshape(n).astype(jnp.float32)
        if mask is not None
        else jnp.ones((n,), jnp.float32)
    )
    w_row = w_row * ((t >= 0) & (t < v)).astype(jnp.float32)
    op_dtype = compute_dtype or jnp.result_type(hidden, wte)
    dt, dv, dtx, dvx = _blocks_for_dim(d)
    blocks = (block_tokens or dt, block_vocab or dv,
              block_tokens_dx or dtx, block_vocab_dx or dvx)
    return _fused(x2, wte, t, w_row, op_dtype, blocks, interpret)
