"""Flash-attention kernel dispatch (Pallas TPU).

Placeholder gate for round-1 build order (SURVEY.md §7 step 9): the Pallas
kernel lands behind :func:`supported`; until then everything routes to the
XLA path, which XLA already fuses reasonably on TPU.
"""

from __future__ import annotations

import jax


def supported(q, k, v, *, mask=None) -> bool:
    return False


def flash_attention(q, k, v, *, mask=None, causal=False) -> jax.Array:
    from .attention import xla_attention  # noqa: PLC0415

    return xla_attention(q, k, v, mask=mask, causal=causal)
