"""Pallas TPU flash attention.

The compiled-kernel replacement for the reference stack's fused-attention
needs (SURVEY.md §2.4 native-code obligations): attention scores never hit
HBM — each q-block computes its (block_q, S) score tile in VMEM, does the
softmax in fp32, and writes only the (block_q, D) output plus the
log-sum-exp rows needed by the backward pass.

Forward: one Pallas kernel, grid (batch, heads, q_blocks); K/V live in VMEM
per (batch, head) — at BERT/long-context head dims (64..128) a full K/V head
fits VMEM comfortably up to ~8k tokens, which is also the per-device shard
regime ring attention (``parallel/ring_attention.py``) operates in.

Backward: two Pallas kernels (the standard TPU flash-attention split) —
a dq kernel sweeping k-blocks innermost and a dk/dv kernel sweeping
q-blocks innermost, both recomputing the p-tile in VMEM from the saved
LSE so no (S, S) score tile ever reaches HBM.  An XLA blockwise-recompute
fallback (`_flash_backward_xla`) is kept as the golden reference; select
with ``BACKWARD_IMPL``.

Layout: BSHD (batch, seq, heads, head_dim) to match ``ops.attention``.
"""

from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9

#: Block tiling.  Retuned on the v5e 2026-08-01 (tools/sweep_flash_blocks.py,
#: artifacts BENCH_RESULTS/flashsweep_20260801_*.json): 1024x1024 q/k blocks
#: win at EVERY swept length — fwd+bwd vs the old 128x512 default:
#: 9.11 vs 12.57 ms at seq 1024 (B16 H12 D64), 17.1 vs 28.3 ms at 4k,
#: 25.8 vs 49.0 ms at 8k.  The kernel is VPU/softmax-bound, not matmul-
#: bound, so fewer+bigger grid steps amortize per-step scalar/DMA overhead;
#: (1024, 1024) fp32 score tiles (+temps) still fit Mosaic's 16 MB stack
#: (1024x2048 does not — compile-checked on chip).
DEFAULT_BLOCK_Q = 1024


def _env_block(name: str) -> int | None:
    """On-chip sweep override for a block size (read per call so one
    process can A/B several tilings; see tools/sweep_flash_blocks.py)."""
    import os

    v = os.environ.get(name)
    if not v:
        return None
    try:
        n = int(v)
    except ValueError as e:
        raise ValueError(f"{name}={v!r}: expected a positive integer") from e
    if n <= 0:
        raise ValueError(f"{name}={v!r}: expected a positive integer")
    return n


def _env_divisible(name: str, seq_len: int) -> int | None:
    """The env-override block when set AND it divides the sequence; a
    non-dividing override warns (``warnings.warn`` — NOT a bare print:
    bench JSON consumers parse this process's stdout/stderr) and falls
    through to the next resolution tier."""
    o = _env_block(name)
    if not o:
        return None
    if seq_len % o == 0:
        return o
    warnings.warn(
        f"flash_attention: {name}={o} does not divide seq {seq_len}; "
        "using the default chain",
        stacklevel=3,
    )
    return None


def _default_chain(seq_len: int, first: int) -> int | None:
    for b in (first, 512, 256, 128, 64, 32, 16, 8):
        if seq_len % b == 0:
            return b
    return None


def _pick_block_q(seq_len: int) -> int | None:
    o = _env_divisible("DTFT_FLASH_BLOCK_Q", seq_len)
    return o or _default_chain(seq_len, DEFAULT_BLOCK_Q)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


#: Auto-dispatch threshold.  Re-measured on the real v5e 2026-08-01 after
#: the 1024x1024 block retune (tools/sweep_flash_blocks.py, artifact
#: flashsweep_20260801_023237.json, B=16 H=12 D=64 bf16 causal — the GPT
#: headline shapes): at seq 1024 the kernel now beats XLA's fused dense
#: attention 1.22x fwd / 1.60x fwd+full-bwd (6.46/9.11 ms vs 7.94/14.61),
#: where the OLD 128x512 tiling only managed 1.16x fwd+bwd — which is why
#: this threshold used to sit at 4096.  At 4k the win is 3.3x, at 8k the
#: dense path OOMs (attn_20260801_014350.json).  Below 1024 the dense
#: path keeps the job: score tensors are small enough that XLA's fusion
#: is competitive and the kernel's fixed overhead dominates — pending the
#: seq-512 probe (VERDICT r4 #5): the env seed lets the watcher A/B BERT
#: with the threshold at 512 (`DTF_MIN_SEQ_FOR_PALLAS=512 bench_bert.py`)
#: in the same window as the attn_512 kernel probe, so the decision and
#: its end-to-end consequence land together.  Mutable module global,
#: re-read at each trace (tests monkeypatch it).
MIN_SEQ_FOR_PALLAS = int(os.environ.get("DTF_MIN_SEQ_FOR_PALLAS", "1024"))


def _gqa_ok(qshape, kshape) -> bool:
    """Same (B, S, D) and q heads an integer multiple of kv heads."""
    return (
        qshape[0] == kshape[0] and qshape[1] == kshape[1]
        and qshape[3] == kshape[3] and kshape[2] > 0
        and qshape[2] % kshape[2] == 0
    )


def supported(q, k, v, *, mask=None, segment_ids=None) -> bool:
    """True when auto-dispatch should take the Pallas kernel for this call."""
    if q.ndim != 4 or k.shape != v.shape or not _gqa_ok(q.shape, k.shape):
        return False
    if not _on_tpu():
        return False
    seq = q.shape[1]
    if seq < MIN_SEQ_FOR_PALLAS or _pick_block_q(seq) is None:
        return False
    if q.dtype not in (jnp.bfloat16, jnp.float32):
        return False
    if segment_ids is not None and not _is_segment_ids(segment_ids, q.shape):
        return False
    return mask is None or _is_padding_mask(mask, q.shape)


def _is_padding_mask(mask, qshape) -> bool:
    """Accept (B, S) or its broadcast form (B, 1, 1, S)."""
    b, s = qshape[0], qshape[1]
    return tuple(mask.shape) in ((b, s), (b, 1, 1, s))


def _as_padding_mask(mask, qshape):
    if mask is None:
        return None
    b, s = qshape[0], qshape[1]
    return mask.reshape(b, s).astype(jnp.bool_)


def _is_segment_ids(segment_ids, qshape) -> bool:
    """(B, S) integer ids: tokens attend only within their own segment
    (packed-sequence / example-packing semantics, BERT-style pretraining)."""
    return (
        tuple(segment_ids.shape) == (qshape[0], qshape[1])
        and jnp.issubdtype(segment_ids.dtype, jnp.integer)
    )


# --- Forward kernel ---------------------------------------------------------


DEFAULT_BLOCK_K = 1024  # see the DEFAULT_BLOCK_Q sweep note


def _pick_block_k(seq_len: int) -> int | None:
    o = _env_divisible("DTFT_FLASH_BLOCK_K", seq_len)
    return o or _default_chain(seq_len, DEFAULT_BLOCK_K)


def _tuned_blocks(batch: int, heads: int, seq: int,
                  depth: int, dtype) -> tuple[int, int] | None:
    """Autotune-cache consult (ops/flash_tuning.py): the (block_q,
    block_k) a sweep or XPlane analysis recorded for this (shape, dtype,
    platform), or None.  Never raises — a broken cache must degrade to
    the default chain, not break the kernel."""
    try:
        from . import flash_tuning

        return flash_tuning.lookup(
            platform=jax.default_backend(),
            dtype=jnp.dtype(dtype).name,
            seq=seq, depth=depth, batch=batch, heads=heads,
        )
    except Exception:
        return None


def _resolve_blocks(batch: int, heads: int, seq: int, depth: int, dtype,
                    block_q: int | None,
                    block_k: int | None) -> tuple[int, int]:
    """The kernel's block tiling, resolved: explicit argument > env
    override > autotune cache > retuned default chain.  Callers
    validated divisibility of explicit args; env/cache tiers self-skip
    when they don't divide."""
    if block_q is not None and block_k is not None:
        return block_q, block_k
    env_q = _env_divisible("DTFT_FLASH_BLOCK_Q", seq)
    env_k = _env_divisible("DTFT_FLASH_BLOCK_K", seq)
    tuned = None
    if (block_q or env_q) is None or (block_k or env_k) is None:
        tuned = _tuned_blocks(batch, heads, seq, depth, dtype)
    bq = (block_q or env_q or (tuned[0] if tuned else None)
          or _default_chain(seq, DEFAULT_BLOCK_Q))
    bk = (block_k or env_k or (tuned[1] if tuned else None)
          or _default_chain(seq, DEFAULT_BLOCK_K))
    return bq, bk


def _segment_mask(s, qseg_ref, kseg_ref):
    """Mask score tile entries whose q and k tokens are in different packed
    segments (qseg: (block_q,), kseg: (block_k,))."""
    qseg = qseg_ref[0, 0, :]
    kseg = kseg_ref[0, 0, :]
    return jnp.where(qseg[:, None] == kseg[None, :], s, NEG_INF)


def _masked_scores(q, k, qi, kj, *, scale, block_q, block_k, causal,
                   have_mask, mask_ref, qseg_ref, kseg_ref, window=None):
    """The (block_q, block_k) fp32 score tile with every mask applied.

    THE shared recompute of all four kernels (fwd, dq, dkv, fused bwd):
    qk^T contraction, causal iota mask, sliding-window lower edge,
    padding mask, packed-segment mask.  One definition so a
    masking-semantics change cannot desynchronize the forward from one
    of the backward variants.  ``window`` (static) keeps only keys in
    ``(q_pos - window, q_pos]``."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal or window is not None:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if causal:
            keep = q_pos >= k_pos
            if window is not None:
                keep &= k_pos > q_pos - window
        else:
            keep = k_pos > q_pos - window
        s = jnp.where(keep, s, NEG_INF)
    if have_mask:
        keep = mask_ref[0, 0, :]  # (block_k,)
        s = jnp.where(keep[None, :], s, NEG_INF)
    if qseg_ref is not None:
        s = _segment_mask(s, qseg_ref, kseg_ref)
    return s


def _straddles_diagonal(qi, kj, block_q, block_k):
    """Traced scalar: does this running (q-block, k-block) pair cross the
    causal diagonal?  A running pair that does NOT (its last k position
    <= its first q position) is fully visible, so the per-element iota/
    compare/select causal passes are pure VPU waste — at 8 blocks per
    axis only 8 of the 36 running pairs straddle.  Callers split the
    step body on this scalar with ``pl.when`` so the off-diagonal
    majority skips the masking entirely."""
    return kj * block_k + block_k - 1 > qi * block_q


def _straddles_window(qi, kj, block_q, block_k, window):
    """Traced scalar: does the pair cross the sliding-window LOWER edge
    (some k in the block is <= some q's q_pos - window)?  Fully-inside
    pairs (min k > max q - window) need no lower-edge mask."""
    return kj * block_k <= qi * block_q + block_q - 1 - window


def _band_run(qi, kj, block_q, block_k, causal, window):
    """Python-or-traced: does this block pair contribute at all?

    Upper cut (causal): first k <= last q position.  Lower cut (window):
    last k position >= first q position - (window - 1) — a pair entirely
    below the band is all-masked, so its matmuls are skipped outright
    (this is what turns O(S^2) into O(S*window) at long sequence)."""
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1
    if window is not None:
        in_band = kj * block_k + block_k - 1 >= qi * block_q - (window - 1)
        run = in_band if run is True else (run & in_band)
    return run


def _causal_step_split(qi, kj, run, *, block_q, block_k, causal, step,
                       window=None):
    """Run ``step(apply_causal, apply_window)`` under the band split.

    ``step`` is the kernel body parameterized on which mask passes are
    emitted; identical numerics either way (skipping is only legal for
    pairs fully inside the respective edge).  Pairs needing neither
    edge (the band interior) run completely unmasked; with no window
    and no causal flag there is a single unmasked body (``run`` is the
    Python literal True there — every block pair runs)."""
    if not causal and window is None:
        step(False, False)
        return
    need_diag = (
        _straddles_diagonal(qi, kj, block_q, block_k) if causal
        else jnp.bool_(False)
    )
    need_win = (
        _straddles_window(qi, kj, block_q, block_k, window)
        if window is not None else jnp.bool_(False)
    )

    @pl.when(run & need_diag & need_win)
    def _():
        step(True, True)

    @pl.when(run & need_diag & jnp.logical_not(need_win))
    def _():
        step(True, False)

    @pl.when(run & jnp.logical_not(need_diag) & need_win)
    def _():
        step(False, True)

    @pl.when(run & jnp.logical_not(need_diag) & jnp.logical_not(need_win))
    def _():
        step(False, False)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, block_q, block_k, causal,
                have_mask, mask_ref=None, qseg_ref=None, kseg_ref=None,
                window=None):
    """One (q-block, k-block) grid step of online-softmax accumulation.

    Grid is (B, H, n_q, n_k) with k innermost; the m/l/acc state for the
    current q-block lives in VMEM scratch across the k sweep (the classic
    flash-attention recurrence).  Fully-causally-masked k-blocks are skipped.
    """
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:, :] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:, :] = jnp.zeros_like(l_scr)
        acc_scr[:, :] = jnp.zeros_like(acc_scr)

    # A k-block strictly above the causal diagonal or entirely below the
    # sliding-window band contributes nothing — skip its matmuls entirely
    # (halves causal FLOPs; makes windowed cost O(S*window)).
    run = _band_run(qi, kj, block_q, block_k, causal, window)

    def _step(apply_causal, apply_window):
        q = q_ref[0, 0, :, :]  # (block_q, D)
        k = k_ref[0, 0, :, :]  # (block_k, D)
        v = v_ref[0, 0, :, :]  # (block_k, D)
        s = _masked_scores(
            q, k, qi, kj, scale=scale, block_q=block_q, block_k=block_k,
            causal=apply_causal, have_mask=have_mask, mask_ref=mask_ref,
            qseg_ref=qseg_ref, kseg_ref=kseg_ref,
            window=window if apply_window else None,
        )
        m_prev = m_scr[:, :1]  # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:, :] = acc_scr[:, :] * alpha + pv
        m_scr[:, :] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:, :] = jnp.broadcast_to(l_new, l_scr.shape)

    _causal_step_split(qi, kj, run, block_q=block_q, block_k=block_k,
                       causal=causal, step=_step, window=window)

    @pl.when(kj == n_k - 1)
    def _finalize():
        # l is always > 0: even a fully-masked row has p = exp(NEG_INF -
        # NEG_INF) = 1 per entry, so such rows output the uniform average of
        # V — identical to the XLA softmax path's behavior.
        l = l_scr[:, :1]
        o_ref[0, 0, :, :] = (acc_scr[:, :] / l).astype(o_ref.dtype)
        lse_ref[0, 0, 0, pl.ds(qi * block_q, block_q)] = (
            m_scr[:, 0] + jnp.log(l_scr[:, 0])
        )


def _fwd_kernel_1k(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_q,
                   block_k, causal, have_mask, mask_ref=None,
                   qseg_ref=None, kseg_ref=None, window=None):
    """Single-k-block forward: the softmax in one pass, no online state.

    When the whole K/V sequence fits one k block (the seq<=1024 headline
    regime under the 1024x1024 retune, where the kernel is VPU-bound —
    docs/LM_PERF.md), the online-softmax recurrence degenerates to a
    plain row softmax: the m/l/acc scratch buffers, their init pass, the
    alpha rescale of the accumulator, and the (block_q, 128) broadcast
    writes are all dead work this kernel simply does not emit.  Same
    reduction order and masked-row semantics as :func:`_fwd_kernel` with
    n_k == 1 (a fully-masked row averages V, l = exp(0)*block_k > 0), so
    outputs are bit-identical.
    """
    qi = pl.program_id(2)
    # With K spanning the sequence, every causal q block straddles the
    # diagonal — no point splitting on it (see _causal_step_split).
    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    s = _masked_scores(
        q, k, qi, 0, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, have_mask=have_mask, mask_ref=mask_ref,
        qseg_ref=qseg_ref, kseg_ref=kseg_ref, window=window,
    )
    m = jnp.max(s, axis=-1, keepdims=True)       # (block_q, 1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0, :, :] = (pv / l).astype(o_ref.dtype)
    lse_ref[0, 0, 0, pl.ds(qi * block_q, block_q)] = (
        m[:, 0] + jnp.log(l[:, 0])
    )


def _extra_specs_and_args(mask, segment_ids, batch, seq, block_q, block_k,
                          mem, *, swap_grid=False, kv_segment_ids=None):
    """(in_specs, args, ref_names) for the optional mask / segment-id inputs.

    ``swap_grid``: the dkv kernel's grid is (B, H, n_k, n_q) — its index_map
    axis roles are swapped relative to the fwd/dq grids.
    ``kv_segment_ids``: distinct key/value-side segment array (ring
    attention rotates K/V chunks, so their segments differ from the local
    q shard's); defaults to ``segment_ids`` (self-attention).
    """
    if swap_grid:
        kidx = lambda b, h, j, i: (b, 0, j)
        qidx = lambda b, h, j, i: (b, 0, i)
    else:
        kidx = lambda b, h, i, j: (b, 0, j)
        qidx = lambda b, h, i, j: (b, 0, i)
    specs, args, names = [], [], []
    if mask is not None:
        specs.append(pl.BlockSpec((1, 1, block_k), kidx, memory_space=mem))
        args.append(mask.reshape(batch, 1, seq))
        names.append("mask_ref")
    if segment_ids is not None:
        qseg3 = segment_ids.reshape(batch, 1, seq).astype(jnp.int32)
        kseg = segment_ids if kv_segment_ids is None else kv_segment_ids
        kseg3 = kseg.reshape(batch, 1, seq).astype(jnp.int32)
        specs.append(pl.BlockSpec((1, 1, block_q), qidx, memory_space=mem))
        args.append(qseg3)
        names.append("qseg_ref")
        specs.append(pl.BlockSpec((1, 1, block_k), kidx, memory_space=mem))
        args.append(kseg3)
        names.append("kseg_ref")
    return specs, args, names


def _wrap_kernel(inner, n_fixed_in, extra_names, **kw):
    """Adapt ``inner(*fixed_refs, *outs_and_scratch, **extras, **kw)`` to the
    positional ref list pallas_call passes (fixed inputs, extra inputs,
    outputs+scratch)."""
    n_extra = len(extra_names)

    def kernel(*refs):
        fixed = refs[:n_fixed_in]
        extras = dict(zip(extra_names, refs[n_fixed_in:n_fixed_in + n_extra]))
        rest = refs[n_fixed_in + n_extra:]
        inner(*fixed, *rest, have_mask="mask_ref" in extras, **extras, **kw)

    return kernel


def _flash_forward(q, k, v, mask, segment_ids, kv_segment_ids=None, *,
                   causal, interpret, window=None,
                   block_q=None, block_k=None):
    # Mosaic needs the trailing two block dims tile-aligned or full-size:
    # run the kernel in BHSD so (seq, depth) are the trailing dims.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    o, lse, _ = _flash_forward_bhsd(qt, kt, vt, mask, segment_ids,
                                    kv_segment_ids, causal=causal,
                                    interpret=interpret, window=window,
                                    block_q=block_q, block_k=block_k)
    return o, lse


def _flash_forward_bhsd(qt, kt, vt, mask, segment_ids, kv_segment_ids=None,
                        *, causal, interpret, window=None,
                        block_q=None, block_k=None):
    """Forward on already-BHSD operands; returns (o BSHD, lse, o BHSD).

    The BHSD output is handed back so the custom VJP can save the
    transposed operands as residuals — the backward kernels consume
    BHSD, and re-deriving it there from BSHD residuals would re-emit
    the relayouts the forward already paid for.

    GQA (kt/vt with fewer heads): the kv index map sends q-head grid
    step ``h`` to kv head ``h // group`` — every q head in a group reads
    the SAME kv tile, so the sharing is zero-copy (no (B, Hq, S, D)
    broadcast ever exists in HBM)."""
    batch, heads, seq, depth = qt.shape
    group = heads // kt.shape[1]
    block_q, block_k = _resolve_blocks(
        batch, heads, seq, depth, qt.dtype, block_q, block_k
    )
    scale = 1.0 / (depth ** 0.5)
    grid = (batch, heads, seq // block_q, seq // block_k)
    mem = pl.ANY if interpret else pltpu.VMEM

    qspec = pl.BlockSpec(
        (1, 1, block_q, depth), lambda b, h, i, j: (b, h, i, 0),
        memory_space=mem,
    )
    kvspec = pl.BlockSpec(
        (1, 1, block_k, depth), lambda b, h, i, j: (b, h // group, j, 0),
        memory_space=mem,
    )
    extra_specs, extra_args, extra_names = _extra_specs_and_args(
        mask, segment_ids, batch, seq, block_q, block_k, mem,
        kv_segment_ids=kv_segment_ids,
    )
    one_k = seq // block_k == 1
    kernel = _wrap_kernel(
        _fwd_kernel_1k if one_k else _fwd_kernel, 3, extra_names,
        scale=scale, block_q=block_q, block_k=block_k, causal=causal,
        window=window,
    )

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qspec, kvspec, kvspec, *extra_specs],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, depth),
                         lambda b, h, i, j: (b, h, i, 0), memory_space=mem),
            # (B, H, 1, S) keeps the trailing block dims (1, S) tile-legal
            pl.BlockSpec((1, 1, 1, seq), lambda b, h, i, j: (b, h, 0, 0),
                         memory_space=mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, qt.dtype),
            jax.ShapeDtypeStruct((batch, heads, 1, seq), jnp.float32),
        ],
        scratch_shapes=[] if one_k else [
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum l
            pltpu.VMEM((block_q, depth), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt, *extra_args)
    return o.transpose(0, 2, 1, 3), lse[:, :, 0, :], o


# --- Backward: Pallas kernels (fused single sweep, or dq + dkv split) -------

#: "pallas" (default: the fused single-sweep kernel when the dq scratch
#: fits VMEM, else the split pair), "pallas_split" (force the two-kernel
#: dq/dkv path), or "xla" — the XLA blockwise recompute kept as the
#: golden reference for A/B numerics and as an escape hatch.  Read at TRACE
#: time: a function jitted before flipping this keeps its compiled backward
#: (jit caching) — for a reliable A/B pass ``backward_impl=`` to
#: :func:`flash_attention` and re-jit instead of mutating mid-run.
BACKWARD_IMPL = "pallas"

#: The fused backward keeps the WHOLE (S, D) fp32 dq for the current
#: (batch, head) in VMEM scratch; above this budget the split pair runs
#: instead (at D=64 the cutoff is seq 8192).  2 MiB, not 4: the scratch
#: shares the 16 MB VMEM with the (1024, 1024) fp32 score/p/dp/ds tiles,
#: and a 4 MiB scratch compiled but OOM'd AT RUN TIME on the v5e at
#: seq 16384 (measured 2026-08-01; 8192 runs and is 11% faster than
#: split end-to-end).
FUSED_BWD_DQ_SCRATCH_BYTES = 2 * 2**20


def _bwd_fused_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dq_all_scr, dk_scr, dv_scr,
                      *, scale, block_q, block_k, causal,
                      have_mask, mask_ref=None, qseg_ref=None,
                      kseg_ref=None, window=None):
    """dq, dk and dv in ONE sweep — the p-tile is recomputed once.

    The split pair pays 7 matmuls + 2 exp-of-score-tile passes per
    (q-block, k-block) pair (each kernel recomputes s and p); this kernel
    pays 5 matmuls + 1 exp.  Grid (B, H, n_k, n_q), q innermost:

    - dk/dv accumulate per-k-block in scratch, flushed at the last
      q-block — the same consecutive-revisit pattern as the split dkv
      kernel;
    - dq accumulates into a full (S, D) fp32 scratch for the current
      (b, h) (zeroed at the slice's first grid step).  Its output block
      is indexed by the INNER axis, so every visit writes the running
      partial sum unconditionally — Pallas flushes an output buffer
      whenever its index changes, and a visit that skipped the write
      (e.g. under the causal guard) would flush stale bytes from the
      previous q-block.  The final sweep (j == n_k-1) overwrites every
      block with the completed sum.
    """
    j = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when((j == 0) & (i == 0))
    def _init_dq():
        dq_all_scr[:, :] = jnp.zeros_like(dq_all_scr)

    @pl.when(i == 0)
    def _init_dkv():
        dk_scr[:, :] = jnp.zeros_like(dk_scr)
        dv_scr[:, :] = jnp.zeros_like(dv_scr)

    run = _band_run(i, j, block_q, block_k, causal, window)

    def _step(apply_causal, apply_window):
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        gq = g_ref[0, 0, :, :]
        s = _masked_scores(
            q, k, i, j, scale=scale, block_q=block_q, block_k=block_k,
            causal=apply_causal, have_mask=have_mask, mask_ref=mask_ref,
            qseg_ref=qseg_ref, kseg_ref=kseg_ref,
            window=window if apply_window else None,
        )
        lse = lse_ref[0, 0, 0, :]  # (block_q,)
        p = jnp.exp(s - lse[:, None])
        dv_scr[:, :] = dv_scr[:, :] + jax.lax.dot_general(
            p.astype(gq.dtype), gq, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, D)
        dp = jax.lax.dot_general(
            gq, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        delta = delta_ref[0, 0, 0, :]
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk_scr[:, :] = dk_scr[:, :] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, D)
        row = pl.ds(i * block_q, block_q)
        dq_all_scr[row] = dq_all_scr[row] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, D)

    _causal_step_split(i, j, run, block_q=block_q, block_k=block_k,
                       causal=causal, step=_step, window=window)

    # Unconditional writes: see the docstring on flush semantics.
    dq_ref[0, 0, :, :] = dq_all_scr[pl.ds(i * block_q, block_q)].astype(
        dq_ref.dtype
    )
    n_q = pl.num_programs(3)

    @pl.when(i == n_q - 1)
    def _flush_dkv():
        dk_ref[0, 0, :, :] = dk_scr[:, :].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:, :].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, block_q, block_k, causal,
                   have_mask, mask_ref=None, qseg_ref=None, kseg_ref=None,
                   window=None):
    """dq for one q-block, accumulated over the k sweep (k innermost).

    Recomputes the p-tile from the saved LSE:
      p  = exp(q k^T * scale - lse)
      ds = p * (g v^T - delta) * scale
      dq = sum_k ds @ k
    """
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_scr[:, :] = jnp.zeros_like(dq_scr)

    run = _band_run(qi, kj, block_q, block_k, causal, window)

    def _step(apply_causal, apply_window):
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        gq = g_ref[0, 0, :, :]
        s = _masked_scores(
            q, k, qi, kj, scale=scale, block_q=block_q, block_k=block_k,
            causal=apply_causal, have_mask=have_mask, mask_ref=mask_ref,
            qseg_ref=qseg_ref, kseg_ref=kseg_ref,
            window=window if apply_window else None,
        )
        lse = lse_ref[0, 0, 0, :]  # (block_q,)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            gq, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        delta = delta_ref[0, 0, 0, :]  # (block_q,)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[:, :] = dq_scr[:, :] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _causal_step_split(qi, kj, run, block_q=block_q, block_k=block_k,
                       causal=causal, step=_step, window=window)

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scr[:, :].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, block_q,
                    block_k, causal, have_mask, mask_ref=None,
                    qseg_ref=None, kseg_ref=None, window=None):
    """dk/dv for one k-block, accumulated over the q sweep (q innermost).

      dv = sum_q p^T @ g
      dk = sum_q ds^T @ q
    """
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:, :] = jnp.zeros_like(dk_scr)
        dv_scr[:, :] = jnp.zeros_like(dv_scr)

    # A q-block strictly above the causal diagonal (all q < all k) never
    # attends to this k-block; one entirely below the window band neither.
    run = _band_run(qi, kj, block_q, block_k, causal, window)

    def _step(apply_causal, apply_window):
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        gq = g_ref[0, 0, :, :]
        s = _masked_scores(
            q, k, qi, kj, scale=scale, block_q=block_q, block_k=block_k,
            causal=apply_causal, have_mask=have_mask, mask_ref=mask_ref,
            qseg_ref=qseg_ref, kseg_ref=kseg_ref,
            window=window if apply_window else None,
        )
        lse = lse_ref[0, 0, 0, :]  # (block_q,)
        p = jnp.exp(s - lse[:, None])  # (block_q, block_k)
        dv_scr[:, :] = dv_scr[:, :] + jax.lax.dot_general(
            p.astype(gq.dtype), gq, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, D)
        dp = jax.lax.dot_general(
            gq, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0, 0, 0, :]
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[:, :] = dk_scr[:, :] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, D)

    _causal_step_split(qi, kj, run, block_q=block_q, block_k=block_k,
                       causal=causal, step=_step, window=window)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scr[:, :].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:, :].astype(dv_ref.dtype)


def _flash_backward_pallas(res, g, *, causal, interpret, force_split=False,
                           window=None, block_q=None, block_k=None):
    """Backward from the custom-VJP residuals (BHSD operands + BHSD o).

    GQA residuals hold K/V compact (Hkv heads).  The forward shares
    tiles zero-copy via its index map; the backward instead broadcasts
    K/V to Hq for the unchanged kernels and group-sums dk/dv afterwards
    — a deliberate simplicity trade: training-side GQA gains are in the
    QKV projection, not here, while the decode path (where the cache
    stream IS the bound) gets native grouping in ops.attention."""
    qt, kt, vt, mask, segment_ids, ot, lse = res
    heads, kv_heads = qt.shape[1], kt.shape[1]
    if kv_heads != heads:
        group = heads // kv_heads
        kt, vt = (
            jnp.repeat(x, group, axis=1) for x in (kt, vt)
        )
    gt = g.transpose(0, 2, 1, 3)
    # delta = rowsum(dO * O): cheap elementwise+reduce, XLA fuses it.
    delta = jnp.einsum(
        "bhqd,bhqd->bhq", gt.astype(jnp.float32), ot.astype(jnp.float32)
    )
    dqt, dkt, dvt = _flash_backward_pallas_bhsd(
        qt, kt, vt, gt, mask, lse, delta, segment_ids=segment_ids,
        causal=causal, interpret=interpret, force_split=force_split,
        window=window, block_q=block_q, block_k=block_k,
    )
    if kv_heads != heads:
        b, _, s, d = dkt.shape
        dkt = dkt.reshape(b, kv_heads, group, s, d).sum(axis=2)
        dvt = dvt.reshape(b, kv_heads, group, s, d).sum(axis=2)
    bsdh = lambda x: x.transpose(0, 2, 1, 3)
    return bsdh(dqt), bsdh(dkt), bsdh(dvt)


def _flash_backward_pallas_core(q, k, v, mask, g, lse, delta, *,
                                segment_ids=None, kv_segment_ids=None,
                                causal, interpret, force_split=False,
                                window=None):
    """dq/dk/dv kernels from externally-supplied LSE and delta rows.

    BSHD entry kept for ring attention (``parallel/ring_attention.py``),
    which drives the same kernels per K/V chunk with the *global*
    (cross-chunk) LSE.  ``lse``/``delta`` are (B, H, S) fp32.
    """
    qt, kt, vt, gt = (x.transpose(0, 2, 1, 3) for x in (q, k, v, g))
    dqt, dkt, dvt = _flash_backward_pallas_bhsd(
        qt, kt, vt, gt, mask, lse, delta, segment_ids=segment_ids,
        kv_segment_ids=kv_segment_ids, causal=causal, interpret=interpret,
        force_split=force_split, window=window,
    )
    bsdh = lambda x: x.transpose(0, 2, 1, 3)
    return bsdh(dqt), bsdh(dkt), bsdh(dvt)


def _flash_backward_pallas_bhsd(qt, kt, vt, gt, mask, lse, delta, *,
                                segment_ids=None, kv_segment_ids=None,
                                causal, interpret, force_split=False,
                                window=None, block_q=None, block_k=None):
    """The dq/dk/dv kernels on BHSD operands; grads returned BHSD.

    Dispatch: the fused single-sweep kernel (one p-recompute) when the
    (S, D) fp32 dq scratch fits ``FUSED_BWD_DQ_SCRATCH_BYTES``, else —
    or under ``force_split`` — the original dq + dkv pair.
    """
    batch, heads, seq, depth = qt.shape
    block_q, block_k = _resolve_blocks(
        batch, heads, seq, depth, qt.dtype, block_q, block_k
    )
    scale = 1.0 / (depth ** 0.5)
    mem = pl.ANY if interpret else pltpu.VMEM

    # (B, H, 1, S) keeps kernel blocks' trailing dims tile-legal like lse.
    delta = delta[:, :, None, :]
    lse4 = lse[:, :, None, :]  # (B, H, 1, S)

    if not force_split and seq * depth * 4 <= FUSED_BWD_DQ_SCRATCH_BYTES:
        fused_specs = [
            pl.BlockSpec((1, 1, block_q, depth),
                         lambda b, h, j, i: (b, h, i, 0),
                         memory_space=mem),  # q
            pl.BlockSpec((1, 1, block_k, depth),
                         lambda b, h, j, i: (b, h, j, 0),
                         memory_space=mem),  # k
            pl.BlockSpec((1, 1, block_k, depth),
                         lambda b, h, j, i: (b, h, j, 0),
                         memory_space=mem),  # v
            pl.BlockSpec((1, 1, block_q, depth),
                         lambda b, h, j, i: (b, h, i, 0),
                         memory_space=mem),  # g
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda b, h, j, i: (b, h, 0, i),
                         memory_space=mem),  # lse
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda b, h, j, i: (b, h, 0, i),
                         memory_space=mem),  # delta
        ]
        extra_specs, extra_args, extra_names = _extra_specs_and_args(
            mask, segment_ids, batch, seq, block_q, block_k, mem,
            swap_grid=True, kv_segment_ids=kv_segment_ids,
        )
        kernel = _wrap_kernel(
            _bwd_fused_kernel, 6, extra_names,
            scale=scale, block_q=block_q, block_k=block_k, causal=causal,
            window=window,
        )
        dqt, dkt, dvt = pl.pallas_call(
            kernel,
            grid=(batch, heads, seq // block_k, seq // block_q),
            in_specs=fused_specs + extra_specs,
            out_specs=[
                pl.BlockSpec((1, 1, block_q, depth),
                             lambda b, h, j, i: (b, h, i, 0),
                             memory_space=mem),
                pl.BlockSpec((1, 1, block_k, depth),
                             lambda b, h, j, i: (b, h, j, 0),
                             memory_space=mem),
                pl.BlockSpec((1, 1, block_k, depth),
                             lambda b, h, j, i: (b, h, j, 0),
                             memory_space=mem),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qt.shape, qt.dtype),
                jax.ShapeDtypeStruct(kt.shape, kt.dtype),
                jax.ShapeDtypeStruct(vt.shape, vt.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((seq, depth), jnp.float32),     # dq, whole (b,h)
                pltpu.VMEM((block_k, depth), jnp.float32),  # dk
                pltpu.VMEM((block_k, depth), jnp.float32),  # dv
            ],
            interpret=interpret,
        )(qt, kt, vt, gt, lse4, delta, *extra_args)
        return dqt, dkt, dvt

    # --- dq kernel: grid (B, H, n_q, n_k), k innermost ---
    dq_in_specs = [
        pl.BlockSpec((1, 1, block_q, depth), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=mem),  # q
        pl.BlockSpec((1, 1, block_k, depth), lambda b, h, i, j: (b, h, j, 0),
                     memory_space=mem),  # k
        pl.BlockSpec((1, 1, block_k, depth), lambda b, h, i, j: (b, h, j, 0),
                     memory_space=mem),  # v
        pl.BlockSpec((1, 1, block_q, depth), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=mem),  # g
        pl.BlockSpec((1, 1, 1, block_q), lambda b, h, i, j: (b, h, 0, i),
                     memory_space=mem),  # lse
        pl.BlockSpec((1, 1, 1, block_q), lambda b, h, i, j: (b, h, 0, i),
                     memory_space=mem),  # delta
    ]
    extra_specs, extra_args, extra_names = _extra_specs_and_args(
        mask, segment_ids, batch, seq, block_q, block_k, mem,
        kv_segment_ids=kv_segment_ids,
    )
    dq_in_specs += extra_specs
    dq_args = [qt, kt, vt, gt, lse4, delta, *extra_args]
    dq_kernel = _wrap_kernel(
        _bwd_dq_kernel, 6, extra_names,
        scale=scale, block_q=block_q, block_k=block_k, causal=causal,
        window=window,
    )

    dqt = pl.pallas_call(
        dq_kernel,
        grid=(batch, heads, seq // block_q, seq // block_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, depth),
                               lambda b, h, i, j: (b, h, i, 0),
                               memory_space=mem),
        out_shape=jax.ShapeDtypeStruct(qt.shape, qt.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, depth), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    # --- dk/dv kernel: grid (B, H, n_k, n_q), q innermost ---
    dkv_in_specs = [
        pl.BlockSpec((1, 1, block_q, depth), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=mem),  # q
        pl.BlockSpec((1, 1, block_k, depth), lambda b, h, j, i: (b, h, j, 0),
                     memory_space=mem),  # k
        pl.BlockSpec((1, 1, block_k, depth), lambda b, h, j, i: (b, h, j, 0),
                     memory_space=mem),  # v
        pl.BlockSpec((1, 1, block_q, depth), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=mem),  # g
        pl.BlockSpec((1, 1, 1, block_q), lambda b, h, j, i: (b, h, 0, i),
                     memory_space=mem),  # lse
        pl.BlockSpec((1, 1, 1, block_q), lambda b, h, j, i: (b, h, 0, i),
                     memory_space=mem),  # delta
    ]
    extra_specs2, extra_args2, extra_names2 = _extra_specs_and_args(
        mask, segment_ids, batch, seq, block_q, block_k, mem, swap_grid=True,
        kv_segment_ids=kv_segment_ids,
    )
    dkv_in_specs += extra_specs2
    dkv_args = [qt, kt, vt, gt, lse4, delta, *extra_args2]
    dkv_kernel = _wrap_kernel(
        _bwd_dkv_kernel, 6, extra_names2,
        scale=scale, block_q=block_q, block_k=block_k, causal=causal,
        window=window,
    )

    dkt, dvt = pl.pallas_call(
        dkv_kernel,
        grid=(batch, heads, seq // block_k, seq // block_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, depth),
                         lambda b, h, j, i: (b, h, j, 0), memory_space=mem),
            pl.BlockSpec((1, 1, block_k, depth),
                         lambda b, h, j, i: (b, h, j, 0), memory_space=mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kt.shape, kt.dtype),
            jax.ShapeDtypeStruct(vt.shape, vt.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, depth), jnp.float32),
            pltpu.VMEM((block_k, depth), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)

    return dqt, dkt, dvt


# --- Backward (blockwise XLA recompute from LSE — golden fallback) ----------


def _flash_backward_xla(res, g, *, causal, window=None):
    q, k, v, mask, segment_ids, o, lse = res
    batch, seq, heads, depth = q.shape
    # Fixed 128-row blocks, deliberately NOT _pick_block_q: this path's
    # per-scan-step (B, H, block_q, S) fp32 score/p/ds temporaries scale
    # with block_q, and the 1024-block Pallas retune (or a sweep env
    # override) would inflate them 8x — at 32k seq that is ~1.6 GB per
    # live temporary, an HBM OOM on exactly the long sequences this
    # recompute fallback exists to fit.
    block_q = next(
        (b for b in (128, 64, 32, 16, 8) if seq % b == 0), None
    )
    scale = 1.0 / (depth ** 0.5)
    n_blocks = seq // block_q

    # fp32 working copies, BHSD-free: keep BSHD, contract with einsum strings
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    of = o.astype(jnp.float32)
    delta = jnp.einsum("bqhd,bqhd->bhq", gf, of)  # rowsum(dO * O)

    def reblock(x):  # (B, S, H, D) -> (n, B, bq, H, D)
        return x.reshape(batch, n_blocks, block_q, heads, depth).transpose(
            1, 0, 2, 3, 4
        )

    q_blocks = reblock(qf)
    g_blocks = reblock(gf)
    lse_blocks = lse.reshape(batch, heads, n_blocks, block_q).transpose(2, 0, 1, 3)
    delta_blocks = delta.reshape(batch, heads, n_blocks, block_q).transpose(2, 0, 1, 3)
    k_pos = jnp.arange(seq)
    seg_blocks = (
        segment_ids.reshape(batch, n_blocks, block_q).transpose(1, 0, 2)
        if segment_ids is not None else jnp.zeros((n_blocks, batch, 1), jnp.int32)
    )

    def body(carry, xs):
        dk_acc, dv_acc = carry
        qb, gb, lseb, deltab, segb, blk = xs
        s = jnp.einsum("bqhd,bkhd->bhqk", qb, kf) * scale
        if causal or window is not None:
            q_pos = blk * block_q + jnp.arange(block_q)
            keep = (
                q_pos[:, None] >= k_pos[None, :] if causal
                else jnp.ones((block_q, seq), bool)
            )
            if window is not None:
                keep &= k_pos[None, :] > q_pos[:, None] - window
            s = jnp.where(keep[None, None, :, :], s, NEG_INF)
        if mask is not None:
            s = jnp.where(mask[:, None, None, :], s, NEG_INF)
        if segment_ids is not None:
            s = jnp.where(
                segb[:, None, :, None] == segment_ids[:, None, None, :],
                s, NEG_INF,
            )
        p = jnp.exp(s - lseb[:, :, :, None])  # (B, H, bq, S)
        dv_acc = dv_acc + jnp.einsum("bhqk,bqhd->bkhd", p, gb)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gb, vf)
        ds = p * (dp - deltab[:, :, :, None]) * scale
        dqb = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dk_acc = dk_acc + jnp.einsum("bhqk,bqhd->bkhd", ds, qb)
        return (dk_acc, dv_acc), dqb

    zeros = jnp.zeros_like(kf)
    (dk, dv), dq_blocks = jax.lax.scan(
        body, (zeros, zeros),
        (q_blocks, g_blocks, lse_blocks, delta_blocks, seg_blocks,
         jnp.arange(n_blocks)),
    )
    dq = dq_blocks.transpose(1, 0, 2, 3, 4).reshape(batch, seq, heads, depth)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --- Public entry with custom VJP -------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, mask, segment_ids, causal, interpret, backward_impl,
           window, block_q, block_k):
    o, _ = _flash_forward(q, k, v, mask, segment_ids, causal=causal,
                          interpret=interpret, window=window,
                          block_q=block_q, block_k=block_k)
    return o


def _flash_fwd(q, k, v, mask, segment_ids, causal, interpret, backward_impl,
               window, block_q, block_k):
    # Residuals are saved in the BHSD layout the kernels consume: the
    # forward already paid for these relayouts, and saving the BSHD
    # originals instead would make the backward re-emit all four
    # (profiled at ~6 ms/step of pure transposes, docs/LM_PERF.md).
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o, lse, ot = _flash_forward_bhsd(qt, kt, vt, mask, segment_ids,
                                     causal=causal, interpret=interpret,
                                     window=window,
                                     block_q=block_q, block_k=block_k)
    return o, (qt, kt, vt, mask, segment_ids, ot, lse)


def _flash_bwd(causal, interpret, backward_impl, window, block_q, block_k,
               res, g):
    impl = backward_impl or BACKWARD_IMPL
    if impl in ("pallas", "pallas_split"):
        dq, dk, dv = _flash_backward_pallas(
            res, g, causal=causal, interpret=interpret,
            force_split=(impl == "pallas_split"), window=window,
            block_q=block_q, block_k=block_k,
        )
    else:
        qt, kt, vt, mask, segment_ids, ot, lse = res
        q, k, v, o = (t.transpose(0, 2, 1, 3) for t in (qt, kt, vt, ot))
        heads, kv_heads = q.shape[2], k.shape[2]
        if kv_heads != heads:  # GQA: broadcast for the equal-head fallback
            group = heads // kv_heads
            k, v = (jnp.repeat(x, group, axis=2) for x in (k, v))
        dq, dk, dv = _flash_backward_xla(
            (q, k, v, mask, segment_ids, o, lse), g, causal=causal,
            window=window,
        )
        if kv_heads != heads:
            b, s, _, d = dk.shape
            dk = dk.reshape(b, s, kv_heads, group, d).sum(axis=3)
            dv = dv.reshape(b, s, kv_heads, group, d).sum(axis=3)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, mask=None, segment_ids=None, causal=False,
                    interpret=None, backward_impl=None, window=None,
                    block_q=None, block_k=None):
    """Flash attention, BSHD layout; differentiable.

    ``mask`` is a padding mask (B, S) or (B, 1, 1, S), True = attend.
    ``segment_ids`` is an int (B, S) array for packed sequences (BERT-style
    example packing): tokens attend only within their own segment; composes
    with ``mask`` and ``causal``.
    ``interpret=None`` auto-selects interpreter mode off-TPU (for tests).
    ``backward_impl`` picks the backward: None = module ``BACKWARD_IMPL``
    default, "pallas" = fused single-sweep kernel (split pair when the dq
    scratch exceeds VMEM budget), "pallas_split" = force the dq + dkv
    pair, "xla" = blockwise-recompute golden path.
    ``window`` (int, requires ``causal=True``) enables sliding-window
    attention: token i attends keys in ``(i - window, i]``.  Block pairs
    entirely below the band are skipped outright, so cost scales
    O(S * window) instead of O(S^2); ``window >= seq`` degrades to plain
    causal.
    ``block_q`` / ``block_k`` pin the kernel tiling explicitly (the sweep
    driver ``tools/autotune_flash.py`` and A/B benches use this); left
    None, the tiling resolves env override > autotune cache
    (``ops/flash_tuning.py``, keyed on shape/dtype/platform) > the
    retuned default chain.
    Raises ValueError for shapes/masks the kernel cannot handle (callers
    wanting silent fallback should go through
    ``ops.attention.dot_product_attention`` with ``implementation="auto"``).
    """
    if q.ndim != 4 or k.shape != v.shape or not _gqa_ok(q.shape, k.shape):
        raise ValueError(
            f"flash_attention needs BSHD q/k/v with matching (B, S, D) and "
            f"q heads a multiple of kv heads (GQA), got {q.shape} "
            f"{k.shape} {v.shape}"
        )
    if _pick_block_q(q.shape[1]) is None:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by any supported "
            "q-block size (multiple of 8 required)"
        )
    if mask is not None and not _is_padding_mask(mask, q.shape):
        raise ValueError(
            f"mask shape {mask.shape} unsupported: need (B, S) or "
            "(B, 1, 1, S) padding mask"
        )
    if segment_ids is not None and not _is_segment_ids(segment_ids, q.shape):
        raise ValueError(
            f"segment_ids shape/dtype unsupported: need int (B, S), got "
            f"{segment_ids.shape} {segment_ids.dtype}"
        )
    if window is not None:
        if not causal:
            raise ValueError(
                "window (sliding-window attention) requires causal=True — "
                "a lower-edge-only band has unbounded lookahead"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window >= q.shape[1]:
            window = None  # full causal attention; skip the dead masking
    for name, b in (("block_q", block_q), ("block_k", block_k)):
        if b is not None and (b <= 0 or q.shape[1] % b):
            raise ValueError(
                f"{name}={b} must be a positive divisor of seq "
                f"{q.shape[1]}"
            )
    if interpret is None:
        interpret = not _on_tpu()
    pad = _as_padding_mask(mask, q.shape)
    return _flash(q, k, v, pad, segment_ids, causal, interpret,
                  backward_impl, window, block_q, block_k)
