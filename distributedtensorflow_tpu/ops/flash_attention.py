"""Pallas TPU flash attention.

The compiled-kernel replacement for the reference stack's fused-attention
needs (SURVEY.md §2.4 native-code obligations): attention scores never hit
HBM — each q-block computes its (block_q, S) score tile in VMEM, does the
softmax in fp32, and writes only the (block_q, D) output plus the
log-sum-exp rows needed by the backward pass.

Forward: one Pallas kernel, grid (batch, heads, q_blocks); K/V live in VMEM
per (batch, head) — at BERT/long-context head dims (64..128) a full K/V head
fits VMEM comfortably up to ~8k tokens, which is also the per-device shard
regime ring attention (``parallel/ring_attention.py``) operates in.

Backward: blockwise recompute in XLA (lax.scan over q-blocks, memory-bounded
— never materializes (S, S)); standard flash-attention gradient math from
the saved LSE.  A Pallas backward kernel is a later optimization; the
contraction-heavy steps here already land on the MXU.

Layout: BSHD (batch, seq, heads, head_dim) to match ``ops.attention``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9

DEFAULT_BLOCK_Q = 128


def _pick_block_q(seq_len: int) -> int | None:
    for b in (DEFAULT_BLOCK_Q, 64, 32, 16, 8):
        if seq_len % b == 0:
            return b
    return None


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


#: Auto-dispatch threshold: measured on TPU v5e, XLA's fused attention wins
#: below ~4k tokens (few, huge batched matmuls), while the Pallas kernel wins
#: above (7x at 8k) and keeps working where XLA's (S, S) scores OOM (32k+).
MIN_SEQ_FOR_PALLAS = 4096


def supported(q, k, v, *, mask=None) -> bool:
    """True when auto-dispatch should take the Pallas kernel for this call."""
    if q.ndim != 4 or q.shape != k.shape or q.shape != v.shape:
        return False
    if not _on_tpu():
        return False
    seq = q.shape[1]
    if seq < MIN_SEQ_FOR_PALLAS or _pick_block_q(seq) is None:
        return False
    if q.dtype not in (jnp.bfloat16, jnp.float32):
        return False
    return mask is None or _is_padding_mask(mask, q.shape)


def _is_padding_mask(mask, qshape) -> bool:
    """Accept (B, S) or its broadcast form (B, 1, 1, S)."""
    b, s = qshape[0], qshape[1]
    return tuple(mask.shape) in ((b, s), (b, 1, 1, s))


def _as_padding_mask(mask, qshape):
    if mask is None:
        return None
    b, s = qshape[0], qshape[1]
    return mask.reshape(b, s).astype(jnp.bool_)


# --- Forward kernel ---------------------------------------------------------


DEFAULT_BLOCK_K = 512


def _pick_block_k(seq_len: int) -> int | None:
    for b in (DEFAULT_BLOCK_K, 256, 128, 64, 32, 16, 8):
        if seq_len % b == 0:
            return b
    return None


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, block_q, block_k, causal,
                have_mask, mask_ref=None):
    """One (q-block, k-block) grid step of online-softmax accumulation.

    Grid is (B, H, n_q, n_k) with k innermost; the m/l/acc state for the
    current q-block lives in VMEM scratch across the k sweep (the classic
    flash-attention recurrence).  Fully-causally-masked k-blocks are skipped.
    """
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:, :] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:, :] = jnp.zeros_like(l_scr)
        acc_scr[:, :] = jnp.zeros_like(acc_scr)

    # Under causal masking, a k-block strictly above the diagonal contributes
    # nothing — skip its matmuls entirely (halves causal FLOPs).
    run = (not causal) or (kj * block_k <= qi * block_q + block_q - 1)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0, :, :]  # (block_q, D)
        k = k_ref[0, 0, :, :]  # (block_k, D)
        v = v_ref[0, 0, :, :]  # (block_k, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if have_mask:
            keep = mask_ref[0, 0, :]  # (block_k,)
            s = jnp.where(keep[None, :], s, NEG_INF)
        m_prev = m_scr[:, :1]  # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:, :] = acc_scr[:, :] * alpha + pv
        m_scr[:, :] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:, :] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kj == n_k - 1)
    def _finalize():
        # l is always > 0: even a fully-masked row has p = exp(NEG_INF -
        # NEG_INF) = 1 per entry, so such rows output the uniform average of
        # V — identical to the XLA softmax path's behavior.
        l = l_scr[:, :1]
        o_ref[0, 0, :, :] = (acc_scr[:, :] / l).astype(o_ref.dtype)
        lse_ref[0, 0, 0, pl.ds(qi * block_q, block_q)] = (
            m_scr[:, 0] + jnp.log(l_scr[:, 0])
        )


def _flash_forward(q, k, v, mask, *, causal, interpret):
    batch, seq, heads, depth = q.shape
    block_q = _pick_block_q(seq)
    block_k = _pick_block_k(seq)
    scale = 1.0 / (depth ** 0.5)
    grid = (batch, heads, seq // block_q, seq // block_k)
    mem = pl.ANY if interpret else pltpu.VMEM

    # Mosaic needs the trailing two block dims tile-aligned or full-size:
    # run the kernel in BHSD so (seq, depth) are the trailing dims.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    qspec = pl.BlockSpec(
        (1, 1, block_q, depth), lambda b, h, i, j: (b, h, i, 0),
        memory_space=mem,
    )
    kvspec = pl.BlockSpec(
        (1, 1, block_k, depth), lambda b, h, i, j: (b, h, j, 0),
        memory_space=mem,
    )
    in_specs = [qspec, kvspec, kvspec]
    args = [qt, kt, vt]
    have_mask = mask is not None
    if have_mask:
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j),
                         memory_space=mem)
        )
        args.append(mask.reshape(batch, 1, seq))

    common = dict(scale=scale, block_q=block_q, block_k=block_k,
                  causal=causal)
    if have_mask:
        def kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr):
            _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                        m_scr, l_scr, acc_scr, have_mask=True,
                        mask_ref=mask_ref, **common)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr):
            _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                        m_scr, l_scr, acc_scr, have_mask=False, **common)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, depth),
                         lambda b, h, i, j: (b, h, i, 0), memory_space=mem),
            # (B, H, 1, S) keeps the trailing block dims (1, S) tile-legal
            pl.BlockSpec((1, 1, 1, seq), lambda b, h, i, j: (b, h, 0, 0),
                         memory_space=mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, heads, 1, seq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum l
            pltpu.VMEM((block_q, depth), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(*args)
    return o.transpose(0, 2, 1, 3), lse[:, :, 0, :]


# --- Backward (blockwise XLA recompute from LSE) ----------------------------


def _flash_backward(res, g, *, causal):
    q, k, v, mask, o, lse = res
    batch, seq, heads, depth = q.shape
    block_q = _pick_block_q(seq)
    scale = 1.0 / (depth ** 0.5)
    n_blocks = seq // block_q

    # fp32 working copies, BHSD-free: keep BSHD, contract with einsum strings
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    of = o.astype(jnp.float32)
    delta = jnp.einsum("bqhd,bqhd->bhq", gf, of)  # rowsum(dO * O)

    def reblock(x):  # (B, S, H, D) -> (n, B, bq, H, D)
        return x.reshape(batch, n_blocks, block_q, heads, depth).transpose(
            1, 0, 2, 3, 4
        )

    q_blocks = reblock(qf)
    g_blocks = reblock(gf)
    lse_blocks = lse.reshape(batch, heads, n_blocks, block_q).transpose(2, 0, 1, 3)
    delta_blocks = delta.reshape(batch, heads, n_blocks, block_q).transpose(2, 0, 1, 3)
    k_pos = jnp.arange(seq)

    def body(carry, xs):
        dk_acc, dv_acc = carry
        qb, gb, lseb, deltab, blk = xs
        s = jnp.einsum("bqhd,bkhd->bhqk", qb, kf) * scale
        if causal:
            q_pos = blk * block_q + jnp.arange(block_q)
            s = jnp.where(q_pos[None, None, :, None] >= k_pos[None, None, None, :],
                          s, NEG_INF)
        if mask is not None:
            s = jnp.where(mask[:, None, None, :], s, NEG_INF)
        p = jnp.exp(s - lseb[:, :, :, None])  # (B, H, bq, S)
        dv_acc = dv_acc + jnp.einsum("bhqk,bqhd->bkhd", p, gb)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gb, vf)
        ds = p * (dp - deltab[:, :, :, None]) * scale
        dqb = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dk_acc = dk_acc + jnp.einsum("bhqk,bqhd->bkhd", ds, qb)
        return (dk_acc, dv_acc), dqb

    zeros = jnp.zeros_like(kf)
    (dk, dv), dq_blocks = jax.lax.scan(
        body, (zeros, zeros),
        (q_blocks, g_blocks, lse_blocks, delta_blocks, jnp.arange(n_blocks)),
    )
    dq = dq_blocks.transpose(1, 0, 2, 3, 4).reshape(batch, seq, heads, depth)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --- Public entry with custom VJP -------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, mask, causal, interpret):
    o, _ = _flash_forward(q, k, v, mask, causal=causal, interpret=interpret)
    return o


def _flash_fwd(q, k, v, mask, causal, interpret):
    o, lse = _flash_forward(q, k, v, mask, causal=causal, interpret=interpret)
    return o, (q, k, v, mask, o, lse)


def _flash_bwd(causal, interpret, res, g):
    dq, dk, dv = _flash_backward(res, g, causal=causal)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, mask=None, causal=False, interpret=None):
    """Flash attention, BSHD layout; differentiable.

    ``mask`` is a padding mask (B, S) or (B, 1, 1, S), True = attend.
    ``interpret=None`` auto-selects interpreter mode off-TPU (for tests).
    Raises ValueError for shapes/masks the kernel cannot handle (callers
    wanting silent fallback should go through
    ``ops.attention.dot_product_attention`` with ``implementation="auto"``).
    """
    if q.ndim != 4 or q.shape != k.shape or q.shape != v.shape:
        raise ValueError(
            f"flash_attention needs matching BSHD q/k/v, got {q.shape} "
            f"{k.shape} {v.shape}"
        )
    if _pick_block_q(q.shape[1]) is None:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by any supported "
            "q-block size (multiple of 8 required)"
        )
    if mask is not None and not _is_padding_mask(mask, q.shape):
        raise ValueError(
            f"mask shape {mask.shape} unsupported: need (B, S) or "
            "(B, 1, 1, S) padding mask"
        )
    if interpret is None:
        interpret = not _on_tpu()
    pad = _as_padding_mask(mask, q.shape)
    return _flash(q, k, v, pad, causal, interpret)
