"""TPU ops: attention (XLA + Pallas kernels), fused primitives."""

from .attention import dot_product_attention, xla_attention  # noqa: F401
