"""TPU ops: attention (XLA + Pallas kernels), fused primitives."""

from .attention import (  # noqa: F401
    dot_product_attention,
    paged_decode_attention,
    xla_attention,
)
