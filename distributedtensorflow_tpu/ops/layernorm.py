"""Fused LayerNorm: one-pass Pallas kernels, bf16 IO, fp32 statistics.

The reference stack's LayerNorm is Keras ``LayerNormalization``
(keras/src/layers/normalization/layer_normalization.py) compiled by XLA
as separate reduce + apply fusions.  Our models' pre-LN trunks ran the
same way (flax ``nn.LayerNorm(dtype=float32)``): the input is read once
for the statistics reduce and again for the normalize, with an fp32
promotion in between — profiled at ~16.6 ms/step of the GPT-2-small
headline (the multiply_reduce/convert_reduce fusion families,
``BENCH_RESULTS/profile_lm_tpu`` 2026-08-01), second only to the
attention and head kernels.

These kernels read each ``(block_n, D)`` tile ONCE: mean/var/normalize
happen VMEM-resident in fp32 and only the normalized output returns to
HBM.  The backward recomputes the row statistics from the saved input
instead of storing them — per-row mean/rstd live on the sublane axis,
where flushing them to an (N,) output would cost a lane relayout per
tile, while recomputing them is two lane-reductions over a tile the
backward already holds.

Semantics match ``nn.LayerNorm(dtype=float32)`` followed by a cast to
``out_dtype``: statistics and normalization in fp32 regardless of input
dtype, one rounding at the end.  ``tests/test_layernorm.py`` pins value
and gradient equivalence against the flax reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: Token rows per grid step.  VMEM: the fp32 x tile plus 2-3 fp32
#: temporaries at (block_n, D) — 512 x 768 keeps the bundle ~7 MB,
#: comfortably inside Mosaic's 16 MB scoped stack at GPT-2 widths.
BLOCK_TOKENS = 512


def _env_block() -> int:
    import os

    return int(os.environ.get("DTFT_LN_BLOCK_TOKENS", BLOCK_TOKENS))


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (xc * rstd) * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(x_ref, g_ref, dy_ref, dx_ref, dg_ref, db_ref, *, eps):
    """dx for this token block; dγ/dβ accumulated into the single
    (1, D) output blocks, whose index is constant across the grid — the
    consecutive-revisit pattern Pallas TPU keeps resident (same as the
    fused-xent dw kernel)."""
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    dy = dy_ref[...].astype(jnp.float32)
    a = dy * g_ref[...].astype(jnp.float32)
    c1 = jnp.mean(a, axis=1, keepdims=True)
    c2 = jnp.mean(a * xhat, axis=1, keepdims=True)
    dx_ref[...] = (rstd * (a - c1 - xhat * c2)).astype(dx_ref.dtype)
    pg = jnp.sum(dy * xhat, axis=0, keepdims=True)  # (1, D)
    pb = jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _first():
        dg_ref[...] = pg
        db_ref[...] = pb

    @pl.when(i != 0)
    def _rest():
        dg_ref[...] = dg_ref[...] + pg
        db_ref[...] = db_ref[...] + pb


def _pad_rows(x, block):
    n = x.shape[0]
    pad = (-n) % block
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x


def _row_specs(block_n, d, mem):
    return [
        pl.BlockSpec((block_n, d), lambda i: (i, 0), memory_space=mem),
        pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=mem),
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_ln(x2, g, b, eps, out_dtype, interpret):
    y, _ = _fused_ln_fwd(x2, g, b, eps, out_dtype, interpret)
    return y


def _fused_ln_fwd(x2, g, b, eps, out_dtype, interpret):
    n, d = x2.shape
    block = _env_block()
    xp = _pad_rows(x2, block)
    np_ = xp.shape[0]
    mem = pl.ANY if interpret else pltpu.VMEM
    y = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(np_ // block,),
        in_specs=_row_specs(block, d, mem)
        + [pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=mem)],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0),
                               memory_space=mem),
        out_shape=jax.ShapeDtypeStruct((np_, d), out_dtype),
        interpret=interpret,
    )(xp, g.reshape(1, d), b.reshape(1, d))
    return y[:n], (x2, g)


def _fused_ln_bwd(eps, out_dtype, interpret, res, dy):
    x2, g = res
    n, d = x2.shape
    block = _env_block()
    xp = _pad_rows(x2, block)
    dyp = _pad_rows(dy.astype(jnp.float32), block)
    np_ = xp.shape[0]
    mem = pl.ANY if interpret else pltpu.VMEM
    dx, dg, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=(np_ // block,),
        in_specs=_row_specs(block, d, mem)
        + [pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=mem)],
        out_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=mem),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=mem),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((np_, d), x2.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(xp, g.reshape(1, d), dyp)
    return (dx[:n], dg.reshape(d).astype(g.dtype),
            db.reshape(d).astype(g.dtype))


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def _xla_layer_norm(x, scale, bias, eps, out_dtype):
    """Reference path (off-TPU and golden tests): fp32 statistics and
    normalize, one rounding to ``out_dtype`` — the exact semantics of
    ``nn.LayerNorm(dtype=float32)(x).astype(out_dtype)``."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(out_dtype)


def layer_norm(
    x: jax.Array,            # (..., D)
    scale: jax.Array,        # (D,)
    bias: jax.Array,         # (D,)
    *,
    eps: float = 1e-6,  # matches flax nn.LayerNorm
    out_dtype=None,          # None = x.dtype
    impl: str = "auto",      # "auto" | "xla" | "pallas"
    interpret: bool | None = None,
) -> jax.Array:
    """LayerNorm over the last axis; fp32 stats, one output rounding.

    ``impl="auto"`` takes the Pallas kernel on TPU and the XLA reference
    elsewhere (interpret-mode Pallas on CPU is for tests, not the
    training path — models run the XLA form there at full speed).
    """
    out_dtype = out_dtype or x.dtype
    if impl == "auto":
        platform = jax.devices()[0].platform
        impl = "pallas" if platform in ("tpu", "axon") else "xla"
    if impl == "xla":
        return _xla_layer_norm(x, scale, bias, eps, out_dtype)
    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    d = x.shape[-1]
    x2 = x.reshape(-1, d)
    y = _fused_ln(x2, scale.astype(jnp.float32), bias.astype(jnp.float32),
                  eps, out_dtype, interpret)
    return y.reshape(x.shape)

