"""The five reference workload configs as runnable presets.

Reference (BASELINE.json ``configs``; repo glue layer SURVEY.md §1 L7):

1. ``mnist_lenet``      — MNIST LeNet-5, OneDeviceStrategy
2. ``cifar_resnet20``   — CIFAR-10 ResNet-20, MirroredStrategy
3. ``imagenet_resnet50``— ImageNet ResNet-50, MultiWorkerMirroredStrategy+NCCL
4. ``bert_mlm``         — BERT-base MLM, gradient accumulation
5. ``widedeep``         — Wide&Deep, ParameterServerStrategy sparse embeddings

Strategy choice becomes a default :class:`MeshSpec`; every preset runs on any
mesh (a strategy here is just a shape).  Input is synthetic by default (the
sandbox has no datasets); pass a tf.data source for real data.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import jax.numpy as jnp
import numpy as np
import optax

from .data.input_pipeline import InputContext, synthetic_classification
from .models import (
    BertForMLM,
    LeNet5,
    ResNet20,
    ResNet50,
    WideDeep,
    WideDeepConfig,
    bert_base,
    bert_layout,
    bert_tiny,
    max_predictions_for,
    mlm_eval,
    mlm_loss,
    widedeep_layout,
    widedeep_eval,
    widedeep_loss,
    widedeep_test_config,
)
from .parallel.mesh import MeshSpec
from .parallel.sharding import LayoutMap
from .train.losses import classification_eval, classification_loss


@dataclasses.dataclass
class Workload:
    name: str
    model: Any
    loss_fn: Callable
    eval_fn: Callable | None
    make_optimizer: Callable[[], optax.GradientTransformation]
    input_fn: Callable[[InputContext, int], Iterator[dict]]  # (ctx, seed) -> iter
    init_batch: dict[str, np.ndarray]  # example batch (graft entry / benches)
    init_fn: Callable  # rng -> flax variables
    global_batch_size: int
    mesh_spec: MeshSpec
    accum_steps: int = 1
    layout: LayoutMap | None = None
    fsdp: bool = False
    # Optional rebind once the concrete mesh exists (e.g. gpt_lm swaps in
    # sequence-parallel attention when the mesh has a real seq axis).
    finalize: Callable[["Workload", Any], "Workload"] | None = None

    def for_mesh(self, mesh) -> "Workload":
        return self.finalize(self, mesh) if self.finalize else self


def _img_input(shape, classes, dtype=np.float32):
    def input_fn(ctx: InputContext, seed: int):
        return synthetic_classification(
            ctx, image_shape=shape, num_classes=classes, seed=seed, dtype=dtype
        )
    return input_fn


def _img_init(shape, batch=2):
    return {
        "image": np.zeros((batch, *shape), np.float32),
        "label": np.zeros((batch,), np.int32),
    }


def synthetic_mlm(ctx: InputContext, *, vocab_size: int, seq_len: int,
                  mask_rate: float = 0.15, seed: int = 0) -> Iterator[dict]:
    """Synthetic masked-LM batches with the -100 ignore convention."""
    rng = np.random.default_rng(seed + ctx.input_pipeline_id)
    n = ctx.per_host_batch_size
    while True:
        ids = rng.integers(4, vocab_size, size=(n, seq_len))
        mask = rng.random((n, seq_len)) < mask_rate
        labels = np.where(mask, ids, -100)
        inputs = np.where(mask, 3, ids)  # 3 = [MASK]
        yield {
            "input_ids": inputs.astype(np.int32),
            "labels": labels.astype(np.int32),
            "attention_mask": np.ones((n, seq_len), np.int32),
        }


def synthetic_packed_mlm(ctx: InputContext, *, vocab_size: int,
                         seq_len: int, mask_rate: float = 0.15,
                         seed: int = 0) -> Iterator[dict]:
    """Packed masked-LM batches: variable-length synthetic examples packed
    into fixed rows by :func:`data.pack_sequences`, with ``segment_ids`` /
    ``position_ids`` so attention stays within packed examples (the
    BERT-style example-packing pipeline, wired to the flash kernel's
    segment support)."""
    from .data.input_pipeline import pack_sequences

    rng = np.random.default_rng(seed + ctx.input_pipeline_id)
    n = ctx.per_host_batch_size

    def examples():
        while True:
            length = int(rng.integers(seq_len // 4, 3 * seq_len // 4))
            ids = rng.integers(4, vocab_size, size=(length,))
            mask = rng.random(length) < mask_rate
            yield {
                "input_ids": np.where(mask, 3, ids),  # 3 = [MASK]
                "labels": np.where(mask, ids, -100),
            }

    rows = pack_sequences(examples(), seq_len, extra_keys=("labels",))
    while True:
        batch = [next(rows) for _ in range(n)]
        yield {
            k: np.stack([r[k] for r in batch]).astype(np.int32)
            for k in batch[0]
        }


def synthetic_lm(ctx: InputContext, *, vocab_size: int, seq_len: int,
                 seed: int = 0) -> Iterator[dict]:
    """Synthetic next-token LM batches (structured so loss can fall)."""
    rng = np.random.default_rng(seed + ctx.input_pipeline_id)
    n = ctx.per_host_batch_size
    while True:
        # Learnable structure: arithmetic sequences mod vocab — next token
        # is predictable from the previous two.
        start = rng.integers(0, vocab_size, size=(n, 1))
        step = rng.integers(1, 7, size=(n, 1))
        ids = (start + step * np.arange(seq_len)) % vocab_size
        yield {"input_ids": ids.astype(np.int32)}


def synthetic_seq2seq(ctx: InputContext, *, vocab_size: int, seq_len: int,
                      pad_id: int, seed: int = 0):
    """Synthetic copy-task batches for the encoder-decoder preset.

    Targets are the encoder stream itself with a random-length pad tail —
    the decoder can only learn it through cross-attention, so a falling
    loss certifies the enc→dec path end to end (same philosophy as
    synthetic_lm's arithmetic sequences).  Token ids avoid pad_id.
    """
    rng = np.random.default_rng(seed + ctx.input_pipeline_id)
    n = ctx.per_host_batch_size
    while True:
        ids = rng.integers(2, vocab_size, size=(n, seq_len))
        lengths = rng.integers(seq_len // 2, seq_len + 1, size=(n, 1))
        keep = np.arange(seq_len) < lengths
        ids = np.where(keep, ids, pad_id).astype(np.int32)
        yield {"encoder_ids": ids, "targets": ids.copy()}


def synthetic_recsys(ctx: InputContext, cfg: WideDeepConfig, seed: int = 0):
    rng = np.random.default_rng(seed + ctx.input_pipeline_id)
    n = ctx.per_host_batch_size
    vocabs = np.array(cfg.vocab_sizes)
    while True:
        cat = (rng.random((n, len(vocabs))) * vocabs).astype(np.int32)
        dense = rng.standard_normal((n, cfg.num_dense_features)).astype(np.float32)
        # learnable rule: label correlates with first categorical parity
        label = ((cat[:, 0] % 2) ^ (dense[:, 0] > 0)).astype(np.int32)
        yield {"categorical": cat, "dense": dense, "label": label}


def _apply_gpt_overrides(cfg, *, seq, remat, attn_impl, xent_impl,
                         kv_heads, attn_window, quant=None):
    """CLI/bench knob overrides shared by the gpt and gpt_moe families.

    ONE definition so a new knob cannot be wired into one preset family
    and silently ignored by the other (the historical failure mode of
    the previously duplicated blocks).  remat: True/False = whole
    blocks; "attn" = attention-only."""
    if (remat is None and attn_impl is None and xent_impl is None
            and kv_heads is None and attn_window is None
            and quant is None and seq <= cfg.max_seq):
        return cfg
    return dataclasses.replace(
        cfg,
        remat=cfg.remat if remat is None else remat is True,
        remat_attn=cfg.remat_attn if remat is None else remat == "attn",
        attn_impl=attn_impl or cfg.attn_impl,
        xent_impl=xent_impl or cfg.xent_impl,
        num_kv_heads=(kv_heads if kv_heads is not None
                      else cfg.num_kv_heads),
        attn_window=(attn_window if attn_window is not None
                     else cfg.attn_window),
        quant=quant if quant is not None else cfg.quant,
        max_seq=max(cfg.max_seq, seq),
    )


def get_workload(name: str, *, test_size: bool = False,
                 global_batch_size: int | None = None,
                 sp_scheme: str = "ring",
                 pp_virtual: int = 1,
                 pp_handoff: str | None = None,
                 pp_schedule: str = "gpipe",
                 seq_len: int | None = None,
                 remat: bool | str | None = None,
                 attn_impl: str | None = None,
                 xent_impl: str | None = None,
                 kv_heads: int | None = None,
                 attn_window: int | None = None,
                 quant: str | None = None) -> Workload:
    """Build a preset by name.  ``test_size`` shrinks models for CI.

    ``sp_scheme`` picks the sequence-parallel attention used by ``gpt_lm``
    on meshes with a ``seq`` axis: ``"ring"`` (ppermute KV rotation, flash
    chunk kernels) or ``"ulysses"`` (all_to_all head<->sequence reshard).
    ``pp_virtual > 1`` selects the circular (interleaved) pipeline schedule
    for ``gpt_lm`` on meshes with a ``pipe`` axis.  ``pp_schedule`` picks
    the pipeline *training* schedule ("gpipe" | "1f1b" | "interleaved" —
    the fb schedules interleave forward and backward microbatches,
    bounding live activations at O(stages) instead of O(n_micro); see
    parallel.pipeline).  ``pp_handoff``
    ("bfloat16" or None) sets the dtype of the pipeline's inter-stage
    ppermute payload — bf16 halves the wire (ICI) traffic, bit-exactly
    for bf16 models; carries/buffers stay fp32 (see
    PipelinedGPT.handoff_dtype).  ``kv_heads`` enables GQA on the
    gpt family (num_kv_heads; see models.gpt.GPTConfig).  ``seq_len`` / ``remat``
    override the LM presets' sequence length and rematerialization (remat
    trades ~1/3 extra FLOPs for activation memory; benches turn it off when
    the batch fits).  ``quant`` ("int8" / "int8_stochastic" / "fp8",
    ops/quant.py) routes the transformer presets' block matmuls through the
    quantized dot; conv/recsys presets have no quantizable dense trunk and
    reject it rather than silently training full-width.
    """
    if quant and quant not in (None, "none"):
        # The MoE presets are excluded on purpose: their expert MLPs (the
        # dominant matmul FLOPs) are raw-einsum weights outside the
        # dense() switch, so accepting quant= would stamp quant_mode on a
        # mostly-full-width run — the mislabeling this check exists to
        # prevent.
        quantizable = ("gpt_lm", "gpt_medium_lm", "lm_long_context",
                       "bert_mlm", "bert_mlm_packed", "imagenet_vit")
        if name not in quantizable:
            raise ValueError(
                f"workload {name!r} has no quantized-compute path; "
                f"quant={quant!r} is supported for: {', '.join(quantizable)}"
            )
    if name == "mnist_lenet":
        model = LeNet5()
        gbs = global_batch_size or 128
        return Workload(
            name=name, model=model,
            loss_fn=classification_loss(model),
            eval_fn=classification_eval(model),
            make_optimizer=lambda: optax.sgd(0.05, momentum=0.9),
            input_fn=_img_input((28, 28, 1), 10),
            init_batch=_img_init((28, 28, 1)),
            init_fn=lambda r: model.init(r, jnp.zeros((2, 28, 28, 1))),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=1),  # OneDeviceStrategy semantics
        )
    if name == "cifar_resnet20":
        model = ResNet20(dtype=jnp.float32 if test_size else jnp.bfloat16)
        gbs = global_batch_size or 256
        return Workload(
            name=name, model=model,
            loss_fn=classification_loss(model, weight_decay=1e-4),
            eval_fn=classification_eval(model),
            make_optimizer=lambda: optax.sgd(0.1, momentum=0.9, nesterov=True),
            input_fn=_img_input((32, 32, 3), 10),
            init_batch=_img_init((32, 32, 3)),
            init_fn=lambda r: model.init(r, jnp.zeros((2, 32, 32, 3))),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),  # MirroredStrategy: all local devices
        )
    if name == "imagenet_resnet50":
        model = ResNet50(dtype=jnp.bfloat16)
        gbs = global_batch_size or 1024
        size = (64, 64, 3) if test_size else (224, 224, 3)
        return Workload(
            name=name, model=model,
            loss_fn=classification_loss(model, weight_decay=1e-4),
            eval_fn=classification_eval(model, top5=True),
            make_optimizer=lambda: optax.sgd(
                optax.warmup_cosine_decay_schedule(0.0, 0.8, 1563, 112_590),
                momentum=0.9, nesterov=True,
            ),
            input_fn=_img_input(size, 1000),
            init_batch=_img_init(size),
            init_fn=lambda r: model.init(r, jnp.zeros((2, *size))),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),  # MultiWorkerMirrored: all devices
        )
    if name == "imagenet_vit":
        from .models import ViT, vit_layout, vit_s16, vit_tiny

        cfg = vit_tiny() if test_size else vit_s16()
        if quant:
            cfg = dataclasses.replace(cfg, quant=quant)
        model = ViT(cfg)
        gbs = global_batch_size or 1024
        size = (cfg.image_size, cfg.image_size, 3)
        return Workload(
            name=name, model=model,
            loss_fn=classification_loss(model),
            eval_fn=classification_eval(model, top5=not test_size),
            # ViT recipe: adamw + cosine (vs the ResNet SGD recipe)
            make_optimizer=lambda: optax.adamw(
                optax.warmup_cosine_decay_schedule(0.0, 3e-3, 1563, 93_750),
                weight_decay=0.05,
            ),
            input_fn=_img_input(size, cfg.num_classes),
            init_batch=_img_init(size),
            init_fn=lambda r: model.init(r, jnp.zeros((2, *size))),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),
            layout=vit_layout(),
        )
    if name in ("bert_mlm", "bert_mlm_packed"):
        # Config #4 (BERT-base MLM, CollectiveAllReduce + grad accum).  The
        # "_packed" variant feeds example-packed rows (multiple short
        # examples per row, segment-restricted attention via the flash
        # kernel's segment support, per-example positions) — the packed
        # pretraining pipeline; everything else is identical.
        packed = name.endswith("_packed")
        cfg = bert_tiny() if test_size else bert_base()
        gbs = global_batch_size or 256
        seq = seq_len or (128 if test_size else 512)
        if seq > cfg.max_position:
            # grow the position table with the override (same contract as
            # the gpt presets' max_seq growth)
            cfg = dataclasses.replace(cfg, max_position=seq)
        if quant:
            cfg = dataclasses.replace(cfg, quant=quant)
        model = BertForMLM(cfg)
        if packed:
            input_fn = lambda ctx, seed: synthetic_packed_mlm(
                ctx, vocab_size=cfg.vocab_size, seq_len=seq, seed=seed
            )
            init_batch = {
                "input_ids": np.zeros((2, seq), np.int32),
                "labels": np.zeros((2, seq), np.int32),
                "segment_ids": np.zeros((2, seq), np.int32),
                "position_ids": np.zeros((2, seq), np.int32),
            }
        else:
            input_fn = lambda ctx, seed: synthetic_mlm(
                ctx, vocab_size=cfg.vocab_size, seq_len=seq, seed=seed
            )
            init_batch = {
                "input_ids": np.zeros((2, seq), np.int32),
                "labels": np.zeros((2, seq), np.int32),
                "attention_mask": np.ones((2, seq), np.int32),
            }
        return Workload(
            name=name, model=model,
            # Gathered MLM head (models.bert.max_predictions_for).
            loss_fn=mlm_loss(model, max_predictions=max_predictions_for(seq)),
            eval_fn=mlm_eval(model, max_predictions=max_predictions_for(seq)),
            make_optimizer=lambda: optax.adamw(1e-4, weight_decay=0.01),
            input_fn=input_fn,
            init_batch=init_batch,
            init_fn=lambda r: model.init(r, jnp.zeros((2, seq), jnp.int32)),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),
            accum_steps=4,  # the reference BERT config's gradient accumulation
            layout=bert_layout(),
        )
    if name == "widedeep":
        cfg = widedeep_test_config() if test_size else WideDeepConfig()
        model = WideDeep(cfg)
        gbs = global_batch_size or 4096
        return Workload(
            name=name, model=model,
            loss_fn=widedeep_loss(model),
            eval_fn=widedeep_eval(model),
            make_optimizer=lambda: optax.adagrad(0.01),
            input_fn=lambda ctx, seed: synthetic_recsys(ctx, cfg, seed),
            init_batch={
                "categorical": np.zeros((2, len(cfg.vocab_sizes)), np.int32),
                "dense": np.zeros((2, cfg.num_dense_features), np.float32),
                "label": np.zeros((2,), np.int32),
            },
            init_fn=lambda r: model.init(
                r,
                jnp.zeros((2, len(cfg.vocab_sizes)), jnp.int32),
                jnp.zeros((2, cfg.num_dense_features)),
            ),
            global_batch_size=gbs,
            # sharded embeddings over model axis (the PS capability)
            mesh_spec=MeshSpec(data=-1),
            layout=widedeep_layout(),
        )
    if name in ("gpt_lm", "gpt_medium_lm", "lm_long_context"):
        from .models import (
            GPTLM,
            gpt_layout,
            gpt_medium,
            gpt_small,
            gpt_tiny,
            lm_eval,
            lm_loss,
        )

        if test_size:
            cfg = gpt_tiny()
        elif name == "gpt_medium_lm":
            cfg = gpt_medium()
        else:
            cfg = gpt_small()
        if name == "lm_long_context" and not test_size:
            # The long-context flagship preset: 8k tokens by default, the
            # flash/ring attention path (its backward stores no (S, S)
            # tensors), attention-only remat.  Any knob still overrides.
            seq_len = seq_len or 8192
            remat = "attn" if remat is None else remat
            attn_impl = attn_impl or "pallas"
        seq = seq_len or (64 if test_size else 2048)
        cfg = _apply_gpt_overrides(
            cfg, seq=seq, remat=remat, attn_impl=attn_impl,
            xent_impl=xent_impl, kv_heads=kv_heads, attn_window=attn_window,
            quant=quant,
        )
        gbs = global_batch_size or (8 if test_size else 64)

        def build(attn_fn=None):
            model = GPTLM(cfg, attn_fn)
            return model, lm_loss(model), lm_eval(model)

        model, loss, ev = build()

        def finalize(wl: Workload, mesh) -> Workload:
            shape = dict(mesh.shape)
            # With a real pipe axis, swap in the GPipe pipeline over the
            # block stack (embed/head outside) — params gain a stage dim,
            # so init_fn and layout change too.
            if shape.get("pipe", 1) > 1:
                # pipe x seq composes: PipelinedGPT detects a real seq axis
                # and runs ring attention inside each stage.
                from .models.gpt_pipeline import (
                    PipelinedGPT,
                    pipelined_lm_eval,
                    pipelined_lm_loss,
                )

                n_micro = 4 * shape["pipe"]
                local_batch = wl.global_batch_size // max(
                    1, shape.get("data", 1) * shape.get("fsdp", 1)
                )
                while n_micro > 1 and local_batch % n_micro:
                    n_micro //= 2
                if pp_schedule == "interleaved":
                    # interleaved grouping: microbatch count must be a
                    # multiple of the stage count
                    while n_micro > shape["pipe"] and (
                        n_micro % shape["pipe"] or local_batch % n_micro
                    ):
                        n_micro -= 1
                pp = PipelinedGPT(cfg, mesh, n_microbatches=n_micro,
                                  n_virtual=pp_virtual, sp_scheme=sp_scheme,
                                  handoff_dtype=pp_handoff,
                                  schedule=pp_schedule)
                return dataclasses.replace(
                    wl,
                    model=pp,
                    loss_fn=pipelined_lm_loss(pp),
                    eval_fn=pipelined_lm_eval(pp),
                    init_fn=pp.init,
                    layout=pp.layout(),
                )
            # With a real seq axis, swap dense attention for the
            # sequence-parallel shard_map region (ring by default) — the
            # long-context path (SURVEY.md §5.7).
            if shape.get("seq", 1) <= 1:
                return wl
            from .parallel.ring_attention import sequence_parallel_attention_fn

            sp_model, sp_loss, sp_eval = build(
                sequence_parallel_attention_fn(
                    mesh, scheme=sp_scheme, causal=True
                )
            )
            return dataclasses.replace(
                wl, model=sp_model, loss_fn=sp_loss, eval_fn=sp_eval
            )

        return Workload(
            name=name, model=model,
            loss_fn=loss,
            eval_fn=ev,
            make_optimizer=lambda: optax.adamw(3e-4, weight_decay=0.1),
            input_fn=lambda ctx, seed: synthetic_lm(
                ctx, vocab_size=cfg.vocab_size, seq_len=seq, seed=seed
            ),
            init_batch={"input_ids": np.zeros((2, seq), np.int32)},
            init_fn=lambda r: model.init(r, jnp.zeros((2, seq), jnp.int32)),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),
            layout=gpt_layout(),
            finalize=finalize,
        )
    if name == "bert_moe":
        # Encoder MoE with EXPERT-CHOICE routing — the EC router's valid
        # domain (acausal; gpt_moe rejects it).  Same MLM task/head as
        # bert_mlm; every other block's MLP is routed over n_experts, and
        # a mesh with a real `expert` axis gets all_to_all dispatch.
        from .models.bert_moe import (
            BertMoEForMLM,
            bert_moe_base,
            bert_moe_layout,
            bert_moe_tiny,
            bind_expert_parallel_bert,
            moe_mlm_loss,
        )

        cfg = bert_moe_tiny() if test_size else bert_moe_base()
        gbs = global_batch_size or 256
        seq = seq_len or (128 if test_size else 512)
        if seq > cfg.max_position:
            cfg = dataclasses.replace(cfg, max_position=seq)
        if quant:
            cfg = dataclasses.replace(cfg, quant=quant)
        model = BertMoEForMLM(cfg)  # local experts until for_mesh
        max_p = max_predictions_for(seq)

        def finalize(wl: Workload, mesh) -> Workload:
            ep_model = bind_expert_parallel_bert(cfg, mesh)
            if ep_model.moe_fn is None:
                return wl
            return dataclasses.replace(
                wl,
                model=ep_model,
                loss_fn=moe_mlm_loss(ep_model, max_predictions=max_p),
                eval_fn=mlm_eval(ep_model, max_predictions=max_p),
            )

        return Workload(
            name=name, model=model,
            loss_fn=moe_mlm_loss(model, max_predictions=max_p),
            eval_fn=mlm_eval(model, max_predictions=max_p),
            make_optimizer=lambda: optax.adamw(1e-4, weight_decay=0.01),
            input_fn=lambda ctx, seed: synthetic_mlm(
                ctx, vocab_size=cfg.vocab_size, seq_len=seq, seed=seed
            ),
            init_batch={
                "input_ids": np.zeros((2, seq), np.int32),
                "labels": np.zeros((2, seq), np.int32),
                "attention_mask": np.ones((2, seq), np.int32),
            },
            init_fn=lambda r: model.init(r, jnp.zeros((2, seq), jnp.int32)),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),
            accum_steps=4,
            layout=bert_moe_layout(),
            finalize=finalize,
        )
    if name == "gpt_moe":
        from .models.gpt_moe import (
            GPTMoELM,
            bind_expert_parallel,
            gpt_moe_layout,
            gpt_moe_small,
            gpt_moe_tiny,
            moe_lm_eval,
            moe_lm_loss,
        )

        cfg = gpt_moe_tiny() if test_size else gpt_moe_small()
        seq = seq_len or (64 if test_size else 2048)
        cfg = _apply_gpt_overrides(
            cfg, seq=seq, remat=remat, attn_impl=attn_impl,
            xent_impl=xent_impl, kv_heads=kv_heads, attn_window=attn_window,
            quant=quant,
        )
        gbs = global_batch_size or (8 if test_size else 64)
        model = GPTMoELM(cfg)  # local (replicated) experts until for_mesh

        def finalize(wl: Workload, mesh) -> Workload:
            # With a real expert axis, swap in the all_to_all shard_map
            # dispatch region (SURVEY.md §2.4 EP row).
            ep_model = bind_expert_parallel(cfg, mesh)
            if ep_model.moe_fn is None:
                return wl
            return dataclasses.replace(
                wl, model=ep_model, loss_fn=moe_lm_loss(ep_model),
                eval_fn=moe_lm_eval(ep_model),
            )

        return Workload(
            name=name, model=model,
            loss_fn=moe_lm_loss(model),
            eval_fn=moe_lm_eval(model),
            make_optimizer=lambda: optax.adamw(3e-4, weight_decay=0.1),
            input_fn=lambda ctx, seed: synthetic_lm(
                ctx, vocab_size=cfg.vocab_size, seq_len=seq, seed=seed
            ),
            init_batch={"input_ids": np.zeros((2, seq), np.int32)},
            init_fn=lambda r: model.init(r, jnp.zeros((2, seq), jnp.int32)),
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),
            layout=gpt_moe_layout(),
            finalize=finalize,
        )
    if name == "t5_seq2seq":
        # Encoder-decoder seq2seq (the T5-class family; models/seq2seq.py
        # docstring records the TPU-first deviations).  Synthetic copy
        # task: the decoder must reproduce the encoder stream, which is
        # unlearnable without working cross-attention.
        from .models.seq2seq import (
            Seq2SeqLM,
            seq2seq_eval,
            seq2seq_layout,
            seq2seq_loss,
            seq2seq_small,
            seq2seq_tiny,
        )

        cfg = seq2seq_tiny() if test_size else seq2seq_small()
        seq = seq_len or (32 if test_size else 256)
        if seq > cfg.max_seq:  # grow the declared envelope with overrides
            cfg = dataclasses.replace(cfg, max_seq=seq)
        if kv_heads is not None:
            cfg = dataclasses.replace(cfg, num_kv_heads=kv_heads)
        model = Seq2SeqLM(cfg)
        gbs = global_batch_size or (8 if test_size else 64)

        def s2s_init(r):
            z = jnp.zeros((2, seq), jnp.int32)
            return model.init(r, z, z)

        return Workload(
            name=name, model=model,
            loss_fn=seq2seq_loss(model),
            eval_fn=seq2seq_eval(model),
            make_optimizer=lambda: optax.adamw(3e-4, weight_decay=0.1),
            input_fn=lambda ctx, seed: synthetic_seq2seq(
                ctx, vocab_size=cfg.vocab_size, seq_len=seq,
                pad_id=cfg.pad_id, seed=seed,
            ),
            init_batch={
                "encoder_ids": np.zeros((2, seq), np.int32),
                "targets": np.zeros((2, seq), np.int32),
            },
            init_fn=s2s_init,
            global_batch_size=gbs,
            mesh_spec=MeshSpec(data=-1),
            layout=seq2seq_layout(cfg),
        )
    raise ValueError(
        f"unknown workload {name!r}; known: mnist_lenet cifar_resnet20 "
        "imagenet_resnet50 imagenet_vit bert_mlm bert_mlm_packed bert_moe "
        "widedeep gpt_lm gpt_medium_lm lm_long_context gpt_moe t5_seq2seq"
    )


WORKLOADS = (
    "mnist_lenet", "cifar_resnet20", "imagenet_resnet50", "imagenet_vit",
    "bert_mlm", "bert_mlm_packed", "bert_moe", "widedeep", "gpt_lm",
    "gpt_medium_lm", "lm_long_context", "gpt_moe", "t5_seq2seq",
)
