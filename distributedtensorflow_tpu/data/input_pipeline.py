"""Distributed input pipeline: host data → sharded global device batches.

Replaces the reference's L5 layer (SURVEY.md §1, §3.4): ``DistributedDataset``
auto-sharding + rebatching + prefetch-to-device.  The structure maps directly:

- ``AutoShardPolicy.DATA`` → ``tf.data`` ``shard(num_processes, process_index)``
  applied per host (:func:`shard_dataset`);
- rebatch-to-per-replica → nothing: each host feeds its *local* slice and
  ``jax.make_array_from_process_local_data`` assembles the logical global
  batch across hosts (:func:`device_put_batch`);
- prefetch-to-device → a small background-thread prefetcher
  (:class:`Prefetcher`).

``tf.data`` remains the host-side engine per the north star ("the tf.data
input pipeline feeds TPU host infeed unchanged" — BASELINE.json).  Synthetic
sources cover the no-network sandbox and perf benchmarking (host-input-bound
vs compute-bound separation).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from .. import obs
from .adaptive import (  # noqa: F401  (re-exported API surface)
    AdaptiveDepthController,
    input_record_fields,
)
from ..parallel import sharding as shardlib

logger = logging.getLogger("distributedtensorflow_tpu")

PyTree = Any


def _host_bytes(tree: PyTree) -> int:
    """Host bytes of a (pre-placement) numpy batch pytree."""
    return sum(
        int(getattr(x, "nbytes", 0)) for x in jax.tree.leaves(tree)
    )


@dataclasses.dataclass(frozen=True)
class InputContext:
    """Per-host input split info (reference: ``tf.distribute.InputContext``,
    ``distribute_lib.py:841``)."""

    num_input_pipelines: int = 1
    input_pipeline_id: int = 0
    global_batch_size: int = 0

    @property
    def per_host_batch_size(self) -> int:
        if self.global_batch_size % self.num_input_pipelines:
            raise ValueError(
                f"global batch {self.global_batch_size} not divisible by "
                f"{self.num_input_pipelines} hosts"
            )
        return self.global_batch_size // self.num_input_pipelines


def current_input_context(global_batch_size: int) -> InputContext:
    return InputContext(
        num_input_pipelines=jax.process_count(),
        input_pipeline_id=jax.process_index(),
        global_batch_size=global_batch_size,
    )


def shard_dataset(ds, ctx: InputContext):
    """Apply DATA-policy sharding to a tf.data.Dataset (one shard per host)."""
    if ctx.num_input_pipelines > 1:
        ds = ds.shard(ctx.num_input_pipelines, ctx.input_pipeline_id)
    return ds


def device_put_batch(batch: PyTree, mesh: Mesh) -> PyTree:
    """Assemble a host-local numpy batch into a global sharded jax.Array.

    Each process passes its local slice; the result is a logically global
    array whose leading dim is sharded over the mesh batch axes — the
    ``PerReplica``-values handoff of the reference (``values.py:356``) with
    no wrapper type.
    """
    sharding = NamedSharding(mesh, shardlib.batch_spec(mesh))

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(put, batch)


def device_put_bundle(batches: Sequence[PyTree], mesh: Mesh) -> PyTree:
    """Stack ``k`` host-local batches and place them as ONE global array
    per leaf with shape ``(k, B, ...)`` — the input contract of
    ``engine.make_multi_train_step`` (leading step dim REPLICATED, batch
    dim sharded over the mesh batch axes).

    The stack happens on host numpy BEFORE placement: stacking k
    already-placed global arrays would put the step dim under the batch
    sharding, which a multi-controller jit rejects (shardings of committed
    arguments must match exactly — there is no implicit cross-process
    reshard).
    """
    sharding = NamedSharding(
        mesh, shardlib.batch_spec(mesh, leading_unsharded=1)
    )

    def put(*xs):
        x = np.stack([np.asarray(v) for v in xs])
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(put, *batches)


class Prefetcher:
    """Background-thread host→device prefetch (reference:
    ``_SingleWorkerOwnedDatasetIterator`` prefetch-to-device, SURVEY.md §3.4).

    Keeps ``buffer_size`` batches in flight so host input overlaps TPU step
    time.  Device transfer happens on the worker thread; the training loop
    pops ready global arrays.

    ``bundle > 1`` stacks that many consecutive host batches into one
    ``(bundle, B, ...)`` global array per pop (:func:`device_put_bundle`)
    — feeding ``steps_per_call`` training without any device-side
    restacking.  A trailing partial group (source ended mid-bundle) is
    yielded at its true (shorter) length so the consumer sees exactly the
    batches that exist; the Trainer treats a too-short final bundle as
    end-of-data (StopIteration parity with per-step iteration).

    ``adaptive=True`` hands the depth to an
    :class:`AdaptiveDepthController` seeded at ``buffer_size``: the
    worker admits new batches only while fewer than the LIVE depth are
    buffered, so the queue deepens while the consumer blocks on data
    (input-bound — absorb the jitter) and shallows when waits are ~0
    (each buffered batch is device memory held for nothing), bounded by
    ``[1, max_depth]`` and ``bytes_budget`` host bytes.  The live depth
    is exported as the ``data_prefetch_depth{component="prefetcher"}``
    gauge and the ``data_prefetch_depth`` per-record field.
    """

    _DONE = object()

    def __init__(self, it: Iterable[PyTree], mesh: Mesh, buffer_size: int = 2,
                 *, bundle: int = 1, adaptive: bool = False,
                 max_depth: int = 16, bytes_budget: int | None = None,
                 controller: "AdaptiveDepthController | None" = None):
        self._mesh = mesh
        self._bundle = bundle
        if controller is None and adaptive:
            controller = AdaptiveDepthController(
                initial=buffer_size,
                min_depth=1,
                max_depth=max_depth,
                bytes_budget=bytes_budget,
                component="prefetcher",
            )
        self._controller = controller
        self._depth = max(1, int(buffer_size))
        # Unbounded queue; admission is gated on the LIVE depth via the
        # condition below (a Queue's maxsize is frozen at construction).
        self._q: queue.Queue = queue.Queue()
        self._cond = threading.Condition()
        self._err: BaseException | None = None
        self._stop = threading.Event()
        # obs registry handles, resolved once (hot-path discipline).  The
        # wait histogram is the input-bound-vs-compute-bound signal: near-0
        # waits = input keeps up; waits ~ step time = input-bound.
        self._m_batches = obs.counter(
            "data_batches_total", "batches handed to the consumer"
        )
        self._m_wait = obs.histogram(
            "data_wait_seconds", "consumer blocking time per batch fetch"
        )
        self._m_put = obs.histogram(
            "data_device_put_seconds", "host->device placement time per batch"
        )
        self._src = it  # kept so close() can release the source too
        # Consumption acknowledgement (exactly-once across an elastic
        # resize): sources exposing note_consumed(n) — DataServiceClient —
        # get told when batches actually reach the consumer, so batches
        # still in OUR buffer at close are never journaled as trained.
        self._note_consumed = getattr(it, "note_consumed", None)
        self._thread = threading.Thread(
            target=self._run, args=(iter(it),), daemon=True
        )
        self._thread.start()

    def _batches(self, it: Iterator[PyTree]) -> Iterator[PyTree]:
        if self._bundle <= 1:
            yield from it
            return
        while True:
            group = list(itertools.islice(it, self._bundle))
            if group:
                yield group
            if len(group) < self._bundle:
                return

    def _admit(self, item) -> bool:
        """Admission gate on the LIVE depth; re-checks stop so close()
        can't deadlock against a full buffer."""
        with self._cond:
            while not self._stop.is_set() and self._q.qsize() >= self._live_depth():
                self._cond.wait(0.1)
            if self._stop.is_set():
                return False
            self._q.put(item)
            return True

    def _live_depth(self) -> int:
        return self._controller.depth if self._controller else self._depth

    def _run(self, it: Iterator[PyTree]):
        try:
            for batch in self._batches(it):
                if self._stop.is_set():
                    return
                if self._controller is not None:
                    # Budget unit: host bytes of the (pre-placement) batch.
                    self._controller.note_bytes(_host_bytes(batch))
                t0 = time.perf_counter()
                out = (
                    device_put_bundle(batch, self._mesh)
                    if self._bundle > 1
                    else device_put_batch(batch, self._mesh)
                )
                self._m_put.observe(time.perf_counter() - t0)
                # Items ride with their source-batch count (a trailing
                # partial bundle is shorter) so __next__ can acknowledge
                # the exact consumption to the source.
                count = len(batch) if self._bundle > 1 else 1
                if not self._admit((out, count)):
                    return
        except BaseException as e:  # surfaced on the consumer thread
            self._err = e
        finally:
            # The DONE sentinel must not be droppable: a lost sentinel
            # leaves the consumer blocked forever after draining the
            # buffered batches (finite sources end while the buffer is
            # full whenever the consumer is slower than the producer).
            # Same admission gate, yielding to close().
            self._admit(self._DONE)

    def close(self) -> None:
        """Stop the worker and release buffered device batches.

        Must be called for finite consumption of an endless source (e.g. an
        eval round over an infinite iterator), else the thread parks holding
        ``buffer_size`` global batches in device memory.
        """
        self._stop.set()
        with self._cond:
            self._cond.notify_all()  # wake a producer parked on admission
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5)
        # Release the SOURCE too: a DataServiceClient left open would keep
        # one fetcher thread + persistent worker connection per split
        # streaming forever (every supervised restart would leak a set);
        # generator sources get their GeneratorExit.
        close = getattr(self._src, "close", None)
        if callable(close):
            try:
                close()
            except Exception:  # pragma: no cover - source cleanup only
                logger.warning("input source close() failed", exc_info=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = self._q.get()
        wait = time.perf_counter() - t0
        self._m_wait.observe(wait)
        with self._cond:
            self._cond.notify_all()  # freed a buffer slot
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            raise StopIteration
        if self._controller is not None:
            self._controller.observe_wait(wait)
        self._m_batches.inc()
        out, count = item
        if self._note_consumed is not None:
            self._note_consumed(count)
        return out

    @property
    def depth(self) -> int:
        """The live prefetch depth (fixed unless ``adaptive=True``)."""
        return self._live_depth()


# --- Sources -----------------------------------------------------------------


def synthetic_classification(
    ctx: InputContext,
    *,
    image_shape: tuple[int, ...],
    num_classes: int,
    seed: int = 0,
    dtype=np.float32,
    steps: int | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Endless synthetic labeled images (per-host slice of the global batch).

    Class-conditional means keep the task learnable so smoke tests can assert
    loss decrease; generation cost is negligible next to real decode/augment.
    """
    rng = np.random.default_rng(seed + ctx.input_pipeline_id)
    n = ctx.per_host_batch_size
    i = 0
    while steps is None or i < steps:
        labels = rng.integers(0, num_classes, size=(n,))
        images = rng.standard_normal((n, *image_shape), dtype=np.float32) * 0.1
        images += (labels / num_classes).reshape((n,) + (1,) * len(image_shape))
        yield {"image": images.astype(dtype), "label": labels.astype(np.int32)}
        i += 1


def tfdata_iterator(ds) -> Iterator[PyTree]:
    """Iterate a tf.data.Dataset as numpy pytrees (host-side)."""
    for batch in ds.as_numpy_iterator():
        yield batch


def make_input_fn_dataset(
    input_fn: Callable[[InputContext], Any], global_batch_size: int
):
    """``distribute_datasets_from_function`` equivalent (``input_lib.py:1077``):
    the user fn sees an InputContext and returns a per-host dataset/iterator."""
    ctx = current_input_context(global_batch_size)
    return input_fn(ctx), ctx


def pack_sequences(
    examples,
    seq_len: int,
    *,
    pad_value: int = 0,
    extra_keys: Sequence[str] = (),
    fill_values: dict | None = None,
):
    """Greedy next-fit packing of variable-length token examples.

    The packed-pretraining input transform (BERT/T5-style example packing):
    each output row concatenates whole examples in arrival order until the
    next one no longer fits (next-fit: only the currently open row is
    considered — streaming-friendly; a bin-packing first-fit would trade
    memory for slightly denser rows).  Emits ``segment_ids`` (1-based per
    packed example, 0 = padding) and ``position_ids`` (restarting at 0 per
    example) so attention stays within segments (``ops.flash_attention``
    segment support) and positions are per-example.

    ``examples`` is an iterable of dicts with an ``input_ids`` 1-D array
    plus any ``extra_keys`` (same length, packed alongside).  Padding fill
    per extra key comes from ``fill_values``; keys ending in ``"labels"``
    default to ``-100`` (the ignore-index convention ``mlm_loss`` masks
    on), everything else to ``pad_value`` — pass ``fill_values`` explicitly
    for label-like keys under other names.

    Yields dicts of (seq_len,) int32 arrays: ``input_ids``, ``segment_ids``,
    ``position_ids``, and each extra key.  An example longer than
    ``seq_len`` is truncated.
    """
    fills = {
        key: (fill_values or {}).get(
            key, -100 if key.endswith("labels") else pad_value
        )
        for key in extra_keys
    }

    def new_row():
        row = {
            "input_ids": np.full(seq_len, pad_value, np.int32),
            "segment_ids": np.zeros(seq_len, np.int32),
            "position_ids": np.zeros(seq_len, np.int32),
        }
        for key in extra_keys:
            row[key] = np.full(seq_len, fills[key], np.int32)
        return row, 0, 0  # row, used, n_segments

    row, used, n_seg = new_row()
    for ex in examples:
        ids = np.asarray(ex["input_ids"], np.int32)[:seq_len]
        n = len(ids)
        if n == 0:
            continue
        if used + n > seq_len:
            yield row
            row, used, n_seg = new_row()
        sl = slice(used, used + n)
        row["input_ids"][sl] = ids
        row["segment_ids"][sl] = n_seg + 1
        row["position_ids"][sl] = np.arange(n)
        for key in extra_keys:
            row[key][sl] = np.asarray(ex[key], np.int32)[:n]
        used += n
        n_seg += 1
    if used:
        yield row


def skip_batches(it: Iterator[PyTree], n: int) -> Iterator[PyTree]:
    """Fast-forward an input iterator past ``n`` already-consumed batches.

    The resume-position half of the reference's tf.data iterator
    checkpointing (`input_lib.py` iterators save their position with the
    model): our inputs are deterministic functions of (seed, step), so
    restoring to step N means draining N batches — otherwise a resumed run
    re-trains on the first N batches and diverges from the uninterrupted
    run.  Generation-cost note: synthetic sources regenerate in microseconds;
    recordio sources re-read (the tf.data ``skip()`` cost) — callers with a
    step-keyed source can seek instead.
    """
    # Spanned as part of restore cost: re-reading N batches is real resume
    # wall time, and the goodput ledger books `input_fastforward` under its
    # `checkpoint_restore` bucket.
    with obs.span("input_fastforward"):
        for i in range(n):
            try:
                next(it)
            except StopIteration:
                logger.warning(
                    "input exhausted after skipping %d/%d batches on resume",
                    i, n,
                )
                break
    return it
