"""Record-file datasets on the native C++ reader, with auto-sharding.

Connects the compiled record IO (``native.RecordReader`` — the tf.data
C++-reader role) to the input pipeline, reproducing the reference's
``AutoShardPolicy`` semantics (SURVEY.md §2.3: `options.py:89`
{OFF, AUTO, FILE, DATA}, graph-rewrite in `input_ops.py:28`):

- **FILE**: each host reads a disjoint subset of the files — zero wasted
  IO, requires ``len(files) % num_hosts == 0`` for balance (the reference
  errors likewise when files < workers).
- **DATA**: every host reads every file but keeps only its every-k-th
  record — works for any file count, k-1/k of decode bandwidth wasted
  (exactly the reference's trade-off).
- **AUTO**: FILE when the file count divides evenly, else DATA.
- **OFF**: no sharding (every host sees everything).

Examples on disk are raw-tensor-wire feature dicts (``data.wire``: one
JSON header + raw array bytes per record — numpy arrays only, no pickle,
no per-record zip container), written by :func:`write_example`.
:func:`decode_example` sniffs the payload, so files written by the older
``.npz``-per-record codec keep reading; the record framing's own CRC32C
already covers integrity, so the wire-level checksum stays off here.
"""

from __future__ import annotations

import io
from collections.abc import Callable, Iterator, Sequence

import numpy as np

from . import wire as wirelib
from .input_pipeline import InputContext
from ..native import RecordReader, RecordWriter

Example = dict[str, np.ndarray]


def encode_example(example: Example, wire: str = "raw") -> bytes:
    """Serialize one example (``wire="raw"`` default; ``"npz"`` writes the
    legacy per-record zip archive for old readers)."""
    if wire == "raw":
        return wirelib.encode_tensors(example)
    if wire != "npz":
        raise ValueError(f"unknown wire format {wire!r}")
    buf = io.BytesIO()
    np.savez(buf, **example)
    return buf.getvalue()


def decode_example(record: bytes) -> Example:
    if wirelib.is_raw(record):
        return wirelib.decode_tensors(record)
    with np.load(io.BytesIO(record)) as z:
        return {k: z[k] for k in z.files}


def write_example(writer: RecordWriter, example: Example) -> None:
    writer.write(encode_example(example))


def _resolve_policy(policy: str, n_files: int, n_hosts: int) -> str:
    policy = policy.upper()
    if policy == "AUTO":
        return "FILE" if n_files % n_hosts == 0 else "DATA"
    if policy not in ("FILE", "DATA", "OFF"):
        raise ValueError(f"unknown shard policy {policy!r}")
    return policy


def _shuffled(examples_fn, buffer_size: int, rng) -> Callable[[], Iterator[Example]]:
    """Streaming shuffle over an iterator factory (host-side, post-shard)."""

    def gen() -> Iterator[Example]:
        buf: list[Example] = []
        for ex in examples_fn():
            buf.append(ex)
            if len(buf) >= buffer_size:
                ix = int(rng.integers(len(buf)))
                buf[ix], buf[-1] = buf[-1], buf[ix]
                yield buf.pop()
        rng.shuffle(buf)
        yield from buf

    return gen


def record_dataset(
    files: Sequence[str],
    ctx: InputContext | None = None,
    *,
    batch_size: int | None = None,
    policy: str = "AUTO",
    decode_fn: Callable[[bytes], Example] = decode_example,
    shuffle_buffer: int = 0,
    seed: int = 0,
    num_threads: int | None = None,
    drop_remainder: bool = True,
) -> Iterator[Example]:
    """Stream batches from record files, sharded per host.

    ``num_threads=None`` (the default) gates reader threads on the host:
    ``min(4, cpu_count)`` — on a 1-core host extra reader threads only add
    contention (measured: 4t slower than 1t, bench_input.py).  Pass an
    explicit value to force it (e.g. for file interleaving semantics).

    Yields dicts of stacked arrays with a leading ``batch_size`` dim (the
    per-host batch; pass ``ctx.per_host_batch_size`` upstream).  With
    ``batch_size=None`` yields individual decoded examples.

    Argument validation happens HERE, eagerly — not at first iteration —
    so a config typo fails at job setup rather than inside a prefetch
    thread mid-training.
    """
    files = list(files)
    if not files:
        raise ValueError("record_dataset needs at least one file")
    if num_threads is None:
        from ..native.recordio import available_cpus

        # CPUs this PROCESS may use (affinity/cgroup-aware), not the
        # machine's core count — a container pinned to 1 CPU on a 64-core
        # host must not spawn 4 contending readers.
        num_threads = max(1, min(4, available_cpus()))
    n_hosts = ctx.num_input_pipelines if ctx else 1
    host = ctx.input_pipeline_id if ctx else 0
    policy = _resolve_policy(policy, len(files), n_hosts)

    if policy == "FILE" and n_hosts > 1:
        if len(files) < n_hosts:
            raise ValueError(
                f"FILE sharding needs >= 1 file per host "
                f"({len(files)} files, {n_hosts} hosts)"
            )
        files = files[host::n_hosts]

    return _record_dataset_iter(
        files, policy, host, n_hosts, batch_size=batch_size,
        decode_fn=decode_fn, shuffle_buffer=shuffle_buffer, seed=seed,
        num_threads=num_threads, drop_remainder=drop_remainder,
    )


def _record_dataset_iter(
    files, policy, host, n_hosts, *, batch_size, decode_fn, shuffle_buffer,
    seed, num_threads, drop_remainder,
) -> Iterator[Example]:
    data_sharded = policy == "DATA" and n_hosts > 1
    # DATA sharding partitions by *stream position*, so every host must see
    # the IDENTICAL stream order: single reader thread, no native shuffle,
    # host-independent everything.  Shuffling then happens host-side (below)
    # on the post-shard subset.  FILE/OFF streams are per-host already, so
    # the native threaded reader + in-reader shuffle are safe there.
    reader = RecordReader(
        files,
        num_threads=1 if data_sharded else num_threads,
        shuffle_buffer=0 if data_sharded else shuffle_buffer,
        seed=seed * 1_000_003 + host,
    )

    def examples() -> Iterator[Example]:
        with reader:
            for i, record in enumerate(reader):
                if data_sharded and i % n_hosts != host:
                    continue
                yield decode_fn(record)

    if data_sharded and shuffle_buffer > 1:
        examples = _shuffled(
            examples, shuffle_buffer,
            np.random.default_rng(seed * 1_000_003 + host),
        )

    if batch_size is None:
        yield from examples()
        return

    stack: list[Example] = []
    for ex in examples():
        stack.append(ex)
        if len(stack) == batch_size:
            yield {
                k: np.stack([e[k] for e in stack]) for k in stack[0]
            }
            stack = []
    if stack and not drop_remainder:
        yield {k: np.stack([e[k] for e in stack]) for k in stack[0]}


def write_record_shards(
    examples: Iterator[Example],
    path_template: str,  # e.g. "/data/train-{:05d}.rec"
    *,
    num_shards: int,
) -> list[str]:
    """Round-robin examples into ``num_shards`` record files; returns paths."""
    paths = [path_template.format(i) for i in range(num_shards)]
    writers = [RecordWriter(p) for p in paths]
    try:
        for i, ex in enumerate(examples):
            write_example(writers[i % num_shards], ex)
    finally:
        for w in writers:
            w.close()
    return paths


def repeated_record_dataset(
    files: Sequence[str],
    ctx: InputContext | None = None,
    *,
    batch_size: int | None = None,
    policy: str = "AUTO",
    decode_fn: Callable[[bytes], Example] = decode_example,
    shuffle_buffer: int = 0,
    seed: int = 0,
    on_epoch=None,
) -> Iterator[Example]:
    """Endless epoch-cycling stream over record files (tf.data ``repeat()``).

    Finite files must not end training with StopIteration; each epoch
    reshuffles with ``seed + epoch``.  ``on_epoch(epoch)`` (optional) is
    called after each completed pass — the trainer logs it.
    """
    epoch = 0
    while True:
        yielded = False
        for batch in record_dataset(
            files, ctx, batch_size=batch_size, policy=policy,
            decode_fn=decode_fn, shuffle_buffer=shuffle_buffer,
            seed=seed + epoch,
        ):
            yielded = True
            yield batch
        if not yielded:
            # drop_remainder batching of an undersized shard: without this
            # the loop would re-read the files forever yielding nothing.
            raise ValueError(
                f"record epoch produced 0 batches from {len(files)} files "
                f"(batch_size={batch_size}): this host's shard holds fewer "
                "examples than one batch — shrink the batch or add data"
            )
        epoch += 1
        if on_epoch is not None:
            on_epoch(epoch)
