"""Raw tensor wire format for the input plane.

Replaces per-batch ``np.savez``/``np.load`` on the data-service and
record-file hot paths.  The npz archive costs a zip container per batch
(central directory, per-member headers, a full payload memcpy through the
``ZipFile`` machinery on BOTH ends); at pod-scale input rates that is pure
protocol tax.  This format is one JSON header describing the tensors plus
their raw bytes back to back:

``"DTW1" | uint32 LE header_len | header JSON | payload``

- header: ``{"v": 1, "t": [{"name", "dtype", "shape"}, ...], "crc": int?}``
  — tensor order is the dict's insertion order; each tensor's byte length
  is ``prod(shape) * itemsize``, so no offsets are stored;
- payload: each tensor's C-contiguous bytes, concatenated in header order;
- ``crc``: optional CRC32C of the payload (hardware-accelerated via the
  native layer when available — the same ``crc32c`` the record framing
  uses).  Encoding with ``crc=True`` degrades to no checksum when the
  native library cannot load; decoding verifies only when both sides have
  the checksum.

Decoding is zero-copy: each array is a read-only ``np.frombuffer`` view
into the received buffer (consumers that mutate batches must copy — the
training path stacks/places them, which already does).

Legacy npz payloads start with the zip magic ``PK\\x03\\x04``, so
:func:`is_raw` lets one decoder sniff both formats (rolling-upgrade and
old-file compatibility).
"""

from __future__ import annotations

import json
import math
import struct
from typing import Any, Mapping

import numpy as np

MAGIC = b"DTW1"
_HEADER_LEN = struct.Struct("<I")

#: Wire formats the service negotiates per request.
WIRE_FORMATS = ("raw", "npz")


class WireError(ValueError):
    """Malformed, truncated, or checksum-failing raw wire payload."""


def _crc32c(data) -> int | None:
    """CRC32C via the native layer; None when it cannot load (the wire
    then carries / verifies no checksum rather than failing the batch)."""
    try:
        from ..native import crc32c
        return int(crc32c(bytes(data)))
    except Exception:  # missing toolchain, load failure — degrade, not die
        return None


def encode_tensors(tensors: Mapping[str, Any], *, crc: bool = False,
                   trace: Mapping[str, str] | None = None) -> bytes:
    """Serialize a dict of arrays to the raw wire format.

    Arrays are made C-contiguous (a copy only when the input is not);
    object/void dtypes are rejected — the wire carries numeric/bool bytes
    only, never pickle.

    ``trace`` (a ``{"trace_id", "span_id"}`` dict — the distributed
    request-tracing context of ``obs.tracing``) is echoed verbatim in the
    header so a traced batch carries its trace id end to end; decoders
    that don't care ignore it, :func:`peek_trace` reads it back.
    """
    meta = []
    parts: list[bytes | memoryview] = []
    for name, value in tensors.items():
        a = np.asarray(value)
        if not a.flags["C_CONTIGUOUS"]:
            # NOT ascontiguousarray unconditionally: that helper promotes
            # 0-d arrays to shape (1,), silently changing the decoded rank.
            a = np.ascontiguousarray(a)
        if a.dtype.hasobject or a.dtype.kind == "V":
            raise WireError(
                f"tensor {name!r} has non-wire dtype {a.dtype!r} "
                "(numeric/bool arrays only)"
            )
        meta.append({
            "name": str(name),
            "dtype": a.dtype.str,
            "shape": list(a.shape),
        })
        # memoryview.cast rejects 0-d and zero-size views; tobytes() on
        # those copies nothing meaningful anyway.
        if a.ndim == 0 or a.size == 0:
            parts.append(a.tobytes())
        else:
            parts.append(memoryview(a).cast("B"))
    header: dict = {"v": 1, "t": meta}
    if trace:
        header["trace"] = {str(k): str(v) for k, v in dict(trace).items()}
    if crc:
        # The checksum needs the contiguous payload; this path pays one
        # extra full-payload copy.
        payload = b"".join(parts)
        c = _crc32c(payload)
        if c is not None:
            header["crc"] = c
        parts = [payload]
    hdr = json.dumps(header, separators=(",", ":")).encode()
    # One join = one copy of the tensor bytes (the memcpy the npz zip
    # container paid twice is the tax this format exists to remove).
    return b"".join([MAGIC, _HEADER_LEN.pack(len(hdr)), hdr, *parts])


def is_raw(data) -> bool:
    """True when ``data`` starts with the raw-wire magic."""
    return bytes(data[:4]) == MAGIC


def peek_header(data) -> dict:
    """Parse and return just the JSON header of a raw payload (no tensor
    decode, no CRC verification) — cheap wire introspection."""
    mv = memoryview(data)
    if bytes(mv[:4]) != MAGIC:
        raise WireError("not a raw tensor payload (bad magic)")
    if len(mv) < 8:
        raise WireError("truncated header length")
    (hlen,) = _HEADER_LEN.unpack(mv[4:8])
    if 8 + hlen > len(mv):
        raise WireError("truncated header")
    try:
        header = json.loads(bytes(mv[8:8 + hlen]))
    except json.JSONDecodeError as e:
        raise WireError(f"bad header JSON: {e}") from e
    if not isinstance(header, dict):
        raise WireError("header is not an object")
    return header


def peek_trace(data) -> dict | None:
    """The echoed trace context of a raw payload (``encode_tensors``'s
    ``trace=``), or None — including for npz payloads, which carry none."""
    if not is_raw(data):
        return None
    trace = peek_header(data).get("trace")
    return trace if isinstance(trace, dict) else None


def decode_tensors(data) -> dict[str, np.ndarray]:
    """Parse a raw wire payload into ``{name: read-only array view}``."""
    mv = memoryview(data)
    if bytes(mv[:4]) != MAGIC:
        raise WireError("not a raw tensor payload (bad magic)")
    if len(mv) < 8:
        raise WireError("truncated header length")
    (hlen,) = _HEADER_LEN.unpack(mv[4:8])
    if 8 + hlen > len(mv):
        raise WireError("truncated header")
    try:
        header = json.loads(bytes(mv[8:8 + hlen]))
    except json.JSONDecodeError as e:
        raise WireError(f"bad header JSON: {e}") from e
    if not isinstance(header, dict) or header.get("v") != 1:
        raise WireError(f"unsupported wire version {header.get('v')!r}")
    payload = mv[8 + hlen:]
    want_crc = header.get("crc")
    if want_crc is not None:
        got = _crc32c(payload)
        if got is not None and got != want_crc:
            raise WireError(
                f"payload CRC32C mismatch (got {got}, header {want_crc})"
            )
    out: dict[str, np.ndarray] = {}
    offset = 0
    for t in header.get("t", ()):
        try:
            dt = np.dtype(t["dtype"])
            shape = tuple(int(d) for d in t["shape"])
            name = t["name"]
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f"bad tensor entry {t!r}: {e}") from e
        count = math.prod(shape)
        nbytes = count * dt.itemsize
        if offset + nbytes > len(payload):
            raise WireError(
                f"tensor {name!r} overruns payload "
                f"({offset + nbytes} > {len(payload)} bytes)"
            )
        out[name] = np.frombuffer(
            payload, dtype=dt, count=count, offset=offset
        ).reshape(shape)
        offset += nbytes
    if offset != len(payload):
        raise WireError(
            f"{len(payload) - offset} trailing payload bytes after the "
            "declared tensors"
        )
    return out


def tensor_bytes(tensors: Mapping[str, Any]) -> int:
    """Host bytes of a batch (the adaptive-prefetch budget unit)."""
    return sum(np.asarray(v).nbytes for v in tensors.values())
