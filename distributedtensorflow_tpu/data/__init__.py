"""Distributed input pipeline (host tf.data / synthetic → sharded device batches)."""

from .recordio_dataset import (  # noqa: F401
    decode_example,
    encode_example,
    record_dataset,
    repeated_record_dataset,
    write_example,
    write_record_shards,
)
from .service import (  # noqa: F401
    DataServiceClient,
    DispatcherJournal,
    DispatchServer,
    WorkerServer,
)
from .wire import (  # noqa: F401
    WireError,
    decode_tensors,
    encode_tensors,
)
from .input_pipeline import (  # noqa: F401
    AdaptiveDepthController,
    InputContext,
    Prefetcher,
    input_record_fields,
    current_input_context,
    device_put_batch,
    device_put_bundle,
    make_input_fn_dataset,
    pack_sequences,
    shard_dataset,
    skip_batches,
    synthetic_classification,
    tfdata_iterator,
)
