"""Disaggregated input service: dispatcher + data workers + client.

The tf.data-service equivalent (SURVEY.md §2.3: ``DispatchServer``
`tf/python/data/experimental/service/server_lib.py:131`, ``WorkerServer``
`:349`): input preprocessing runs on a separate pool of cheap CPU hosts so
TPU hosts never stall on data.  Shapes of the design kept from the
reference; the implementation is this framework's own socket protocol (the
reference's is gRPC/protobuf into the tf.data C++ runtime):

- a **dispatcher** process tracks the worker pool and assigns each worker a
  shard index (``distributed_epoch`` semantics: the dataset is partitioned
  across workers, every element produced exactly once per epoch);
- **data workers** run the actual input pipeline (e.g. the native
  ``RecordReader`` + decode) and serve batches over TCP;
- the **client** (one per trainer host) round-robins over workers; a worker
  death mid-epoch drops that worker's remaining shard after a configurable
  policy (``ignore_errors=True``) or raises — the reference's fault
  semantics for dynamic worker pools.

Wire format: every frame is ``uint64 LE length + payload``.  A request is
one JSON frame; a response is one JSON frame optionally followed by one
binary frame carrying an ``.npz`` archive of the batch (numpy arrays only —
no pickle on the wire).
"""

from __future__ import annotations

import io
import json
import logging
import socket
import socketserver
import threading
import time
from collections.abc import Callable, Iterator

import numpy as np

logger = logging.getLogger("distributedtensorflow_tpu")

Batch = dict[str, np.ndarray]
# input_fn(shard_index, num_shards) -> iterator of batches
WorkerInputFn = Callable[[int, int], Iterator[Batch]]

_HEARTBEAT_INTERVAL_S = 2.0
_WORKER_TIMEOUT_S = 10.0


# --- framing ----------------------------------------------------------------


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(len(payload).to_bytes(8, "little") + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = (int.from_bytes(_recv_exact(sock, 8), "little"),)
    if n > (1 << 31):
        raise ConnectionError(f"oversized frame ({n} bytes)")
    return _recv_exact(sock, n)


def _send_msg(sock: socket.socket, header: dict, data: bytes | None = None) -> None:
    header = dict(header, has_data=data is not None)
    _send_frame(sock, json.dumps(header).encode())
    if data is not None:
        _send_frame(sock, data)


def _recv_msg(sock: socket.socket) -> tuple[dict, bytes | None]:
    header = json.loads(_recv_frame(sock))
    data = _recv_frame(sock) if header.get("has_data") else None
    return header, data


def _rpc(addr: str, request: dict, *, timeout: float = 30.0) -> tuple[dict, bytes | None]:
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        _send_msg(s, request)
        return _recv_msg(s)


def encode_batch(batch: Batch) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **batch)
    return buf.getvalue()


def decode_batch(data: bytes) -> Batch:
    with np.load(io.BytesIO(data)) as z:
        return {k: z[k] for k in z.files}


# --- dispatcher -------------------------------------------------------------


class DispatchServer:
    """Tracks the data-worker pool; hands out shard assignments.

    The reference's ``DispatchServer`` (`server_lib.py:131`).  State is
    in-memory: workers re-register after a dispatcher restart (the
    fault-tolerance mode the reference calls non-fault-tolerant dispatch).
    """

    def __init__(self, port: int = 0):
        self._lock = threading.Lock()
        # addr -> {"shard": int, "last_seen": float}
        self._workers: dict[str, dict] = {}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    req, _ = _recv_msg(self.request)
                    _send_msg(self.request, outer._handle(req))
                except (ConnectionError, json.JSONDecodeError):
                    pass

        self._server = socketserver.ThreadingTCPServer(
            ("0.0.0.0", port), Handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dtf-dispatcher", daemon=True
        )
        self._thread.start()
        logger.info("data-service dispatcher on port %d", self.port)

    def _evict_stale(self, now: float) -> None:
        stale = [
            a
            for a, w in self._workers.items()
            if now - w["last_seen"] >= _WORKER_TIMEOUT_S
        ]
        for a in stale:
            logger.warning("data worker %s timed out; freeing shard %d",
                           a, self._workers[a]["shard"])
            del self._workers[a]

    def _handle(self, req: dict) -> dict:
        kind = req.get("kind")
        with self._lock:
            now = time.monotonic()
            self._evict_stale(now)
            if kind == "register_worker":
                addr = req["addr"]
                if addr not in self._workers:
                    # Lowest free shard index: replacement workers take over
                    # a dead worker's shard rather than growing the index
                    # space (which would break the exactly-once partition).
                    used = {w["shard"] for w in self._workers.values()}
                    shard = next(i for i in range(len(used) + 1) if i not in used)
                    self._workers[addr] = {"shard": shard, "last_seen": now}
                else:
                    self._workers[addr]["last_seen"] = now
                return {"ok": True, "shard": self._workers[addr]["shard"]}
            if kind == "deregister_worker":
                self._workers.pop(req["addr"], None)
                return {"ok": True}
            if kind == "heartbeat":
                w = self._workers.get(req["addr"])
                if w is None:  # dispatcher restarted: ask to re-register
                    return {"ok": False, "reregister": True}
                w["last_seen"] = now
                return {"ok": True}
            if kind == "get_workers":
                return {
                    "ok": True,
                    "workers": {
                        a: w["shard"] for a, w in self._workers.items()
                    },
                }
            return {"ok": False, "error": f"unknown rpc {kind!r}"}

    def target(self) -> str:
        return f"127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# --- worker -----------------------------------------------------------------


class WorkerServer:
    """Runs the input pipeline; serves batches (reference `server_lib.py:349`).

    ``input_fn(shard_index, num_shards_hint)`` builds the batch iterator.
    ``num_shards_hint`` is the pool size at epoch start — with
    distributed_epoch sharding each worker reads only its ``shard_index``-th
    slice of the files.
    """

    def __init__(
        self,
        dispatcher: str,
        input_fn: WorkerInputFn,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        pool_size_hint: int | None = None,
    ):
        self._dispatcher = dispatcher
        self._input_fn = input_fn
        self._lock = threading.Lock()  # guards _iters/_epoch_locks/shard_index
        # epoch -> (iterator, per-epoch lock, num_shards it was built for).
        # Per-epoch locking: requests for different epochs (or the
        # iterator-creation fast path) don't serialize the whole worker
        # behind one long next(it).
        self._iters: dict[str, tuple[Iterator[Batch], threading.Lock, int]] = {}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    req, _ = _recv_msg(self.request)
                    header, data = outer._handle(req)
                    _send_msg(self.request, header, data)
                except (ConnectionError, json.JSONDecodeError):
                    pass

        self._server = socketserver.ThreadingTCPServer(
            ("0.0.0.0", port), Handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.addr = f"{host}:{self.port}"
        self._pool_size_hint = pool_size_hint

        resp = _rpc(dispatcher, {"kind": "register_worker", "addr": self.addr})
        if not resp[0].get("ok"):
            raise ConnectionError(f"worker registration failed: {resp[0]}")
        self.shard_index = int(resp[0]["shard"])

        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._server.serve_forever,
                name="dtf-data-worker",
                daemon=True,
            ),
            threading.Thread(
                target=self._heartbeat_loop,
                name="dtf-data-worker-hb",
                daemon=True,
            ),
        ]
        for t in self._threads:
            t.start()
        logger.info(
            "data worker %s up (shard %d)", self.addr, self.shard_index
        )

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(_HEARTBEAT_INTERVAL_S):
            try:
                resp, _ = _rpc(
                    self._dispatcher,
                    {"kind": "heartbeat", "addr": self.addr},
                    timeout=5.0,
                )
                if resp.get("reregister"):
                    resp, _ = _rpc(
                        self._dispatcher,
                        {"kind": "register_worker", "addr": self.addr},
                        timeout=5.0,
                    )
                    new_shard = int(resp["shard"])
                    with self._lock:
                        if new_shard != self.shard_index:
                            # Shard moved (dispatcher restart): serving the
                            # old slice would duplicate/lose data — drop
                            # cached iterators so new epochs use the new
                            # shard.
                            logger.warning(
                                "data worker %s: shard %d -> %d after "
                                "dispatcher restart",
                                self.addr, self.shard_index, new_shard,
                            )
                            self.shard_index = new_shard
                            self._iters.clear()
            except OSError:
                logger.warning("data worker %s: dispatcher unreachable", self.addr)

    def _handle(self, req: dict) -> tuple[dict, bytes | None]:
        if req.get("kind") != "get_next":
            return {"ok": False, "error": "unknown rpc"}, None
        epoch = str(req.get("epoch", 0))
        num_shards = int(req.get("num_shards") or self._pool_size_hint or 1)
        with self._lock:
            # A worker evicted by heartbeat timeout that re-registered may
            # hold a shard index outside the client's num_shards snapshot
            # (the pool grew past it); serving input_fn(shard, num_shards)
            # then would overlap another worker's slice and break the
            # exactly-once epoch guarantee.  Refuse instead.
            if self.shard_index >= num_shards:
                return {
                    "ok": False,
                    "error": (
                        f"shard {self.shard_index} >= num_shards "
                        f"{num_shards}: worker pool changed since the "
                        "client snapshotted it"
                    ),
                }, None
            entry = self._iters.get(epoch)
            if entry is None:
                entry = (
                    self._input_fn(self.shard_index, num_shards),
                    threading.Lock(),
                    num_shards,
                )
                self._iters[epoch] = entry
            elif entry[2] != num_shards:
                # Cached iterator was built for a different pool snapshot;
                # its slice doesn't partition cleanly under this client's
                # num_shards.
                return {
                    "ok": False,
                    "error": (
                        f"epoch {epoch} iterator built with num_shards="
                        f"{entry[2]}, request has {num_shards}"
                    ),
                }, None
        it, epoch_lock, _ = entry
        with epoch_lock:  # iterators aren't thread-safe; serialize per epoch
            try:
                batch = next(it)
            except StopIteration:
                return {"ok": True, "eof": True}, None
        return {"ok": True, "eof": False}, encode_batch(batch)

    def stop(self) -> None:
        self._stop.set()
        try:  # planned shutdown: free our shard immediately, don't wait
            _rpc(
                self._dispatcher,
                {"kind": "deregister_worker", "addr": self.addr},
                timeout=5.0,
            )
        except OSError:
            pass
        self._server.shutdown()
        self._server.server_close()


# --- client -----------------------------------------------------------------


class DataServiceClient:
    """Round-robin batch puller over the live worker pool.

    One epoch = every worker's shard drained to EOF.  ``ignore_errors``
    controls mid-epoch worker death: True drops the dead worker's remaining
    data (dynamic-pool semantics), False raises.
    """

    def __init__(
        self,
        dispatcher: str,
        *,
        epoch: int = 0,
        ignore_errors: bool = False,
        wait_for_workers_s: float = 30.0,
        get_next_timeout_s: float = 120.0,
    ):
        self._dispatcher = dispatcher
        self._epoch = epoch
        self._ignore_errors = ignore_errors
        self._timeout = get_next_timeout_s
        deadline = time.monotonic() + wait_for_workers_s
        self._workers: list[str] = []
        while time.monotonic() < deadline:
            try:
                resp, _ = _rpc(dispatcher, {"kind": "get_workers"}, timeout=5.0)
            except OSError:
                # Dispatcher still starting up — that's what the grace
                # window is for.
                time.sleep(0.2)
                continue
            self._workers = sorted(
                resp.get("workers", {}), key=lambda a: resp["workers"][a]
            )
            if self._workers:
                break
            time.sleep(0.2)
        if not self._workers:
            raise TimeoutError("no data workers registered")
        self._num_shards = len(self._workers)
        self._live = list(self._workers)
        self._rr = 0

    def __iter__(self) -> Iterator[Batch]:
        return self

    def __next__(self) -> Batch:
        while self._live:
            addr = self._live[self._rr % len(self._live)]
            try:
                header, data = _rpc(
                    addr,
                    {
                        "kind": "get_next",
                        "epoch": self._epoch,
                        "num_shards": self._num_shards,
                    },
                    timeout=self._timeout,
                )
            except OSError as e:
                if not self._ignore_errors:
                    raise ConnectionError(
                        f"data worker {addr} died mid-epoch"
                    ) from e
                logger.warning("dropping dead data worker %s", addr)
                self._live.remove(addr)
                continue
            if not header.get("ok"):
                # Worker refused (shard/pool mismatch after membership
                # change) — its data can't be served consistently this epoch.
                if not self._ignore_errors:
                    raise RuntimeError(
                        f"data worker {addr}: {header.get('error')}"
                    )
                logger.warning(
                    "dropping data worker %s: %s", addr, header.get("error")
                )
                self._live.remove(addr)
                continue
            if header.get("eof"):
                self._live.remove(addr)
                continue
            self._rr += 1
            return decode_batch(data)
        raise StopIteration
