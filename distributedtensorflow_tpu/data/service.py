"""Disaggregated input service: dispatcher + data workers + streaming client.

The tf.data-service equivalent (SURVEY.md §2.3: ``DispatchServer``
`tf/python/data/experimental/service/server_lib.py:131`, ``WorkerServer``
`:349`): input preprocessing runs on a separate pool of cheap CPU hosts so
TPU hosts never stall on data.  Shapes of the design kept from the
reference; the implementation is this framework's own socket protocol (the
reference's is gRPC/protobuf into the tf.data C++ runtime).

Throughput architecture (the pod-scale input plane, ROADMAP item 4 /
MLPerf 1909.09756):

- a **dispatcher** tracks the worker pool AND owns per-epoch split
  assignment: ``start_epoch`` snapshots the pool into ``num_shards``
  splits (``distributed_epoch`` semantics — the dataset is partitioned,
  every element produced exactly once per epoch) under an epoch
  **generation counter** that bumps on every re-assignment;
- **data workers** run the actual input pipeline and serve batches over
  persistent TCP connections — one handler loop per connection serves
  any number of pipelined ``get_next`` requests (a v1 single-shot client
  that closes after one response still works);
- the **client** opens one fetcher thread per split, each holding a
  persistent connection with a **credit window** of W outstanding
  ``get_next`` requests (pipelined: W requests on the wire before the
  first response is read), feeding one bounded client-side buffer the
  consumer pops from.  W autotunes from the observed consumer wait
  (``data.AdaptiveDepthController``) unless pinned.

**Elastic re-sharding** (mid-epoch worker death): the client counts every
fully-received batch per split; on a dead connection it reports the
cumulative counts to the dispatcher (``report_worker_failure``), which
evicts the worker, bumps the epoch generation, and re-assigns the dead
worker's splits to survivors with ``skip`` = batches already delivered.
Survivors rebuild ``input_fn(split, num_shards)`` and fast-forward past
the delivered prefix, so every batch is delivered **exactly once** as
long as ``input_fn`` is deterministic in ``(split, num_shards)`` — the
same contract ``data.skip_batches`` resume already relies on.  A batch is
counted only after it is fully received, so a response torn mid-wire is
re-fetched and a buffered one is never duplicated.  One client per epoch
owns the accounting (multi-host setups give each host its own epoch key
or pre-partitioned splits).

Wire format: every frame is ``uint64 LE length + payload``.  A request is
one JSON frame; a response is one JSON frame optionally followed by one
binary frame carrying the batch — ``wire="raw"`` (default for the
streaming client) uses the header+raw-bytes tensor format of
:mod:`data.wire` (optional CRC32C via the native layer), ``wire="npz"``
the legacy ``np.savez`` archive.  :func:`decode_batch` sniffs both.

**Resilient transport** (ISSUE 13): every control-plane RPC routes
through :mod:`..net.rpc` — per-call deadlines propagated in the wire
header, bounded retries with backoff+jitter, per-endpoint circuit
breakers — and the streaming client treats a delayed or severed stream
as a TRANSPORT fault first: it reconnects to the SAME worker (bounded
retries, resuming via a per-stream ``sid`` token + its absolute
delivered count) and only reports the worker dead to the dispatcher once
reconnection fails.  The worker honors resume by comparing the incoming
stream's ``skip`` against its slot position: a matching position adopts
the new stream in place, a short one rebuilds the deterministic iterator
from the requested skip — exactly-once either way.

**Durable dispatcher** (:class:`DispatcherJournal`): with
``journal_path``, every state mutation — worker registration, epoch
start, reshard, client progress report — is appended to
``dispatcher.journal`` (one JSON line, fsync'd) and replayed on
construction, so a dispatcher restart mid-epoch preserves epoch
generations, split assignments and per-client received counts instead of
orphaning every fetcher.

Telemetry (obs registry, no-op when obs/jax is unavailable on a plain
CPU worker host): ``data_service_fetch_seconds{worker=}`` per-worker
fetch histogram, ``data_service_client_wait_seconds`` consumer blocking,
``data_service_workers_dropped_total`` / ``data_service_resharded_splits_
total`` counters, a ``data_reshard`` flight event per re-assignment,
``data_service_stream_resumes_total`` same-worker stream reconnections,
plus the ``rpc_*`` / ``breaker_*`` families from :mod:`..net`.
"""

from __future__ import annotations

import collections
import io
import json
import logging
import os
import queue
import random
import socket
import socketserver
import threading
import time
import uuid
from collections.abc import Callable, Iterator

import numpy as np

from ..net import rpc as netrpc
from . import wire as wirelib
from .adaptive import AdaptiveDepthController

logger = logging.getLogger("distributedtensorflow_tpu")

Batch = dict[str, np.ndarray]
# input_fn(shard_index, num_shards) -> iterator of batches
WorkerInputFn = Callable[[int, int], Iterator[Batch]]

DEFAULT_HEARTBEAT_INTERVAL_S = 2.0
DEFAULT_WORKER_TIMEOUT_S = 10.0
# Back-compat aliases (pre-knob module constants).
_HEARTBEAT_INTERVAL_S = DEFAULT_HEARTBEAT_INTERVAL_S
_WORKER_TIMEOUT_S = DEFAULT_WORKER_TIMEOUT_S

WIRE_FORMATS = wirelib.WIRE_FORMATS
PROTOCOLS = ("streaming", "per_connection")

#: Worker-side iterator caches are pruned to the newest epochs so a
#: supervisor that rebuilds its client per restart (fresh epoch key each
#: time) cannot grow worker memory without bound.
_MAX_CACHED_EPOCHS = 4
#: Dispatcher-side epoch-assignment state kept, same reason.
_MAX_TRACKED_EPOCHS = 16


# Telemetry degrades to no-ops where obs (which pulls jax) is absent —
# data workers are deliberately runnable on bare CPU hosts.  One guarded
# import, shared with the adaptive controller.
from .adaptive import (  # noqa: F401  (shared degradation shims)
    _counter,
    _histogram,
    _record_event,
    _remote_span,
)


# --- framing (shared substrate: net/rpc.py owns the wire now) ----------------

_send_frame = netrpc.send_frame
_recv_exact = netrpc.recv_exact
_recv_frame = netrpc.recv_frame
_send_msg = netrpc.send_msg
_recv_msg = netrpc.recv_msg


def _rpc(addr: str, request: dict, *, timeout: float = 30.0,
         trace: dict | None = None, endpoint: str | None = None,
         policy: netrpc.RetryPolicy | None = None) -> tuple[dict, bytes | None]:
    """One resilient unary RPC (delegates to :func:`net.rpc.call`):
    ``timeout`` is the TOTAL deadline including retries; the remaining
    budget rides the wire header as ``deadline_s``."""
    if policy is None:
        policy = netrpc.RetryPolicy(deadline_s=timeout)
    return netrpc.call(
        addr, request, endpoint=endpoint or f"data_worker:{addr}",
        policy=policy, deadline_s=timeout, trace=trace,
    )


def _request_trace(req: dict) -> dict | None:
    """The trace context a request frame carries, or None."""
    trace = req.get("trace")
    if isinstance(trace, dict) and trace.get("trace_id"):
        return trace
    return None


def encode_batch(batch: Batch, wire: str = "npz", *, crc: bool = False,
                 trace: dict | None = None) -> bytes:
    """Serialize a batch for the wire.  ``"npz"`` (the legacy default —
    the param-server shard protocol still speaks it) or ``"raw"`` (the
    header+raw-bytes format of :mod:`data.wire`; ``crc`` adds a CRC32C
    over the payload when the native layer is available; ``trace`` echoes
    a distributed-tracing context in the raw header)."""
    if wire == "raw":
        return wirelib.encode_tensors(batch, crc=crc, trace=trace)
    if wire != "npz":
        raise ValueError(f"unknown wire format {wire!r} (known: {WIRE_FORMATS})")
    buf = io.BytesIO()
    np.savez(buf, **batch)
    return buf.getvalue()


def decode_batch(data: bytes) -> Batch:
    """Decode either wire format (sniffed by magic)."""
    if wirelib.is_raw(data):
        return wirelib.decode_tensors(data)
    with np.load(io.BytesIO(data)) as z:
        return {k: z[k] for k in z.files}


# --- dispatcher journal ------------------------------------------------------


#: Journal record kinds, in the only orders replay accepts (the schema
#: checker mirrors this tuple stdlib-side): ``open``/``replay`` are
#: lifecycle markers; ``epoch_start`` must precede any ``reshard`` /
#: ``client_progress`` for its epoch; reshard generations are strictly
#: increasing per epoch.
JOURNAL_KINDS = (
    "open", "replay", "worker_register", "worker_deregister",
    "epoch_start", "reshard", "client_progress",
)


class DispatcherJournal:
    """Append-only durability log for the dispatcher's control-plane
    state (``<logdir>/dispatcher.journal``).

    One JSON object per line, each carrying a strictly-increasing ``seq``
    and a wall ``t``.  Appends are a single ``write`` + flush + fsync —
    a crash can tear at most the final line, and :meth:`replay`
    tolerates exactly that (a torn last line is dropped; a torn line
    anywhere else is corruption and raises).

    The journal is one continuous file across dispatcher restarts: a
    restarting dispatcher replays it, appends a ``replay`` marker, and
    keeps appending — so the file itself is the audit trail
    ``tools/check_metrics_schema.py`` validates (monotonic seq, known
    kinds, per-epoch generation ordering) and ``tools/run_report.py``
    summarizes.
    """

    def __init__(self, path: str, *, next_seq: int | None = None):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._truncate_torn_tail(path)
        self._f = open(path, "a")
        self._lock = threading.Lock()
        # The dispatcher's replay already parsed the file and hands the
        # continuation seq in; a standalone journal parses once itself.
        self._seq = self._last_seq() + 1 if next_seq is None else next_seq

    @staticmethod
    def _truncate_torn_tail(path: str) -> None:
        """Drop a torn (newline-less) final fragment BEFORE appending:
        the first post-crash append would otherwise concatenate onto the
        fragment and turn the one legal tail tear into mid-file
        corruption that poisons every future replay."""
        try:
            with open(path, "rb+") as f:
                data = f.read()
                if not data or data.endswith(b"\n"):
                    return
                cut = data.rfind(b"\n") + 1  # 0 when no newline at all
                f.truncate(cut)
                logger.warning(
                    "dispatcher journal %s: truncated %d torn tail "
                    "byte(s) before reopening", path, len(data) - cut,
                )
        except FileNotFoundError:
            return
        except OSError:  # pragma: no cover - leave the tail to replay()
            logger.exception("journal tail check failed for %s", path)

    def _last_seq(self) -> int:
        try:
            records, _torn = self.replay(self.path)
        except (OSError, ValueError):
            return -1
        return records[-1]["seq"] if records else -1

    def append(self, kind: str, **fields) -> None:
        if kind not in JOURNAL_KINDS:
            raise ValueError(f"unknown journal record kind {kind!r}")
        with self._lock:
            row = {"seq": self._seq, "t": time.time(), "kind": kind,
                   **fields}
            self._seq += 1
            self._f.write(json.dumps(row) + "\n")
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:  # pragma: no cover - exotic filesystems
                pass

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:  # pragma: no cover
                pass

    @staticmethod
    def replay(path: str) -> tuple[list[dict], bool]:
        """Parse ``path`` into ``(records, torn_tail)``: all well-formed
        records in order, plus whether a torn final line was dropped.
        Raises ``ValueError`` on corruption anywhere but the tail."""
        records: list[dict] = []
        torn = False
        with open(path) as f:
            lines = f.read().split("\n")
        # split() leaves one trailing "" for a well-terminated file.
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    torn = True  # torn tail: the one legal partial write
                    break
                raise ValueError(
                    f"{path}: corrupt journal line {i + 1}"
                ) from None
            if not isinstance(row, dict) or not isinstance(
                row.get("seq"), int
            ):
                raise ValueError(f"{path}: malformed record at line {i + 1}")
            records.append(row)
        return records, torn


# --- dispatcher -------------------------------------------------------------


class DispatchServer:
    """Tracks the data-worker pool; owns shard assignment per epoch.

    The reference's ``DispatchServer`` (`server_lib.py:131`).  Without a
    journal, state is in-memory: workers re-register after a dispatcher
    restart (the fault-tolerance mode the reference calls
    non-fault-tolerant dispatch) and epoch assignment state is lost.
    With ``journal_path``, every mutation is appended to a
    :class:`DispatcherJournal` and REPLAYED on construction: a restarted
    dispatcher comes back knowing its workers' shard assignments (so
    re-registration returns the same shard and no worker retires its
    epochs), every epoch's generation + split map, and the per-client
    received counts — elastic re-sharding and exactly-once accounting
    survive the restart.

    Binds loopback by default (the StatusServer hardening pattern): pass
    ``host="0.0.0.0"`` only on a trusted cluster network.
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        worker_timeout_s: float = DEFAULT_WORKER_TIMEOUT_S,
        journal_path: str | None = None,
    ):
        self._lock = threading.Lock()
        self._worker_timeout_s = float(worker_timeout_s)
        # addr -> {"shard": int, "last_seen": float}
        self._workers: dict[str, dict] = {}
        # epoch -> {"num_shards", "gen",
        #           "splits": {int: {"addr", "skip"}},
        #           "received": {int: count}}   (client progress reports)
        self._epochs: dict[str, dict] = {}
        self._journal: DispatcherJournal | None = None
        if journal_path:
            replayed, last_seq = self._replay_journal(journal_path)
            self._journal = DispatcherJournal(journal_path,
                                              next_seq=last_seq + 1)
            if replayed:
                self._journal.append(
                    "replay",
                    restored_workers=len(self._workers),
                    restored_epochs=len(self._epochs),
                    replayed_records=replayed,
                )
            else:
                self._journal.append("open")
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    req, _ = _recv_msg(self.request)
                    ctx = _request_trace(req)
                    if ctx is not None:
                        # Traced RPC: the dispatcher's span lands in THIS
                        # process's trace.jsonl under the caller's
                        # trace_id (rare control-plane calls only — the
                        # batch hot path never passes through here).
                        with _remote_span(
                            f"dispatcher.{req.get('kind')}", context=ctx,
                            epoch=str(req.get("epoch", "")),
                        ):
                            resp = outer._handle(req)
                    else:
                        resp = outer._handle(req)
                    _send_msg(self.request, resp)
                except (ConnectionError, json.JSONDecodeError, OSError):
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            # A journal-replaying dispatcher restarts on its OLD port
            # (clients hold the address); without reuse the bind races
            # TIME_WAIT remnants of its predecessor's connections.
            allow_reuse_address = True

        self._server = _Server((host, port), Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dtf-dispatcher", daemon=True
        )
        self._thread.start()
        logger.info("data-service dispatcher on %s:%d", host, self.port)

    def _replay_journal(self, path: str) -> tuple[int, int]:
        """Restore workers + epochs from an existing journal; returns
        ``(records_replayed, last_seq)`` (``(0, -1)`` when the file is
        absent/empty/unusable) so the journal continues the seq chain
        without re-parsing the file.  Replayed workers get
        ``last_seen = now``: a genuinely dead one is re-evicted after the
        normal timeout, a live one's next heartbeat simply confirms its
        (unchanged) shard."""
        if not os.path.exists(path):
            return 0, -1
        try:
            records, torn = DispatcherJournal.replay(path)
        except (OSError, ValueError) as e:
            logger.error("dispatcher journal %s unusable (%s); starting "
                         "with empty state", path, e)
            return 0, -1
        if torn:
            logger.warning("dispatcher journal %s had a torn final line "
                           "(dropped)", path)
        now = time.monotonic()
        for row in records:
            kind = row.get("kind")
            if kind == "worker_register":
                self._workers[row["addr"]] = {
                    "shard": int(row["shard"]), "last_seen": now,
                }
            elif kind == "worker_deregister":
                self._workers.pop(row.get("addr"), None)
            elif kind == "epoch_start":
                self._epochs[str(row["epoch"])] = {
                    "num_shards": int(row["num_shards"]),
                    "gen": int(row["gen"]),
                    "splits": {
                        int(s): {"addr": v["addr"], "skip": int(v["skip"])}
                        for s, v in row["splits"].items()
                    },
                    "received": {},
                }
                while len(self._epochs) > _MAX_TRACKED_EPOCHS:
                    self._epochs.pop(next(iter(self._epochs)))
            elif kind == "reshard":
                self._workers.pop(row.get("dead_worker"), None)
                ep = self._epochs.get(str(row["epoch"]))
                if ep is not None:
                    ep["gen"] = int(row["gen"])
                    ep["splits"] = {
                        int(s): {"addr": v["addr"], "skip": int(v["skip"])}
                        for s, v in row["splits"].items()
                    }
            elif kind == "client_progress":
                ep = self._epochs.get(str(row["epoch"]))
                if ep is not None:
                    rec = ep.setdefault("received", {})
                    for s, n in (row.get("received") or {}).items():
                        rec[int(s)] = max(rec.get(int(s), 0), int(n))
        if records:
            logger.warning(
                "dispatcher journal %s replayed: %d record(s) -> "
                "%d worker(s), %d epoch(s)", path, len(records),
                len(self._workers), len(self._epochs),
            )
        return len(records), (records[-1]["seq"] if records else -1)

    def _journal_append(self, kind: str, **fields) -> None:
        if self._journal is not None:
            try:
                self._journal.append(kind, **fields)
            except OSError:
                # Durability is best-effort: a full disk must not take
                # the live control plane down with it.
                logger.exception("dispatcher journal append failed")

    def _evict_stale(self, now: float) -> None:
        stale = [
            a
            for a, w in self._workers.items()
            if now - w["last_seen"] >= self._worker_timeout_s
        ]
        for a in stale:
            logger.warning("data worker %s timed out; freeing shard %d",
                           a, self._workers[a]["shard"])
            del self._workers[a]
            self._journal_append("worker_deregister", addr=a,
                                 reason="timeout")

    @staticmethod
    def _epoch_view(ep: dict) -> dict:
        return {
            "num_shards": ep["num_shards"],
            "gen": ep["gen"],
            "splits": {
                str(s): dict(v) for s, v in sorted(ep["splits"].items())
            },
            # Merged per-split progress (max over every client report,
            # journal-replayed across dispatcher restarts): an elastic
            # resume — same process after a resize, or another trainer
            # host joining the SAME epoch — seeds its delivered ledger
            # from these counts, which is what makes one epoch shareable
            # across clients exactly-once.
            "received": {
                str(s): int(n)
                for s, n in sorted((ep.get("received") or {}).items())
            },
        }

    def _handle(self, req: dict) -> dict:
        kind = req.get("kind")
        with self._lock:
            now = time.monotonic()
            self._evict_stale(now)
            if kind == "register_worker":
                addr = req["addr"]
                if addr not in self._workers:
                    # Lowest free shard index: replacement workers take over
                    # a dead worker's shard rather than growing the index
                    # space (which would break the exactly-once partition).
                    used = {w["shard"] for w in self._workers.values()}
                    shard = next(i for i in range(len(used) + 1) if i not in used)
                    self._workers[addr] = {"shard": shard, "last_seen": now}
                    self._journal_append("worker_register", addr=addr,
                                         shard=shard)
                else:
                    self._workers[addr]["last_seen"] = now
                return {"ok": True, "shard": self._workers[addr]["shard"]}
            if kind == "deregister_worker":
                if self._workers.pop(req["addr"], None) is not None:
                    self._journal_append("worker_deregister",
                                         addr=req["addr"], reason="planned")
                return {"ok": True}
            if kind == "heartbeat":
                w = self._workers.get(req["addr"])
                if w is None:  # dispatcher restarted: ask to re-register
                    return {"ok": False, "reregister": True}
                w["last_seen"] = now
                return {"ok": True}
            if kind == "get_workers":
                return {
                    "ok": True,
                    "workers": {
                        a: w["shard"] for a, w in self._workers.items()
                    },
                }
            if kind == "start_epoch":
                epoch = str(req.get("epoch", 0))
                ep = self._epochs.get(epoch)
                if ep is None:
                    if not self._workers:
                        return {"ok": False, "error": "no data workers"}
                    ordered = sorted(
                        self._workers, key=lambda a: self._workers[a]["shard"]
                    )
                    ep = {
                        "num_shards": len(ordered),
                        "gen": 0,
                        "splits": {
                            i: {"addr": a, "skip": 0}
                            for i, a in enumerate(ordered)
                        },
                        "received": {},
                    }
                    self._epochs[epoch] = ep
                    while len(self._epochs) > _MAX_TRACKED_EPOCHS:
                        self._epochs.pop(next(iter(self._epochs)))
                    self._journal_append(
                        "epoch_start", epoch=epoch,
                        num_shards=ep["num_shards"], gen=0,
                        splits={str(s): dict(v)
                                for s, v in ep["splits"].items()},
                    )
                return {"ok": True, **self._epoch_view(ep)}
            if kind == "get_assignments":
                ep = self._epochs.get(str(req.get("epoch", 0)))
                if ep is None:
                    return {"ok": False, "error": "unknown epoch"}
                return {"ok": True, **self._epoch_view(ep)}
            if kind == "report_progress":
                # Exactly-once bookkeeping for a dispatcher restart: the
                # streaming client periodically reports its cumulative
                # fully-received counts; they are journaled and become the
                # reshard skip fallback when a later failure report cannot
                # supply a count itself.
                ep = self._epochs.get(str(req.get("epoch", 0)))
                if ep is None:
                    return {"ok": False, "error": "unknown epoch"}
                rec = ep.setdefault("received", {})
                changed = False
                for s, n in (req.get("received") or {}).items():
                    n = int(n)
                    if n > rec.get(int(s), -1):
                        rec[int(s)] = n
                        changed = True
                if changed:
                    self._journal_append(
                        "client_progress", epoch=str(req.get("epoch", 0)),
                        client=str(req.get("client", "")),
                        received={str(s): n for s, n in rec.items()},
                    )
                return {"ok": True}
            if kind == "report_worker_failure":
                return self._reshard_locked(req)
            return {"ok": False, "error": f"unknown rpc {kind!r}"}

    def _reshard_locked(self, req: dict) -> dict:
        """Evict a client-reported dead worker and hand its splits (with
        delivered-batch skip counts) to survivors under a new generation.

        With ``split`` in the request only THAT split moves — the protocol
        the streaming client uses: each split's own fetcher reports its
        own cumulative count, so a sibling fetcher mid-decode can never
        have its count snapshotted one batch short (which would deliver
        that batch twice).  Without ``split``, all of the dead worker's
        splits move at once using the supplied count map."""
        epoch = str(req.get("epoch", 0))
        addr = req.get("addr")
        received = req.get("received") or {}
        ep = self._epochs.get(epoch)
        if ep is None:
            return {
                "ok": False,
                "error": f"unknown epoch {epoch!r} (dispatcher restarted?)",
            }
        self._workers.pop(addr, None)
        if req.get("split") is not None:
            orphans = [int(req["split"])]
            if ep["splits"].get(orphans[0], {}).get("addr") != addr:
                # already moved (e.g. a full-worker report raced in) —
                # idempotent success with the current view
                return {"ok": True, "moved": [], **self._epoch_view(ep)}
        else:
            orphans = sorted(
                s for s, a in ep["splits"].items() if a["addr"] == addr
            )
        if orphans:
            survivors = sorted(
                self._workers, key=lambda a: self._workers[a]["shard"]
            )
            if not survivors:
                return {
                    "ok": False,
                    "error": (
                        f"no surviving workers to take over splits {orphans}"
                    ),
                }
            ep["gen"] += 1
            progress = ep.get("received") or {}
            for i, split in enumerate(orphans):
                # The client's cumulative delivered count is authoritative;
                # without one (a whole-worker report, or a client that
                # itself restarted), the journaled progress report is the
                # next-best truth; a split never pulled from keeps its
                # prior skip.
                skip = received.get(
                    str(split),
                    progress.get(split, ep["splits"][split]["skip"]),
                )
                ep["splits"][split] = {
                    "addr": survivors[i % len(survivors)],
                    "skip": int(skip),
                }
            self._journal_append(
                "reshard", epoch=epoch, gen=ep["gen"],
                dead_worker=addr,
                splits={str(s): dict(v) for s, v in ep["splits"].items()},
            )
            logger.warning(
                "data worker %s reported dead; splits %s resharded to "
                "%d survivor(s) (epoch %s gen %d)",
                addr, orphans, len(survivors), epoch, ep["gen"],
            )
        return {"ok": True, "moved": orphans, **self._epoch_view(ep)}

    def target(self) -> str:
        host = "127.0.0.1" if self.host in ("0.0.0.0", "") else self.host
        return f"{host}:{self.port}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._journal is not None:
            self._journal.close()

    def kill(self) -> None:
        """Simulated crash (chaos ``dispatcher_kill``): the sockets die,
        the journal file handle is abandoned WITHOUT a clean close —
        durability must come from the per-record fsync, not a shutdown
        hook."""
        self._server.shutdown()
        self._server.server_close()


# --- worker -----------------------------------------------------------------


class _IterSlot:
    """One (epoch, gen, split) iterator: built lazily (skip draining runs
    under the per-slot lock, not the worker-global one).

    ``sid`` is the OWNING stream's resume token, ``rid`` its monotonic
    per-split attempt number, and ``pos`` the absolute batch index the
    next ``next()`` will serve (initial skip + batches served) —
    together they implement reconnect-with-resume: a new stream (higher
    ``rid``) whose ``skip`` matches ``pos`` adopts the slot in place, a
    mismatch (batches died on the severed wire) rebuilds the
    deterministic iterator from the client's own delivered count, and a
    STALE stream's leftover pipelined frames (lower ``rid``, buffered on
    the dead connection) are refused instead of stealing the slot back
    and rewinding the iterator into duplicates."""

    __slots__ = ("factory", "lock", "num_shards", "it", "sid", "rid",
                 "pos")

    def __init__(self, factory, num_shards: int, *,
                 sid: str | None = None, rid: int = 0, pos: int = 0):
        self.factory = factory
        self.lock = threading.Lock()
        self.num_shards = num_shards
        self.it = None
        self.sid = sid
        self.rid = int(rid)
        self.pos = int(pos)

    def ensure(self) -> Iterator[Batch]:
        if self.it is None:
            self.it = self.factory()
        return self.it


class WorkerServer:
    """Runs the input pipeline; serves batches (reference `server_lib.py:349`).

    ``input_fn(shard_index, num_shards_hint)`` builds the batch iterator.
    A connection is served in a loop, so a streaming client pipelines any
    number of ``get_next`` requests over one socket; a v1 client that
    closes after one response ends the loop via EOF.

    Binds ``host`` (loopback by default — the StatusServer hardening
    pattern) and advertises ``advertise_host or host`` to the dispatcher;
    pass ``advertise_host`` when binding ``0.0.0.0``.  ``wire_crc=True``
    adds a CRC32C to every raw-wire batch (native layer permitting).

    ``status_port`` (None = off; 0 = ephemeral, loopback-default via
    ``status_host``) embeds an ``obs.StatusServer`` so worker health is a
    first-class scrape target of the chief's ``FleetAggregator`` instead
    of being inferable only from client-side fetch histograms — the
    bound address is ``worker.status_addr``.  Degrades to a warning on a
    bare host where ``obs`` (which pulls jax) cannot import.
    """

    def __init__(
        self,
        dispatcher: str,
        input_fn: WorkerInputFn,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        advertise_host: str | None = None,
        pool_size_hint: int | None = None,
        heartbeat_interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
        wire_crc: bool = False,
        max_cached_epochs: int = _MAX_CACHED_EPOCHS,
        status_port: int | None = None,
        status_host: str = "127.0.0.1",
    ):
        self._dispatcher = dispatcher
        self._input_fn = input_fn
        self._heartbeat_interval_s = float(heartbeat_interval_s)
        self._wire_crc = bool(wire_crc)
        self._max_cached_epochs = max(1, int(max_cached_epochs))
        self._lock = threading.Lock()  # guards _iters/_epoch_order/shard_index
        # (epoch, gen, split) -> _IterSlot
        self._iters: dict[tuple[str, int, int], _IterSlot] = {}
        self._epoch_order: list[str] = []
        # Epochs whose slots were dropped (cache pruning or a dispatcher-
        # restart shard move).  Requests for them must be REFUSED: the
        # stream-start `skip` frozen into a client's pipelined requests
        # predates the drop, so silently rebuilding the iterator would
        # re-serve batches the client already counted — duplicated data
        # with exactly-once still claimed.  Insertion-ordered and bounded
        # (dict-as-ordered-set): a long-lived worker must not grow with
        # restart count, and a client stale past ~1k retirements is gone.
        self._retired_epochs: dict[str, None] = {}
        self._m_served = _counter(
            "data_service_batches_served_total",
            "batches this data worker put on the wire",
        )
        self._served = 0  # local count (the registry counter may be shared)
        # Live connections, so kill() can sever in-flight streams (the
        # listening socket alone leaves established handlers serving).
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:  # persistent connection: loop until EOF
                        req, _ = _recv_msg(self.request)
                        try:
                            header, data = outer._handle(req)
                        except Exception as e:
                            # A request that fails (input_fn raised, batch
                            # not wire-encodable, bad wire value) must be
                            # ANSWERED, not die with the connection: a
                            # severed stream reads as worker death, and an
                            # elastic client would evict this healthy
                            # worker and cascade the same deterministic
                            # failure across every takeover.
                            logger.exception(
                                "data worker %s: request failed", outer.addr
                            )
                            header, data = {
                                "ok": False,
                                "error": f"{type(e).__name__}: {e}",
                            }, None
                        _send_msg(self.request, header, data)
                except (ConnectionError, json.JSONDecodeError, OSError):
                    pass
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        self._server = socketserver.ThreadingTCPServer(
            (host, port), Handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        if advertise_host is None:
            advertise_host = socket.gethostname() if host == "0.0.0.0" else host
        self.addr = f"{advertise_host}:{self.port}"
        self._pool_size_hint = pool_size_hint

        resp = _rpc(dispatcher, {"kind": "register_worker", "addr": self.addr},
                    endpoint=f"dispatcher:{dispatcher}")
        if not resp[0].get("ok"):
            raise ConnectionError(f"worker registration failed: {resp[0]}")
        self.shard_index = int(resp[0]["shard"])

        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._server.serve_forever,
                name="dtf-data-worker",
                daemon=True,
            ),
            threading.Thread(
                target=self._heartbeat_loop,
                name="dtf-data-worker-hb",
                daemon=True,
            ),
        ]
        for t in self._threads:
            t.start()

        #: Embedded introspection server (fleet scrape target); None when
        #: off or unavailable on this host.
        self.status_server = None
        self.status_addr: str | None = None
        if status_port is not None:
            try:
                from ..obs.server import StatusServer  # noqa: PLC0415

                self.status_server = StatusServer(
                    status_port,
                    host=status_host,
                    status_fn=self._status,
                    health_fn=self._health,
                ).start()
                # Advertise a reachable address, not the bind wildcard —
                # the same advertise_host rule the data port follows
                # (a remote aggregator scraping "0.0.0.0:P" connects to
                # itself).
                adv = (advertise_host
                       if status_host in ("0.0.0.0", "") else status_host)
                self.status_addr = f"{adv}:{self.status_server.port}"
            except Exception:  # bare host without obs/jax, or bind failure
                logger.exception(
                    "data worker %s: embedded status server unavailable; "
                    "continuing without it", self.addr,
                )
        logger.info(
            "data worker %s up (shard %d)%s", self.addr, self.shard_index,
            f" status {self.status_addr}" if self.status_addr else "",
        )

    def _status(self) -> dict:
        with self._lock:
            cached = len(self._iters)
            retired = len(self._retired_epochs)
        return {
            "data_worker": {
                "addr": self.addr,
                "shard": self.shard_index,
                "batches_served": self._served,
                "cached_iterators": cached,
                "retired_epochs": retired,
            }
        }

    def _health(self) -> dict:
        return {
            "ok": not self._stop.is_set(),
            "addr": self.addr,
            "shard": self.shard_index,
        }

    def _heartbeat_loop(self) -> None:
        ep = f"dispatcher:{self._dispatcher}"
        # Single-shot per tick: the loop itself IS the retry schedule —
        # stacking per-call retries on top would stretch a tick past the
        # heartbeat interval.
        policy = netrpc.RetryPolicy(deadline_s=5.0, max_attempts=1)
        while not self._stop.wait(self._heartbeat_interval_s):
            try:
                resp, _ = _rpc(
                    self._dispatcher,
                    {"kind": "heartbeat", "addr": self.addr},
                    timeout=5.0, endpoint=ep, policy=policy,
                )
                if resp.get("reregister"):
                    resp, _ = _rpc(
                        self._dispatcher,
                        {"kind": "register_worker", "addr": self.addr},
                        timeout=5.0, endpoint=ep, policy=policy,
                    )
                    new_shard = int(resp["shard"])
                    with self._lock:
                        if new_shard != self.shard_index:
                            # Shard moved (dispatcher restart): serving the
                            # old slice would duplicate/lose data — drop
                            # cached iterators so new epochs use the new
                            # shard.
                            logger.warning(
                                "data worker %s: shard %d -> %d after "
                                "dispatcher restart",
                                self.addr, self.shard_index, new_shard,
                            )
                            self.shard_index = new_shard
                            for old in self._epoch_order:
                                self._retire_epoch_locked(old)
                            self._iters.clear()
                            self._epoch_order.clear()
            except OSError:
                logger.warning("data worker %s: dispatcher unreachable", self.addr)

    def _retire_epoch_locked(self, epoch: str) -> None:
        self._retired_epochs[epoch] = None
        while len(self._retired_epochs) > 1024:
            self._retired_epochs.pop(next(iter(self._retired_epochs)))

    def _prune_epochs_locked(self, epoch: str) -> None:
        if epoch in self._epoch_order:
            return
        self._epoch_order.append(epoch)
        while len(self._epoch_order) > self._max_cached_epochs:
            old = self._epoch_order.pop(0)
            self._retire_epoch_locked(old)
            for key in [k for k in self._iters if k[0] == old]:
                del self._iters[key]

    def _handle(self, req: dict) -> tuple[dict, bytes | None]:
        ctx = _request_trace(req)
        if ctx is None:
            return self._get_next(req, None)
        # Traced request (the streaming client injects its context into
        # the FIRST get_next of each stream only — never per batch): the
        # worker's span lands in this process's trace.jsonl under the
        # client's trace_id, and the response batch echoes the context in
        # its wire header.
        with _remote_span(
            "data_worker.get_next", context=ctx,
            epoch=str(req.get("epoch", "")), split=req.get("split"),
            worker=self.addr,
        ) as sp:
            return self._get_next(req, sp.context)

    def _get_next(self, req: dict,
                  trace_ctx: dict | None) -> tuple[dict, bytes | None]:
        if req.get("kind") != "get_next":
            return {"ok": False, "error": "unknown rpc"}, None
        epoch = str(req.get("epoch", 0))
        gen = int(req.get("gen", 0))
        num_shards = int(req.get("num_shards") or self._pool_size_hint or 1)
        skip = int(req.get("skip", 0))
        wire_fmt = str(req.get("wire", "npz"))
        sid = req.get("sid")
        split = req.get("split")
        with self._lock:
            if epoch in self._retired_epochs:
                return {
                    "ok": False,
                    "error": (
                        f"epoch {epoch} was retired on this worker (cache "
                        "pruned past it or the shard moved); its iterators "
                        "cannot be rebuilt without re-serving delivered "
                        "batches"
                    ),
                }, None
            if split is None:
                # v1 client: serve this worker's registered shard.  A
                # worker evicted by heartbeat timeout that re-registered
                # may hold a shard index outside the client's num_shards
                # snapshot; serving it would overlap another worker's
                # slice.  Refuse instead.
                if self.shard_index >= num_shards:
                    return {
                        "ok": False,
                        "error": (
                            f"shard {self.shard_index} >= num_shards "
                            f"{num_shards}: worker pool changed since the "
                            "client snapshotted it"
                        ),
                    }, None
                split = self.shard_index
            split = int(split)
            rid = int(req.get("rid", 0))
            key = (epoch, gen, split)
            entry = self._iters.get(key)
            if entry is None:
                entry = _IterSlot(
                    self._make_iter_factory(split, num_shards, skip),
                    num_shards, sid=sid, rid=rid, pos=skip,
                )
                self._iters[key] = entry
                self._prune_epochs_locked(epoch)
            elif entry.num_shards != num_shards:
                # Cached iterator was built for a different pool snapshot;
                # its slice doesn't partition cleanly under this client's
                # num_shards.
                return {
                    "ok": False,
                    "error": (
                        f"epoch {epoch} gen {gen} split {split} iterator "
                        f"built with num_shards={entry.num_shards}, "
                        f"request has {num_shards}"
                    ),
                }, None
            elif sid is not None and sid != entry.sid:
                rid = int(req.get("rid", 0))
                if rid <= entry.rid:
                    # A STALE stream's leftover pipelined frame (its
                    # connection was severed, but frames it had already
                    # put on the wire are still being read): honoring it
                    # would rewind the slot under the live resume stream
                    # and re-serve counted batches.  Refuse — the answer
                    # goes to a dead socket anyway.
                    # ``stale_rid`` lets a LIVE successor stream (a new
                    # CLIENT whose per-client rid counter restarted — an
                    # elastic-resize resume, or another host taking the
                    # slot) escalate past the slot's counter and retry;
                    # a dead predecessor's buffered frame gets the same
                    # refusal on a socket nobody reads.
                    return {
                        "ok": False,
                        "error": (
                            f"stale resume token (attempt {rid} <= "
                            f"current {entry.rid}) for epoch {epoch} "
                            f"split {split}"
                        ),
                        "stale_rid": entry.rid,
                    }, None
                # Reconnect-with-resume: a NEW stream took over a live
                # slot.  The slot lock is taken INSIDE the worker lock
                # (serve path takes it alone — consistent order, no
                # deadlock) so any in-flight next() for the dead stream
                # lands its pos increment before the comparison.
                with entry.lock:
                    entry.rid = rid
                    if skip == entry.pos:
                        # Nothing was lost on the severed wire: adopt the
                        # iterator in place and keep streaming.
                        entry.sid = sid
                    else:
                        # Batches died in flight (served but never
                        # received): rebuild the deterministic iterator
                        # from the client's own delivered count.
                        logger.info(
                            "data worker %s: stream resume rebuilt "
                            "epoch %s split %d at %d (slot was at %d)",
                            self.addr, epoch, split, skip, entry.pos,
                        )
                        entry = _IterSlot(
                            self._make_iter_factory(split, num_shards,
                                                    skip),
                            num_shards, sid=sid, rid=rid, pos=skip,
                        )
                        self._iters[key] = entry
        with entry.lock:  # iterators aren't thread-safe; serialize per slot
            try:
                batch = next(entry.ensure())
            except StopIteration:
                return {"ok": True, "eof": True, "split": split}, None
            entry.pos += 1
        self._m_served.inc()
        self._served += 1
        return (
            {"ok": True, "eof": False, "split": split},
            encode_batch(batch, wire=wire_fmt, crc=self._wire_crc,
                         trace=trace_ctx),
        )

    def _make_iter_factory(self, split: int, num_shards: int, skip: int):
        def factory() -> Iterator[Batch]:
            it = self._input_fn(split, num_shards)
            for i in range(skip):
                # Elastic takeover: fast-forward past batches the dead
                # worker already delivered (deterministic input_fn).
                try:
                    next(it)
                except StopIteration:
                    logger.warning(
                        "split %d exhausted after %d/%d skip batches",
                        split, i, skip,
                    )
                    return iter(())
            if skip:
                logger.info(
                    "data worker %s took over split %d (skipped %d "
                    "delivered batches)", self.addr, split, skip,
                )
            return it

        return factory

    def kill(self) -> None:
        """Tear down WITHOUT deregistering — a simulated crash (tests /
        chaos): established streams are severed mid-flight and the
        dispatcher learns via heartbeat timeout or a client failure
        report."""
        self._stop.set()
        self._close_status_server()  # the fleet aggregator sees it refuse
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            for s in list(self._conns):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    def _close_status_server(self) -> None:
        if self.status_server is not None:
            try:
                self.status_server.stop()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
            self.status_server = None

    def stop(self) -> None:
        self._stop.set()
        self._close_status_server()
        try:  # planned shutdown: free our shard immediately, don't wait
            _rpc(
                self._dispatcher,
                {"kind": "deregister_worker", "addr": self.addr},
                timeout=5.0, endpoint=f"dispatcher:{self._dispatcher}",
                policy=netrpc.RetryPolicy(deadline_s=5.0, max_attempts=1),
            )
        except OSError:
            pass
        self._server.shutdown()
        self._server.server_close()


# --- client -----------------------------------------------------------------


class _WorkerRefusal(RuntimeError):
    """Worker answered but refused the request (pool-snapshot mismatch).

    ``stale_rid`` (when the worker sent one) is the slot's current stream-
    attempt number: a LIVE successor stream — a post-resize client or
    another host resuming the slot — escalates past it and retries, which
    a dead predecessor's leftover pipelined frame can never do (its
    refusal lands on a closed socket)."""

    def __init__(self, message: str, *, stale_rid: int | None = None):
        super().__init__(message)
        self.stale_rid = stale_rid


class DataServiceClient:
    """Streaming batch puller over the live worker pool.

    One epoch = every split of the dispatcher's epoch snapshot drained to
    EOF.  ``protocol="streaming"`` (default) keeps one persistent
    connection + fetcher thread per split with a pipelined credit window;
    ``protocol="per_connection"`` is the v1 blocking round-robin (one TCP
    connection and one full round-trip per batch) kept as the measurable
    baseline (bench_input.py) and for v1 workers.

    Fault policy on mid-epoch worker death:

    - ``elastic=True`` (default, streaming only): report the death to the
      dispatcher, which re-assigns the dead worker's splits to survivors
      with delivered-batch skip counts — the epoch completes exactly-once.
    - ``elastic=False, ignore_errors=True``: drop the dead worker's
      remaining data (the reference's dynamic-pool semantics).
    - ``elastic=False, ignore_errors=False``: raise ``ConnectionError``.

    ``window`` is the per-split credit window (outstanding pipelined
    requests); with ``adaptive_window=True`` it autotunes between 1 and
    ``max_window`` from consumer blocking time, bounded by
    ``bytes_budget`` (see :class:`data.AdaptiveDepthController`).
    """

    _DONE = object()
    _ERR = object()

    def __init__(
        self,
        dispatcher: str,
        *,
        epoch: int | str = 0,
        ignore_errors: bool = False,
        elastic: bool = True,
        protocol: str = "streaming",
        wire: str = "raw",
        window: int = 2,
        adaptive_window: bool = True,
        max_window: int = 8,
        bytes_budget: int | None = None,
        buffer_batches: int | None = None,
        wait_for_workers_s: float = 30.0,
        get_next_timeout_s: float = 120.0,
        stream_retries: int = 2,
        progress_interval_s: float = 2.0,
    ):
        if protocol not in PROTOCOLS:
            raise ValueError(f"unknown protocol {protocol!r} ({PROTOCOLS})")
        if wire not in WIRE_FORMATS:
            raise ValueError(f"unknown wire {wire!r} ({WIRE_FORMATS})")
        self._dispatcher = dispatcher
        self._dispatcher_ep = f"dispatcher:{dispatcher}"
        self._epoch = str(epoch)
        self._ignore_errors = ignore_errors
        self._protocol = protocol
        self._elastic = elastic and protocol == "streaming"
        self._wire = wire
        self._timeout = get_next_timeout_s
        self._window = max(1, int(window))
        #: Bounded SAME-WORKER stream reconnections per fault before the
        #: failure is reported to the dispatcher (elastic eviction): a
        #: transient delay/sever is a transport fault, not a dead worker.
        self._stream_retries = max(0, int(stream_retries))
        self._stream_policy = netrpc.RetryPolicy(
            deadline_s=get_next_timeout_s, backoff_base_s=0.05,
            backoff_max_s=0.5,
        )
        self._client_id = uuid.uuid4().hex[:8]
        self._progress_interval_s = float(progress_interval_s)

        # metric handles resolved once (hot-path discipline)
        self._m_batches = _counter(
            "data_batches_total", "batches handed to the consumer"
        )
        self._m_wait = _histogram(
            "data_service_client_wait_seconds",
            "consumer blocking time per data-service batch",
        )
        self._m_fetch = _histogram(
            "data_service_fetch_seconds",
            "per-worker wire time per pipelined batch response",
        )
        self._m_dropped = _counter(
            "data_service_workers_dropped_total",
            "data workers dropped from this client's pool",
        )
        self._m_resharded = _counter(
            "data_service_resharded_splits_total",
            "splits elastically re-assigned after a worker death",
        )
        self._m_resumes = _counter(
            "data_service_stream_resumes_total",
            "same-worker stream reconnections (transport fault absorbed "
            "without evicting the worker)",
        )

        # Distributed tracing: ONE trace per epoch.  This root span is the
        # client anchor; the dispatcher's start_epoch span and every
        # split's fetch-stream span (and through it the workers') parent
        # under its trace_id, so `timeline.py --fleet` can stitch one
        # data-service fetch across processes.
        deadline = time.monotonic() + wait_for_workers_s
        resp: dict = {}
        with _remote_span(
            "data_service.start_epoch", epoch=self._epoch,
            dispatcher=dispatcher,
        ) as _ep_span:
            while time.monotonic() < deadline:
                try:
                    resp, _ = _rpc(
                        dispatcher,
                        {"kind": "start_epoch", "epoch": self._epoch},
                        timeout=5.0,
                        trace=_ep_span.context,
                        endpoint=self._dispatcher_ep,
                        # this grace loop IS the retry schedule
                        policy=netrpc.RetryPolicy(deadline_s=5.0,
                                                  max_attempts=1),
                    )
                except OSError:
                    # Dispatcher still starting up — that's what the grace
                    # window is for.
                    time.sleep(0.2)
                    continue
                if resp.get("ok"):
                    break
                time.sleep(0.2)
        self._trace_ctx = getattr(_ep_span, "context", None)
        if not resp.get("ok"):
            raise TimeoutError("no data workers registered")
        self._num_shards = int(resp["num_shards"])
        self._gen = int(resp["gen"])
        self._assignments: dict[int, dict] = {
            int(s): dict(v) for s, v in resp["splits"].items()
        }
        # Elastic resume: seed the delivered ledger from the dispatcher's
        # journaled per-split progress (max-merged over every client that
        # reported against this epoch), so a rebuilt client — the same
        # process after a resize, or another trainer host sharing the
        # epoch — fast-forwards past what the run already trained on
        # instead of re-pulling it.
        _progress = {
            int(s): int(n) for s, n in (resp.get("received") or {}).items()
        }
        self._received: dict[int, int] = {
            s: max(0, _progress.get(s, 0)) for s in self._assignments
        }
        # Batches actually handed to the consumer, per split.  `_received`
        # counts decode completion and drives stream-level resume WITHIN
        # this client (a buffered batch must not be refetched — it is
        # still going to be consumed); a batch sitting in the buffer at
        # close was never trained on, so CROSS-client continuation must
        # resume at the consumed position (re-fetching the buffered
        # remainder) or those batches are silently lost.  This is the
        # ledger progress reports and the drain handoff publish.
        self._consumed: dict[int, int] = dict(self._received)
        # Handout order of batches given to the puller but not yet
        # acknowledged as consumed (note_consumed pops from the left).
        self._handout: collections.deque[int] = collections.deque()
        # Monotonic per-split stream-attempt counter: rides each stream's
        # requests as ``rid`` so the worker can refuse a severed stream's
        # leftover pipelined frames (stale < current) instead of letting
        # them steal the slot back from the live resume stream.
        self._stream_rids: dict[int, int] = {s: 0 for s in self._assignments}
        self._dead_workers: set[str] = set()
        self._reshard_lock = threading.Lock()
        self._err: BaseException | None = None
        self._closed = False
        self._finished = False

        if protocol == "per_connection":
            # v1 path: blocking round-robin, no threads.  _rr indexes the
            # CURRENT live list (clamped on every shrink), so dropping a
            # worker can no longer skew rotation order.
            self._live = [
                self._assignments[s]["addr"]
                for s in sorted(self._assignments)
            ]
            self._rr = 0
            return

        self._controller = (
            AdaptiveDepthController(
                initial=self._window,
                min_depth=1,
                max_depth=max_window,
                bytes_budget=bytes_budget,
                component="client",
            )
            if adaptive_window
            else None
        )
        n = max(1, len(self._assignments))
        self._q: queue.Queue = queue.Queue(
            maxsize=buffer_batches or max(4, 2 * n)
        )
        self._pending = n  # fetchers still running
        self._pending_lock = threading.Lock()
        self._fetchers = [
            threading.Thread(
                target=self._fetch_loop,
                args=(split,),
                name=f"dtf-data-fetch-{split}",
                daemon=True,
            )
            for split in sorted(self._assignments)
        ]
        for t in self._fetchers:
            t.start()
        # Periodic exactly-once progress reports: the dispatcher journals
        # them, so a dispatcher restart mid-epoch still knows how far each
        # split got even before any failure report supplies a count.
        self._progress_stop = threading.Event()
        self._progress_thread = None
        if self._progress_interval_s > 0:
            self._progress_thread = threading.Thread(
                target=self._progress_loop,
                name="dtf-data-progress",
                daemon=True,
            )
            self._progress_thread.start()

    def _progress_loop(self) -> None:
        policy = netrpc.RetryPolicy(deadline_s=2.0, max_attempts=1)
        while not self._progress_stop.wait(self._progress_interval_s):
            try:
                self.flush_progress(timeout=2.0, policy=policy)
            except (OSError, ConnectionError):
                # Best-effort durability: a briefly-unreachable (or
                # breaker-open) dispatcher costs one report, nothing more.
                pass

    def flush_progress(self, timeout: float = 5.0,
                       policy: netrpc.RetryPolicy | None = None) -> bool:
        """Report the CONSUMED-batch ledger to the dispatcher now.

        The journaled counts are what a successor client (elastic resize,
        another trainer host on the same epoch) seeds from, so a drain
        calls this synchronously before :meth:`close` — the periodic loop
        alone could be up to ``progress_interval_s`` stale.  Reports
        consumed (trained-on) counts, not received: buffered batches die
        with this client and must be re-fetched by the successor.
        Returns True when the dispatcher acknowledged."""
        if self._protocol == "per_connection":
            return False
        with self._reshard_lock:
            consumed = {str(s): n for s, n in self._consumed.items()}
        resp, _ = _rpc(
            self._dispatcher,
            {
                "kind": "report_progress",
                "epoch": self._epoch,
                "client": self._client_id,
                "received": consumed,
            },
            timeout=timeout, endpoint=self._dispatcher_ep,
            policy=policy or netrpc.RetryPolicy(deadline_s=timeout,
                                                max_attempts=1),
        )
        return bool(resp.get("ok"))

    # -- streaming fetchers ---------------------------------------------------

    def _window_depth(self) -> int:
        return self._controller.depth if self._controller else self._window

    def _buffer_put(self, item) -> bool:
        """Bounded put that re-checks close, so a consumer that stops
        popping can never wedge a fetcher forever."""
        while not self._closed:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _fail(self, err: BaseException) -> None:
        self._err = err
        self._buffer_put(self._ERR)

    def _fetch_loop(self, split: int) -> None:
        resume_attempts = 0
        rid_retries = 0
        try:
            while not self._closed:
                with self._reshard_lock:
                    asg = dict(self._assignments[split])
                    gen = self._gen
                    # Resume position: the stream always starts at this
                    # client's ABSOLUTE delivered count (>= the
                    # assignment's skip once any batch has landed) — the
                    # worker's sid/pos reconciliation fast-forwards or
                    # adopts accordingly.
                    skip = max(int(asg["skip"]), self._received[split])
                addr = asg["addr"]
                try:
                    self._stream_split(split, addr, skip, gen)
                    return  # EOF: split fully delivered
                except _WorkerRefusal as e:
                    if (e.stale_rid is not None
                            and rid_retries < self._stream_retries):
                        # The slot's stream-attempt counter outran this
                        # client's (a fresh client resuming a slot a
                        # predecessor streamed — elastic resize, shared
                        # epoch): escalate past it and retry.  Bounded so
                        # two clients fighting over one slot fail instead
                        # of livelocking.
                        rid_retries += 1
                        with self._reshard_lock:
                            self._stream_rids[split] = max(
                                self._stream_rids[split], int(e.stale_rid)
                            )
                        logger.info(
                            "data stream split %d to %s: resume token "
                            "behind slot (rid -> %d); retry %d/%d",
                            split, addr, self._stream_rids[split] + 1,
                            rid_retries, self._stream_retries,
                        )
                        continue
                    # Config-level refusal (pool-snapshot mismatch), not a
                    # death — re-sharding can't fix it.
                    if self._ignore_errors:
                        self._m_dropped.inc()
                        logger.warning("dropping data worker %s: %s", addr, e)
                        return
                    self._fail(RuntimeError(str(e)))
                    return
                except (OSError, ConnectionError, wirelib.WireError) as e:
                    if self._closed:
                        return
                    with self._reshard_lock:
                        progressed = self._received[split] > skip
                        moved = self._assignments[split]["addr"] != addr
                    if progressed or moved:
                        # A fresh fault (or a reshard by a sibling) gets
                        # the full same-worker retry budget back.
                        resume_attempts = 0
                    if not moved and resume_attempts < self._stream_retries:
                        # Transport fault first: reconnect to the SAME
                        # worker with backoff+jitter before telling the
                        # dispatcher to evict it.
                        delay = netrpc.backoff_s(
                            self._stream_policy, resume_attempts
                        )
                        resume_attempts += 1
                        self._m_resumes.inc()
                        logger.info(
                            "data stream split %d to %s faulted (%s); "
                            "resume attempt %d/%d in %.2fs",
                            split, addr, e, resume_attempts,
                            self._stream_retries, delay,
                        )
                        time.sleep(delay)
                        continue
                    if not self._handle_stream_failure(split, addr, e):
                        return
                    resume_attempts = 0
        except BaseException as e:  # pragma: no cover - belt and braces
            self._fail(e)
        finally:
            with self._pending_lock:
                self._pending -= 1
                last = self._pending == 0
            if last:
                self._buffer_put(self._DONE)

    def _stream_split(self, split: int, addr: str, skip: int, gen: int) -> None:
        """Pipelined pull of one split over one persistent connection.

        One cross-process span per stream (parented under the epoch's
        trace); its context rides the FIRST ``get_next`` only — the
        worker records one matching span per stream, never per batch."""
        with _remote_span(
            "data_service.fetch_split", context=self._trace_ctx,
            split=split, worker=addr, skip=skip, gen=gen,
        ) as sp:
            self._stream_split_traced(
                split, addr, skip, gen, getattr(sp, "context", None)
            )

    def _stream_split_traced(
        self, split: int, addr: str, skip: int, gen: int,
        trace_ctx: dict | None,
    ) -> None:
        with self._reshard_lock:
            self._stream_rids[split] += 1
            rid = self._stream_rids[split]
        request = {
            "kind": "get_next",
            "epoch": self._epoch,
            "split": split,
            "num_shards": self._num_shards,
            "skip": skip,
            "gen": gen,
            "wire": self._wire,
            # Per-stream resume token + monotonic attempt number: the
            # worker adopts/rebuilds its iterator slot by comparing this
            # stream's skip to the slot position whenever the sid changes
            # (reconnect-with-resume), and refuses frames whose rid is
            # stale (a severed predecessor's buffered pipeline).
            "sid": f"{self._client_id}-{split}-{uuid.uuid4().hex[:8]}",
            "rid": rid,
        }
        # Dialing rides the net substrate: backoff+jitter inside a short
        # connect deadline (the fetch loop owns the longer retry/evict
        # policy), breaker feed, and sever-target registration (chaos).
        s, token = netrpc.connect_stream(
            addr, endpoint=f"data_worker:{addr}", timeout_s=self._timeout,
            connect_deadline_s=2.0, policy=self._stream_policy,
        )
        try:
            self._stream_pump(s, request, split, addr, trace_ctx)
        finally:
            netrpc.unregister_stream(token)
            try:
                s.close()
            except OSError:
                pass

    def _stream_pump(self, s: socket.socket, request: dict, split: int,
                     addr: str, trace_ctx: dict | None) -> None:
        outstanding = 0
        traced_sent = trace_ctx is None  # inject once per stream
        while not self._closed:
            # Credit window: keep W get_nexts on the wire.  Requests
            # are tiny JSON frames; the responses stream back in order
            # on the same socket while we decode/enqueue.
            target = max(1, self._window_depth())
            while outstanding < target:
                if not traced_sent:
                    traced_sent = True
                    _send_msg(s, dict(request, trace=trace_ctx))
                else:
                    _send_msg(s, request)
                outstanding += 1
            t0 = time.perf_counter()
            header, data = _recv_msg(s)
            self._m_fetch.observe(time.perf_counter() - t0, worker=addr)
            outstanding -= 1
            if not header.get("ok"):
                raise _WorkerRefusal(
                    f"data worker {addr}: {header.get('error')}",
                    stale_rid=header.get("stale_rid"),
                )
            if header.get("eof"):
                # In-flight requests beyond EOF answer eof too; the
                # socket just closes under them.
                return
            batch = decode_batch(data)
            # Exactly-once accounting: count only fully-received,
            # decoded batches — a response torn mid-wire is refetched
            # by the takeover worker, a counted one never is.
            with self._reshard_lock:
                self._received[split] += 1
            if self._controller:
                self._controller.note_bytes(wirelib.tensor_bytes(batch))
            if not self._buffer_put((split, batch)):
                return

    def _handle_stream_failure(
        self, split: int, addr: str, err: BaseException
    ) -> bool:
        """True = assignment refreshed, retry the split; False = stop."""
        with self._reshard_lock:
            if self._assignments[split]["addr"] != addr:
                return True  # assignment already refreshed elsewhere
            # Snapshot ONLY this fetcher's split with ONLY its own count:
            # a sibling fetcher of the same dead worker may be holding a
            # decoded-but-not-yet-counted batch, and a whole-worker report
            # would snapshot its count one short (delivering that batch
            # twice after takeover).
            count = int(self._received[split])
        if self._elastic:
            # The RPC runs OUTSIDE the lock: holding it across a blocking
            # (up to 10 s) dispatcher round-trip would stall every healthy
            # fetcher at its per-batch count increment.
            with _remote_span(
                "data_service.report_failure", context=self._trace_ctx,
                worker=addr, split=split,
            ) as _rp_span:
                try:
                    resp, _ = _rpc(
                        self._dispatcher,
                        {
                            "kind": "report_worker_failure",
                            "epoch": self._epoch,
                            "addr": addr,
                            "split": split,
                            "received": {str(split): count},
                        },
                        timeout=10.0,
                        trace=getattr(_rp_span, "context", None),
                        endpoint=self._dispatcher_ep,
                    )
                except OSError as e:
                    resp = {
                        "ok": False,
                        "error": f"dispatcher unreachable: {e}",
                    }
            if resp.get("ok"):
                with self._reshard_lock:
                    # Concurrent reports interleave; only move forward (a
                    # lower-gen response must not roll assignments back).
                    if int(resp["gen"]) >= self._gen:
                        self._gen = int(resp["gen"])
                        self._assignments = {
                            int(s): dict(v)
                            for s, v in resp["splits"].items()
                        }
                    if addr not in self._dead_workers:
                        self._dead_workers.add(addr)
                        self._m_dropped.inc()
                    gen = self._gen
                moved = resp.get("moved", [])
                self._m_resharded.inc(len(moved))
                _record_event(
                    "data_reshard",
                    worker=addr,
                    splits=len(moved),
                    gen=gen,
                    epoch=self._epoch,
                )
                logger.warning(
                    "data worker %s died mid-epoch (%s); splits %s "
                    "resharded at gen %d",
                    addr, err, moved, gen,
                )
                return True
            logger.warning(
                "elastic reshard for %s failed: %s",
                addr, resp.get("error"),
            )
        if self._ignore_errors:
            self._m_dropped.inc()
            logger.warning(
                "dropping dead data worker %s (split %d remainder lost)",
                addr, split,
            )
            return False
        e = ConnectionError(f"data worker {addr} died mid-epoch")
        e.__cause__ = err
        self._fail(e)
        return False

    # -- consumer -------------------------------------------------------------

    def __iter__(self) -> Iterator[Batch]:
        return self

    def __next__(self) -> Batch:
        if self._protocol == "per_connection":
            return self._next_per_connection()
        if self._finished:
            if self._err is not None:
                raise self._err
            raise StopIteration
        t0 = time.perf_counter()
        try:
            item = self._q.get(timeout=self._timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no batch from the data service within {self._timeout}s"
            ) from None
        wait = time.perf_counter() - t0
        self._m_wait.observe(wait)
        if self._controller:
            self._controller.observe_wait(wait)
        if item is self._ERR or item is self._DONE:
            self._finished = True
            if self._err is not None:
                raise self._err
            raise StopIteration
        _split, batch = item
        with self._reshard_lock:
            # Not consumed YET: the puller (the Prefetcher) buffers
            # ahead of the trainer, and a batch still in ITS buffer at
            # close was never trained on.  Remember the handout order;
            # note_consumed() advances the per-split consumed ledger
            # when the downstream consumer actually takes the batch.
            self._handout.append(_split)
        self._m_batches.inc()
        return batch

    def note_consumed(self, n: int = 1) -> None:
        """Advance the consumed ledger by ``n`` batches, in handout order.

        Called by the downstream consumer (``Prefetcher.__next__``) when
        batches actually reach the training loop — counting at our own
        ``__next__`` would overshoot by whatever the consumer still has
        buffered at close, and a same-epoch successor would skip batches
        that were never trained on (lost work)."""
        with self._reshard_lock:
            for _ in range(n):
                if not self._handout:
                    break
                s = self._handout.popleft()
                self._consumed[s] = self._consumed.get(s, 0) + 1

    def _next_per_connection(self) -> Batch:
        while self._live:
            if self._rr >= len(self._live):
                self._rr = 0
            addr = self._live[self._rr]
            try:
                header, data = _rpc(
                    addr,
                    {
                        "kind": "get_next",
                        "epoch": self._epoch,
                        "num_shards": self._num_shards,
                        "wire": self._wire,
                    },
                    timeout=self._timeout,
                    # get_next is NOT idempotent: a transport retry after
                    # a lost response would skip a batch — the v1 fault
                    # policy (drop/raise) handles it instead.
                    policy=netrpc.RetryPolicy(deadline_s=self._timeout,
                                              max_attempts=1),
                )
            except OSError as e:
                if not self._ignore_errors:
                    raise ConnectionError(
                        f"data worker {addr} died mid-epoch"
                    ) from e
                logger.warning("dropping dead data worker %s", addr)
                self._m_dropped.inc()
                self._live.remove(addr)
                continue
            if not header.get("ok"):
                # Worker refused (shard/pool mismatch after membership
                # change) — its data can't be served consistently this epoch.
                if not self._ignore_errors:
                    raise RuntimeError(
                        f"data worker {addr}: {header.get('error')}"
                    )
                logger.warning(
                    "dropping data worker %s: %s", addr, header.get("error")
                )
                self._m_dropped.inc()
                self._live.remove(addr)
                continue
            if header.get("eof"):
                self._live.remove(addr)
                continue
            self._rr = (self._rr + 1) % len(self._live)
            self._m_batches.inc()
            return decode_batch(data)
        raise StopIteration

    def received_counts(self) -> dict[int, int]:
        """Cumulative fully-received batches per split (the exactly-once
        ledger the elastic re-shard skip counts come from)."""
        if self._protocol == "per_connection":
            return {}
        with self._reshard_lock:
            return dict(self._received)

    def consumed_counts(self) -> dict[int, int]:
        """Cumulative batches handed to the consumer per split (the
        cross-client continuation ledger — what a drain journals)."""
        if self._protocol == "per_connection":
            return {}
        with self._reshard_lock:
            return dict(self._consumed)

    def close(self) -> None:
        """Stop fetcher threads and release buffered batches.  Flushes a
        final progress report first (best-effort), so a successor client
        on the same epoch seeds from this client's true consumed
        position rather than a stale periodic report."""
        if self._protocol == "per_connection":
            return
        try:
            self.flush_progress(timeout=2.0)
        except (OSError, ConnectionError):
            pass
        self._closed = True
        self._progress_stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        # The drain above may have discarded the DONE sentinel; re-arm it
        # so a consumer blocked in __next__ wakes NOW instead of sitting
        # out the full get_next_timeout_s.
        try:
            self._q.put_nowait(self._DONE)
        except queue.Full:  # pragma: no cover - queue was just drained
            pass
        for t in self._fetchers:
            t.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
