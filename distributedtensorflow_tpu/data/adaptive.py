"""Adaptive prefetch-depth control for the input plane.

One controller class drives both knobs the pod-scale input plane tunes at
runtime (ROADMAP item 4: "host-side prefetch depth tuned from the goodput
ledger's data_wait bucket"):

- the :class:`data.Prefetcher` host→device buffer depth, and
- the :class:`data.DataServiceClient` per-split credit window.

Policy — driven by the same consumer-blocking signal the
``data_wait_seconds`` histogram records:

- **grow** while the consumer blocks (mean wait over the last ``interval``
  pops above ``grow_wait_s``): the pipeline is input-bound or bursty, more
  in-flight batches absorb the jitter;
- **shrink** when waits are ~0 (below ``shrink_wait_s``): the buffer is
  always full and every extra slot is idle host/device memory;
- always bounded by ``[min_depth, max_depth]`` AND a **bytes budget**: the
  depth cap is ``bytes_budget // observed_batch_bytes`` (EWMA of
  :meth:`note_bytes`), so a fatter batch automatically means a shallower
  queue.

Every decision is exported: the ``data_prefetch_depth{component=}`` gauge
tracks the live depth, ``data_prefetch_resizes_total{component=,direction=}``
counts decisions, and :func:`input_record_fields` (re-exported from
``data.input_pipeline``) stamps the depths into every metric record the
Trainer logs.

Telemetry degrades to no-ops where the obs registry (which pulls jax) is
unavailable — the controller also runs inside bare data-worker hosts.
"""

from __future__ import annotations

import threading

# The one guarded obs import for the data package (service.py shares
# these shims): telemetry degrades to no-ops where obs — which pulls jax
# — is absent.
try:  # pragma: no cover - exercised implicitly everywhere obs imports
    from ..obs.registry import counter as _counter
    from ..obs.registry import gauge as _gauge
    from ..obs.registry import histogram as _histogram
    from ..obs.flight_recorder import record_event as _record_event
    from ..obs.tracing import remote_span as _remote_span
except Exception:  # pragma: no cover
    class _Null:
        def inc(self, *a, **k): pass
        def set(self, *a, **k): pass
        def observe(self, *a, **k): pass
        def value(self, *a, **k): return 0.0

    def _counter(name, help=""): return _Null()
    def _gauge(name, help=""): return _Null()
    def _histogram(name, help=""): return _Null()
    def _record_event(kind, **fields): pass

    class _remote_span:  # no-op cross-process span (context stays None)
        context = None

        def __init__(self, name, **fields): pass
        def __enter__(self): return self
        def __exit__(self, *exc): return False

#: Live controllers by component name ("prefetcher" / "client"), for the
#: per-record fields.  Last constructed wins — one Prefetcher + one client
#: per training process is the wiring train.py builds.
_CONTROLLERS: dict[str, "AdaptiveDepthController"] = {}
_CONTROLLERS_LOCK = threading.Lock()

#: Component → metric-record field name.
_RECORD_FIELDS = {
    "prefetcher": "data_prefetch_depth",
    "client": "data_client_window",
}


class AdaptiveDepthController:
    """Autotunes a queue depth / credit window from consumer wait times.

    Thread contract: ``observe_wait`` is called by the consumer thread,
    ``note_bytes`` by producer threads, ``depth`` read from anywhere; all
    state updates run under one small lock (per-batch cadence, not
    per-element — nowhere near hot).
    """

    def __init__(
        self,
        *,
        initial: int = 2,
        min_depth: int = 1,
        max_depth: int = 16,
        grow_wait_s: float = 2e-3,
        shrink_wait_s: float = 2e-4,
        interval: int = 8,
        bytes_budget: int | None = None,
        component: str = "prefetcher",
    ):
        if min_depth < 1 or max_depth < min_depth:
            raise ValueError(
                f"bad depth bounds [{min_depth}, {max_depth}]"
            )
        if shrink_wait_s > grow_wait_s:
            raise ValueError(
                f"shrink_wait_s {shrink_wait_s} exceeds grow_wait_s "
                f"{grow_wait_s} (the controller would oscillate)"
            )
        self.min_depth = int(min_depth)
        self.max_depth = int(max_depth)
        self.grow_wait_s = float(grow_wait_s)
        self.shrink_wait_s = float(shrink_wait_s)
        self.interval = max(1, int(interval))
        self.bytes_budget = bytes_budget
        self.component = component
        self._lock = threading.Lock()
        self._depth = min(max(int(initial), self.min_depth), self.max_depth)
        self._waits: list[float] = []
        self._item_bytes = 0.0  # EWMA of observed batch bytes
        self._g_depth = _gauge(
            "data_prefetch_depth",
            "live adaptive prefetch depth / credit window",
        )
        self._m_resizes = _counter(
            "data_prefetch_resizes_total",
            "adaptive depth-controller decisions",
        )
        self._g_depth.set(self._depth, component=component)
        with _CONTROLLERS_LOCK:
            _CONTROLLERS[component] = self

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def item_bytes(self) -> float:
        return self._item_bytes

    def byte_cap(self) -> int:
        """Depth allowed by the bytes budget (max_depth when unbudgeted
        or before the first batch size lands)."""
        if not self.bytes_budget or self._item_bytes <= 0:
            return self.max_depth
        return min(
            self.max_depth,
            max(self.min_depth, int(self.bytes_budget // self._item_bytes)),
        )

    def note_bytes(self, nbytes: int) -> None:
        with self._lock:
            self._item_bytes = (
                float(nbytes) if self._item_bytes == 0.0
                else 0.9 * self._item_bytes + 0.1 * float(nbytes)
            )
            # A budget violation shrinks immediately, without waiting for
            # the next wait-window decision.
            cap = self.byte_cap()
            if self._depth > cap:
                self._set_depth(cap, "shrink")

    def observe_wait(self, seconds: float) -> int:
        """Record one consumer blocking time; returns the (possibly
        updated) depth."""
        with self._lock:
            self._waits.append(float(seconds))
            if len(self._waits) >= self.interval:
                mean = sum(self._waits) / len(self._waits)
                self._waits.clear()
                cap = self.byte_cap()
                d = self._depth
                if mean > self.grow_wait_s:
                    d += 1
                elif mean < self.shrink_wait_s:
                    d -= 1
                d = min(max(d, self.min_depth), cap)
                if d != self._depth:
                    self._set_depth(
                        d, "grow" if d > self._depth else "shrink"
                    )
            return self._depth

    def _set_depth(self, d: int, direction: str) -> None:
        self._depth = d
        self._g_depth.set(d, component=self.component)
        self._m_resizes.inc(direction=direction, component=self.component)


def input_record_fields() -> dict[str, float]:
    """Live input-plane depths as per-record metric fields
    (``data_prefetch_depth`` / ``data_client_window``); empty when no
    adaptive controller is running."""
    out: dict[str, float] = {}
    with _CONTROLLERS_LOCK:
        for component, ctl in _CONTROLLERS.items():
            field = _RECORD_FIELDS.get(component)
            if field is not None:
                out[field] = float(ctl.depth)
    return out
