"""Strategy-compatibility shim: the reference API surface on the mesh engine.

Migration layer for users of the reference's ``tf.distribute`` strategy zoo
(SURVEY.md §2.1): each strategy class here resolves to its mesh equivalent
(§2.4 coverage matrix) — because under SPMD **a strategy is just a mesh
shape**.  The classes expose the strategy surface that survives the
paradigm change:

- ``scope()`` — enters the mesh (``jax.sharding.set_mesh``); sharded-state
  creation inside behaves like variable creation under a strategy scope.
- ``num_replicas_in_sync`` — data-parallel width.
- ``experimental_distribute_dataset`` / ``distribute_datasets_from_function``
  — per-host input sharding (`InputContext` semantics,
  `distribute_lib.py:841/:1349`).
- ``run(fn, args)`` — jit-compiles ``fn`` over the mesh; with batch-leading
  args this is the ``strategy.run`` data-parallel step
  (`distribute_lib.py:1557`).
- ``reduce(op, value)`` — cross-replica reduction of a sharded array
  (`distribute_lib.py:1675`).

Semantic deltas from the reference (documented, deliberate):
- ``ParameterServerStrategy`` maps to *synchronous* training with
  embeddings sharded over the ``model`` axis (SURVEY.md §7 hard parts:
  TPU has no async PS; capability parity is sharded big-embedding
  training + the ``parallel.Coordinator`` for host-side fan-out).
- ``MultiWorkerMirroredStrategy`` boots the JAX distributed runtime
  (coordination service) instead of a gRPC server mesh.
"""

from __future__ import annotations

import collections
import contextlib
import logging
from collections.abc import Callable, Iterator

import jax
import jax.numpy as jnp

from .data.input_pipeline import (
    InputContext,
    current_input_context,
    shard_dataset,
    tfdata_iterator,
)
from .parallel import bootstrap
from .parallel.mesh import (
    MeshSpec,
    build_mesh,
    mirrored_mesh,
    multi_worker_mesh,
    one_device_mesh,
)

logger = logging.getLogger("distributedtensorflow_tpu")


class Strategy:
    """Base: a named mesh shape plus the surviving strategy surface."""

    def __init__(self, mesh_spec: MeshSpec | None = None, devices=None,
                 *, mesh=None):
        self.mesh = mesh if mesh is not None else build_mesh(
            mesh_spec or MeshSpec(data=-1), devices
        )
        # Bounded FIFO cache (a weak-key dict would never evict: the jitted
        # value strongly references the key fn).  Stable fn references hit
        # the cache; per-step lambdas churn through it without growing it.
        self._jit_cache: collections.OrderedDict = collections.OrderedDict()
        self._jit_cache_max = 64
        self._reducers: dict = {}

    # --- scope ------------------------------------------------------------

    @contextlib.contextmanager
    def scope(self):
        """Enter the mesh: jit calls inside see it as the ambient mesh."""
        with jax.sharding.set_mesh(self.mesh):
            yield self

    # --- replica topology -------------------------------------------------

    @property
    def num_replicas_in_sync(self) -> int:
        shape = dict(self.mesh.shape)
        return shape.get("data", 1) * shape.get("fsdp", 1)

    # --- input ------------------------------------------------------------

    def distribute_datasets_from_function(
        self, dataset_fn: Callable[[InputContext], Iterator], *,
        global_batch_size: int = 0,
    ) -> Iterator:
        ctx = current_input_context(global_batch_size)
        return dataset_fn(ctx)

    def experimental_distribute_dataset(self, ds) -> Iterator:
        """Shard a tf.data.Dataset per host (DATA policy) and iterate numpy."""
        ctx = current_input_context(0)
        return tfdata_iterator(shard_dataset(ds, ctx))

    # --- compute ----------------------------------------------------------

    def run(self, fn: Callable, args: tuple = (), kwargs: dict | None = None):
        """Run ``fn`` jitted over the mesh (once — SPMD, not per-replica).

        The jitted wrapper is cached per ``fn`` (weakly) so per-step calls
        with a STABLE function reference hit the jit cache instead of
        retracing (strategy.run is the reference's per-step entry point).
        A fresh lambda per call defeats the cache — hoist it.
        """
        jitted = self._jit_cache.get(fn)
        if jitted is None:
            jitted = self._jit_cache[fn] = jax.jit(fn)
            while len(self._jit_cache) > self._jit_cache_max:
                self._jit_cache.popitem(last=False)
        with jax.sharding.set_mesh(self.mesh):
            return jitted(*args, **(kwargs or {}))

    def reduce(self, reduce_op: str, value: jax.Array, axis=None):
        """Cross-replica reduce (`distribute_lib.py:1675`): 'sum' | 'mean' |
        'max' | 'min' over ``axis`` (None = all axes).

        Under SPMD a sharded ``jax.Array`` IS the across-all-replicas value,
        so the reduction is compiled over the mesh — for sharded inputs XLA
        emits the cross-device collective (psum-family) on device, and only
        the reduced result is fetched to host.  The jitted reducer is cached
        per (op, axis).
        """
        ops = {"sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min}
        if isinstance(axis, list):
            axis = tuple(axis)
        key = (reduce_op.lower(), axis)
        fn = self._reducers.get(key)
        if fn is None:
            op = ops[key[0]]  # KeyError on unknown op, matching the reference
            fn = self._reducers[key] = jax.jit(lambda v: op(v, axis=axis))
        with jax.sharding.set_mesh(self.mesh):
            return jax.device_get(fn(value))

    def gather(self, value: jax.Array, axis: int = 0):
        """Reference ``Strategy.gather`` (`distribute_lib.py:2109`): the
        per-replica shards concatenated along ``axis``, as one host array on
        every process.

        Under SPMD the global sharded array already has the concatenated
        semantics (``axis`` is its existing batch dim, kept for signature
        parity); this returns a fully-replicated host copy — in multi-host
        runs the shards other processes own are all-gathered first.
        """
        del axis  # global arrays are already concatenated along it
        import numpy as np

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(value, tiled=True))
        return np.asarray(jax.device_get(value))


class OneDeviceStrategy(Strategy):
    """Reference `one_device_strategy.py:39` → mesh with every axis = 1
    (on a *local* device — `mesh.one_device_mesh`)."""

    def __init__(self, device=None):
        super().__init__(mesh=one_device_mesh(device))


class MirroredStrategy(Strategy):
    """Reference `mirrored_strategy.py:200` (in-host sync DP) →
    ``data=-1`` over this process's devices (`mesh.mirrored_mesh`)."""

    def __init__(self, devices=None):
        super().__init__(mesh=mirrored_mesh(devices))


class MultiWorkerMirroredStrategy(Strategy):
    """Reference `collective_all_reduce_strategy.py:57` (multi-host sync DP)
    → distributed runtime up + ``data=-1`` over ALL devices."""

    def __init__(self, cluster=None):
        bootstrap.initialize(cluster)
        super().__init__(mesh=multi_worker_mesh())


class ParameterServerStrategy(Strategy):
    """Reference `parameter_server_strategy_v2.py:77` →  **sync** training
    with parameters shardable over the ``model`` axis (embedding-TP replaces
    PS-sharded variables; see module docstring for the semantic delta)."""

    def __init__(self, model_axis_size: int = -1, devices=None):
        n = len(devices if devices is not None else jax.devices())
        if model_axis_size == -1:
            # Largest divisor of n that is <= n//2 (1 when n is 1 or prime).
            model_axis_size = next(
                (d for d in range(n // 2, 0, -1) if n % d == 0), 1
            )
        super().__init__(MeshSpec(data=-1, model=model_axis_size), devices)
        logger.info(
            "ParameterServerStrategy maps to sync sharded-variable training "
            "(model axis = %d); use parallel.Coordinator for async host-side "
            "dispatch", model_axis_size,
        )


class TPUStrategy(Strategy):
    """Reference `tpu_strategy.py:243` → the native path: all devices, DP by
    default; pass a richer ``MeshSpec`` directly for tp/pp/sp/ep."""

    def __init__(self, mesh_spec: MeshSpec | None = None):
        super().__init__(mesh_spec or MeshSpec(data=-1))
