"""Resilient RPC substrate: deadlines, retries, reconnection, breakers.

Every cross-process byte in this codebase rides one of four transports —
data-service RPCs/streams (``data/service.py``), MPMD pipeline links
(``parallel/pipeline_mpmd.py``), fleet ``/varz`` scrapes (``obs/fleet.py``)
and the serve HTTP path — and before this module each of them treated a
transient network fault as a hard failure.  This is the shared substrate
they all route through instead:

- **per-call deadlines with propagation**: a :class:`Deadline` bounds the
  whole call (connect + send + recv + every retry), and the *remaining*
  budget is stamped into the request frame as ``deadline_s`` so the
  server can bound its own work / downstream calls by the caller's
  actual patience (:func:`remaining_from_request`);
- **bounded retries with exponential backoff + jitter**
  (:class:`RetryPolicy`, :func:`backoff_s`): transport-level failures
  (refused/severed/timed out) retry until the attempt budget or the
  deadline runs out — application-level refusals (``ok: false``) are
  returned, never retried;
- **transparent reconnection for persistent streams**:
  :func:`connect_stream` dials with the same backoff/deadline machinery,
  registers the socket so chaos can sever it (:func:`sever_streams`),
  and the owning stream protocol resumes exactly-once via its own resume
  token (see ``data/service.py``'s ``sid`` contract);
- **per-endpoint circuit breakers** (:mod:`net.breaker`): a persistently
  dead endpoint fails fast locally instead of burning a full timeout per
  call; the half-open probe re-closes it when the peer returns.

Wire format: unchanged from the data-service v1 protocol — every frame is
``uint64 LE length + payload``; a request/response is one JSON frame
optionally followed by one binary frame (``has_data``).  The framing
primitives live HERE now (``data/service.py`` re-exports them) so the
substrate has no dependency on any one transport.

Telemetry (obs registry; no-ops on bare hosts without jax/obs):
``rpc_retries_total{endpoint,outcome}`` (every retried attempt, by
whether the retry succeeded), ``rpc_deadline_exceeded_total{endpoint}``,
``rpc_attempt_seconds{endpoint}`` per-attempt wall histograms, plus the
``breaker_*`` family from :mod:`net.breaker`.

Chaos hooks (``resilience/chaos.py`` ``net_*`` fault kinds): faults are
armed process-locally with :func:`arm_fault` (``net_delay`` /
``net_drop`` credit-bounded against matching endpoints) or injected
immediately with :func:`sever_streams`; the first successful matching
call after a fault's credits are spent fires its ``on_recovered``
callback — that is what pairs the ``recovered`` row in ``faults.jsonl``.

Endpoint identities are low-cardinality strings naming the failure
domain: ``"dispatcher"``, ``"data_worker:<addr>"``, ``"mpmd_link:<i>"``,
``"fleet_peer:<name>"``.  The prefix before the first ``:`` must come
from :data:`ENDPOINT_PREFIXES` — ``tools/check_metrics_schema.py`` gates
the exported label values against it.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import random
import socket
import threading
import time

from .breaker import (
    BreakerOpenError,
    _counter,
    _histogram,
    breaker_for,
)

logger = logging.getLogger("distributedtensorflow_tpu")

__all__ = [
    "Deadline",
    "DeadlineExceeded",
    "ENDPOINT_PREFIXES",
    "RetryPolicy",
    "arm_fault",
    "backoff_s",
    "call",
    "clear_faults",
    "connect_stream",
    "connect_with_retry",
    "http_get",
    "http_post",
    "note_success",
    "recv_frame",
    "recv_msg",
    "register_stream",
    "remaining_from_request",
    "send_frame",
    "send_msg",
    "sever_streams",
    "unregister_stream",
    "watch_recovery",
]

#: Known endpoint-identity prefixes (the part before the first ``:``).
#: The schema checker mirrors this tuple — a typo'd endpoint label would
#: silently fork every ``rpc_*`` time series.
ENDPOINT_PREFIXES = (
    "dispatcher", "data_worker", "mpmd_link", "fleet_peer", "serve",
    "peer", "webhook",
)

#: ``rpc_retries_total`` outcome label values (mirrored by the checker).
RETRY_OUTCOMES = ("ok", "error")

_M_RETRIES = _counter(
    "rpc_retries_total",
    "retried RPC attempts by endpoint and retry outcome",
)
_M_DEADLINE = _counter(
    "rpc_deadline_exceeded_total",
    "RPC calls abandoned at their deadline, by endpoint",
)
_H_ATTEMPT = _histogram(
    "rpc_attempt_seconds",
    "wall time of one RPC attempt (connect+send+recv), by endpoint",
)


class DeadlineExceeded(OSError):
    """The call's total wall budget ran out (connect, retry backoff, or
    response wait).  Subclasses ``OSError`` so every existing transport
    fault policy handles it like the timeout it is."""

    def __init__(self, message: str, *, endpoint: str = ""):
        super().__init__(message)
        self.endpoint = endpoint


class Deadline:
    """Absolute wall-clock budget carried through one logical operation."""

    __slots__ = ("_t_end",)

    def __init__(self, seconds: float):
        self._t_end = time.monotonic() + float(seconds)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(seconds)

    def remaining(self) -> float:
        return self._t_end - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry/deadline shape of one call family.

    ``deadline_s`` bounds the WHOLE call including backoff sleeps;
    ``max_attempts`` bounds transport-level retries (1 = no retry);
    backoff for attempt ``k`` (0-based retry index) is
    ``min(backoff_base_s * 2**k, backoff_max_s)`` stretched by a
    uniform jitter in ``[1 - jitter, 1 + jitter]``.
    """

    deadline_s: float = 30.0
    max_attempts: int = 4
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.5
    connect_timeout_s: float = 5.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got "
                             f"{self.max_attempts}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")


DEFAULT_POLICY = RetryPolicy()
#: Single-shot policy for callers with their own outer retry loop.
ONESHOT_POLICY = RetryPolicy(max_attempts=1)


def backoff_s(policy: RetryPolicy, retry_index: int,
              rng: random.Random | None = None) -> float:
    """Backoff before retry ``retry_index`` (0-based): capped exponential
    with multiplicative jitter.  Pass a seeded ``rng`` for a reproducible
    schedule (tests; chaos determinism)."""
    base = min(
        policy.backoff_base_s * (2.0 ** retry_index), policy.backoff_max_s
    )
    if policy.jitter <= 0.0:
        return base
    r = rng if rng is not None else random
    return base * (1.0 + policy.jitter * (2.0 * r.random() - 1.0))


def remaining_from_request(req: dict) -> float | None:
    """The caller's remaining deadline budget a request frame carries
    (``deadline_s``, stamped by :func:`call`), or None.  Servers use it
    to bound their own work — honoring a deadline end-to-end means never
    working past the moment the caller stopped listening."""
    v = req.get("deadline_s")
    if isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0:
        return None
    return float(v)


# --- framing (the shared length-prefixed JSON[+binary] wire) -----------------


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(len(payload).to_bytes(8, "little") + payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> bytes:
    n = int.from_bytes(recv_exact(sock, 8), "little")
    if n > (1 << 31):
        raise ConnectionError(f"oversized frame ({n} bytes)")
    return recv_exact(sock, n)


def send_msg(sock: socket.socket, header: dict,
             data: bytes | None = None) -> None:
    header = dict(header, has_data=data is not None)
    send_frame(sock, json.dumps(header).encode())
    if data is not None:
        send_frame(sock, data)


def recv_msg(sock: socket.socket) -> tuple[dict, bytes | None]:
    header = json.loads(recv_frame(sock))
    data = recv_frame(sock) if header.get("has_data") else None
    return header, data


# --- chaos fault injection ---------------------------------------------------


class _Fault:
    __slots__ = ("kind", "match", "calls", "delay_s", "on_recovered",
                 "exhausted")

    def __init__(self, kind, match, calls, delay_s, on_recovered):
        self.kind = kind
        self.match = match
        self.calls = calls
        self.delay_s = delay_s
        self.on_recovered = on_recovered
        self.exhausted = calls is not None and calls <= 0


_FAULTS: list[_Fault] = []
_FAULTS_LOCK = threading.Lock()
#: Live persistent-stream sockets by id: (socket, endpoint).
_STREAMS: dict[int, tuple[socket.socket, str]] = {}
_STREAMS_LOCK = threading.Lock()
_STREAM_IDS = iter(range(1, 1 << 62))


def arm_fault(kind: str, *, calls: int = 1, delay_s: float = 0.0,
              match: str = "", on_recovered=None) -> None:
    """Arm a deterministic transport fault against the next ``calls``
    attempts whose endpoint contains ``match`` (chaos hook):

    - ``net_delay``: sleep ``delay_s`` before the attempt proceeds;
    - ``net_drop``: fail the attempt with ``ConnectionError`` before any
      byte is sent.

    Once the credits are spent, the first successful matching attempt
    fires ``on_recovered()`` (exactly once) — proof the transport
    absorbed the fault.
    """
    if kind not in ("net_delay", "net_drop"):
        raise ValueError(f"unknown net fault kind {kind!r}")
    with _FAULTS_LOCK:
        _FAULTS.append(_Fault(kind, match, int(calls), float(delay_s),
                              on_recovered))


def watch_recovery(match: str = "", on_recovered=None) -> None:
    """Fire ``on_recovered()`` on the next successful matching attempt
    (used by ``net_sever``, whose injection is immediate)."""
    with _FAULTS_LOCK:
        f = _Fault("watch", match, None, 0.0, on_recovered)
        f.exhausted = True
        _FAULTS.append(f)


def clear_faults() -> None:
    """Drop every armed fault/watch (test isolation)."""
    with _FAULTS_LOCK:
        _FAULTS.clear()


def _apply_faults(endpoint: str) -> None:
    """Consume one credit of every armed fault matching ``endpoint``;
    sleeps (delay) happen outside the lock, drops raise."""
    delay = 0.0
    drop = False
    with _FAULTS_LOCK:
        for f in _FAULTS:
            if f.exhausted or f.match not in endpoint:
                continue
            f.calls -= 1
            if f.calls <= 0:
                f.exhausted = True
            if f.kind == "net_delay":
                delay = max(delay, f.delay_s)
            elif f.kind == "net_drop":
                drop = True
    if delay > 0.0:
        time.sleep(delay)
    if drop:
        raise ConnectionError(f"chaos: dropped rpc to {endpoint}")


def note_success(endpoint: str) -> None:
    """Record a successful attempt against ``endpoint``: exhausted
    matching faults fire their recovery callback and retire."""
    fired = []
    with _FAULTS_LOCK:
        keep = []
        for f in _FAULTS:
            if f.exhausted and f.match in endpoint:
                if f.on_recovered is not None:
                    fired.append(f.on_recovered)
            else:
                keep.append(f)
        _FAULTS[:] = keep
    for cb in fired:
        try:
            cb()
        except Exception:  # pragma: no cover - chaos bookkeeping only
            logger.exception("net fault recovery callback failed")


def register_stream(sock: socket.socket, endpoint: str) -> int:
    """Track a live persistent-stream socket (chaos sever target).
    Returns a token for :func:`unregister_stream`."""
    sid = next(_STREAM_IDS)
    with _STREAMS_LOCK:
        _STREAMS[sid] = (sock, endpoint)
    return sid


def unregister_stream(token: int) -> None:
    with _STREAMS_LOCK:
        _STREAMS.pop(token, None)


def sever_streams(match: str = "") -> int:
    """Forcibly shut down every registered stream whose endpoint contains
    ``match`` (the ``net_sever`` chaos kind).  Returns how many were
    severed; the owners see a ``ConnectionError`` and reconnect through
    their resume protocol."""
    with _STREAMS_LOCK:
        doomed = [(t, s, e) for t, (s, e) in _STREAMS.items()
                  if match in e]
    n = 0
    for token, sock, _endpoint in doomed:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        unregister_stream(token)
        n += 1
    return n


# --- unary call --------------------------------------------------------------


def _split_addr(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def call(
    addr: str,
    request: dict,
    *,
    endpoint: str | None = None,
    policy: RetryPolicy = DEFAULT_POLICY,
    deadline_s: float | None = None,
    trace: dict | None = None,
    breaker=None,
    rng: random.Random | None = None,
) -> tuple[dict, bytes | None]:
    """One resilient unary RPC: connect, send one JSON frame, read one
    JSON[+binary] response.

    The request frame is stamped with the remaining ``deadline_s`` (and
    the ``trace`` context when given).  Transport failures retry under
    ``policy``; the endpoint's circuit breaker is consulted before every
    attempt and fed after it.  Application-level refusals (a response
    with ``ok: false``) are RETURNED — only the transport retries.

    Raises :class:`DeadlineExceeded` when the budget runs out,
    :class:`~net.breaker.BreakerOpenError` when the breaker fails fast,
    or the last transport error once ``max_attempts`` is spent.
    """
    endpoint = endpoint or addr
    br = breaker if breaker is not None else breaker_for(endpoint)
    dl = Deadline(policy.deadline_s if deadline_s is None else deadline_s)
    host, port = _split_addr(addr)
    if trace:
        request = dict(request, trace=trace)
    last_err: BaseException | None = None
    for attempt in range(policy.max_attempts):
        br.check()
        t0 = time.perf_counter()
        try:
            _apply_faults(endpoint)
            remaining = dl.remaining()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"rpc to {endpoint} out of budget before attempt "
                    f"{attempt}", endpoint=endpoint,
                )
            with socket.create_connection(
                (host, port),
                timeout=min(policy.connect_timeout_s, remaining),
            ) as s:
                s.settimeout(max(dl.remaining(), 1e-3))
                send_msg(s, dict(request,
                                 deadline_s=round(max(dl.remaining(), 0.0),
                                                  3)))
                resp = recv_msg(s)
        except (OSError, ConnectionError, socket.timeout,
                json.JSONDecodeError) as e:
            _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
            br.record_failure()
            if attempt > 0:
                _M_RETRIES.inc(endpoint=endpoint, outcome="error")
            if isinstance(e, DeadlineExceeded) or dl.expired:
                _M_DEADLINE.inc(endpoint=endpoint)
                if isinstance(e, DeadlineExceeded):
                    raise
                raise DeadlineExceeded(
                    f"rpc to {endpoint} exceeded its deadline "
                    f"({type(e).__name__}: {e})", endpoint=endpoint,
                ) from e
            last_err = e
            if attempt + 1 >= policy.max_attempts:
                raise
            delay = backoff_s(policy, attempt, rng)
            if dl.remaining() <= delay:
                _M_DEADLINE.inc(endpoint=endpoint)
                raise DeadlineExceeded(
                    f"rpc to {endpoint}: deadline leaves no room for "
                    f"retry backoff ({delay:.3f}s)", endpoint=endpoint,
                ) from e
            time.sleep(delay)
            continue
        _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
        br.record_success()
        note_success(endpoint)
        if attempt > 0:
            _M_RETRIES.inc(endpoint=endpoint, outcome="ok")
        return resp
    raise last_err if last_err is not None else RuntimeError("unreachable")


# --- persistent streams ------------------------------------------------------


def connect_with_retry(
    factory,
    *,
    endpoint: str,
    deadline_s: float,
    policy: RetryPolicy = DEFAULT_POLICY,
    retryable: tuple = (OSError, ValueError),
    breaker=None,
    rng: random.Random | None = None,
):
    """Run ``factory()`` (any connect-shaped callable) under the backoff/
    deadline/breaker machinery until it returns, a non-retryable error
    escapes, or the deadline expires (:class:`DeadlineExceeded`).  Unlike
    :func:`call` there is no attempt cap — rendezvous loops (MPMD links,
    worker startup) legitimately outwait a peer's whole respawn — and an
    OPEN breaker paces the dialing (wait out the cooldown, then probe)
    instead of failing the loop: fast-fail is for unary callers with
    somewhere else to go, which a rendezvous does not have."""
    br = breaker if breaker is not None else breaker_for(endpoint)
    dl = Deadline(deadline_s)
    retry_index = 0
    while True:
        while not br.allow():
            if dl.remaining() <= 0.05:
                _M_DEADLINE.inc(endpoint=endpoint)
                raise DeadlineExceeded(
                    f"connect to {endpoint}: deadline expired waiting "
                    "out the open breaker", endpoint=endpoint,
                )
            time.sleep(0.05)
        t0 = time.perf_counter()
        try:
            _apply_faults(endpoint)
            result = factory()
        except retryable as e:
            _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
            br.record_failure()
            if retry_index > 0:
                _M_RETRIES.inc(endpoint=endpoint, outcome="error")
            delay = backoff_s(policy, retry_index, rng)
            retry_index += 1
            if dl.remaining() <= delay:
                _M_DEADLINE.inc(endpoint=endpoint)
                raise DeadlineExceeded(
                    f"connect to {endpoint} failed for {deadline_s:.0f}s "
                    f"({type(e).__name__}: {e})", endpoint=endpoint,
                ) from e
            time.sleep(delay)
            continue
        _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
        br.record_success()
        note_success(endpoint)
        if retry_index > 0:
            _M_RETRIES.inc(endpoint=endpoint, outcome="ok")
        return result


def connect_stream(
    addr: str,
    *,
    endpoint: str,
    timeout_s: float,
    connect_deadline_s: float | None = None,
    policy: RetryPolicy = DEFAULT_POLICY,
) -> tuple[socket.socket, int]:
    """Dial a persistent stream with retry/backoff/breaker, register it
    as a chaos sever target, and return ``(socket, token)``.  The caller
    owns the socket and must :func:`unregister_stream` the token on
    close.  ``timeout_s`` becomes the socket's per-op timeout."""
    host, port = _split_addr(addr)

    def _dial():
        s = socket.create_connection(
            (host, port), timeout=min(policy.connect_timeout_s, timeout_s)
        )
        s.settimeout(timeout_s)
        return s

    sock = connect_with_retry(
        _dial,
        endpoint=endpoint,
        deadline_s=(connect_deadline_s if connect_deadline_s is not None
                    else policy.deadline_s),
        policy=policy,
        retryable=(OSError,),
    )
    return sock, register_stream(sock, endpoint)


# --- deadline-bounded HTTP GET (fleet scrapes) -------------------------------


def http_get(url: str, *, deadline_s: float, endpoint: str,
             max_bytes: int = 16 << 20, breaker=None) -> tuple[int, str]:
    """GET ``url`` under a HARD wall deadline: connect, headers and every
    body chunk are all charged to one :class:`Deadline`, so a peer that
    accepts and then trickles (or never sends) bytes costs at most
    ``deadline_s`` — not a per-socket-op timeout multiplied by however
    many ops it strings along.  Returns ``(status, body)``; raises
    :class:`DeadlineExceeded` / ``OSError`` on transport failure.  One
    attempt, no retry — scrape-shaped callers have their own cadence."""
    br = breaker if breaker is not None else breaker_for(endpoint)
    br.check()
    dl = Deadline(deadline_s)
    if not url.startswith("http://"):
        raise ValueError(f"http_get supports http:// urls only: {url!r}")
    hostport, _, path = url[len("http://"):].partition("/")
    host, port = _split_addr(hostport)
    t0 = time.perf_counter()
    conn = http.client.HTTPConnection(
        host, port, timeout=max(dl.remaining(), 1e-3)
    )
    try:
        _apply_faults(endpoint)
        conn.request("GET", "/" + path)
        if conn.sock is not None:
            conn.sock.settimeout(max(dl.remaining(), 1e-3))
        resp = conn.getresponse()
        chunks: list[bytes] = []
        total = 0
        while True:
            if dl.expired:
                raise DeadlineExceeded(
                    f"scrape of {endpoint} exceeded {deadline_s:.1f}s "
                    "mid-body", endpoint=endpoint,
                )
            if conn.sock is not None:
                conn.sock.settimeout(max(min(dl.remaining(), 0.25), 1e-3))
            try:
                chunk = resp.read(65536)
            except socket.timeout:
                continue  # re-check the deadline, then keep reading
            if not chunk:
                break
            total += len(chunk)
            if total > max_bytes:
                raise DeadlineExceeded(
                    f"scrape of {endpoint} exceeded {max_bytes} bytes",
                    endpoint=endpoint,
                )
            chunks.append(chunk)
        status = resp.status
        body = b"".join(chunks).decode("utf-8", errors="replace")
    except socket.timeout as e:
        _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
        _M_DEADLINE.inc(endpoint=endpoint)
        br.record_failure()
        raise DeadlineExceeded(
            f"scrape of {endpoint} timed out within {deadline_s:.1f}s",
            endpoint=endpoint,
        ) from e
    except DeadlineExceeded:
        _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
        _M_DEADLINE.inc(endpoint=endpoint)
        br.record_failure()
        raise
    except (OSError, http.client.HTTPException):
        _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
        br.record_failure()
        raise
    finally:
        conn.close()
    _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
    br.record_success()
    note_success(endpoint)
    return status, body


def http_post(
    url: str,
    payload: dict,
    *,
    endpoint: str,
    policy: RetryPolicy = DEFAULT_POLICY,
    deadline_s: float | None = None,
    breaker=None,
    rng: random.Random | None = None,
) -> tuple[int, str]:
    """POST ``payload`` as JSON under the full unary machinery — the
    deadline bounds connect + send + response + backoff sleeps, transport
    failures retry under ``policy``, the endpoint's breaker is consulted
    before and fed after every attempt, and armed chaos faults apply
    (webhook delivery is chaos-testable like any RPC).  A 5xx status is a
    transport-shaped failure (the receiver exists but is broken) and
    retries; any other status is RETURNED as ``(status, body)``.  Raises
    :class:`DeadlineExceeded` / :class:`~net.breaker.BreakerOpenError` /
    the last transport error like :func:`call`."""
    br = breaker if breaker is not None else breaker_for(endpoint)
    dl = Deadline(policy.deadline_s if deadline_s is None else deadline_s)
    if not url.startswith("http://"):
        raise ValueError(f"http_post supports http:// urls only: {url!r}")
    hostport, _, path = url[len("http://"):].partition("/")
    host, port = _split_addr(hostport)
    body = json.dumps(payload).encode("utf-8")
    last_err: BaseException | None = None
    for attempt in range(policy.max_attempts):
        br.check()
        t0 = time.perf_counter()
        conn = None
        try:
            _apply_faults(endpoint)
            remaining = dl.remaining()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"post to {endpoint} out of budget before attempt "
                    f"{attempt}", endpoint=endpoint,
                )
            conn = http.client.HTTPConnection(
                host, port,
                timeout=min(policy.connect_timeout_s, remaining),
            )
            conn.request(
                "POST", "/" + path, body=body,
                headers={"Content-Type": "application/json"},
            )
            if conn.sock is not None:
                conn.sock.settimeout(max(dl.remaining(), 1e-3))
            resp = conn.getresponse()
            text = resp.read(1 << 20).decode("utf-8", errors="replace")
            if resp.status >= 500:
                raise OSError(
                    f"webhook {endpoint} answered {resp.status}")
            status = resp.status
        except (OSError, http.client.HTTPException) as e:
            _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
            br.record_failure()
            if attempt > 0:
                _M_RETRIES.inc(endpoint=endpoint, outcome="error")
            if isinstance(e, DeadlineExceeded) or dl.expired:
                _M_DEADLINE.inc(endpoint=endpoint)
                if isinstance(e, DeadlineExceeded):
                    raise
                raise DeadlineExceeded(
                    f"post to {endpoint} exceeded its deadline "
                    f"({type(e).__name__}: {e})", endpoint=endpoint,
                ) from e
            last_err = e
            if attempt + 1 >= policy.max_attempts:
                raise
            delay = backoff_s(policy, attempt, rng)
            if dl.remaining() <= delay:
                _M_DEADLINE.inc(endpoint=endpoint)
                raise DeadlineExceeded(
                    f"post to {endpoint}: deadline leaves no room for "
                    f"retry backoff ({delay:.3f}s)", endpoint=endpoint,
                ) from e
            time.sleep(delay)
            continue
        finally:
            if conn is not None:
                conn.close()
        _H_ATTEMPT.observe(time.perf_counter() - t0, endpoint=endpoint)
        br.record_success()
        note_success(endpoint)
        if attempt > 0:
            _M_RETRIES.inc(endpoint=endpoint, outcome="ok")
        return status, text
    raise last_err if last_err is not None else RuntimeError("unreachable")
