"""Resilient network substrate shared by every cross-process transport.

``net.rpc`` — deadline-bounded, retrying, breaker-guarded unary calls +
persistent-stream dialing + a hard-deadline HTTP GET;
``net.breaker`` — the per-endpoint closed/open/half-open circuit
breakers.  Importable on bare hosts (no jax): telemetry degrades to
no-ops where the obs registry is unavailable.
"""

from . import breaker, rpc  # noqa: F401
from .breaker import BreakerOpenError, CircuitBreaker, breaker_for  # noqa: F401
from .rpc import (  # noqa: F401
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    backoff_s,
    call,
    connect_stream,
    connect_with_retry,
    http_get,
    remaining_from_request,
)
