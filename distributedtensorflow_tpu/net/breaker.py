"""Per-endpoint circuit breakers for the resilient RPC substrate.

A breaker sits in front of every :mod:`net.rpc` endpoint and turns a
*persistently* failing peer into a fast local failure instead of a queue
of doomed connect attempts, each burning its full timeout (the classic
closed/open/half-open state machine):

- ``closed``    — healthy; calls pass through.  ``failure_threshold``
  CONSECUTIVE failures trip it open (one success resets the streak).
- ``open``      — calls fail immediately with :class:`BreakerOpenError`
  (no socket is touched) until ``open_for_s`` has elapsed.
- ``half_open`` — after the cooldown exactly ONE probe call is let
  through; its success closes the breaker, its failure re-opens it for a
  fresh cooldown.

Telemetry (obs registry; no-ops on a bare host without jax/obs):
``breaker_state{endpoint}`` gauge encoding the state numerically
(0 = closed, 1 = half_open, 2 = open) and
``breaker_transitions_total{endpoint,to}`` counting every state change —
the counter is what makes an open → half_open → closed recovery cycle
visible in a post-hoc ``metrics.prom`` snapshot, where the gauge only
shows the final state.

Breakers are process-global, keyed by the caller-supplied endpoint
identity string (:func:`breaker_for`); use one identity per failure
domain — e.g. ``"dispatcher"`` but ``"data_worker:<addr>"`` — so one
dead worker can never trip the breaker of its healthy siblings.
"""

from __future__ import annotations

import threading
import time

__all__ = [
    "BREAKER_STATES",
    "BreakerOpenError",
    "CircuitBreaker",
    "breaker_for",
    "reset_breakers",
]

# Telemetry degrades to no-ops where obs (which pulls jax) is absent —
# the net layer runs inside bare data-worker hosts (the data/adaptive.py
# degradation pattern; net/rpc.py imports these shims from here).
try:  # pragma: no cover - exercised implicitly wherever obs imports
    from ..obs.registry import counter as _counter
    from ..obs.registry import gauge as _gauge
    from ..obs.registry import histogram as _histogram
except Exception:  # pragma: no cover
    class _Null:
        def inc(self, *a, **k): pass
        def set(self, *a, **k): pass
        def observe(self, *a, **k): pass
        def value(self, *a, **k): return 0.0

    def _counter(name, help=""): return _Null()
    def _gauge(name, help=""): return _Null()
    def _histogram(name, help="", buckets=()): return _Null()


#: The states, in gauge-encoding order: ``breaker_state{endpoint}`` is
#: the state's index in this tuple (0 closed, 1 half_open, 2 open).
BREAKER_STATES = ("closed", "half_open", "open")

_G_STATE = _gauge(
    "breaker_state",
    "circuit breaker state per endpoint (0=closed, 1=half_open, 2=open)",
)
_M_TRANSITIONS = _counter(
    "breaker_transitions_total",
    "circuit breaker state transitions, by endpoint and target state",
)


class BreakerOpenError(ConnectionError):
    """Raised by :meth:`CircuitBreaker.check` / ``net.rpc.call`` when the
    endpoint's breaker is open — the call failed locally, without
    touching the network.  Subclasses ``ConnectionError`` so existing
    fault policies (elastic eviction, supervisor classification) treat it
    exactly like the refused connection it stands in for."""


class CircuitBreaker:
    """One endpoint's closed/open/half-open state machine (thread-safe).

    ``clock`` is injectable (tests drive transitions without sleeping);
    defaults to ``time.monotonic``.
    """

    def __init__(
        self,
        endpoint: str,
        *,
        failure_threshold: int = 5,
        open_for_s: float = 2.0,
        clock=time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.endpoint = str(endpoint)
        self.failure_threshold = int(failure_threshold)
        self.open_for_s = float(open_for_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0  # consecutive failures while closed
        self._opened_at = 0.0
        self._probing = False  # a half-open probe is in flight
        _G_STATE.set(0, endpoint=self.endpoint)

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _transition_locked(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        _G_STATE.set(BREAKER_STATES.index(to), endpoint=self.endpoint)
        _M_TRANSITIONS.inc(endpoint=self.endpoint, to=to)

    def _maybe_half_open_locked(self) -> None:
        if self._state == "open" \
                and self._clock() - self._opened_at >= self.open_for_s:
            self._transition_locked("half_open")
            self._probing = False

    # -- call-site protocol --------------------------------------------------

    def allow(self) -> bool:
        """True when a call may proceed: always while closed; exactly one
        probe per half-open window; never while open (pre-cooldown)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == "closed":
                return True
            if self._state == "half_open" and not self._probing:
                self._probing = True
                return True
            return False

    def check(self) -> None:
        """:meth:`allow` or raise :class:`BreakerOpenError`."""
        if not self.allow():
            raise BreakerOpenError(
                f"circuit breaker for {self.endpoint!r} is "
                f"{self.state} (endpoint failing; backing off)"
            )

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state in ("half_open", "open"):
                # open → closed happens when a call raced the trip: it was
                # admitted while closed and finished after the breaker
                # opened — the endpoint evidently answers again.
                self._transition_locked("closed")
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == "half_open":
                # failed probe: back to open for a fresh cooldown
                self._opened_at = self._clock()
                self._transition_locked("open")
                self._probing = False
                return
            self._failures += 1
            if self._state == "closed" \
                    and self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition_locked("open")


_BREAKERS: dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def breaker_for(endpoint: str, *, failure_threshold: int = 5,
                open_for_s: float = 2.0) -> CircuitBreaker:
    """The process-global breaker for ``endpoint`` (created on first use;
    the construction-time knobs of the first caller win)."""
    with _BREAKERS_LOCK:
        b = _BREAKERS.get(endpoint)
        if b is None:
            b = CircuitBreaker(
                endpoint,
                failure_threshold=failure_threshold,
                open_for_s=open_for_s,
            )
            _BREAKERS[endpoint] = b
        return b


def reset_breakers() -> None:
    """Drop every process-global breaker (test isolation)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()
