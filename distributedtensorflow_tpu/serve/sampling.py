"""Decode-time sampling: ONE reference implementation, every consumer.

The serving engine historically had two samplers that could drift: the
compiled-program side (device) and ``Engine._sample`` (a numpy fallback
that quietly up-cast to float64, so its probabilities disagreed with any
fp32 device sampler in the last ulps).  This module is the fix and the
ISSUE 15 fast path:

- :func:`logits_to_probs` — the logits→probabilities REFERENCE
  (temperature scaling, dynamic per-row top-k via a sort threshold,
  fp32 softmax, explicit greedy one-hot).  Written against the array
  namespace (``xp=np`` or ``xp=jnp``) so the numpy fallback, the fused
  device program, and the parity tests literally share one function.
- :func:`sample_burst` — the fused device sampler (traced inside
  ``serve.model.make_fused_decode_fn``): greedy / temperature+top-k
  sampling of ONE token per slot, generalized to **draft verification
  by rejection sampling** for self-speculative decoding.  Draft
  proposals come from the model-free n-gram drafter (``serve.draft``),
  i.e. a *deterministic* proposal ``q = onehot(d)``: a draft token
  ``d`` is accepted with probability ``min(1, p(d)/q(d)) = p(d)``, and
  on rejection the replacement is drawn from the residual
  ``max(p - q, 0)`` renormalized — so the emitted distribution is
  EXACTLY the target model's ``p``, token by token (the standard
  speculative-sampling correctness argument; pinned by the
  distribution test in tests/test_serve_spec.py).  At temperature 0
  this degenerates to ``accept iff d == argmax(p)`` and the output is
  token-for-token identical to sequential greedy decoding.
- :func:`sample_one` — the same math applied eagerly to one logits row
  (the engine's first-token sample at prefill completion, so the host
  and device samplers cannot diverge).

Randomness contract: each request owns a base key (``PRNGKey(seed)``,
resident on device in the engine); the draw for the token at emitted
index ``t`` uses ``fold_in(base, t)`` split into an accept-uniform and
a sample key.  Keying by *emitted index* (not decode step) keeps a
request's sampling stream independent of how many tokens each
speculative step happened to accept.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["logits_to_probs", "sample_burst", "sample_one"]


def logits_to_probs(logits, temperature, top_k, *, xp=np):
    """``(..., V)`` logits → fp32 probabilities; the one reference.

    ``temperature`` and ``top_k`` broadcast against the leading dims
    (scalars or per-row arrays).  ``top_k=0`` disables truncation;
    ``temperature <= 0`` is greedy and returns the exact one-hot of the
    (first) argmax — NOT a softmax at a tiny temperature, so ties
    resolve identically to ``argmax``.  fp32 throughout: the numpy
    fallback must match the device sampler bit-for-bit in structure (no
    float64 up-cast), which is what makes it usable as the parity
    reference.  Pass ``xp=jnp`` to trace the same math on device.
    """
    v = logits.shape[-1]
    logits = logits.astype(xp.float32)
    rows = logits.shape[:-1]
    t = xp.broadcast_to(
        xp.asarray(temperature, dtype=xp.float32), rows)[..., None]
    k = xp.broadcast_to(xp.asarray(top_k, dtype=xp.int32), rows)[..., None]
    scaled = logits / xp.maximum(t, xp.asarray(1e-6, dtype=xp.float32))
    # dynamic per-row top-k: threshold at the k-th largest via one sort
    # (jax.lax.top_k needs a static k; the per-request k is data here)
    srt = xp.sort(scaled, axis=-1)  # ascending
    kth = xp.take_along_axis(
        srt, xp.clip(v - k, 0, v - 1).astype(xp.int32), axis=-1)
    neg_inf = xp.asarray(-np.inf, dtype=xp.float32)
    scaled = xp.where((k > 0) & (scaled < kth), neg_inf, scaled)
    m = xp.max(scaled, axis=-1, keepdims=True)
    p = xp.exp(scaled - m)
    soft = p / xp.sum(p, axis=-1, keepdims=True)
    # greedy rows: exact one-hot of the first argmax (tie semantics ==
    # argmax, unlike a temperature->0 softmax which splits tie mass)
    am = xp.argmax(logits, axis=-1)
    onehot = (xp.arange(v, dtype=xp.int32)[None, :]
              == xp.reshape(am, (-1, 1))).reshape(logits.shape)
    return xp.where(t <= 0, onehot.astype(xp.float32), soft)


def _fold_keys(keys, positions):
    """Per-(row, position) (accept-uniform, sample) key pairs from the
    per-row base keys: ``fold_in(base, position)`` then one split."""

    def one(key, pos):
        k = jax.random.fold_in(key, pos)
        ku, ks = jax.random.split(k)
        return ku, ks

    return jax.vmap(jax.vmap(one, in_axes=(None, 0)))(keys, positions)


def _categorical(key, probs):
    """One draw from a probability vector (zeros stay unreachable)."""
    return jax.random.categorical(
        key, jnp.where(probs > 0, jnp.log(probs), -jnp.inf)
    ).astype(jnp.int32)


def sample_burst(logits, tokens, draft_lens, keys, sample_pos, temperature,
                 top_k, active):
    """Fused sampling + speculative verification (traced, device side).

    Args (``B`` slots, ``T = 1 + max draft`` query positions):

    - ``logits`` ``(B, T, V)`` fp32 — position ``i``'s logits condition
      on the last committed token plus drafts ``d_1..d_i``;
    - ``tokens`` ``(B, T)`` — ``[:, 0]`` is each slot's last committed
      token (whose K/V this step wrote), ``[:, 1:]`` the draft tokens;
    - ``draft_lens`` ``(B,)`` — how many drafts are real (0 = plain
      decode; ``T=1`` is the non-speculative fused program);
    - ``keys`` ``(B, 2)`` per-request base PRNG keys, ``sample_pos``
      ``(B,)`` the emitted index of each slot's next token;
    - ``temperature``/``top_k`` ``(B,)`` per-request sampling params;
    - ``active`` ``(B,)`` bool slot mask.

    Returns ``(out_tokens (B, T), n_emitted (B,), next_feed (B,))``:
    ``out_tokens[b, :n]`` are the emitted tokens (accepted draft prefix
    + one correction/bonus token, so ``1 <= n <= draft_lens[b] + 1``),
    and ``next_feed`` is each slot's last emitted token (the next
    step's input, kept device-resident by the engine; inactive slots
    pass their input through).
    """
    b, t_width, v = logits.shape
    argmx = jnp.argmax(logits, axis=-1).astype(jnp.int32)        # (B, T)
    greedy = (temperature <= 0.0)[:, None]                       # (B, 1)
    drafts_pad = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1
    )                                                            # (B, T)
    draft_mask = jnp.arange(t_width - 1)[None, :] < draft_lens[:, None]

    def _assemble(accepted, corr):
        i_idx = jnp.arange(t_width)[None, :]
        out = jnp.where(
            i_idx < accepted[:, None], drafts_pad,
            jnp.where(i_idx == accepted[:, None], corr, 0),
        ).astype(jnp.int32)
        n_emitted = jnp.where(active, accepted + 1, 0).astype(jnp.int32)
        last = jnp.take_along_axis(out, accepted[:, None], axis=1)[:, 0]
        next_feed = jnp.where(active, last, tokens[:, 0]).astype(jnp.int32)
        return out, n_emitted, next_feed

    def _prefix_len(acc):
        return jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)

    def _greedy_branch():
        # All-greedy batches (the common serving default) skip the probs
        # machinery entirely: accept iff the draft IS the argmax, emit
        # argmaxes — token-for-token the sequential greedy path.
        if t_width > 1:
            accepted = _prefix_len(
                (tokens[:, 1:] == argmx[:, :-1]) & draft_mask)
        else:
            accepted = jnp.zeros((b,), jnp.int32)
        return _assemble(accepted, argmx)

    def _general_branch():
        probs = logits_to_probs(logits, temperature[:, None],
                                top_k[:, None], xp=jnp)
        pos = sample_pos[:, None] + jnp.arange(t_width)[None, :]
        ku, ks = _fold_keys(keys, pos)
        u = jax.vmap(jax.vmap(jax.random.uniform))(ku)           # (B, T)
        if t_width > 1:
            d = tokens[:, 1:]                                    # (B, T-1)
            p_d = jnp.take_along_axis(
                probs[:, :-1], d[:, :, None], axis=-1)[..., 0]
            acc = jnp.where(greedy, d == argmx[:, :-1], u[:, :-1] < p_d)
            accepted = _prefix_len(acc & draft_mask)
        else:
            accepted = jnp.zeros((b,), jnp.int32)
        # Correction (rejected draft: residual max(p - onehot(d), 0)
        # renormalized) / bonus (all drafts accepted: full distribution)
        # token for EVERY position; position `accepted` is the one used.
        has_draft = jnp.arange(t_width)[None, :] < draft_lens[:, None]
        onehot_d = jax.nn.one_hot(drafts_pad, v, dtype=probs.dtype)
        resid = jnp.where(has_draft[..., None],
                          jnp.maximum(probs - onehot_d, 0.0), probs)
        denom = resid.sum(-1, keepdims=True)
        # p == onehot(d) exactly means accept probability 1 — the
        # residual is unreachable; guard the 0/0 anyway.
        resid = jnp.where(denom > 0, resid / jnp.maximum(denom, 1e-30),
                          probs)
        samp = jax.vmap(jax.vmap(_categorical))(ks, resid)       # (B, T)
        corr = jnp.where(greedy, argmx, samp).astype(jnp.int32)
        return _assemble(accepted, corr)

    # Runtime (not trace-time) gate: greedy rows inside a mixed batch
    # take the argmax/argmax-accept where's of the general branch, so
    # the fast branch is exactly the all-greedy specialization of it.
    return jax.lax.cond(jnp.all(greedy), _greedy_branch, _general_branch)


@jax.jit
def _sample_one_impl(logits_row, key, index, temperature, top_k):
    out, _, _ = sample_burst(
        logits_row.astype(jnp.float32)[None, None, :],
        jnp.zeros((1, 1), jnp.int32),
        jnp.zeros((1,), jnp.int32),
        key[None],
        index[None],
        temperature[None],
        top_k[None],
        jnp.ones((1,), bool),
    )
    return out[0, 0]


def sample_one(logits_row, key, index, temperature, top_k) -> int:
    """One token from one logits row with the device sampler's exact
    math and key schedule (the engine's first-token sample when fused
    sampling is on — host and device draws stay one stream).  Jitted:
    an eager ``sample_burst`` would re-trace its ``lax.cond`` branches
    on every call."""
    return int(_sample_one_impl(
        jnp.asarray(logits_row), jnp.asarray(key),
        jnp.asarray(index, jnp.int32),
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
    ))
