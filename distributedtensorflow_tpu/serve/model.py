"""The serving engine's two compiled programs: chunked prefill + paged decode.

Prefill/decode disaggregation: a serving step is either (a) teacher-forced
ingestion of a prompt chunk — big matmuls, compute-bound — or (b) one
token for every active slot — cache streaming, memory-bound.  Fusing them
(the ``models.generate`` whole-batch scan) forces every request in the
batch to the same phase; splitting them lets the scheduler admit a new
prompt while other slots keep decoding.  Both programs have fully static
shapes, so a serving process compiles **exactly two** XLA executables:

- :func:`make_prefill_fn` — one ``prefill_chunk``-wide slice of one
  prompt through :func:`models.generate.prefill` (the dense flax cache
  path, so prefill math is byte-identical to training-side decode), plus
  a scatter of the chunk's K/V into the paged pool.  Any prompt length =
  a Python loop of these fixed-width calls.
- :func:`make_decode_fn` — one token for all ``max_slots`` slots against
  the paged pool (``ops.attention.paged_decode_attention``).  The forward
  is rebuilt here from the raw param tree (flax's cache collection owns a
  dense per-slot buffer and can't address a shared pool); equivalence
  with ``GPTLM`` is pinned by tests/test_serve.py, and every dtype choice
  (bf16 matmuls, fp32 layernorm/softmax/logits) mirrors ``models/gpt.py``
  line for line.
- :func:`make_gather_cache_fn` — rebuild the dense prefill cache for one
  slot from its pool blocks (gather through the page-table row).  This is
  what makes chunked prefill *stateless*: any slot's next chunk can run
  at any time by re-materializing its cache from the pool, so the
  scheduler can interleave prefill chunks of several requests with
  decode steps (ISSUE 14 budgeted prefill), and a request admitted onto
  a cached prefix starts from the shared blocks without a special load
  path.  The gathered values are the exact bytes prefill scattered out
  (or that an earlier request with the same prefix scattered), so the
  chunk math stays byte-identical to an uninterrupted prefill.

(There is also a tiny pool-level block-copy program in ``serve.kv_cache``
— the copy-on-write path — compiled only if a CoW ever fires.)

The pool arrays are donated: steady-state serving does not allocate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..models.generate import prefill
from ..models.gpt import GPTConfig, rope, rope_tables
from ..ops.attention import paged_decode_attention, paged_verify_attention
from ..ops.layernorm import layer_norm
from ..ops.xent import tied_head_logits
from .sampling import sample_burst

__all__ = [
    "make_prefill_cache",
    "make_prefill_fn",
    "make_decode_fn",
    "make_fused_decode_fn",
    "make_gather_cache_fn",
    "reset_cache_index",
]


def _check_servable(cfg: GPTConfig) -> None:
    if cfg.attn_window is not None:
        raise ValueError(
            "the paged decode program does not implement sliding-window "
            "masking yet; serve with attn_window=None"
        )
    if cfg.dropout_rate:
        raise ValueError("serving is deterministic; set dropout_rate=0")


def make_prefill_cache(cfg: GPTConfig):
    """Zeroed dense prefill cache, structurally identical to the flax
    ``"cache"`` collection ``GPTLM(decode=True)`` would create — built by
    hand so the engine never traces a third (cache-creating) program.
    One buffer serves every admission: :func:`reset_cache_index` rewinds
    it and stale K/V beyond the index is masked by the decode-mode
    validity rule (``k_idx <= q_pos``)."""
    head_dim = cfg.hidden_size // cfg.num_heads
    kv = (1, cfg.kv_heads, cfg.max_seq, head_dim)
    return {
        f"h{i}": {"attn": {
            "cached_key": jnp.zeros(kv, cfg.dtype),
            "cached_value": jnp.zeros(kv, cfg.dtype),
            "cache_index": jnp.zeros((), jnp.int32),
        }}
        for i in range(cfg.num_layers)
    }


def reset_cache_index(cache):
    """Rewind a prefill cache to position 0 for the next admission (host
    dict rebuild; the K/V buffers are reused in place)."""
    return {
        name: {"attn": {**layer["attn"],
                        "cache_index": jnp.zeros((), jnp.int32)}}
        for name, layer in cache.items()
    }


def make_prefill_fn(cfg: GPTConfig, *, chunk: int, block_size: int):
    """Compiled program (a): one fixed-width prompt chunk.

    ``fn(params, k_pool, v_pool, cache, tokens, start, table_row,
    last_ix) -> (last_logits, cache, k_pool, v_pool)`` where ``tokens``
    is ``(1, chunk)``, ``start`` the chunk's first absolute position,
    ``table_row`` the slot's ``(blocks_per_slot,)`` page-table row, and
    ``last_ix`` the in-chunk index whose logits the engine wants (the
    final prompt token's, clamped into range on non-final chunks whose
    logits are discarded).  The chunk's K/V are sliced out of the dense
    flax cache and scattered to the slot's pool blocks."""
    _check_servable(cfg)

    @functools.partial(jax.jit, donate_argnums=(1, 2, 3))
    def prefill_chunk(params, k_pool, v_pool, cache, tokens, start,
                      table_row, last_ix):
        positions = (start + jnp.arange(chunk, dtype=jnp.int32))[None, :]
        logits, cache = prefill(params, tokens, positions, cfg=cfg,
                                cache=cache)
        num_layers, nb_total, bs, h_kv, d = k_pool.shape
        pos = start + jnp.arange(chunk)
        idx = table_row[pos // block_size] * bs + pos % block_size  # (chunk,)
        k_new = jnp.stack([
            jax.lax.dynamic_slice_in_dim(
                cache[f"h{i}"]["attn"]["cached_key"], start, chunk, axis=2
            )[0].transpose(1, 0, 2)  # (chunk, Hkv, D)
            for i in range(num_layers)
        ])  # (L, chunk, Hkv, D)
        v_new = jnp.stack([
            jax.lax.dynamic_slice_in_dim(
                cache[f"h{i}"]["attn"]["cached_value"], start, chunk, axis=2
            )[0].transpose(1, 0, 2)
            for i in range(num_layers)
        ])
        k_pool = k_pool.reshape(num_layers, nb_total * bs, h_kv, d) \
            .at[:, idx].set(k_new).reshape(k_pool.shape)
        v_pool = v_pool.reshape(num_layers, nb_total * bs, h_kv, d) \
            .at[:, idx].set(v_new).reshape(v_pool.shape)
        return logits[0, last_ix], cache, k_pool, v_pool

    return prefill_chunk


def make_gather_cache_fn(cfg: GPTConfig, *, block_size: int):
    """Compiled program: rebuild one slot's dense prefill cache from the
    paged pool.

    ``fn(k_pool, v_pool, cache, table_row, start) -> cache`` gathers ALL
    ``max_seq`` positions through ``table_row`` into the (donated) dense
    cache buffer and sets ``cache_index = start`` — the position the next
    prefill chunk writes at.  Positions >= ``start`` gather garbage
    (scratch / stale blocks) but are exactly the positions the decode-mode
    validity rule masks (``k_idx <= q_pos``) until a chunk overwrites
    them, so no dynamic-shape masking is needed and the program stays
    static.  Positions < ``start`` reproduce bit-for-bit the K/V a
    straight-line prefill would have left in the cache (the pool holds
    the same bytes the dense cache was sliced into)."""
    _check_servable(cfg)
    num_layers = cfg.num_layers

    @functools.partial(jax.jit, donate_argnums=(2,))
    def gather_cache(k_pool, v_pool, cache, table_row, start):
        _, nb_total, bs, h_kv, d = k_pool.shape
        pos = jnp.arange(cfg.max_seq)
        idx = table_row[pos // block_size] * bs + pos % bs
        kf = k_pool.reshape(num_layers, nb_total * bs, h_kv, d)[:, idx]
        vf = v_pool.reshape(num_layers, nb_total * bs, h_kv, d)[:, idx]
        # (L, max_seq, Hkv, D) -> per-layer (1, Hkv, max_seq, D), the flax
        # decode-cache layout make_prefill_cache builds.
        return {
            f"h{i}": {"attn": {
                "cached_key": kf[i].transpose(1, 0, 2)[None],
                "cached_value": vf[i].transpose(1, 0, 2)[None],
                "cache_index": start.astype(jnp.int32),
            }}
            for i in range(num_layers)
        }

    return gather_cache


def make_decode_fn(cfg: GPTConfig):
    """Compiled program (b): one decode token for every slot.

    ``fn(params, k_pool, v_pool, tokens, block_tables, seq_lens, active)
    -> (logits, k_pool, v_pool)`` with ``tokens`` ``(max_slots,)`` (each
    slot's last sampled token), ``seq_lens`` the resident token counts
    (the new token is written at that position, then attends ``seq_len +
    1`` positions), and ``active`` masking unoccupied slots: their write
    lands in the reserved scratch block and their logits are discarded by
    the engine, so the program shape never depends on occupancy."""
    _check_servable(cfg)
    num_layers = cfg.num_layers
    n_heads = cfg.num_heads
    h_kv = cfg.kv_heads
    head_dim = cfg.hidden_size // n_heads
    hidden = cfg.hidden_size
    kv_width = h_kv * head_dim

    def _ln(x, p, out_dtype=None):
        return layer_norm(x, p["scale"], p["bias"], eps=1e-6,
                          out_dtype=out_dtype or x.dtype)

    def _dense(x, kernel):
        # flax nn.Dense(dtype=cfg.dtype, use_bias=False): both operands
        # cast to the compute dtype, default accumulation.
        return x @ kernel.astype(cfg.dtype)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def decode(params, k_pool, v_pool, tokens, block_tables, seq_lens,
               active):
        b = tokens.shape[0]
        _, nb_total, bs, _, _ = k_pool.shape
        x = params["wte"]["embedding"].astype(cfg.dtype)[tokens][:, None, :]
        positions = seq_lens.astype(jnp.int32)[:, None]  # (B, 1)
        tabs = rope_tables(positions, head_dim, cfg.rope_theta, cfg.dtype)
        # Write coordinates for the new token: active slots append at
        # seq_len inside their own pages; inactive slots hit scratch.
        blk = jnp.take_along_axis(
            block_tables, (seq_lens // bs)[:, None], axis=1
        )[:, 0]
        idx = jnp.where(active, blk * bs + seq_lens % bs,
                        (nb_total - 1) * bs)
        attend_lens = jnp.where(active, seq_lens + 1, 1)
        kf = k_pool.reshape(num_layers, nb_total * bs, h_kv, head_dim)
        vf = v_pool.reshape(num_layers, nb_total * bs, h_kv, head_dim)
        for layer in range(num_layers):
            p = params[f"h{layer}"]
            h = _ln(x, p["ln1"])
            qkv = _dense(h, p["attn"]["qkv"]["kernel"])
            q = qkv[..., :hidden].reshape(b, 1, n_heads, head_dim)
            k = qkv[..., hidden:hidden + kv_width].reshape(b, 1, h_kv,
                                                           head_dim)
            v = qkv[..., hidden + kv_width:].reshape(b, 1, h_kv, head_dim)
            q = rope(q, positions, cfg.rope_theta, tabs)
            k = rope(k, positions, cfg.rope_theta, tabs)
            kf = kf.at[layer, idx].set(k[:, 0])
            vf = vf.at[layer, idx].set(v[:, 0])
            out = paged_decode_attention(
                q[:, 0],
                kf[layer].reshape(nb_total, bs, h_kv, head_dim),
                vf[layer].reshape(nb_total, bs, h_kv, head_dim),
                block_tables, attend_lens,
            ).reshape(b, 1, hidden).astype(cfg.dtype)
            x = x + _dense(out, p["attn"]["proj"]["kernel"])
            h = _ln(x, p["ln2"])
            m = _dense(jax.nn.gelu(_dense(h, p["fc_in"]["kernel"])),
                       p["fc_out"]["kernel"])
            x = x + m
        xf = _ln(x, params["ln_f"], out_dtype=jnp.float32)
        logits = tied_head_logits(
            xf[:, 0], params["wte"]["embedding"], cfg.dtype
        )
        return logits, kf.reshape(k_pool.shape), vf.reshape(v_pool.shape)

    return decode


def make_fused_decode_fn(cfg: GPTConfig, *, block_size: int, draft: int = 0):
    """Compiled program (b'): the decode **fast path** — forward, K/V
    append, AND sampling in one dispatch; optionally speculative.

    ``fn(params, k_pool, v_pool, tokens, draft_lens, block_tables,
    seq_lens, active, keys, prompt_lens, temperature, top_k) ->
    (packed, next_feed, k_pool, v_pool)`` with ``T = draft + 1`` query
    positions per slot: column 0 is each slot's last
    committed token, columns ``1..draft_lens`` its n-gram draft
    proposals (``serve.draft``), the rest padding.  The program writes
    K/V for the committed token and every draft at consecutive
    positions (pad/inactive writes land in the scratch block), runs ONE
    multi-token paged attention pass
    (:func:`ops.attention.paged_verify_attention`) with causal masking
    inside the draft window, and applies the fused sampler
    (:func:`serve.sampling.sample_burst`): greedy / temperature+top-k
    with per-slot PRNG keys resident in ``keys``, generalized to
    rejection-sampled draft verification — the emitted distribution is
    exactly the target model's, and greedy output is token-for-token
    the sequential path's.

    Versus :func:`make_decode_fn` + host sampling, the host round-trip
    per token collapses to one small ``(out_tokens, n_emitted)`` fetch
    per *iteration* (EOS/logging), ``next_feed`` stays device-resident
    as the next step's input, and with ``draft > 0`` one dispatch can
    emit up to ``draft + 1`` tokens per slot.  ``draft=0`` (``T = 1``)
    is the non-speculative fused program — same signature, so the
    engine swaps between the two without a third code path.

    Every forward-pass dtype choice mirrors :func:`make_decode_fn` line
    for line; the accepted-token logits are therefore the same numbers
    the one-token program would have produced (parity pinned by
    tests/test_serve_spec.py, incl. bf16).
    """
    _check_servable(cfg)
    num_layers = cfg.num_layers
    n_heads = cfg.num_heads
    h_kv = cfg.kv_heads
    head_dim = cfg.hidden_size // n_heads
    hidden = cfg.hidden_size
    kv_width = h_kv * head_dim
    t_width = draft + 1

    def _ln(x, p, out_dtype=None):
        return layer_norm(x, p["scale"], p["bias"], eps=1e-6,
                          out_dtype=out_dtype or x.dtype)

    def _dense(x, kernel):
        return x @ kernel.astype(cfg.dtype)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def fused_decode(params, k_pool, v_pool, tokens, draft_lens,
                     block_tables, seq_lens, active, keys, prompt_lens,
                     temperature, top_k):
        b = tokens.shape[0]
        _, nb_total, bs, _, _ = k_pool.shape
        nb_table = block_tables.shape[1]
        x = params["wte"]["embedding"].astype(cfg.dtype)[tokens]  # (B,T,H)
        positions = (seq_lens[:, None]
                     + jnp.arange(t_width, dtype=jnp.int32)[None, :])
        tabs = rope_tables(positions, head_dim, cfg.rope_theta, cfg.dtype)
        # Write coordinates: the committed token (column 0) and the real
        # drafts append at consecutive positions inside the slot's pages;
        # pad columns and inactive slots hit scratch.  Rejected drafts
        # leave garbage PAST the committed seq_len — masked by the
        # validity rule until a later write overwrites it (the K/V-level
        # rollback; the host-side retreat is kv_cache.rollback).
        valid_w = active[:, None] & (
            jnp.arange(t_width)[None, :] <= draft_lens[:, None]
        )
        blk = jnp.take_along_axis(
            block_tables, jnp.clip(positions // bs, 0, nb_table - 1), axis=1
        )
        idx = jnp.where(valid_w, blk * bs + positions % bs,
                        (nb_total - 1) * bs)                    # (B, T)
        attend_lens = jnp.where(active, seq_lens + 1, 1)
        kf = k_pool.reshape(num_layers, nb_total * bs, h_kv, head_dim)
        vf = v_pool.reshape(num_layers, nb_total * bs, h_kv, head_dim)
        for layer in range(num_layers):
            p = params[f"h{layer}"]
            h = _ln(x, p["ln1"])
            qkv = _dense(h, p["attn"]["qkv"]["kernel"])
            q = qkv[..., :hidden].reshape(b, t_width, n_heads, head_dim)
            k = qkv[..., hidden:hidden + kv_width].reshape(
                b, t_width, h_kv, head_dim)
            v = qkv[..., hidden + kv_width:].reshape(
                b, t_width, h_kv, head_dim)
            q = rope(q, positions, cfg.rope_theta, tabs)
            k = rope(k, positions, cfg.rope_theta, tabs)
            kf = kf.at[layer, idx.reshape(-1)].set(
                k.reshape(b * t_width, h_kv, head_dim))
            vf = vf.at[layer, idx.reshape(-1)].set(
                v.reshape(b * t_width, h_kv, head_dim))
            out = paged_verify_attention(
                q,
                kf[layer].reshape(nb_total, bs, h_kv, head_dim),
                vf[layer].reshape(nb_total, bs, h_kv, head_dim),
                block_tables, attend_lens,
            ).reshape(b, t_width, hidden).astype(cfg.dtype)
            x = x + _dense(out, p["attn"]["proj"]["kernel"])
            h = _ln(x, p["ln2"])
            m = _dense(jax.nn.gelu(_dense(h, p["fc_in"]["kernel"])),
                       p["fc_out"]["kernel"])
            x = x + m
        xf = _ln(x, params["ln_f"], out_dtype=jnp.float32)
        logits = tied_head_logits(
            xf, params["wte"]["embedding"], cfg.dtype
        )                                                       # (B, T, V)
        # Emitted-token index of each slot's next sample, derived
        # on-device (decode invariant: seq_len = prompt + emitted - 1)
        # so the host ships nothing per step that it can avoid —
        # prompt_lens changes only at admission.
        sample_pos = jnp.maximum(seq_lens - prompt_lens + 1, 0)
        out_tokens, n_emitted, next_feed = sample_burst(
            logits, tokens, draft_lens, keys, sample_pos, temperature,
            top_k, active,
        )
        # out_tokens and n_emitted packed into ONE array so the host
        # pays a single small device->host fetch per iteration;
        # next_feed keeps the feed shape (B, 1) so the next T=1 call
        # consumes it with zero host-side reshaping.
        packed = jnp.concatenate([out_tokens, n_emitted[:, None]], axis=1)
        return (packed, next_feed[:, None],
                kf.reshape(k_pool.shape), vf.reshape(v_pool.shape))

    return fused_decode
