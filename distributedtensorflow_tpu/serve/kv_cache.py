"""Paged KV cache: refcounted block pool + prefix index + page tables.

The dense serving cache (``models.generate``) pins ``max_seq`` tokens of
K/V per batch slot for the whole request lifetime — a 16-token reply in a
slot sized for 2048 tokens wastes 99% of the slot's HBM.  This module is
the vLLM-style fix, built on the same sequence-chunking idiom as
``ops/blockwise.py``: K/V live in a pool of fixed-size **blocks** shared
by every slot, each slot's **page table** row names the blocks holding
its sequence, and a refcounted **allocator** hands blocks out per request
— so memory held is proportional to tokens actually resident, and a
finished sequence's blocks return to the pool the moment it is evicted.

**Prefix caching** (ISSUE 14): identical prompt prefixes — system
prompts, few-shot headers — are the dominant redundancy in request
traffic, and re-prefilling them re-computes and re-stores the same K/V
every request.  The pool therefore keeps a **prefix index**: every FULL
token-aligned block of a completed prompt is registered under a chained
content hash (``h_i = hash((h_{i-1}, block_i_tokens))``, so a block's
hash commits to the whole prefix up to it, not just its own tokens).
Admission looks up the longest indexed chain for the new prompt and maps
those blocks into the request's page table at ``refcount + 1`` — prefill
then only runs the uncached tail.  The match is capped at
``(prompt_len - 1) // block_size`` blocks so at least one prompt token
always runs through prefill (the last token's logits seed sampling).

Block states (``BlockAllocator``):

- **free** — on the free list, contents meaningless;
- **active** — refcount >= 1: mapped by that many slot page tables.  A
  block with refcount > 1 is *shared* and must never be written in place
  (copy-on-write below);
- **cached** — refcount 0 but registered in the prefix index: the K/V
  stay warm for future lookups.  Cached blocks form an LRU; ``alloc``
  evicts from it only under pressure (dropping the index entry), and a
  **mapped block is never evicted** — eviction only ever sees
  refcount-0 blocks.

``release`` therefore *decrements* instead of freeing: a registered
block outlives its first request as a cached block, an unregistered one
goes straight back to the free list.

**Copy-on-write**: :meth:`PagedKVCache.ensure_writable` guards every
in-place write position — a shared target block is copied into a fresh
block first (pool-level device copy) and the writer's table re-pointed;
a registered-but-exclusive target is unregistered (the write would
invalidate the indexed content).  In the engine's steady state neither
fires: only FULL prompt blocks are ever registered/shared and all
appends land past the prompt — but the guard is what turns a future
scheduler bug into a local copy instead of silent cross-request cache
corruption.  One deliberate exception: a prefill chunk that straddles
the cached-prefix boundary re-writes the tail of the shared prefix with
**bitwise-identical** K/V (same tokens, same positions, same compiled
program — and causal masking makes positions ``< p`` independent of the
differing suffix), which is benign and keeps the chunk grid anchored at
zero so the admission footprint math is unchanged.

Device-side state is functional (jnp arrays threaded through the
compiled serving programs — see ``serve.model``); this module owns the
HOST-side bookkeeping: the allocator states, the prefix index, the
numpy page tables and sequence lengths the engine mutates between
steps.  Single-writer by design: only the engine loop thread touches a
``PagedKVCache`` (the HTTP threads go through the engine's queue), so
there are no locks here.

Layout: ``(num_layers, num_blocks + 1, block_size, kv_heads, head_dim)``
per pool — one stacked array for all layers so the decode program indexes
layers without a pytree of leaves.  The extra physical block at index
``num_blocks`` is the **scratch block**: inactive slots' writes land
there (static-shape decode steps always write ``max_slots`` tokens), and
unallocated page-table entries point at it, so no masking is needed on
the write path and garbage reads are confined to slots whose outputs the
engine discards anyway.
"""

from __future__ import annotations

import collections
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np


class OutOfBlocksError(RuntimeError):
    """Raised on ``free``/refcount/table misuse; ``alloc`` returns None
    instead."""


@functools.lru_cache(maxsize=1)
def _copy_block_fn():
    """Compiled pool-level block copy (the copy-on-write program).

    Compiled lazily on the first CoW — steady-state serving with
    full-block prefix sharing never triggers it (see module docstring)."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def copy_block(k_pool, v_pool, src, dst):
        return (k_pool.at[:, dst].set(k_pool[:, src]),
                v_pool.at[:, dst].set(v_pool[:, src]))

    return copy_block


class BlockAllocator:
    """Refcounted allocator over ``num_blocks`` uniform physical blocks.

    ``alloc(n)`` is all-or-nothing (a request is admitted only when its
    whole worst-case footprint fits — no mid-flight OOM, see
    ``serve.engine``) and may evict LRU *cached* (refcount-0, registered)
    blocks to satisfy the grant — a mapped (refcount >= 1) block is never
    evicted.  ``free``/:meth:`decref` decrement and reject double-frees
    loudly (an over-decrement means two slots think they own a block's
    last reference — silent cache corruption).  Blocks are uniform so
    there is no external fragmentation; the waste mode is *internal*
    (allocated-but-unused tokens inside a request's last block and its
    not-yet-generated tail), reported by :meth:`PagedKVCache.stats`.
    """

    def __init__(self, num_blocks: int, on_evict=None):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))  # pop() -> block 0 first
        self._ref: dict[int, int] = {}
        #: refcount-0 registered blocks, insertion order = LRU order.
        self._cached: collections.OrderedDict[int, None] = \
            collections.OrderedDict()
        self._registered: set[int] = set()
        self._on_evict = on_evict
        self.evictions = 0

    # -- state census --------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Blocks with refcount >= 1 (mapped by some page table)."""
        return len(self._ref)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 blocks kept warm for the prefix index (evictable)."""
        return len(self._cached)

    @property
    def allocatable_blocks(self) -> int:
        """Blocks ``alloc`` could grant right now (free + evictable)."""
        return len(self._free) + len(self._cached)

    @property
    def total_refs(self) -> int:
        """Sum of refcounts (> used_blocks means prefix sharing is live)."""
        return sum(self._ref.values())

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def is_registered(self, block: int) -> bool:
        return block in self._registered

    # -- grant / return ------------------------------------------------------

    def alloc(self, n: int) -> list[int] | None:
        """``n`` physical block ids at refcount 1, or None when fewer than
        ``n`` are grantable (all-or-nothing: never a partial grant).
        Evicts LRU cached blocks only as needed — never a mapped block."""
        if n < 0:
            raise ValueError(f"alloc({n}) is negative")
        if n > self.allocatable_blocks:
            return None
        while len(self._free) < n:
            self._evict_lru()
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._ref[b] = 1
        return blocks

    def incref(self, block: int) -> None:
        """Map a block into one more page table (prefix reuse).  A cached
        block is reactivated (leaves the eviction LRU)."""
        if block in self._ref:
            self._ref[block] += 1
        elif block in self._cached:
            del self._cached[block]
            self._ref[block] = 1
        else:
            raise OutOfBlocksError(
                f"incref({block}): block is neither active nor cached"
            )

    def decref(self, block: int) -> None:
        """Drop one reference.  At refcount 0 a registered block parks in
        the cached LRU (contents stay lookup-able); an unregistered one
        returns to the free list."""
        if block not in self._ref:
            raise OutOfBlocksError(
                f"decref({block}): block is not allocated (double free or "
                "foreign id)"
            )
        self._ref[block] -= 1
        if self._ref[block]:
            return
        del self._ref[block]
        if block in self._registered:
            self._cached[block] = None  # MRU end of the eviction LRU
        else:
            self._free.append(block)

    def free(self, blocks: list[int]) -> None:
        """Drop one reference per block (the release path)."""
        for b in blocks:
            self.decref(b)

    # -- prefix-index hooks --------------------------------------------------

    def register(self, block: int) -> None:
        """Mark an active block as holding indexed prefix content: when
        its refcount drops to 0 it becomes cached instead of free."""
        if block not in self._ref:
            raise OutOfBlocksError(
                f"register({block}): block is not active"
            )
        self._registered.add(block)

    def unregister(self, block: int) -> None:
        """Forget a block's indexed status (a write is about to change
        its contents, or the index dropped it)."""
        self._registered.discard(block)
        if block in self._cached:
            # no references AND no longer indexed: nothing can reach it
            del self._cached[block]
            self._free.append(block)

    def _evict_lru(self) -> None:
        block, _ = self._cached.popitem(last=False)
        self._registered.discard(block)
        self.evictions += 1
        if self._on_evict is not None:
            self._on_evict(block)
        self._free.append(block)


@dataclasses.dataclass
class SlotPages:
    """One slot's page-table bookkeeping (host side)."""

    blocks: list[int]          # physical block ids, logical order
    capacity_tokens: int       # blocks * block_size
    used_tokens: int = 0       # K/V positions actually written so far
    prefix_tokens: int = 0     # tokens mapped from the prefix cache at admit


class PagedKVCache:
    """Block-pool KV storage for ``max_slots`` concurrent sequences.

    Device arrays (``k_pool``/``v_pool``) are created once and threaded
    functionally through the serving programs; the engine assigns the
    updated arrays back after every call.  Host state (page tables,
    lengths, the prefix index) advances in lockstep on the engine thread.
    """

    def __init__(self, *, num_layers: int, kv_heads: int, head_dim: int,
                 max_slots: int, num_blocks: int, block_size: int,
                 max_context: int, dtype=jnp.float32):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if max_context % block_size:
            raise ValueError(
                f"max_context={max_context} must be a multiple of "
                f"block_size={block_size}"
            )
        self.block_size = block_size
        self.max_slots = max_slots
        self.max_context = max_context
        self.blocks_per_slot = max_context // block_size
        self.scratch_block = num_blocks  # reserved physical block
        self.allocator = BlockAllocator(num_blocks, on_evict=self._on_evict)
        shape = (num_layers, num_blocks + 1, block_size, kv_heads, head_dim)
        self.k_pool = jnp.zeros(shape, dtype)
        self.v_pool = jnp.zeros(shape, dtype)
        # Unallocated entries point at the scratch block (always a legal
        # physical index; reads through it are masked by seq_lens).
        self.block_tables = np.full(
            (max_slots, self.blocks_per_slot), self.scratch_block, np.int32
        )
        #: bumped on every page-table mutation (admit/release/CoW
        #: repoint) so the engine can cache the device copy of
        #: ``block_tables`` across the many decode steps between
        #: admissions instead of re-shipping it per step.
        self.tables_version = 0
        self.seq_lens = np.zeros((max_slots,), np.int32)
        self.pages: list[SlotPages | None] = [None] * max_slots
        # prefix index: chained content hash -> (physical block, the
        # block's token tuple), + reverse map for eviction.  The tokens
        # are stored so every lookup VERIFIES them — hash() is 64-bit
        # and non-cryptographic, and an unverified chain collision would
        # silently map another prompt's K/V into a new request (the
        # vLLM prefix-cache CVE class).  Verifying each matched block's
        # own tokens suffices: a wrong mapping would need a colliding
        # parent hash at some earlier step WITH equal tokens at every
        # step up to it — and token-equal at every step IS the same
        # prefix.
        self._hash_to_block: dict[int, tuple[int, tuple[int, ...]]] = {}
        self._block_hash: dict[int, int] = {}
        # admission-time accounting (the engine mirrors these into the
        # obs registry; stats() derives hit rate / occupancy from them)
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_cached_tokens = 0
        self.cow_copies = 0

    def _on_evict(self, block: int) -> None:
        h = self._block_hash.pop(block, None)
        if h is not None:
            self._hash_to_block.pop(h, None)

    # -- prefix index (engine thread only) -----------------------------------

    def _chained_hashes(self, tokens):
        """(chained hash, block token tuple) per FULL block of
        ``tokens`` — each hash commits to the entire prefix through its
        block."""
        h = 0
        bs = self.block_size
        for i in range(len(tokens) // bs):
            tok = tuple(tokens[i * bs:(i + 1) * bs])
            h = hash((h, tok))
            yield h, tok

    def lookup_prefix(self, tokens) -> list[int]:
        """Longest indexed chain of full blocks matching ``tokens``,
        capped so at least one prompt token remains for prefill (the
        final token's logits must be computed to sample from).  Every
        matched entry's stored tokens are compared, so a hash collision
        degrades to a cache miss, never to serving another prompt's
        K/V.  Pure lookup: no state change, no refcounts taken."""
        limit = (len(tokens) - 1) // self.block_size
        blocks: list[int] = []
        for i, (h, tok) in enumerate(self._chained_hashes(tokens)):
            if i >= limit:
                break
            entry = self._hash_to_block.get(h)
            if entry is None or entry[1] != tok:
                break
            blocks.append(entry[0])
        return blocks

    def register_prefix(self, slot: int, tokens) -> int:
        """Index every FULL block of a slot's freshly prefilled prompt.
        First writer wins: a hash already indexed (necessarily the block
        this slot mapped at admission, or a concurrent identical prompt
        that prefilled its own copy) keeps its existing entry.  Returns
        the number of newly indexed blocks."""
        pages = self.pages[slot]
        if pages is None:
            raise OutOfBlocksError(f"slot {slot} has no pages")
        added = 0
        for i, (h, tok) in enumerate(self._chained_hashes(tokens)):
            b = pages.blocks[i]
            if h in self._hash_to_block:
                continue
            self._hash_to_block[h] = (b, tok)
            self._block_hash[b] = h
            self.allocator.register(b)
            added += 1
        return added

    # -- admission / eviction (engine thread only) ---------------------------

    def blocks_for(self, tokens: int) -> int:
        """Physical blocks needed to hold ``tokens`` K/V positions."""
        return -(-tokens // self.block_size)

    def admit(self, slot: int, tokens: int, prompt=None) -> SlotPages | None:
        """Reserve a slot's worst-case footprint (``tokens`` positions).

        With ``prompt`` (the token list), the longest indexed prefix is
        mapped into the page table at refcount+1 and only the remaining
        blocks are freshly allocated — the all-or-nothing contract then
        covers the worst-case footprint MINUS the mapped prefix.  Returns
        the slot's :class:`SlotPages` (``prefix_tokens`` tells how much
        was mapped) or None under pool pressure — a failed grant rolls
        the prefix mappings back.  The slot must be empty (engine
        invariant)."""
        if self.pages[slot] is not None:
            raise OutOfBlocksError(f"slot {slot} is already occupied")
        if tokens > self.max_context:
            raise ValueError(
                f"{tokens} tokens exceed max_context={self.max_context}"
            )
        prefix_blocks: list[int] = []
        if prompt is not None:
            prefix_blocks = self.lookup_prefix(prompt)
        n = self.blocks_for(tokens)
        for b in prefix_blocks:
            self.allocator.incref(b)  # pinned: alloc's eviction can't touch
        fresh = self.allocator.alloc(n - len(prefix_blocks))
        if fresh is None:
            for b in prefix_blocks:
                self.allocator.decref(b)
            return None
        # counted on SUCCESS only — a pool-pressure head retries admission
        # every scheduler iteration and must not inflate the denominator
        prefix_tokens = len(prefix_blocks) * self.block_size
        if prompt is not None:
            self.prefix_lookups += 1
        if prefix_blocks:
            self.prefix_hits += 1
            self.prefix_cached_tokens += prefix_tokens
        blocks = prefix_blocks + fresh
        pages = SlotPages(blocks, n * self.block_size,
                          used_tokens=prefix_tokens,
                          prefix_tokens=prefix_tokens)
        self.pages[slot] = pages
        self.block_tables[slot, :] = self.scratch_block
        self.block_tables[slot, : len(blocks)] = blocks
        self.tables_version += 1
        self.seq_lens[slot] = prefix_tokens
        return pages

    def release(self, slot: int) -> None:
        """Drop the slot's block references (eviction path): registered
        blocks park in the cached LRU, the rest return to the pool."""
        pages = self.pages[slot]
        if pages is None:
            return
        self.allocator.free(pages.blocks)
        self.pages[slot] = None
        self.block_tables[slot, :] = self.scratch_block
        self.tables_version += 1
        self.seq_lens[slot] = 0

    def ensure_writable(self, slot: int, pos: int) -> str | None:
        """Copy-on-write guard for an in-place write at ``pos``.

        Returns ``"cow"`` when the target block was shared (refcount > 1)
        and has been copied into a fresh exclusive block (page table
        re-pointed), ``"unregistered"`` when it was exclusive but indexed
        (the entry is dropped — the write would invalidate the cached
        content), or None when the write was already safe.  Raises under
        pool pressure if a copy is needed but no block is grantable (the
        engine's admission contract makes that unreachable: appends land
        past the prompt, and only full prompt blocks are ever shared)."""
        pages = self.pages[slot]
        if pages is None:
            raise OutOfBlocksError(f"slot {slot} has no pages")
        li = pos // self.block_size
        if li >= len(pages.blocks):
            raise OutOfBlocksError(
                f"slot {slot}: write at {pos} exceeds reserved capacity "
                f"{pages.capacity_tokens}"
            )
        b = pages.blocks[li]
        if self.allocator.refcount(b) > 1:
            fresh = self.allocator.alloc(1)
            if fresh is None:
                raise OutOfBlocksError(
                    f"slot {slot}: copy-on-write at position {pos} needs a "
                    "block but the pool is exhausted"
                )
            dst = fresh[0]
            self.k_pool, self.v_pool = _copy_block_fn()(
                self.k_pool, self.v_pool, jnp.int32(b), jnp.int32(dst)
            )
            self.allocator.decref(b)
            pages.blocks[li] = dst
            self.block_tables[slot, li] = dst
            self.tables_version += 1
            self.cow_copies += 1
            return "cow"
        if self.allocator.is_registered(b):
            self._on_evict(b)  # drop the index entry
            self.allocator.unregister(b)
            return "unregistered"
        return None

    def ensure_writable_range(self, slot: int, start: int, end: int) -> int:
        """Copy-on-write guard over every block a multi-token write
        ``[start, end)`` touches (the speculative verify program appends
        the committed token plus all drafts in one dispatch).  Returns
        the number of blocks that needed a CoW copy or an unregister —
        steady state 0, same as the single-position guard."""
        if end <= start:
            return 0
        fixed = 0
        bs = self.block_size
        for li in range(start // bs, (end - 1) // bs + 1):
            if self.ensure_writable(slot, li * bs) is not None:
                fixed += 1
        return fixed

    def rollback(self, slot: int, tokens: int) -> None:
        """Retreat a slot's resident-token count to ``tokens`` (rejected
        or discarded speculative drafts: the K/V past the new extent is
        dead and will be overwritten by the next append).

        Two hard rules.  (1) **Never into the mapped prefix**: positions
        below ``prefix_tokens`` are another request's cached content
        mapped refcount+1 — retreating "past" them would claim the slot
        re-owns positions it never wrote.  (2) **No block is freed**:
        the admission contract reserved the slot's whole worst-case
        footprint all-or-nothing, and handing blocks back on a retreat
        would let another admission claim them and force a mid-flight
        re-alloc (the OOM class admission control exists to prevent)
        when this slot's generation advances again.  As belt and braces
        the retreat also refuses to cross any *shared* (refcount > 1)
        block — the engine only ever speculates past the prompt, so a
        shared block inside the retreat window means scheduler state
        went inconsistent and silently continuing would corrupt the
        shared content's accounting."""
        pages = self.pages[slot]
        if pages is None:
            raise OutOfBlocksError(f"slot {slot} has no pages")
        if tokens > pages.used_tokens:
            raise OutOfBlocksError(
                f"slot {slot}: rollback target {tokens} exceeds resident "
                f"{pages.used_tokens} (rollback only retreats)"
            )
        if tokens < pages.prefix_tokens:
            raise OutOfBlocksError(
                f"slot {slot}: rollback to {tokens} would retreat into the "
                f"mapped shared prefix ({pages.prefix_tokens} tokens)"
            )
        if tokens == pages.used_tokens:
            return  # empty retreat window
        bs = self.block_size
        for li in range(tokens // bs,
                        min((pages.used_tokens - 1) // bs + 1,
                            len(pages.blocks))):
            if self.allocator.refcount(pages.blocks[li]) > 1:
                raise OutOfBlocksError(
                    f"slot {slot}: rollback window covers shared block "
                    f"{pages.blocks[li]} (refcount "
                    f"{self.allocator.refcount(pages.blocks[li])})"
                )
        pages.used_tokens = tokens
        self.seq_lens[slot] = tokens

    def note_written(self, slot: int, tokens: int) -> None:
        """Advance a slot's resident-token count (after a program wrote
        K/V); bounded by the reservation so a scheduler bug trips here,
        not as silent cross-slot corruption."""
        pages = self.pages[slot]
        if pages is None:
            raise OutOfBlocksError(f"slot {slot} has no pages")
        if tokens > pages.capacity_tokens:
            raise OutOfBlocksError(
                f"slot {slot}: {tokens} tokens exceed reserved capacity "
                f"{pages.capacity_tokens}"
            )
        pages.used_tokens = tokens
        self.seq_lens[slot] = tokens

    # -- introspection -------------------------------------------------------

    def billed_blocks(self, slot: int) -> float:
        """Refcount-weighted block footprint of one slot: each mapped
        block charged at ``1/refcount``, so a prefix block shared by N
        slots costs each of them 1/N and summing over all occupied slots
        can never exceed the pool's mapped-block count (the per-tenant
        usage ledger's no-double-billing invariant).  Engine thread only,
        like all host-side page-table state."""
        pages = self.pages[slot]
        if pages is None:
            return 0.0
        alloc = self.allocator
        return sum(1.0 / alloc.refcount(b) for b in pages.blocks)

    def stats(self) -> dict:
        """Pool occupancy, internal fragmentation, and prefix-cache
        occupancy/hit-rate (for ``GET /generatez``, the registry gauges,
        and the engine's metrics.jsonl rows)."""
        used = [p for p in self.pages if p is not None]
        allocated_tokens = sum(p.capacity_tokens for p in used)
        used_tokens = sum(p.used_tokens for p in used)
        alloc = self.allocator
        return {
            "block_size": self.block_size,
            "blocks_total": alloc.num_blocks,
            "blocks_free": alloc.free_blocks,
            "blocks_used": alloc.used_blocks,
            "blocks_cached": alloc.cached_blocks,
            "block_refs": alloc.total_refs,
            "slots_occupied": len(used),
            "allocated_tokens": allocated_tokens,
            "resident_tokens": used_tokens,
            # 0 = every allocated token holds real K/V; 1 = all waste.
            "fragmentation": (
                1.0 - used_tokens / allocated_tokens if allocated_tokens
                else 0.0
            ),
            # prefix cache: share of the pool holding indexed content
            # (mapped-shared OR parked cached), and the admission hit rate
            "prefix_blocks_indexed": len(self._hash_to_block),
            "prefix_occupancy": len(self._hash_to_block) / alloc.num_blocks,
            "prefix_lookups": self.prefix_lookups,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": (
                self.prefix_hits / self.prefix_lookups
                if self.prefix_lookups else 0.0
            ),
            "prefix_cached_tokens": self.prefix_cached_tokens,
            "prefix_evictions": alloc.evictions,
            "cow_copies": self.cow_copies,
        }
